"""``ray_tpu.train.huggingface`` — HF Transformers fine-tuning on TPU.

Parity: ``python/ray/train/huggingface/`` (TransformersTrainer), built
TPU-native: checkpoints port into the in-tree XLA GPT once and train
sharded (see ``transformers_trainer.py``).
"""

from ray_tpu.train.huggingface.transformers_trainer import (
    TransformersTrainer)
from ray_tpu.train.huggingface.weights import (export_gpt2, gpt2_config,
                                               load_model, port_gpt2)

__all__ = ["TransformersTrainer", "port_gpt2", "export_gpt2",
           "gpt2_config", "load_model"]
