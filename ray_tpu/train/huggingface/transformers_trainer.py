"""TransformersTrainer — HF checkpoint in, pod-sharded TPU fine-tune out.

Parity target: ``python/ray/train/huggingface/transformers/`` (the
reference wraps a per-worker ``transformers.Trainer``).  TPU-native
design: the HF GPT-2 checkpoint is ported ONCE (driver side) into the
in-tree XLA GPT (``train.huggingface.weights.port_gpt2``), shipped to
workers as numpy arrays through the object store, and trained with the
sharded ``build_gpt_train`` step over a device mesh — so the fine-tune
runs the same fused kernels / sharding rules as the native flagship,
not a torch graph under emulation.

Three-line user path::

    trainer = TransformersTrainer(model=hf_model_or_name,
                                  datasets={"train": ds},
                                  scaling_config=ScalingConfig(num_workers=1))
    result = trainer.fit()

``datasets["train"]`` rows need an ``input_ids`` field (HF-tokenizer
output); ``fit()`` reports ``loss`` per logging step and registers an
orbax-backed checkpoint each ``save_steps``.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

from ray_tpu.train.config import RunConfig, ScalingConfig
from ray_tpu.train.data_parallel_trainer import DataParallelTrainer
from ray_tpu.train.jax.config import JaxConfig


def _pack_token_stream(row_iter, seq_len: int, batch_size: int,
                       eos_id: int):
    """Pack variable-length ``input_ids`` rows into dense LM batches.

    Standard packing: concatenate rows (eos-joined) into a stream, cut
    ``[batch, seq_len+1]`` windows; yields (tokens, targets) int32.
    """
    import numpy as np
    need = batch_size * (seq_len + 1)
    buf: list = []
    for row in row_iter:
        ids = row["input_ids"] if isinstance(row, dict) else row
        buf.extend(int(t) for t in ids)
        buf.append(eos_id)
        while len(buf) >= need:
            chunk = np.asarray(buf[:need], dtype=np.int32).reshape(
                batch_size, seq_len + 1)
            buf = buf[need:]
            yield chunk[:, :-1], chunk[:, 1:]


def _default_hf_train_loop(config: Dict[str, Any]) -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from ray_tpu import train
    from ray_tpu.models import training as training_mod
    from ray_tpu.models.gpt import GPTConfig
    from ray_tpu.parallel.mesh import make_mesh
    from ray_tpu.train.checkpoint import save_pytree, load_pytree

    args = config.get("training_args", {})
    cfg = GPTConfig(**config["model_config"])
    params_np = config["model_params"]
    seq_len = int(args.get("seq_len") or min(cfg.max_seq, 1024))
    per_device_bs = int(args.get("per_device_train_batch_size", 8))
    lr = float(args.get("learning_rate", 5e-5))
    max_steps = int(args.get("max_steps", 100))
    log_steps = int(args.get("logging_steps", 10))
    save_steps = int(args.get("save_steps", max_steps))
    weight_decay = float(args.get("weight_decay", 0.01))
    warmup = int(args.get("warmup_steps", 0))
    eos_id = int(args.get("eos_token_id", 50256) % cfg.vocab_size)
    mesh_axes = dict(args.get("mesh") or {"dp": -1})

    mesh = make_mesh(**mesh_axes)
    n_data = 1
    for name, size in zip(mesh.axis_names, mesh.devices.shape):
        if name in ("dp", "fsdp"):
            n_data *= size
    batch = per_device_bs * n_data

    tx = optax.chain(
        optax.clip_by_global_norm(1.0),
        optax.adamw(
            optax.warmup_cosine_decay_schedule(
                0.0, lr, max(warmup, 1), max(max_steps, warmup + 1),
                lr * 0.1),
            b1=0.9, b2=0.999, weight_decay=weight_decay),
    )
    fns = training_mod.build_gpt_train(cfg, mesh, optimizer=tx)
    st_sh = fns["state_shardings"]

    # place the ported weights onto the mesh with their rule shardings
    params = jax.tree.map(
        lambda x, sh: jax.device_put(jnp.asarray(x, dtype=cfg.dtype), sh),
        params_np, st_sh.params)
    ckpt = train.get_checkpoint()
    if ckpt is not None:
        with ckpt.as_directory() as d:
            params = load_pytree(d, target=params)
    opt_state = jax.jit(tx.init, out_shardings=st_sh.opt_state)(params)
    state = training_mod.TrainState(params, opt_state,
                                    jnp.zeros((), jnp.int32))

    shard = train.get_dataset_shard("train")
    if shard is not None:
        def rows():
            while True:  # re-iterate epochs until max_steps
                n = 0
                for r in shard.iter_rows():
                    n += 1
                    yield r
                if n == 0:
                    return
    else:
        stream = np.asarray(config["token_stream"], dtype=np.int32)

        def rows():
            while True:
                yield stream

    packer = _pack_token_stream(rows(), seq_len, batch, eos_id)
    import tempfile
    step_fn = fns["step_fn"]
    for step in range(1, max_steps + 1):
        try:
            tokens, targets = next(packer)
        except StopIteration:
            break
        state, metrics = step_fn(
            state, {"tokens": jnp.asarray(tokens),
                    "targets": jnp.asarray(targets)})
        if step % log_steps == 0 or step == max_steps:
            m = {"loss": float(metrics["loss"]),
                 "step": step,
                 "grad_norm": float(metrics["grad_norm"]),
                 "epoch": 0}
            checkpoint = None
            if (step % save_steps == 0 or step == max_steps) and \
                    train.get_context().get_world_rank() == 0:
                d = tempfile.mkdtemp(prefix="hf_ckpt_")
                save_pytree(jax.tree.map(np.asarray, state.params), d)
                checkpoint = train.Checkpoint.from_directory(d)
            train.report(m, checkpoint=checkpoint)


class TransformersTrainer(DataParallelTrainer):
    """Fine-tune an HF Transformers checkpoint on TPU meshes.

    ``model``: HF model instance / hub name / (state_dict, config) —
    ported on the driver via ``weights.port_gpt2``.  ``training_args``
    mirrors the HF names (``per_device_train_batch_size``,
    ``learning_rate``, ``max_steps``, ``logging_steps``, ``save_steps``,
    ``seq_len``) plus ``mesh`` ({axis: size}) for sharding beyond DP.
    Pass ``train_loop_per_worker`` to override the built-in loop
    (reference: ``TransformersTrainer(trainer_init_per_worker=...)``).
    """

    def __init__(self, *, model: Any = None,
                 training_args: Optional[Dict[str, Any]] = None,
                 train_loop_per_worker: Optional[Callable] = None,
                 train_loop_config: Optional[Dict[str, Any]] = None,
                 token_stream: Any = None,
                 dtype: Any = None,
                 scaling_config: Optional[ScalingConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 datasets: Optional[Dict[str, Any]] = None,
                 **kwargs):
        loop_config = dict(train_loop_config or {})
        if train_loop_per_worker is None:
            if model is None:
                raise ValueError(
                    "TransformersTrainer needs `model=` (HF model, hub "
                    "name, or (state_dict, config)) unless a custom "
                    "train_loop_per_worker is given")
            from ray_tpu.train.huggingface import weights as hfw
            if isinstance(model, tuple):
                cfg, params = hfw.port_gpt2(model[0], hf_config=model[1],
                                            dtype=dtype)
            else:
                cfg, params = hfw.load_model(model, dtype=dtype)
            import dataclasses
            import numpy as np
            model_config = dataclasses.asdict(cfg)
            loop_config.update({
                "model_config": model_config,
                "model_params": params,
                "training_args": dict(training_args or {}),
            })
            if token_stream is not None:
                loop_config["token_stream"] = np.asarray(
                    token_stream, dtype=np.int32)
            train_loop_per_worker = _default_hf_train_loop
        super().__init__(
            train_loop_per_worker,
            train_loop_config=loop_config,
            backend_config=kwargs.pop("backend_config", None) or JaxConfig(),
            scaling_config=scaling_config,
            run_config=run_config,
            datasets=datasets,
            **kwargs)
