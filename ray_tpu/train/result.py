"""Result type (parity: ``python/ray/air/result.py``)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from ray_tpu.train.checkpoint import Checkpoint


@dataclass
class Result:
    metrics: Dict[str, Any] = field(default_factory=dict)
    checkpoint: Optional[Checkpoint] = None
    path: Optional[str] = None
    error: Optional[BaseException] = None
    metrics_history: List[Dict[str, Any]] = field(default_factory=list)
    best_checkpoints: List[tuple] = field(default_factory=list)

    @property
    def metrics_dataframe(self):
        import pandas as pd
        return pd.DataFrame(self.metrics_history)

    @property
    def config(self) -> Optional[Dict[str, Any]]:
        return self.metrics.get("config")
