"""Checkpoint — a directory of files plus metadata.

Parity: ``python/ray/train/_checkpoint.py`` (from_directory/to_directory/
as_directory, metadata).  Storage is a filesystem path (local or fsspec-
mountable); jax pytrees get helpers built on orbax when available, with a
numpy fallback.
"""

from __future__ import annotations

import json
import os
import shutil
import tempfile
import uuid
from contextlib import contextmanager
from typing import Any, Dict, Optional

_METADATA_FILE = ".metadata.json"


class Checkpoint:
    def __init__(self, path: str):
        from ray_tpu.train.storage import is_remote_uri
        self._remote = is_remote_uri(path)
        self.path = path if self._remote else os.path.abspath(path)

    def _local(self) -> str:
        """A local directory with this checkpoint's contents (downloads
        remote checkpoints into a cached temp dir once per process)."""
        if not self._remote:
            return self.path
        if getattr(self, "_local_cache", None) is None:
            from ray_tpu.train.storage import download_dir
            self._local_cache = download_dir(
                self.path, tempfile.mkdtemp(prefix="rtpu_ckpt_dl_"))
        return self._local_cache

    # ------------------------------------------------------------ builders
    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        return cls(path)

    @classmethod
    def from_dict(cls, data: Dict[str, Any]) -> "Checkpoint":
        """Convenience for small state dicts (pickled into the dir)."""
        import cloudpickle
        path = tempfile.mkdtemp(prefix="rtpu_ckpt_")
        with open(os.path.join(path, "dict_checkpoint.pkl"), "wb") as f:
            cloudpickle.dump(data, f)
        return cls(path)

    def to_dict(self) -> Dict[str, Any]:
        import cloudpickle
        with open(os.path.join(self._local(), "dict_checkpoint.pkl"),
                  "rb") as f:
            return cloudpickle.load(f)

    # ------------------------------------------------------------ metadata
    def set_metadata(self, metadata: Dict[str, Any]) -> None:
        # tmp + rename: a crash mid-write must not leave a torn file
        # that breaks the next run's rehydration
        target = os.path.join(self.path, _METADATA_FILE)
        tmp = target + ".tmp"
        with open(tmp, "w") as f:
            json.dump(metadata, f)
        os.replace(tmp, target)

    def get_metadata(self) -> Dict[str, Any]:
        p = os.path.join(self._local(), _METADATA_FILE)
        if not os.path.exists(p):
            return {}
        with open(p) as f:
            return json.load(f)

    # ------------------------------------------------------------ movement
    def to_directory(self, dest: Optional[str] = None) -> str:
        dest = dest or tempfile.mkdtemp(prefix="rtpu_ckpt_")
        os.makedirs(dest, exist_ok=True)
        local = self._local()
        for name in os.listdir(local):
            src = os.path.join(local, name)
            dst = os.path.join(dest, name)
            if os.path.isdir(src):
                shutil.copytree(src, dst, dirs_exist_ok=True)
            else:
                shutil.copy2(src, dst)
        return dest

    @contextmanager
    def as_directory(self):
        yield self._local()

    def persist(self, storage_dir: str, name: Optional[str] = None) -> \
            "Checkpoint":
        """Copy into durable storage — a local path or any fsspec URI
        (``gs://`` / ``s3://`` / ``memory://`` …); returns the
        persisted checkpoint."""
        from ray_tpu.train.storage import is_remote_uri, upload_dir
        name = name or f"checkpoint_{uuid.uuid4().hex[:8]}"
        if is_remote_uri(storage_dir):
            dest = f"{storage_dir.rstrip('/')}/{name}"
            upload_dir(self._local(), dest)
            return Checkpoint(dest)
        dest = os.path.join(storage_dir, name)
        os.makedirs(storage_dir, exist_ok=True)
        if os.path.abspath(self.path) == os.path.abspath(dest):
            return self
        self.to_directory(dest)
        return Checkpoint(dest)

    def __repr__(self):
        return f"Checkpoint({self.path})"

    def __reduce__(self):
        return (Checkpoint, (self.path,))


# ---------------------------------------------------------------- pytrees
_ORBAX_WARNED = False


def save_pytree(tree, path: str, *, name: str = "state") -> None:
    """Save a jax pytree: orbax if usable, else npz + structure pickle.

    The npz fallback handles ml_dtypes leaves (bf16/fp8): ``np.savez``
    cannot serialize custom dtypes, so those leaves are written as raw
    uint8 with their (dtype, shape) recorded beside the treedef and
    reconstructed by :func:`load_pytree` via a view."""
    global _ORBAX_WARNED
    os.makedirs(path, exist_ok=True)
    try:
        import orbax.checkpoint as ocp
    except ImportError:    # not installed: the documented quiet fallback
        ocp = None
    except Exception as e:  # noqa: BLE001 — broken install (jax skew):
        ocp = None          # fall back like before, but say so
        if not _ORBAX_WARNED:
            import sys
            print(f"save_pytree: orbax import failed ({e!r}); falling "
                  "back to the npz writer (warning once per process)",
                  file=sys.stderr)
            _ORBAX_WARNED = True
    if ocp is not None:
        try:
            ckptr = ocp.StandardCheckpointer()
            target = os.path.join(path, name)
            if os.path.exists(target):
                shutil.rmtree(target)
            ckptr.save(target, tree)
            ckptr.wait_until_finished()
            return
        except Exception as e:  # noqa: BLE001 - fall back, loudly
            # a partial orbax dir would shadow the npz fallback at
            # load time (load_pytree routes on isdir)
            shutil.rmtree(os.path.join(path, name), ignore_errors=True)
            if not _ORBAX_WARNED:
                import sys
                print(f"save_pytree: orbax save failed ({e!r}); falling "
                      "back to the npz writer (warning once per process)",
                      file=sys.stderr)
                _ORBAX_WARNED = True
    import cloudpickle
    import jax
    import numpy as np
    leaves, treedef = jax.tree.flatten(tree)
    arrays, exotic = {}, {}
    for i, leaf in enumerate(leaves):
        # NOT ascontiguousarray: it silently promotes 0-d leaves (the
        # step counter, optimizer counts) to shape (1,), so a restored
        # TrainState would no longer match the live one
        arr = np.asarray(leaf)
        if arr.ndim and not arr.flags["C_CONTIGUOUS"]:
            arr = np.ascontiguousarray(arr)
        if arr.dtype.kind == "V":      # ml_dtypes: npz can't serialize
            exotic[str(i)] = (str(arr.dtype), arr.shape)
            arr = arr.reshape(-1).view(np.uint8)
        arrays[str(i)] = arr
    np.savez(os.path.join(path, f"{name}.npz"), **arrays)
    with open(os.path.join(path, f"{name}.treedef.pkl"), "wb") as f:
        cloudpickle.dump({"treedef": treedef, "exotic": exotic}, f)


def load_pytree(path: str, *, name: str = "state", target=None):
    """Load a pytree saved by save_pytree.

    ``target``: example pytree (for orbax restore typing / structure).
    """
    orbax_dir = os.path.join(path, name)
    if os.path.isdir(orbax_dir):
        import orbax.checkpoint as ocp
        ckptr = ocp.StandardCheckpointer()
        if target is not None:
            import jax
            abstract = jax.tree.map(
                lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), target)
            return ckptr.restore(orbax_dir, abstract)
        return ckptr.restore(orbax_dir)
    import cloudpickle
    import jax
    import numpy as np
    data = np.load(os.path.join(path, f"{name}.npz"))
    with open(os.path.join(path, f"{name}.treedef.pkl"), "rb") as f:
        saved = cloudpickle.load(f)
    if isinstance(saved, dict):
        treedef, exotic = saved["treedef"], saved.get("exotic", {})
    else:                    # pre-r10 files pickled the bare treedef
        treedef, exotic = saved, {}
    leaves = []
    for i in range(len(data.files)):
        arr = data[str(i)]
        if str(i) in exotic:
            dtype, shape = exotic[str(i)]
            arr = arr.view(np.dtype(dtype)).reshape(shape)
        leaves.append(arr)
    return jax.tree.unflatten(treedef, leaves)
