"""Checkpoint — a directory of files plus metadata.

Parity: ``python/ray/train/_checkpoint.py`` (from_directory/to_directory/
as_directory, metadata).  Storage is a filesystem path (local or fsspec-
mountable); jax pytrees get helpers built on orbax when available, with a
numpy fallback.
"""

from __future__ import annotations

import json
import os
import shutil
import tempfile
import uuid
from contextlib import contextmanager
from typing import Any, Dict, Optional

_METADATA_FILE = ".metadata.json"


class Checkpoint:
    def __init__(self, path: str):
        from ray_tpu.train.storage import is_remote_uri
        self._remote = is_remote_uri(path)
        self.path = path if self._remote else os.path.abspath(path)

    def _local(self) -> str:
        """A local directory with this checkpoint's contents (downloads
        remote checkpoints into a cached temp dir once per process)."""
        if not self._remote:
            return self.path
        if getattr(self, "_local_cache", None) is None:
            from ray_tpu.train.storage import download_dir
            self._local_cache = download_dir(
                self.path, tempfile.mkdtemp(prefix="rtpu_ckpt_dl_"))
        return self._local_cache

    # ------------------------------------------------------------ builders
    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        return cls(path)

    @classmethod
    def from_dict(cls, data: Dict[str, Any]) -> "Checkpoint":
        """Convenience for small state dicts (pickled into the dir)."""
        import cloudpickle
        path = tempfile.mkdtemp(prefix="rtpu_ckpt_")
        with open(os.path.join(path, "dict_checkpoint.pkl"), "wb") as f:
            cloudpickle.dump(data, f)
        return cls(path)

    def to_dict(self) -> Dict[str, Any]:
        import cloudpickle
        with open(os.path.join(self._local(), "dict_checkpoint.pkl"),
                  "rb") as f:
            return cloudpickle.load(f)

    # ------------------------------------------------------------ metadata
    def set_metadata(self, metadata: Dict[str, Any]) -> None:
        # tmp + rename: a crash mid-write must not leave a torn file
        # that breaks the next run's rehydration
        target = os.path.join(self.path, _METADATA_FILE)
        tmp = target + ".tmp"
        with open(tmp, "w") as f:
            json.dump(metadata, f)
        os.replace(tmp, target)

    def get_metadata(self) -> Dict[str, Any]:
        p = os.path.join(self._local(), _METADATA_FILE)
        if not os.path.exists(p):
            return {}
        with open(p) as f:
            return json.load(f)

    # ------------------------------------------------------------ movement
    def to_directory(self, dest: Optional[str] = None) -> str:
        dest = dest or tempfile.mkdtemp(prefix="rtpu_ckpt_")
        os.makedirs(dest, exist_ok=True)
        local = self._local()
        for name in os.listdir(local):
            src = os.path.join(local, name)
            dst = os.path.join(dest, name)
            if os.path.isdir(src):
                shutil.copytree(src, dst, dirs_exist_ok=True)
            else:
                shutil.copy2(src, dst)
        return dest

    @contextmanager
    def as_directory(self):
        yield self._local()

    def persist(self, storage_dir: str, name: Optional[str] = None) -> \
            "Checkpoint":
        """Copy into durable storage — a local path or any fsspec URI
        (``gs://`` / ``s3://`` / ``memory://`` …); returns the
        persisted checkpoint."""
        from ray_tpu.train.storage import is_remote_uri, upload_dir
        name = name or f"checkpoint_{uuid.uuid4().hex[:8]}"
        if is_remote_uri(storage_dir):
            dest = f"{storage_dir.rstrip('/')}/{name}"
            upload_dir(self._local(), dest)
            return Checkpoint(dest)
        dest = os.path.join(storage_dir, name)
        os.makedirs(storage_dir, exist_ok=True)
        if os.path.abspath(self.path) == os.path.abspath(dest):
            return self
        self.to_directory(dest)
        return Checkpoint(dest)

    def __repr__(self):
        return f"Checkpoint({self.path})"

    def __reduce__(self):
        return (Checkpoint, (self.path,))


# ---------------------------------------------------------------- pytrees
def save_pytree(tree, path: str, *, name: str = "state") -> None:
    """Save a jax pytree: orbax if importable, else npz + structure pickle."""
    os.makedirs(path, exist_ok=True)
    try:
        import orbax.checkpoint as ocp
        ckptr = ocp.StandardCheckpointer()
        target = os.path.join(path, name)
        if os.path.exists(target):
            shutil.rmtree(target)
        ckptr.save(target, tree)
        ckptr.wait_until_finished()
        return
    except Exception:  # noqa: BLE001 - fall back to numpy
        pass
    import cloudpickle
    import jax
    import numpy as np
    leaves, treedef = jax.tree.flatten(tree)
    np.savez(os.path.join(path, f"{name}.npz"),
             **{str(i): np.asarray(leaf) for i, leaf in enumerate(leaves)})
    with open(os.path.join(path, f"{name}.treedef.pkl"), "wb") as f:
        cloudpickle.dump(treedef, f)


def load_pytree(path: str, *, name: str = "state", target=None):
    """Load a pytree saved by save_pytree.

    ``target``: example pytree (for orbax restore typing / structure).
    """
    orbax_dir = os.path.join(path, name)
    if os.path.isdir(orbax_dir):
        import orbax.checkpoint as ocp
        ckptr = ocp.StandardCheckpointer()
        if target is not None:
            import jax
            abstract = jax.tree.map(
                lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), target)
            return ckptr.restore(orbax_dir, abstract)
        return ckptr.restore(orbax_dir)
    import cloudpickle
    import jax
    import numpy as np
    data = np.load(os.path.join(path, f"{name}.npz"))
    with open(os.path.join(path, f"{name}.treedef.pkl"), "rb") as f:
        treedef = cloudpickle.load(f)
    leaves = [data[str(i)] for i in range(len(data.files))]
    return jax.tree.unflatten(treedef, leaves)
