"""``ray_tpu.train`` — distributed training (parity: ``ray.train``)."""

from ray_tpu.train.backend import (Backend, BackendConfig, BackendExecutor,
                                   TrainingFailedError)
from ray_tpu.train.checkpoint import Checkpoint, load_pytree, save_pytree
from ray_tpu.train.config import (CheckpointConfig, FailureConfig,
                                  RunConfig, ScalingConfig)
from ray_tpu.train.data_parallel_trainer import DataParallelTrainer
from ray_tpu.train.result import Result
from ray_tpu.train.session import (get_checkpoint, get_context,
                                   get_dataset_shard, report)
from ray_tpu.train.worker_group import WorkerGroup

__all__ = [
    "Backend", "BackendConfig", "BackendExecutor", "TrainingFailedError",
    "Checkpoint", "save_pytree", "load_pytree", "CheckpointConfig",
    "FailureConfig", "RunConfig", "ScalingConfig", "DataParallelTrainer",
    "Result", "get_checkpoint", "get_context", "get_dataset_shard",
    "report", "WorkerGroup",
]
