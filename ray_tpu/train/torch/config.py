"""TorchTrainer — torch.distributed (gloo) data-parallel backend.

Parity: ``python/ray/train/torch/config.py``
(``_setup_torch_process_group``): worker 0 picks MASTER_ADDR/PORT, every
worker sets RANK/WORLD_SIZE and calls ``init_process_group``.  CPU/gloo
here (no CUDA in this stack); the TPU path is JaxTrainer.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import ray_tpu
from ray_tpu.train.backend import Backend, BackendConfig
from ray_tpu.train.data_parallel_trainer import DataParallelTrainer
from ray_tpu.train.worker_group import WorkerGroup


@dataclass
class TorchConfig(BackendConfig):
    backend: str = "gloo"
    init_method: str = "env"
    timeout_s: int = 180

    def backend_cls(self):
        return _TorchBackend


def _setup_process_group(master_addr: str, master_port: int, rank: int,
                         world_size: int, backend: str, timeout_s: int):
    import datetime
    import os

    import torch.distributed as dist
    os.environ["MASTER_ADDR"] = master_addr
    os.environ["MASTER_PORT"] = str(master_port)
    os.environ["RANK"] = str(rank)
    os.environ["WORLD_SIZE"] = str(world_size)
    if not dist.is_initialized():
        dist.init_process_group(
            backend=backend, rank=rank, world_size=world_size,
            timeout=datetime.timedelta(seconds=timeout_s))
    return True


def _free_port() -> int:
    import socket
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class _TorchBackend(Backend):
    def on_start(self, worker_group: WorkerGroup,
                 backend_config: TorchConfig):
        n = len(worker_group)
        if n == 0:
            return
        ip = ray_tpu.get(worker_group.workers[0].node_ip.remote(),
                         timeout=30)
        port = _free_port()
        refs = [w.execute.remote(_setup_process_group, ip, port, rank, n,
                                 backend_config.backend,
                                 backend_config.timeout_s)
                for rank, w in enumerate(worker_group.workers)]
        ray_tpu.get(refs, timeout=backend_config.timeout_s + 60)

    def on_shutdown(self, worker_group: WorkerGroup):
        def teardown():
            import torch.distributed as dist
            if dist.is_initialized():
                dist.destroy_process_group()
            return True
        try:
            worker_group.execute(teardown)
        except Exception:  # noqa: BLE001
            pass


def prepare_model(model, parallel_strategy: Optional[str] = "ddp"):
    """Wrap a torch model for DP (parity: train_loop_utils.prepare_model)."""
    import torch.distributed as dist
    from torch.nn.parallel import DistributedDataParallel as DDP
    if parallel_strategy == "ddp" and dist.is_initialized() and \
            dist.get_world_size() > 1:
        return DDP(model)
    return model


def prepare_data_loader(data_loader):
    """Shard a DataLoader across workers via DistributedSampler."""
    import torch.distributed as dist
    from torch.utils.data import DataLoader
    from torch.utils.data.distributed import DistributedSampler
    if not dist.is_initialized() or dist.get_world_size() <= 1:
        return data_loader
    sampler = DistributedSampler(data_loader.dataset)
    return DataLoader(data_loader.dataset,
                      batch_size=data_loader.batch_size,
                      sampler=sampler,
                      num_workers=0,
                      collate_fn=data_loader.collate_fn,
                      drop_last=data_loader.drop_last)


class TorchTrainer(DataParallelTrainer):
    def __init__(self, train_loop_per_worker, *,
                 torch_config: Optional[TorchConfig] = None,
                 backend_config: Optional[TorchConfig] = None, **kwargs):
        # backend_config accepted as an alias so restore() can rebuild
        # a TorchTrainer from the generic trainer blob
        super().__init__(train_loop_per_worker,
                         backend_config=torch_config or backend_config
                         or TorchConfig(),
                         **kwargs)
