from ray_tpu.train.torch.config import TorchConfig, TorchTrainer

__all__ = ["TorchConfig", "TorchTrainer"]
