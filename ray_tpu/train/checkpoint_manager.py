"""Keep-top-k checkpoint bookkeeping.

Parity: ``python/ray/train/_internal/checkpoint_manager.py`` driven by
``CheckpointConfig`` (keep num_to_keep best by score attribute).
"""

from __future__ import annotations

import os
import shutil
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.config import CheckpointConfig
from ray_tpu.train.storage import delete_uri, is_remote_uri, list_uri


class CheckpointManager:
    def __init__(self, storage_dir: str, config: CheckpointConfig,
                 resume: bool = False):
        self.storage_dir = storage_dir
        self.config = config
        self._index = 0
        # list of (score, index, checkpoint, metrics)
        self.best: List[Tuple[float, int, Checkpoint, Dict]] = []
        self.latest: Optional[Checkpoint] = None
        if resume:
            # only a restored trainer adopts prior checkpoints — a fresh
            # run reusing an experiment name must not warm-start from a
            # previous run's weights
            self._rehydrate()

    def _rehydrate(self) -> None:
        """Adopt checkpoints a previous run left in the directory, so a
        restored trainer resumes from its latest (reference:
        experiment-state reconstruction on Trainer.restore)."""
        import glob
        import re
        found = []
        if is_remote_uri(self.storage_dir):
            base = self.storage_dir.rstrip("/")
            entries = [(name, f"{base}/{name}")
                       for name in list_uri(self.storage_dir)]
        else:
            entries = [(os.path.basename(p), p) for p in glob.glob(
                os.path.join(self.storage_dir, "checkpoint_*"))
                if os.path.isdir(p)]
        for name, path in entries:
            m = re.search(r"checkpoint_(\d+)", name)
            if m:
                found.append((int(m.group(1)), path))
        for idx, path in sorted(found):
            ckpt = Checkpoint(path)
            self.latest = ckpt
            self._index = max(self._index, idx + 1)
            try:
                metrics = ckpt.get_metadata().get("metrics", {})
            except Exception:  # noqa: BLE001 — torn metadata write
                metrics = {}
            attr = self.config.checkpoint_score_attribute
            if attr is not None and attr in metrics:
                score = float(metrics[attr])
            else:
                score = float(idx + 1)
            sign = (1.0 if self.config.checkpoint_score_order == "max"
                    else -1.0)
            self.best.append((sign * score, idx + 1, ckpt, metrics))
        self.best.sort(key=lambda t: (t[0], t[1]), reverse=True)

    def register(self, checkpoint: Checkpoint,
                 metrics: Dict[str, Any]) -> Checkpoint:
        clean = {k: v for k, v in metrics.items()
                 if isinstance(v, (int, float, str, bool))}

        def stamp(ckpt: Checkpoint) -> None:
            try:
                meta = ckpt.get_metadata()
                meta["metrics"] = clean
                ckpt.set_metadata(meta)
            except Exception:  # noqa: BLE001 — metadata is best-effort
                import logging
                logging.getLogger(__name__).warning(
                    "checkpoint metadata stamp failed for %s",
                    ckpt.path, exc_info=True)

        # Local destination: stamp the PERSISTED copy (don't mutate the
        # caller's directory).  Remote destination: set_metadata can't
        # write through a URI, so pre-stamp the local source just before
        # the upload carries it; a remote source keeps its metadata.
        dest_remote = is_remote_uri(self.storage_dir)
        if dest_remote and not is_remote_uri(checkpoint.path):
            stamp(checkpoint)
        persisted = checkpoint.persist(
            self.storage_dir, f"checkpoint_{self._index:06d}")
        if not dest_remote:
            stamp(persisted)
        self._index += 1
        self.latest = persisted
        attr = self.config.checkpoint_score_attribute
        if attr is not None and attr in metrics:
            score = float(metrics[attr])
        else:
            score = float(self._index)  # fall back to recency
        sign = 1.0 if self.config.checkpoint_score_order == "max" else -1.0
        self.best.append((sign * score, self._index, persisted,
                          dict(metrics)))
        self.best.sort(key=lambda t: (t[0], t[1]), reverse=True)
        keep = self.config.num_to_keep
        if keep is not None and len(self.best) > keep:
            for _, _, ckpt, _ in self.best[keep:]:
                if self.latest is not None and \
                        ckpt.path == self.latest.path:
                    continue
                if is_remote_uri(ckpt.path):
                    delete_uri(ckpt.path)
                else:
                    shutil.rmtree(ckpt.path, ignore_errors=True)
            self.best = self.best[:keep] + [
                b for b in self.best[keep:]
                if self.latest is not None and b[2].path ==
                self.latest.path]
        return persisted

    @staticmethod
    def _exists(ckpt: Checkpoint) -> bool:
        if is_remote_uri(ckpt.path):
            return bool(list_uri(ckpt.path))
        return os.path.exists(ckpt.path)

    def best_checkpoint(self) -> Optional[Checkpoint]:
        for _, _, ckpt, _ in self.best:
            if self._exists(ckpt):
                return ckpt
        return self.latest

    def best_checkpoints(self) -> List[Tuple[Checkpoint, Dict]]:
        return [(c, m) for _, _, c, m in self.best if self._exists(c)]
