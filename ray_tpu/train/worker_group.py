"""WorkerGroup — the gang of train-worker actors.

Parity: ``python/ray/train/_internal/worker_group.py``.  Workers are
scheduled into a placement group built from the ScalingConfig; each hosts
a ``RayTrainWorker`` that executes arbitrary functions and the train loop.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.train.session import (TrainContext, get_session, init_session,
                                   shutdown_session)
from ray_tpu.util.placement_group import (PlacementGroup, placement_group,
                                          remove_placement_group)
from ray_tpu.util.scheduling_strategies import (
    PlacementGroupSchedulingStrategy)


@ray_tpu.remote
class RayTrainWorker:
    def __init__(self, rank: int, world_size: int):
        self.rank = rank
        self.world_size = world_size
        self._train_thread: Optional[threading.Thread] = None

    def execute(self, fn: Callable, *args, **kwargs):
        return fn(*args, **kwargs)

    def set_env(self, env: Dict[str, str]):
        import os
        os.environ.update(env)
        return True

    def node_ip(self):
        return "127.0.0.1"

    def start_train_fn(self, fn: Callable, config: Dict[str, Any],
                       context: TrainContext, checkpoint,
                       dataset_shards=None):
        session = init_session(context, checkpoint, dataset_shards)

        def runner():
            try:
                import inspect
                sig = inspect.signature(fn)
                if len(sig.parameters) == 0:
                    fn()
                else:
                    fn(config)
            except BaseException as e:  # noqa: BLE001
                session.error = e
            finally:
                session.finished.set()
                session.queue.put(("done", None, None))

        self._train_thread = threading.Thread(target=runner, daemon=True,
                                              name="train-loop")
        self._train_thread.start()
        return True

    def next_report(self, timeout: float = 1.0):
        """(kind, metrics, checkpoint) | None on timeout."""
        import queue as _q
        session = get_session()
        if session is None:
            return ("done", None, None)
        try:
            item = session.queue.get(timeout=timeout)
        except _q.Empty:
            return None
        if item[0] == "done" and session.error is not None:
            from ray_tpu.exceptions import format_remote_traceback
            return ("error", {"message": str(session.error),
                              "traceback": format_remote_traceback(
                                  session.error)}, None)
        return item

    def finish(self):
        shutdown_session()
        return True


class WorkerGroup:
    def __init__(self, num_workers: int,
                 resources_per_worker: Dict[str, float],
                 placement_strategy: str = "PACK"):
        self.num_workers = num_workers
        self.resources = resources_per_worker
        self.pg: Optional[PlacementGroup] = None
        if num_workers > 0:
            bundles = [dict(resources_per_worker)
                       for _ in range(num_workers)]
            self.pg = placement_group(bundles,
                                      strategy=placement_strategy)
            if not self.pg.wait(60):
                remove_placement_group(self.pg)
                raise RuntimeError(
                    f"could not reserve resources for {num_workers} "
                    f"workers x {resources_per_worker}")
        self.workers: List[Any] = []
        for rank in range(num_workers):
            opts: Dict[str, Any] = {
                "num_cpus": resources_per_worker.get("CPU", 1),
                "max_restarts": 0,
            }
            if resources_per_worker.get("TPU"):
                opts["num_tpus"] = resources_per_worker["TPU"]
            extra = {k: v for k, v in resources_per_worker.items()
                     if k not in ("CPU", "GPU", "TPU", "memory")}
            if extra:
                opts["resources"] = extra
            if self.pg is not None:
                opts["scheduling_strategy"] = \
                    PlacementGroupSchedulingStrategy(
                        placement_group=self.pg,
                        placement_group_bundle_index=rank)
            self.workers.append(
                RayTrainWorker.options(**opts).remote(
                    rank, num_workers))

    def execute(self, fn: Callable, *args, **kwargs) -> List[Any]:
        return ray_tpu.get([w.execute.remote(fn, *args, **kwargs)
                            for w in self.workers], timeout=300)

    def execute_async(self, fn: Callable, *args, **kwargs):
        return [w.execute.remote(fn, *args, **kwargs)
                for w in self.workers]

    def set_env(self, envs: List[Dict[str, str]]):
        ray_tpu.get([w.set_env.remote(e)
                     for w, e in zip(self.workers, envs)], timeout=60)

    def shutdown(self):
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:  # noqa: BLE001
                pass
        if self.pg is not None:
            remove_placement_group(self.pg)
        self.workers = []

    def __len__(self):
        return len(self.workers)
