"""``ray_tpu.experimental`` — incubating features (parity:
``ray.experimental``): mutable channels + compiled DAG execution."""

from ray_tpu.experimental.channel import Channel, ChannelClosed

__all__ = ["Channel", "ChannelClosed"]
