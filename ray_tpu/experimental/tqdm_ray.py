"""Distributed-safe progress bars (parity:
``python/ray/experimental/tqdm_ray.py``).

Plain ``tqdm`` from many worker processes interleaves garbage on the
driver's terminal.  Here each bar publishes its state through the
control-plane pubsub channel ``__tqdm__``; the driver side (hooked into
the log monitor's terminal) renders one line per live bar.  Workers
never touch the tty.

Usage inside a task/actor::

    from ray_tpu.experimental import tqdm_ray
    for x in tqdm_ray.tqdm(range(1000), desc="shard 3"):
        ...
"""

from __future__ import annotations

import os
import threading
import time
import uuid
from typing import Any, Dict, Iterable, Optional


def _cp():
    from ray_tpu._private.worker import global_worker
    w = global_worker()
    return w.cp if w is not None else None


_CHANNEL = "__tqdm__"


class tqdm:  # noqa: N801 - match the tqdm API
    """API-compatible subset of ``tqdm.tqdm``: iteration, ``update``,
    ``set_description``, ``close``, context manager."""

    def __init__(self, iterable: Optional[Iterable] = None,
                 desc: str = "", total: Optional[int] = None,
                 flush_interval_s: float = 0.2):
        self._iterable = iterable
        self.desc = desc
        if total is None and iterable is not None:
            try:
                total = len(iterable)  # type: ignore[arg-type]
            except TypeError:
                total = None
        self.total = total
        self.n = 0
        self._bar_id = uuid.uuid4().hex[:12]
        self._flush_interval = flush_interval_s
        self._last_flush = 0.0
        self._closed = False
        self._publish()

    # ------------------------------------------------------------------
    def __iter__(self):
        assert self._iterable is not None, "no iterable given"
        try:
            for x in self._iterable:
                yield x
                self.update(1)
        finally:
            self.close()

    def update(self, n: int = 1) -> None:
        self.n += n
        now = time.monotonic()
        if now - self._last_flush >= self._flush_interval:
            self._publish()

    def set_description(self, desc: str) -> None:
        self.desc = desc
        self._publish()

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._publish(done=True)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # ------------------------------------------------------------------
    def _publish(self, done: bool = False) -> None:
        self._last_flush = time.monotonic()
        cp = _cp()
        if cp is None:
            return
        try:
            cp.publish(_CHANNEL, {
                "bar_id": self._bar_id, "desc": self.desc,
                "n": self.n, "total": self.total, "done": done,
                "pid": os.getpid(),
            })
        except Exception:  # noqa: BLE001 - progress is best-effort
            pass


class DriverSideRenderer:
    """Driver-side consumer: renders every live bar as one tty line.

    Started by the driver (``tqdm_ray.install()``); polls the pubsub
    channel and repaints on change.  Rendering collapses when stdout is
    not a tty (CI): bars print once at completion instead.
    """

    def __init__(self):
        self._bars: Dict[str, Dict[str, Any]] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._painted_lines = 0

    def start(self) -> None:
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="tqdm-render")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def _loop(self) -> None:
        cp = _cp()
        if cp is None:
            return
        seq = 0
        while not self._stop.is_set():
            try:
                seq, msgs = cp.poll(_CHANNEL, seq, 0.5)
            except Exception:  # noqa: BLE001 - session shutting down
                return
            changed = False
            for m in msgs or []:
                changed = True
                if m.get("done"):
                    bar = self._bars.pop(m["bar_id"], None)
                    if bar is not None and not os.isatty(1):
                        print(self._format(m))
                else:
                    self._bars[m["bar_id"]] = m
            if changed and os.isatty(1):
                self._paint()

    @staticmethod
    def _format(m: Dict[str, Any]) -> str:
        total = m.get("total")
        if total:
            pct = 100.0 * m["n"] / total
            return (f"{m.get('desc') or m['bar_id']}: "
                    f"{m['n']}/{total} ({pct:.0f}%)")
        return f"{m.get('desc') or m['bar_id']}: {m['n']}"

    def _paint(self) -> None:
        # move cursor up over the previous frame, repaint every bar
        out = ""
        if self._painted_lines:
            out += f"\x1b[{self._painted_lines}F\x1b[J"
        lines = [self._format(m) for m in self._bars.values()]
        out += "\n".join(lines) + ("\n" if lines else "")
        print(out, end="", flush=True)
        self._painted_lines = len(lines)


_renderer: Optional[DriverSideRenderer] = None


def install() -> DriverSideRenderer:
    """Start the driver-side renderer (idempotent)."""
    global _renderer
    if _renderer is None:
        _renderer = DriverSideRenderer()
        _renderer.start()
    return _renderer
