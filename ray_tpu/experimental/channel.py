"""Mutable shared-memory channels (parity: ``python/ray/experimental/
channel.py:51`` + ``core_worker/experimental_mutable_object_manager.cc``).

A Channel is one *reusable* shm slot between a writer and N readers on
the same host — the transport under compiled DAGs.  Unlike the immutable
object store, a channel is written ten-thousand times with zero
control-plane traffic: the slot carries a seqlock-style header and the
payload in place.

Protocol (x86 total-store-order; all header fields are aligned u64):
- writer: wait until every reader's ack equals the current seq (slot
  consumed), memcpy payload + length, then publish seq+1;
- reader: wait until seq > own ack, read payload, publish ack = seq.
Payload bytes are fully written before the seq bump and read only after
observing it, so torn reads are impossible under TSO.

Capacity is fixed at creation (default 1 MiB); oversized payloads raise.
A ``stop`` flag poisons the channel: readers raise ChannelClosed.
"""

from __future__ import annotations

import mmap
import os
import pickle
import struct
import time
from typing import Any, List, Optional

_MAX_READERS = 8
# header: seq, stop, length, n_readers, acks[8]
_HEADER = struct.Struct("<QQQQ" + "Q" * _MAX_READERS)
HEADER_SIZE = _HEADER.size


class ChannelClosed(Exception):
    pass


class ChannelFull(TimeoutError):
    pass


def _spin_wait(predicate, timeout: Optional[float], what: str):
    deadline = None if timeout is None else time.monotonic() + timeout
    spins = 0
    while not predicate():
        spins += 1
        if spins < 50:
            continue            # burst-poll the mmap header
        # yield quickly at first (a peer on this core may be about to
        # publish), back off to real sleeps if the slot stays idle
        time.sleep(0.00005 if spins < 500 else
                   (0.0005 if spins < 5000 else 0.002))
        if deadline is not None and time.monotonic() > deadline:
            raise ChannelFull(f"channel {what} timed out")


class Channel:
    """One slot, one writer, ``num_readers`` readers (same host).

    Pickleable: the receiving process maps the same shm file.  Each
    reader must claim a distinct ``reader_index``.
    """

    def __init__(self, path: str, capacity: int = 1 << 20,
                 num_readers: int = 1, _create: bool = True):
        if num_readers > _MAX_READERS:
            raise ValueError(f"at most {_MAX_READERS} readers")
        self.path = path
        self.capacity = capacity
        self.num_readers = num_readers
        if _create:
            with open(path, "wb") as f:
                f.truncate(HEADER_SIZE + capacity)
            self._map()
            _HEADER.pack_into(self._mm, 0, 0, 0, 0, num_readers,
                              *([0] * _MAX_READERS))
        else:
            self._map()

    def _map(self):
        self._f = open(self.path, "r+b")
        self._mm = mmap.mmap(self._f.fileno(), HEADER_SIZE + self.capacity)

    # ------------------------------------------------------------------
    def _seq(self) -> int:
        return struct.unpack_from("<Q", self._mm, 0)[0]

    def _stop_flag(self) -> int:
        return struct.unpack_from("<Q", self._mm, 8)[0]

    def _acks(self) -> List[int]:
        return list(struct.unpack_from(
            "<" + "Q" * self.num_readers, self._mm, 32))

    # ------------------------------------------------------------------
    def write(self, value: Any, timeout: Optional[float] = 60.0) -> None:
        payload = pickle.dumps(value, protocol=5)
        if len(payload) > self.capacity:
            raise ValueError(
                f"payload of {len(payload)}B exceeds channel capacity "
                f"{self.capacity}B")
        seq = self._seq()
        _spin_wait(lambda: (all(a >= seq for a in self._acks())
                            or self._stop_flag()),
                   timeout, f"write {self.path}")
        if self._stop_flag():
            raise ChannelClosed(self.path)
        self._mm[HEADER_SIZE:HEADER_SIZE + len(payload)] = payload
        struct.pack_into("<Q", self._mm, 16, len(payload))
        struct.pack_into("<Q", self._mm, 0, seq + 1)   # publish

    def read(self, reader_index: int = 0,
             timeout: Optional[float] = 60.0) -> Any:
        ack_off = 32 + 8 * reader_index
        my_ack = struct.unpack_from("<Q", self._mm, ack_off)[0]
        _spin_wait(lambda: (self._seq() > my_ack or self._stop_flag()),
                   timeout, f"read {self.path}")
        if self._seq() <= my_ack and self._stop_flag():
            raise ChannelClosed(self.path)
        seq = self._seq()
        length = struct.unpack_from("<Q", self._mm, 16)[0]
        payload = bytes(self._mm[HEADER_SIZE:HEADER_SIZE + length])
        struct.pack_into("<Q", self._mm, ack_off, seq)  # release slot
        return pickle.loads(payload)

    def close(self) -> None:
        """Poison the channel: blocked/future readers and writers see
        ChannelClosed."""
        try:
            struct.pack_into("<Q", self._mm, 8, 1)
        except ValueError:
            pass                # already unmapped

    def unlink(self) -> None:
        self.close()
        try:
            self._mm.close()
            self._f.close()
        except OSError:
            pass
        try:
            os.unlink(self.path)
        except OSError:
            pass

    def __reduce__(self):
        return (Channel, (self.path, self.capacity, self.num_readers,
                          False))
