"""ObjectRef — a future for an object in the cluster.

Parity target: ``python/ray/_raylet.pyx`` ``ObjectRef`` /
``ObjectRefGenerator``.  Refs are cheap value types wrapping the binary
ObjectID; they pickle freely (into task args, other objects, etc.).

Lifetime: every live ObjectRef counts toward its object's reference count
(owner-side refcounting; reference ``core_worker/reference_count.cc``).
Construction registers +1 with the process-local ref tracker, __del__
registers -1; deltas flush in batches to the object's OWNER — the node
manager of the process that created the ref (put / task submission) —
which frees the object once its aggregate count stays zero past a grace
period.  The owner address rides the pickled ref, so borrowers anywhere
in the cluster report to the same owner; refs with no owner (internal
ids, e.g. generator items) fall back to control-plane refcounting.
Pickling into a task arg transfers liveness to the task spec (the node
manager pins dependencies, also owner-routed, until the task ends).
"""

from __future__ import annotations

from typing import Optional

from ray_tpu._private.ids import ObjectID


class ObjectRef:
    __slots__ = ("_id", "_tracked", "_owner")

    def __init__(self, object_id: bytes, owner_addr: Optional[str] = None):
        if isinstance(object_id, ObjectID):
            object_id = object_id.binary()
        if not isinstance(object_id, bytes) or len(object_id) != ObjectID.SIZE:
            raise ValueError(f"bad object id: {object_id!r}")
        self._id = object_id
        self._owner = owner_addr
        self._tracked = False
        from ray_tpu._private.ref_tracker import track_ref
        self._tracked = track_ref(object_id, owner_addr)

    def __del__(self):
        if getattr(self, "_tracked", False):
            try:
                from ray_tpu._private.ref_tracker import untrack_ref
                untrack_ref(self._id)
            except Exception:  # noqa: BLE001 - interpreter shutdown
                pass

    def binary(self) -> bytes:
        return self._id

    def hex(self) -> str:
        return self._id.hex()

    def task_id(self) -> bytes:
        from ray_tpu._private.ids import TaskID
        return self._id[:TaskID.SIZE]

    def __hash__(self):
        return hash(self._id)

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other._id == self._id

    def owner_addr(self) -> Optional[str]:
        """RPC address of the node manager owning this object's count."""
        return self._owner

    def __repr__(self):
        return f"ObjectRef({self._id.hex()})"

    def __reduce__(self):
        return (ObjectRef, (self._id, self._owner))

    def future(self):
        """A concurrent.futures.Future resolving to the object's value."""
        import concurrent.futures

        import ray_tpu
        fut: concurrent.futures.Future = concurrent.futures.Future()

        def _resolve():
            try:
                fut.set_result(ray_tpu.get(self))
            except BaseException as e:  # noqa: BLE001
                fut.set_exception(e)

        import threading
        threading.Thread(target=_resolve, daemon=True).start()
        return fut

    def __await__(self):
        """Allow ``await ref`` inside async actors."""
        import asyncio
        return asyncio.wrap_future(self.future()).__await__()


class ObjectRefGenerator:
    """Iterator over the streamed returns of a generator task.

    Mirrors the reference's streaming generators
    (``_raylet.pyx`` ``ObjectRefGenerator``): each ``__next__`` blocks
    until the producer commits the next yield, raising StopIteration once
    the end-of-stream marker is committed.
    """

    def __init__(self, task_id: bytes, worker=None):
        self._task_id = task_id
        self._index = 0
        self._done_at: Optional[int] = None

    def _worker(self):
        from ray_tpu._private.worker import global_worker
        return global_worker()

    def _ref_at(self, index: int) -> ObjectRef:
        # item i is committed at return index i+1 (0 = nominal return)
        from ray_tpu._private.ids import ObjectID, TaskID
        return ObjectRef(
            ObjectID.for_task_return(TaskID(self._task_id),
                                     index + 1).binary())

    def __iter__(self):
        return self

    def __next__(self) -> ObjectRef:
        worker = self._worker()
        length = worker.wait_generator_length(self._task_id)
        if length is not None and self._index >= length:
            raise StopIteration
        # Wait for either the item or the (possibly shorter) final length.
        ref = self._ref_at(self._index)
        worker.wait_ready_or_len(ref.binary(), self._task_id)
        length = worker.peek_generator_length(self._task_id)
        if length is not None and self._index >= length:
            raise StopIteration
        self._index += 1
        return ref

    def __reduce__(self):
        return (ObjectRefGenerator, (self._task_id,))

    def completed(self):
        return self

    def __aiter__(self):
        return self

    async def __anext__(self):
        import asyncio
        loop = asyncio.get_running_loop()

        # StopIteration cannot cross an asyncio Future (it turns into a
        # RuntimeError); carry end-of-stream as a flag instead.
        def step():
            try:
                return (True, self.__next__())
            except StopIteration:
                return (False, None)

        ok, ref = await loop.run_in_executor(None, step)
        if not ok:
            raise StopAsyncIteration
        return ref


StreamingObjectRefGenerator = ObjectRefGenerator
