"""Cross-entropy over a large vocab with bf16 logit residuals.

The no-remat CE keeps the [N, V] logits alive between forward and
backward — at GPT-2 bench shape that is a 4.9 GB f32 tensor whose
write + three reduce passes + backward read run at HBM rate and
dominate the loss block (~25 ms/step).  autodiff *should* be able to
keep the residual in bf16, but XLA materializes the f32 matmul output
when both the lse reduce and the saved residual consume it (measured:
the astype(bf16) round-trip variant is net slower).

This custom_vjp forces the split the hardware wants:

- forward: logits = (x @ head) -> bf16 in the matmul epilogue (f32
  accumulation, no f32 materialization); lse/true-logit reduces read
  the bf16 tensor; exactly that bf16 tensor is saved.
- backward: p = exp(logits - lse) recomputed from bf16 in one fused
  pass; dlogits stays bf16 into the two grad matmuls.

Halves the resident bytes and every pass over them.  The bf16 rounding
of saved logits perturbs gradients well below batch noise (logits are
O(10); bf16 eps ~ 0.008 relative; softmax differences cancel in
p - onehot).  Numerics guard: lse and the loss accumulate in f32.

Measured on the GPT-2 v5e bench (r05, then env RAY_TPU_FUSED_CE=1;
now ``RAY_TPU_CE=fused`` via ``ray_tpu.ops.flash_ce.ce_config``):
~-1.5% step time — the f32 passes it removes were already overlapped
with MXU work by XLA's scheduler at that shape, and the custom_vjp
boundary costs some fusion freedom.  Kept for memory-bound regimes
(the resident-logits footprint halves: 2.5 GB vs 4.9 GB at bench
shape, which is what unlocks larger batches); default off — the r07
streamed-logits ``ops/flash_ce.py`` removes the residual entirely.

Reference role: the loss path of the reference's torch trainers
(F.cross_entropy); the residual-dtype design is TPU-first.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ray_tpu.ops.substrate import Support, supported, unsupported


def supports(N: int, d: int, V: int) -> Support:
    """Dispatch gate (with reason) for the bf16-resident path.

    Plain XLA, so unlike flash-CE there is no grid to tile and no
    single-device gate — the one hard requirement is a real vocab axis
    to reduce over.  Lives here so the substrate's reasoned-gate
    convention covers every CE family member, not just the Pallas one."""
    if N <= 0:
        return unsupported(f"N={N} has no rows")
    if V <= 1:
        return unsupported(f"V={V} has no vocab axis to reduce")
    return supported("bf16-resident XLA path (shards on any mesh)")


@functools.partial(jax.custom_vjp, nondiff_argnums=())
def ce_sum_bf16(x, head, targets):
    """x [N, d] bf16, head [d, V], targets [N] int32 (-1 = masked).

    Returns (sum_nll, n_valid) with bf16 logit residuals."""
    out, _ = _ce_fwd(x, head, targets)
    return out


def _logits_bf16(x, head):
    return jax.lax.dot_general(
        x, head, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32).astype(jnp.bfloat16)


def _ce_fwd(x, head, targets):
    logits = _logits_bf16(x, head)                       # [N, V] bf16
    l32 = logits.astype(jnp.float32)
    lse = jax.scipy.special.logsumexp(l32, axis=-1)      # [N] f32
    true = jnp.take_along_axis(
        l32, jnp.maximum(targets, 0)[:, None], axis=-1)[:, 0]
    mask = (targets >= 0).astype(jnp.float32)
    out = (jnp.sum((lse - true) * mask), jnp.sum(mask))
    return out, (x, head, targets, logits, lse)


def _ce_bwd(res, g):
    x, head, targets, logits, lse = res
    gs, _ = g                                  # d/d(sum_nll); n is count
    n = logits.shape[0]
    mask = (targets >= 0)
    # p - onehot, scaled by the incoming cotangent; one fused pass over
    # the bf16 logits, dlogits written bf16 straight into the matmuls
    p = jnp.exp(logits.astype(jnp.float32) - lse[:, None])
    onehot = jax.nn.one_hot(jnp.maximum(targets, 0), logits.shape[1],
                            dtype=jnp.float32)
    dl = ((p - onehot) * (gs * mask[:, None])).astype(jnp.bfloat16)
    dx = jax.lax.dot_general(
        dl, head, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32).astype(x.dtype)
    dh = jax.lax.dot_general(
        x, dl, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32).astype(head.dtype)
    return dx, dh, None


ce_sum_bf16.defvjp(_ce_fwd, _ce_bwd)
