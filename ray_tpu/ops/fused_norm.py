"""Fused norm epilogue: out-proj matmul + residual add + RMSNorm in
one Pallas kernel (the attention family's epilogue member).

PERF.md's remaining-headroom analysis pins ~18 ms/step of the GPT-2
single-chip gap on work XLA cannot fuse across custom-call boundaries:
the attention out-proj's residual/norm fusions (~13 ms) and the
``[768]``-output reductions that compute the norm-scale gradients
(~10.7 ms of the backward tail).  Once attention itself is a custom
call, the neighbouring norm is orphaned — XLA schedules it as
standalone HBM-rate fusions on either side of the kernel boundary.

This kernel moves the whole residual/norm block *inside* the boundary.
Forward, per ``block_n`` row block (one grid sweep, everything
VMEM-resident):

    p    = attn_blk @ wo            # MXU, f32 accumulation
    r    = resid_blk + p            # the residual stream, written once
    rstd = rsqrt(mean(r^2) + eps)   # norm statistics in the epilogue
    y    = r * rstd * scale         # the next block's normed input

emitting ``(r, y)`` plus an ``[N]``-sized ``rstd`` residual — the norm
statistics are never re-derived from a re-materialized tensor.  The
custom-vjp backward recomputes ``xhat = r * rstd`` from the saved
stats and fuses the norm backward into the matmul grads:

    dr       = rstd * (dy*scale - xhat * mean(dy*scale * xhat)) + dr_in
    da_blk   = dr @ wo^T                      # back into attention
    dwo[i]   = attn_blk^T @ dr                # per-row-block partial
    dscale[i]= sum_rows(dy * xhat)            # per-row-block partial

``dwo``/``dscale`` partials are emitted per row block and summed in
one XLA pass — the ``flash_ce`` dhead idiom — which is what deletes
the standalone ``[768]``-reduction dispatches from the step.

Dispatch is a reasoned gate (:func:`out_proj_norm_plan`): rmsnorm
only, no biases, single-device mesh (``pallas_call`` has no SPMD
rule), lane-aligned ``K``/``d``, and a real sequence (the S=1 decode
step keeps the XLA epilogue — per-token kernel launches lose there).
``RAY_TPU_FUSE_NORM=0`` reverts everything.  Built directly on
``ops/substrate.py``; numerics tests vs the unfused formulation live
in ``tests/test_ops.py``.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ray_tpu.ops.substrate import (STATS_LANES, CompilerParams, Support,
                                   env_flag, env_int, resolve_blocks,
                                   stats_in, supported, unsupported,
                                   use_interpret)


@dataclasses.dataclass(frozen=True)
class FuseNormConfig:
    """Fused-norm-epilogue knobs, resolved once from the environment.

    - ``RAY_TPU_FUSE_NORM`` (default on; ``0`` disables): fold the
      attention out-proj residual/norm and the final-norm CE prologue
      into their neighbouring Pallas kernels wherever the dispatch
      gates pass.
    - ``RAY_TPU_FUSE_NORM_BN`` (default 256): row blocking — the
      backward tile carries ``[bn, K]`` + ``[bn, d]`` f32 work plus
      the ``[K, d]`` weight-grad partial, so it wants a narrower row
      block than the attention kernels' 512/1024.
    """
    enabled: bool = True
    block_n: int = 256


_CONFIG: Optional[FuseNormConfig] = None


def fuse_config(refresh: bool = False) -> FuseNormConfig:
    """The process-wide :class:`FuseNormConfig` (env read once, cached).

    ``refresh=True`` re-reads the environment — for tests and A/B
    drivers that flip flags after import."""
    global _CONFIG
    if _CONFIG is None or refresh:
        _CONFIG = FuseNormConfig(
            enabled=env_flag("RAY_TPU_FUSE_NORM"),
            block_n=env_int("RAY_TPU_FUSE_NORM_BN", 256),
        )
    return _CONFIG


def supports(N: int, K: int, d: int) -> Support:
    """Shapes the matmul+norm grid can tile (XLA epilogue otherwise).

    ``K`` (contraction) and ``d`` (output/norm) are both lane
    dimensions of VMEM-resident tiles, so they must be lane-aligned
    and small enough that the weight block plus its grad partial fit
    VMEM alongside the row blocks."""
    if N <= 0:
        return unsupported(f"N={N} has no rows")
    if K % 128:
        return unsupported(f"K={K} not lane-aligned (128)")
    if d % 128:
        return unsupported(f"d={d} not lane-aligned (128)")
    if K > 1536 or d > 1536:
        return unsupported(f"K={K}, d={d}: weight block + grad partial "
                           "exceed the VMEM budget (cap 1536)")
    return supported("pallas fused out-proj epilogue")


def out_proj_norm_plan(N: int, K: int, d: int, *, norm: str = "rmsnorm",
                       has_bias: bool = False, n_devices: int = 1,
                       seq: Optional[int] = None,
                       enabled: Optional[bool] = None) -> Support:
    """The full out-proj epilogue dispatch gate, with reasons.

    The single source of the fused-vs-XLA decision — shared by
    ``models.gpt.layer_apply`` and the ``bench.py`` reporting mirror so
    the JSON line can't claim a fusion the dispatch declined.
    ``enabled`` pins the knob for A/B drivers (default:
    :func:`fuse_config`)."""
    if enabled is None:
        enabled = fuse_config().enabled
    if not enabled:
        return unsupported("disabled (RAY_TPU_FUSE_NORM=0)")
    if norm != "rmsnorm":
        return unsupported(f"norm={norm!r}: only rmsnorm fuses")
    if has_bias:
        return unsupported("bias projections/norms (GPT-2 exact-"
                           "architecture mode) stay on the XLA path")
    if n_devices > 1:
        return unsupported(f"mesh size {n_devices}: pallas_call has "
                           "no SPMD rule")
    if seq is not None and seq <= 1:
        return unsupported("decode step (S=1): per-token kernel "
                           "launches lose to the XLA epilogue")
    return supports(N, K, d)


# ---------------------------------------------------------------------------
# kernels
# ---------------------------------------------------------------------------

def _fwd_kernel(a_ref, w_ref, r_ref, s_ref, rout_ref, y_ref, rstd_ref,
                *, eps: float):
    p = jax.lax.dot_general(
        a_ref[...], w_ref[...], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)              # [bn, d]
    # the residual add runs in the storage dtype (matching the unfused
    # bf16 einsum + add), the norm statistics in f32 (matching _norm)
    r = r_ref[...] + p.astype(r_ref.dtype)
    rout_ref[...] = r
    r32 = r.astype(jnp.float32)
    rstd = jax.lax.rsqrt(jnp.mean(r32 * r32, -1, keepdims=True) + eps)
    y_ref[...] = (r32 * rstd * s_ref[...].astype(jnp.float32)
                  ).astype(y_ref.dtype)
    rstd_ref[0] = jnp.broadcast_to(rstd, rstd_ref.shape[1:])


def _bwd_kernel(a_ref, w_ref, rout_ref, s_ref, rstd_ref, drout_ref,
                dy_ref, da_ref, dresid_ref, dwp_ref, dsp_ref):
    # (no eps here: the saved rstd already bakes it in — xhat is
    # reconstructed as rout * rstd, never re-derived from statistics)
    r32 = rout_ref[...].astype(jnp.float32)              # [bn, d]
    rstd = rstd_ref[0][:, 0:1]                           # [bn, 1]
    xhat = r32 * rstd
    dy = dy_ref[...].astype(jnp.float32)
    dxhat = dy * s_ref[...].astype(jnp.float32)
    m = jnp.mean(dxhat * xhat, -1, keepdims=True)
    # total cotangent into the residual stream: the norm backward plus
    # whatever flowed in from downstream consumers of r
    dr32 = rstd * (dxhat - xhat * m) + drout_ref[...].astype(jnp.float32)
    dsp_ref[...] = jnp.sum(dy * xhat, 0, keepdims=True)  # [1, d] partial
    dresid_ref[...] = dr32.astype(dresid_ref.dtype)
    dp = dr32.astype(w_ref.dtype)
    da_ref[...] = jax.lax.dot_general(
        dp, w_ref[...], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32).astype(da_ref.dtype)
    dwp_ref[0] = jax.lax.dot_general(
        a_ref[...], dp, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32).astype(dwp_ref.dtype)


# ---------------------------------------------------------------------------
# custom VJP + public API
# ---------------------------------------------------------------------------

def _pad_rows(x, Np: int):
    return x if x.shape[0] == Np else \
        jnp.pad(x, ((0, Np - x.shape[0]),) + ((0, 0),) * (x.ndim - 1))


def _row_blocks(N: int, block_n: int):
    """(bn, Np, num_n) — the substrate's resolve_blocks row half (the
    16-row alignment is the tree-wide bf16-safe sublane tile)."""
    bn, _, Np, _ = resolve_blocks(N, 1, block_n, 1, lane_align=1)
    return bn, Np, Np // bn


def _run_fwd(a, w, resid, scale, eps, block_n):
    N, K = a.shape
    d = w.shape[1]
    bn, Np, num_n = _row_blocks(N, block_n)
    a, resid = _pad_rows(a, Np), _pad_rows(resid, Np)
    rout, y, rstd = pl.pallas_call(
        functools.partial(_fwd_kernel, eps=eps),
        grid=(num_n,),
        compiler_params=CompilerParams(
            dimension_semantics=("parallel",)),
        in_specs=[
            pl.BlockSpec((bn, K), lambda i: (i, 0)),
            pl.BlockSpec((K, d), lambda i: (0, 0)),
            pl.BlockSpec((bn, d), lambda i: (i, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((bn, d), lambda i: (i, 0)),
            pl.BlockSpec((bn, d), lambda i: (i, 0)),
            pl.BlockSpec((1, bn, STATS_LANES), lambda i: (i, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((Np, d), resid.dtype),
            jax.ShapeDtypeStruct((Np, d), resid.dtype),
            jax.ShapeDtypeStruct((num_n, bn, STATS_LANES), jnp.float32),
        ],
        interpret=use_interpret(),
    )(a, w, resid, scale[None, :])
    return rout[:N], y[:N], rstd[:, :, 0].reshape(Np)[:N]


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def _mrn(a, w, resid, scale, eps, block_n):
    (rout, y), _ = _mrn_fwd(a, w, resid, scale, eps, block_n)
    return rout, y


def _mrn_fwd(a, w, resid, scale, eps, block_n):
    rout, y, rstd = _run_fwd(a, w, resid, scale, eps, block_n)
    # residuals are [N]-sized stats plus the inputs the grads contract
    # against — the residual stream is saved once (rout), never both
    # sides of the add
    return (rout, y), (a, w, rout, scale, rstd)


def _mrn_bwd(eps, block_n, res, cts):
    a, w, rout, scale, rstd = res
    drout, dy = cts
    N, K = a.shape
    d = w.shape[1]
    bn, Np, num_n = _row_blocks(N, block_n)
    a, rout = _pad_rows(a, Np), _pad_rows(rout, Np)
    drout, dy = _pad_rows(drout, Np), _pad_rows(dy, Np)
    rstd_b = stats_in(_pad_rows(rstd[:, None], Np)[:, 0], num_n, bn)
    da, dresid, dwp, dsp = pl.pallas_call(
        _bwd_kernel,
        grid=(num_n,),
        compiler_params=CompilerParams(
            dimension_semantics=("parallel",)),
        in_specs=[
            pl.BlockSpec((bn, K), lambda i: (i, 0)),
            pl.BlockSpec((K, d), lambda i: (0, 0)),
            pl.BlockSpec((bn, d), lambda i: (i, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
            pl.BlockSpec((1, bn, STATS_LANES), lambda i: (i, 0, 0)),
            pl.BlockSpec((bn, d), lambda i: (i, 0)),
            pl.BlockSpec((bn, d), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((bn, K), lambda i: (i, 0)),
            pl.BlockSpec((bn, d), lambda i: (i, 0)),
            pl.BlockSpec((1, K, d), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, d), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((Np, K), a.dtype),
            jax.ShapeDtypeStruct((Np, d), rout.dtype),
            jax.ShapeDtypeStruct((num_n, K, d), w.dtype),
            jax.ShapeDtypeStruct((num_n, d), jnp.float32),
        ],
        interpret=use_interpret(),
    )(a, w, rout, scale[None, :], rstd_b, drout, dy)
    # per-row-block partials summed in ONE XLA pass each — these sums
    # replace the standalone [d]-output reduction dispatches
    dw = jnp.sum(dwp.astype(jnp.float32), 0).astype(w.dtype)
    dscale = jnp.sum(dsp, 0).astype(scale.dtype)
    return da[:N], dw, dresid[:N], dscale


_mrn.defvjp(_mrn_fwd, _mrn_bwd)


def matmul_residual_norm(a, w, resid, scale, *, eps: float = 1e-6,
                         block_n: Optional[int] = None
                         ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """``(resid + a @ w, rmsnorm(resid + a @ w) * scale)`` — fused.

    a [N, K] (bf16 ok), w [K, d], resid [N, d], scale [d].  Returns
    ``(r, y)``: the updated residual stream and the normed/scaled
    hidden, with only ``[N]``-sized norm statistics saved between the
    passes.  Differentiable in all four operands; ``dscale``/``dw``
    come back through per-row-block partials (see module docstring).
    Shapes :func:`supports` declines raise — dispatch is the caller's
    job (:func:`out_proj_norm_plan`)."""
    ok = supports(a.shape[0], a.shape[1], w.shape[1])
    if not ok:
        raise ValueError(f"matmul_residual_norm cannot tile: {ok.reason}")
    if block_n is None:
        block_n = fuse_config().block_n
    with jax.named_scope("norm/fused_epilogue"):
        return _mrn(a, w, resid, scale, eps, block_n)


def xla_matmul_residual_norm(a, w, resid, scale, *, eps: float = 1e-6):
    """Unfused XLA reference (the fallback formulation and the parity
    oracle in tests/test_ops.py) — numerics mirror of
    ``models.gpt.layer_apply``'s einsum + add + ``_norm`` path."""
    r = resid + jax.lax.dot_general(
        a, w, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32).astype(resid.dtype)
    r32 = r.astype(jnp.float32)
    rstd = jax.lax.rsqrt(jnp.mean(r32 * r32, -1, keepdims=True) + eps)
    y = (r32 * rstd * scale.astype(jnp.float32)).astype(r.dtype)
    return r, y
