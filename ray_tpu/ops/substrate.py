"""Shared substrate for the Pallas kernel families.

Four kernel families grew up in this tree — flash attention (fwd/bwd +
decode), two-head lane packing (pack2), flash-CE, and the fused norm
epilogues — and by round 12 each carried its own copy of the same
infrastructure: an interpret-mode policy, the jax-version
``CompilerParams`` rename shim, lane-padded row-stats conventions,
block/grid validation, env-knob config plumbing, and (in ``bench.py``)
a hand-rolled compile-failure fallback ladder per kernel.  Copies
drift; ``rmsnorm.py``'s private ``_use_interpret`` was the proof.

This module is the single home for all of it.  A new kernel (quantized
KV strips, ragged prefill, the next norm fusion) should be a page of
code on top of these pieces, not a subsystem:

- :func:`use_interpret` — the one interpret-mode policy (Pallas kernels
  run interpreted off-TPU so the parity suite runs on CPU).
- :data:`CompilerParams` — the ``TPUCompilerParams`` →
  ``CompilerParams`` rename shim, resolved once.
- :data:`NEG_INF` / :data:`STATS_LANES` — masking constant and the
  lane-padded row-stats width shared by every online-softmax kernel.
- :func:`round_up` / :func:`resolve_blocks` / :func:`stats_in` —
  lane/sublane padding and the ``[num_n, bn, STATS_LANES]``
  stats-block convention.
- :class:`Support` — block/grid validation verdicts that carry a
  *reason*, so dispatch gates can decline loudly and tests can assert
  on why.
- :func:`env_int` / :func:`env_str` / :func:`env_flag` — env-knob
  readers for the per-family config dataclasses
  (``attention_config()`` / ``ce_config()`` / ``fuse_config()``).
- :func:`run_ladder` — the cumulative compile-failure fallback ladder
  ``bench.py`` previously reimplemented per kernel: try the most
  capable configuration, degrade loudly rung by rung on Mosaic
  compile/run failures, never silently.
"""

from __future__ import annotations

import os
import sys
from typing import Any, Callable, List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.experimental.pallas import tpu as pltpu

# masking constant for online-softmax kernels (finite: -inf would turn
# fully-masked rows into NaN through exp/max arithmetic)
NEG_INF = -1e30

# per-row statistics (lse, delta, rstd, ...) are stored as
# [.., rows, STATS_LANES] lane-broadcast blocks: a (rows, 8) block
# satisfies the TPU tiling rule (sublane div 8, lane equal to array
# dim) where a 1-D (rows,) column cannot
STATS_LANES = 8

# jax renamed TPUCompilerParams -> CompilerParams around 0.5; resolve
# whichever this jaxlib ships, once, for every pallas_call in the tree
CompilerParams = getattr(pltpu, "CompilerParams", None) \
    or getattr(pltpu, "TPUCompilerParams")


def use_interpret() -> bool:
    """Whether pallas_calls should run in interpret mode.

    The one policy for every kernel family: interpret off-TPU so the
    parity suite (and any CPU smoke run) executes the same kernel
    bodies the chip will."""
    return jax.default_backend() != "tpu"


# ---------------------------------------------------------------------------
# lane/sublane padding helpers
# ---------------------------------------------------------------------------

def round_up(n: int, m: int) -> int:
    return -(-n // m) * m


def resolve_blocks(N: int, V: int, block_n: int, block_v: int,
                   *, row_align: int = 16,
                   lane_align: int = 128) -> Tuple[int, int, int, int]:
    """Resolve ``(bn, bv, Np, Vp)``: actual block sizes and padded dims.

    Blocks shrink to the (tile-aligned) problem size for small shapes;
    otherwise N/V round up to the block grid and the callers pad."""
    bn = min(block_n, round_up(N, row_align))
    bv = min(block_v, round_up(V, lane_align))
    return bn, bv, round_up(N, bn), round_up(V, bv)


def stats_in(a, num_n: int, bn: int):
    """[Np] row stats -> [num_n, bn, STATS_LANES] lane-broadcast layout
    (the input-side mirror of the kernels' stats output blocks)."""
    return jnp.broadcast_to(a[:, None], (num_n * bn, STATS_LANES)) \
        .reshape(num_n, bn, STATS_LANES)


# ---------------------------------------------------------------------------
# dispatch gates with reasons
# ---------------------------------------------------------------------------

class Support(NamedTuple):
    """A dispatch-gate verdict that carries its reason.

    Truthy iff the kernel path applies; ``reason`` states why not (or
    which path was chosen) so fallbacks are loud and testable — the
    dispatch tests assert on these strings, which keeps "silently took
    the slow path" a failing state."""
    ok: bool
    reason: str = ""

    def __bool__(self) -> bool:          # Support(...) gates directly
        return self.ok


def supported(reason: str = "") -> Support:
    return Support(True, reason)


def unsupported(reason: str) -> Support:
    return Support(False, reason)


# ---------------------------------------------------------------------------
# env-knob config plumbing
# ---------------------------------------------------------------------------

def env_int(name: str, default: int) -> int:
    return int(os.environ.get(name, str(default)))


def env_str(name: str, default: str) -> str:
    return os.environ.get(name, default)


def env_flag(name: str, default: bool = True) -> bool:
    """Boolean env knob: unset -> ``default``; ``"0"`` is the one
    falsey spelling (matches every existing ``RAY_TPU_*`` gate)."""
    return os.environ.get(name, "1" if default else "0") != "0"


# ---------------------------------------------------------------------------
# compile-failure fallback ladder
# ---------------------------------------------------------------------------

def run_ladder(attempt: Callable[[Any], Any],
               rungs: Sequence[Tuple[Optional[str], Any]],
               *, log: Optional[Callable[[str], None]] = None
               ) -> Tuple[Any, Any, List[str]]:
    """Cumulative loud fallback ladder for Mosaic compile/run failures.

    ``rungs`` is ``[(what, args), ...]``, most capable first — the
    primary configuration (``what`` is ``None``) followed by the
    fallback rungs, each isolating one suspect.  ``attempt(args)``
    builds and warms one configuration, raising on failure.  Returns
    ``(result, args, taken)`` where ``args`` is the configuration that
    actually ran and ``taken`` lists the descriptions of every rung
    that had to engage (empty = primary ran).

    Every degradation is announced on stderr (or ``log``): a kernel
    that cannot compile on new hardware must show up in the console and
    the headline JSON, never as a silent perf/loss regression.
    """
    emit = log or (lambda msg: print(msg, file=sys.stderr))
    remaining = list(rungs)
    if not remaining:
        raise ValueError("run_ladder needs at least the primary rung")
    taken: List[str] = []
    while True:
        what, args = remaining.pop(0)
        if what:
            taken.append(what)
        try:
            return attempt(args), args, taken
        except Exception as e:
            if not remaining:
                raise
            emit(f"step failed to compile/run ({e!r}); "
                 f"falling back: {remaining[0][0]}")
