"""Fused RMSNorm with a hand-written backward (Pallas, TPU).

Forward is one pass (read x, write y + rstd); backward is one pass
(read x, dy; write dx, accumulate dscale in VMEM scratch across the
sequential row sweep), both at HBM streaming rate — vs XLA's split
backward (per-row stats fusion + dx fusion + a [N, D] -> [D] scale-
grad reduction).

Measured on the GPT-2 v5e bench (env RAY_TPU_PALLAS_NORM=1): step-
neutral — XLA's latency-hiding scheduler already overlaps its norm
reductions with adjacent matmuls, so the traffic this kernel removes
wasn't on the critical path *at that shape*.  Kept as an option for
shapes where norms are exposed (wide d_model, short sequences,
memory-bound stacks); default off.

Reference role: torch.nn.functional.rms_norm + autograd in the
reference's model stacks (e.g. python/ray/train torch models); the
kernelization itself is TPU-first design, not a port.

Interpret mode (CPU) keeps tests runnable off-TPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# interpret policy from the shared substrate — this module's private
# copy was the drift example that motivated ops/substrate.py
from ray_tpu.ops.substrate import use_interpret as _use_interpret

_BLOCK_ROWS = 512


def _fwd_kernel(x_ref, s_ref, y_ref, rstd_ref, *, eps: float):
    x = x_ref[...].astype(jnp.float32)              # [R, D]
    ms = jnp.mean(x * x, axis=-1, keepdims=True)    # [R, 1]
    rstd = jax.lax.rsqrt(ms + eps)
    y_ref[...] = (x * rstd * s_ref[...].astype(jnp.float32)
                  ).astype(y_ref.dtype)
    rstd_ref[...] = jnp.broadcast_to(rstd, rstd_ref.shape)


def _bwd_kernel(x_ref, s_ref, rstd_ref, dy_ref, dx_ref, ds_ref, ds_sc,
                *, nblocks: int):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        ds_sc[...] = jnp.zeros_like(ds_sc)

    x = x_ref[...].astype(jnp.float32)               # [R, D]
    dy = dy_ref[...].astype(jnp.float32)
    s = s_ref[...].astype(jnp.float32)               # [1, D]
    rstd = rstd_ref[...][:, :1]                      # [R, 1]
    xhat = x * rstd
    dxhat = dy * s
    # dx = rstd * (dxhat - xhat * mean(dxhat * xhat))
    m = jnp.mean(dxhat * xhat, axis=-1, keepdims=True)
    dx_ref[...] = (rstd * (dxhat - xhat * m)).astype(dx_ref.dtype)
    ds_sc[...] += jnp.sum(dy * xhat, axis=0, keepdims=True)

    @pl.when(i == nblocks - 1)
    def _emit():
        ds_ref[...] = ds_sc[...]


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def rmsnorm(x, scale, eps: float = 1e-6):
    """y = x * rsqrt(mean(x^2, -1) + eps) * scale, fused fwd/bwd.

    x: [..., D] (any leading dims), scale: [D]."""
    y, _ = _rmsnorm_fwd(x, scale, eps)
    return y


def _pad_rows(n: int) -> int:
    r = min(_BLOCK_ROWS, n)
    return r


def _run_fwd(x2, scale, eps):
    n, d = x2.shape
    r = _pad_rows(n)
    nblocks = pl.cdiv(n, r)
    return pl.pallas_call(
        functools.partial(_fwd_kernel, eps=eps),
        grid=(nblocks,),
        in_specs=[
            pl.BlockSpec((r, d), lambda i: (i, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((r, d), lambda i: (i, 0)),
            pl.BlockSpec((r, 128), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, d), x2.dtype),
            jax.ShapeDtypeStruct((n, 128), jnp.float32),
        ],
        interpret=_use_interpret(),
    )(x2, scale[None, :])


def _rmsnorm_fwd(x, scale, eps):
    shape = x.shape
    d = shape[-1]
    x2 = x.reshape(-1, d)
    y, rstd = _run_fwd(x2, scale, eps)
    return y.reshape(shape), (x2, scale, rstd, shape)


def _rmsnorm_bwd(eps, res, dy):
    x2, scale, rstd, shape = res
    d = shape[-1]
    n = x2.shape[0]
    r = _pad_rows(n)
    nblocks = pl.cdiv(n, r)
    dy2 = dy.reshape(-1, d)
    dx, ds = pl.pallas_call(
        functools.partial(_bwd_kernel, nblocks=nblocks),
        grid=(nblocks,),
        in_specs=[
            pl.BlockSpec((r, d), lambda i: (i, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
            pl.BlockSpec((r, 128), lambda i: (i, 0)),
            pl.BlockSpec((r, d), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((r, d), lambda i: (i, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, d), dy2.dtype),
            jax.ShapeDtypeStruct((1, d), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((1, d), jnp.float32)],
        interpret=_use_interpret(),
    )(x2, scale[None, :], rstd, dy2)
    return dx.reshape(shape), ds[0].astype(scale.dtype)


rmsnorm.defvjp(_rmsnorm_fwd, _rmsnorm_bwd)
