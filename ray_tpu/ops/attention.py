"""Pallas TPU flash attention (forward + custom-VJP backward).

The compute heart of the flagship model path.  The reference delegates
fused attention to torch/CUDA inside the user's train fn; here it is a
first-class TPU kernel: blockwise online-softmax attention that never
materializes the [S, S] score matrix in HBM.  Backward recomputes scores
per block from the saved (o, logsumexp) residuals — activation memory is
O(B*S*H*D) instead of O(B*H*S^2).

Layouts: public API takes ``[B, S, H, D]`` (model layout, matches
``ray_tpu.parallel.ring_attention``); kernels run over ``[B, H, S, D]``.

Two-head lane packing (``pack2``): at head_dim 64 the score and
probability·V matmuls drive the 128-wide MXU at half rate (the
contraction or output dimension fills only 64 of 128 lanes).  When
head_dim == 64 and the head count is even, pairs of heads are
concatenated along the lane dimension — ``[B, H, S, 64]`` becomes
``[B, H/2, S, 128]``, a pure reshape in the model layout — and the
packed kernels keep the two heads' scores from mixing with a
block-diagonal K/V arrangement: every MXU op is then
``[block, 128] x [128, block]``-shaped (full-width contraction or
full-width output) and the op *count* halves.  Controlled by
``attention_config()`` (env ``RAY_TPU_ATTN_PACK2=0`` to disable); odd
head counts, head_dim 128 and shapes the packed grid cannot tile fall
back to the single-head schedule unchanged.

Numerics: scores/stats in f32 regardless of input dtype; probability
blocks are cast back to the value dtype for the MXU matmuls.  Numerics
tests vs the einsum path (packed and unpacked) live in
``tests/test_ops.py``.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# shared kernel infrastructure lives in ops/substrate.py (one home for
# the interpret policy, the CompilerParams rename shim, the lane-padded
# row-stats convention, and env-knob readers); the historical private
# names stay importable — flash_ce/tests grew up on them
from ray_tpu.ops.substrate import (NEG_INF as _NEG_INF, STATS_LANES,
                                   CompilerParams as _CompilerParams,
                                   env_flag, env_int,
                                   use_interpret as _use_interpret)


@dataclasses.dataclass(frozen=True)
class AttentionConfig:
    """Kernel-schedule knobs, resolved once from the environment.

    The single home for attention env flags (scattered module-level
    ``os.environ`` reads grew dead ends in round 5 — ``RAY_TPU_ATTN_EXP2``
    was removed after A/B showed VPU exp is not the bottleneck):

    - ``RAY_TPU_ATTN_BWD_BQ`` / ``RAY_TPU_ATTN_BWD_BK`` (default 512):
      causal-backward blocking, profiled on v5e at GPT-2 shapes.
    - ``RAY_TPU_ATTN_PACK2`` (default on; ``0`` disables): two-head lane
      packing for head_dim-64 even-head attention (see module docstring).
    - ``RAY_TPU_ATTN_PACK2_BQ`` / ``RAY_TPU_ATTN_PACK2_BK`` (default 512):
      packed-kernel blocking — scores are [bq, 2*bk] so the packed
      forward wants smaller blocks than the unpacked 1024 default.
    """
    bwd_block_q: int = 512
    bwd_block_k: int = 512
    pack2: bool = True
    pack2_block_q: int = 512
    pack2_block_k: int = 512


_CONFIG: Optional[AttentionConfig] = None


def attention_config(refresh: bool = False) -> AttentionConfig:
    """The process-wide :class:`AttentionConfig` (env read once, cached).

    ``refresh=True`` re-reads the environment — for tests and A/B
    drivers that flip flags after import."""
    global _CONFIG
    if _CONFIG is None or refresh:
        _CONFIG = AttentionConfig(
            bwd_block_q=env_int("RAY_TPU_ATTN_BWD_BQ", 512),
            bwd_block_k=env_int("RAY_TPU_ATTN_BWD_BK", 512),
            pack2=env_flag("RAY_TPU_ATTN_PACK2"),
            pack2_block_q=env_int("RAY_TPU_ATTN_PACK2_BQ", 512),
            pack2_block_k=env_int("RAY_TPU_ATTN_PACK2_BK", 512),
        )
    return _CONFIG


# ---------------------------------------------------------------------------
# fused RoPE
#
# Applied outside the kernel, the rotation is 4+ HBM passes over q and k
# per layer in a lane-32 layout XLA handles badly (~18 ms/step on the
# GPT-2 bench).  Fused, the rotation is a few VPU ops on VMEM-resident
# blocks.  Formulation that avoids lane-32 slicing: with duplicated
# tables cos2 = [cos, cos], sinm = [-sin, sin] (each [S, D]),
#   rot(x)  = x * cos2 + roll(x, D/2) * sinm       (the RoPE rotation)
#   rotT(g) = g * cos2 - roll(g, D/2) * sinm       (its transpose)
# since roll(x, D/2) swaps halves and the sign pattern folds into sinm.
# ---------------------------------------------------------------------------

def rope_tables(positions, D: int, theta: float, dtype):
    """positions [S] (or any leading shape) -> (cos2, sinm) each
    [*positions.shape, D] for the fused kernels."""
    half = D // 2
    freqs = jnp.exp(-jnp.log(theta) * jnp.arange(half) / half)
    angles = positions[..., None].astype(jnp.float32) * freqs
    cos = jnp.cos(angles)
    sin = jnp.sin(angles)
    cos2 = jnp.concatenate([cos, cos], -1).astype(dtype)
    sinm = jnp.concatenate([-sin, sin], -1).astype(dtype)
    return cos2, sinm


def rope_rotate(x, positions, theta: float):
    """XLA-side RoPE: x [B, S, H, D] rotated per-position.

    ``positions`` is [S] (one schedule shared across the batch — the
    training path) or [B, S] (per-sequence absolute positions — the
    decode path of the inference engine, where co-batched sequences sit
    at different lengths).

    The single source of truth for the rotation outside the kernels —
    ``ray_tpu.models.gpt._rope`` and the ``flash_attention`` fallback
    both call this, so it stays numerically identical to the in-kernel
    ``_rot`` (same duplicated-table formulation)."""
    D = x.shape[-1]
    cos2, sinm = rope_tables(positions, D, theta, x.dtype)
    if positions.ndim == 2:                  # [B, S] -> [B, S, 1, D]
        cos2, sinm = cos2[:, :, None, :], sinm[:, :, None, :]
    else:                                    # [S] -> [1, S, 1, D]
        cos2, sinm = cos2[None, :, None, :], sinm[None, :, None, :]
    return x * cos2 + jnp.roll(x, D // 2, -1) * sinm


def _roll_half(x, D: int):
    # Mosaic's lane rotate is 32-bit only; callers pass f32.
    if _use_interpret():
        return jnp.roll(x, D // 2, axis=-1)
    return pltpu.roll(x, D // 2, 1)


def _rot(x, cos2, sinm, D: int):
    xf = x.astype(jnp.float32)
    out = (xf * cos2.astype(jnp.float32)
           + _roll_half(xf, D) * sinm.astype(jnp.float32))
    return out.astype(x.dtype)


def _rot_t(g, cos2, sinm, D: int):
    gf = g.astype(jnp.float32)
    out = (gf * cos2.astype(jnp.float32)
           - _roll_half(gf, D) * sinm.astype(jnp.float32))
    return out.astype(g.dtype)


def _masked_scores(q, k, i, j, *, scale: float, causal: bool,
                   block_q: int, block_k: int):
    """f32 scaled q@k^T for blocks (i, j) with the causal mask applied."""
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale       # [bq, bk]
    if causal:
        q_idx = (i * block_q
                 + jax.lax.broadcasted_iota(jnp.int32,
                                            (block_q, block_k), 0))
        k_idx = (j * block_k
                 + jax.lax.broadcasted_iota(jnp.int32,
                                            (block_q, block_k), 1))
        s = jnp.where(q_idx >= k_idx, s, _NEG_INF)
    return s


def _block_live(i, j, *, causal: bool, block_q: int, block_k: int):
    """Whether kv block j contributes anything to q block i."""
    return (j * block_k <= i * block_q + block_q - 1) if causal else True


def _grad_blocks(q, k, v, do, lse, delta, i, j, *, scale: float,
                 causal: bool, block_q: int, block_k: int):
    """Shared backward block math: (p [bq,bk] f32, ds [bq,bk] f32).

    p = exp(s - lse) recomputed from the block scores; ds is the score
    gradient.  dq/dk/dv follow as single matmuls against k/q/do in the
    caller (which differ per kernel in what they accumulate)."""
    s = _masked_scores(q, k, i, j, scale=scale, causal=causal,
                       block_q=block_q, block_k=block_k)
    p = jnp.exp(s - lse)
    dp = jax.lax.dot_general(
        do, v, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)              # [bq, bk]
    ds = p * (dp - delta) * scale
    return p, ds


# ---------------------------------------------------------------------------
# two-head lane packing helpers
#
# Packed blocks are [rows, 2*Ds] with head A on lanes :Ds and head B on
# lanes Ds: (Ds = 64, so 2*Ds = 128 = the MXU/VPU lane width).  The
# block-diagonal arrangement
#     kd = [[kA, 0], [0, kB]]        ([2*rows, 128])
# makes one full-width matmul compute both heads without mixing:
#     qp @ kd^T = [sA | sB]          ([bq, 2*bk], lanes annihilate the
#                                     other head's q half)
#     [pA | pB] @ vd = [pA@vA | pB@vB]  (packed output, one matmul)
# ---------------------------------------------------------------------------

def _lane_ids(rows: int, lanes: int = 128):
    return jax.lax.broadcasted_iota(jnp.int32, (rows, lanes), 1)


def _half_mask(rows: int, sub_d: int):
    """bool [rows, 2*sub_d]: True on the first head's lanes."""
    return _lane_ids(rows, 2 * sub_d) < sub_d


def _blockdiag2(x, sub_d: int):
    """Packed rows [r, 2*sub_d] -> block-diagonal [2r, 2*sub_d]."""
    m = _half_mask(x.shape[0], sub_d)
    z = jnp.zeros_like(x)
    return jnp.concatenate([jnp.where(m, x, z), jnp.where(m, z, x)], 0)


def _fold2(t, bk: int, sub_d: int):
    """Inverse of the block-diagonal output: [2*bk, 128] -> [bk, 128].

    Row r of the top half carries head A's useful lanes :sub_d (the rest
    is the cross-head product the packing must discard); row r of the
    bottom half carries head B's lanes sub_d:."""
    return jnp.where(_half_mask(bk, sub_d), t[:bk], t[bk:])


def _roll_sub(x, sub_d: int):
    """Lane roll by sub_d//2 *within* each sub_d-lane group of a packed
    [rows, 2*sub_d] block (the per-sub-head RoPE half-swap).

    A plain 128-lane rotate crosses the head boundary; two full rotates
    select-combined per quarter implement the grouped rotate:
    destination lane l wants source (l - sub_d/2) mod sub_d within its
    group, which is roll(sub_d/2) for the upper half-group and
    roll(sub_d/2 + sub_d) for the lower half-group."""
    if _use_interpret():
        r = x.shape[0]
        return jnp.roll(x.reshape(r, 2, sub_d), sub_d // 2,
                        axis=-1).reshape(r, 2 * sub_d)
    lo = pltpu.roll(x, sub_d // 2, 1)
    hi = pltpu.roll(x, sub_d // 2 + sub_d, 1)
    return jnp.where(_lane_ids(x.shape[0]) % sub_d < sub_d // 2, hi, lo)


def _rot2(x, cos2, sinm, sub_d: int):
    """Per-sub-head RoPE on a packed [rows, 2*sub_d] block (tables are
    the D=sub_d tables duplicated along lanes)."""
    xf = x.astype(jnp.float32)
    out = (xf * cos2.astype(jnp.float32)
           + _roll_sub(xf, sub_d) * sinm.astype(jnp.float32))
    return out.astype(x.dtype)


def _rot2_t(g, cos2, sinm, sub_d: int):
    gf = g.astype(jnp.float32)
    out = (gf * cos2.astype(jnp.float32)
           - _roll_sub(gf, sub_d) * sinm.astype(jnp.float32))
    return out.astype(g.dtype)


def _masked_scores2(qp, kd, i, j, *, scale: float, causal: bool,
                    block_q: int, block_k: int):
    """Packed scores [bq, 2*bk] for blocks (i, j): head A on columns
    :bk, head B on columns bk:.  One [bq, 128] x [128, 2*bk] matmul —
    the zeros in the block-diagonal ``kd`` annihilate the other head's
    q lanes, so no separation mask is needed; the causal mask applies
    per half (both heads sit at the same positions)."""
    s = jax.lax.dot_general(
        qp, kd, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale      # [bq, 2*bk]
    if causal:
        q_idx = (i * block_q
                 + jax.lax.broadcasted_iota(jnp.int32,
                                            (block_q, 2 * block_k), 0))
        k_idx = (j * block_k
                 + jax.lax.broadcasted_iota(jnp.int32,
                                            (block_q, 2 * block_k), 1)
                 % block_k)
        s = jnp.where(q_idx >= k_idx, s, _NEG_INF)
    return s


# ---------------------------------------------------------------------------
# forward kernel
# ---------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, *rest, scale: float, causal: bool,
                block_q: int, block_k: int, num_kv: int,
                has_rope: bool):
    if has_rope:
        (cq_ref, sq_ref, ck_ref, sk_ref,
         o_ref, lse_ref, acc_sc, m_sc, l_sc) = rest
    else:
        o_ref, lse_ref, acc_sc, m_sc, l_sc = rest
    i, j = pl.program_id(2), pl.program_id(3)

    @pl.when(j == 0)
    def _init():
        acc_sc[:] = jnp.zeros_like(acc_sc)
        m_sc[:] = jnp.full_like(m_sc, _NEG_INF)
        l_sc[:] = jnp.zeros_like(l_sc)

    @pl.when(_block_live(i, j, causal=causal, block_q=block_q,
                         block_k=block_k))
    def _compute():
        q = q_ref[0, 0]                      # [bq, D]
        k = k_ref[0, 0]                      # [bk, D]
        v = v_ref[0, 0]
        if has_rope:
            D = q.shape[-1]
            q = _rot(q, cq_ref[...], sq_ref[...], D)
            k = _rot(k, ck_ref[...], sk_ref[...], D)
        s = _masked_scores(q, k, i, j, scale=scale, causal=causal,
                           block_q=block_q, block_k=block_k)
        m_prev = m_sc[:]                      # [bq, 128] (col-bcast)
        m_cur = jnp.max(s, axis=1, keepdims=True)          # [bq, 1]
        m_new = jnp.maximum(m_prev, m_cur)                 # [bq, 128]
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new[:, :1])                      # [bq, bk]
        l_sc[:] = l_sc[:] * alpha + jnp.sum(p, 1, keepdims=True)
        acc_sc[:] = (acc_sc[:] * alpha[:, :1]
                     + jax.lax.dot_general(
                         p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                         preferred_element_type=jnp.float32))
        m_sc[:] = m_new

    @pl.when(j == num_kv - 1)
    def _finalize():
        l = l_sc[:, :1]
        o_ref[0, 0] = (acc_sc[:]
                       / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)
        lse = m_sc[:, :1] + jnp.log(jnp.maximum(l, 1e-30))  # [bq, 1]
        lse_ref[0, 0, 0] = jnp.broadcast_to(lse, lse_ref.shape[3:])


def _fwd(q, k, v, *, scale: float, causal: bool,
         block_q: int, block_k: int, rope=None):
    """q,k,v: [B, H, S, D] -> (o [B, H, S, D],
    lse [B, H, S // bq, bq, STATS_LANES] f32 — lane-padded row stats).

    ``rope``: optional (cos2 [S, D], sinm [S, D]) tables from
    ``rope_tables``; q/k blocks are rotated in-kernel."""
    B, H, S, D = q.shape
    Sk = k.shape[2]
    bq, bk = min(block_q, S), min(block_k, Sk)
    grid = (B, H, S // bq, Sk // bk)
    num_kv = grid[3]

    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, block_q=bq, block_k=bk,
        num_kv=num_kv, has_rope=rope is not None)
    rope_args, rope_specs = (), []
    if rope is not None:
        cos2, sinm = rope
        rope_args = (cos2, sinm, cos2, sinm)
        rope_specs = [
            pl.BlockSpec((bq, D), lambda b, h, i, j: (i, 0)),
            pl.BlockSpec((bq, D), lambda b, h, i, j: (i, 0)),
            pl.BlockSpec((bk, D), lambda b, h, i, j: (j, 0)),
            pl.BlockSpec((bk, D), lambda b, h, i, j: (j, 0)),
        ]
    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        in_specs=[
            pl.BlockSpec((1, 1, bq, D), lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, bk, D), lambda b, h, i, j: (b, h, j, 0)),
            pl.BlockSpec((1, 1, bk, D), lambda b, h, i, j: (b, h, j, 0)),
            *rope_specs,
        ],
        out_specs=[
            pl.BlockSpec((1, 1, bq, D), lambda b, h, i, j: (b, h, i, 0)),
            # row stats as [B, H, num_q, bq, STATS_LANES]: a
            # (.., bq, STATS_LANES) block satisfies the TPU tiling rule
            # ((bq, 8): sublane div 8, lane equal to array dim) where a
            # 1-D (.., bq) row cannot
            pl.BlockSpec((1, 1, 1, bq, STATS_LANES),
                         lambda b, h, i, j: (b, h, i, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, H, S, D), q.dtype),
            jax.ShapeDtypeStruct((B, H, S // bq, bq, STATS_LANES),
                                 jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, D), jnp.float32),
            pltpu.VMEM((bq, 128), jnp.float32),
            pltpu.VMEM((bq, 128), jnp.float32),
        ],
        interpret=_use_interpret(),
    )(q, k, v, *rope_args)
    return o, lse


def _fwd_pack2_kernel(q_ref, k_ref, v_ref, *rest, scale: float,
                      causal: bool, block_q: int, block_k: int,
                      num_kv: int, has_rope: bool, sub_d: int):
    """Packed forward: blocks are [bq, 128] head pairs; scores/stats run
    per half while both matmuls go through the MXU at full lane width
    (one [bq, 128] x [128, 2*bk] score op, one [bq, 2*bk] x [2*bk, 128]
    accumulate op — half the op count of the unpacked pair)."""
    if has_rope:
        (cq_ref, sq_ref, ck_ref, sk_ref,
         o_ref, lse0_ref, lse1_ref, acc_sc, m_sc, l_sc) = rest
    else:
        o_ref, lse0_ref, lse1_ref, acc_sc, m_sc, l_sc = rest
    i, j = pl.program_id(2), pl.program_id(3)

    @pl.when(j == 0)
    def _init():
        acc_sc[:] = jnp.zeros_like(acc_sc)
        m_sc[:] = jnp.full_like(m_sc, _NEG_INF)
        l_sc[:] = jnp.zeros_like(l_sc)

    @pl.when(_block_live(i, j, causal=causal, block_q=block_q,
                         block_k=block_k))
    def _compute():
        qp = q_ref[0, 0]                     # [bq, 128] packed pair
        kp = k_ref[0, 0]                     # [bk, 128]
        vp = v_ref[0, 0]
        if has_rope:
            qp = _rot2(qp, cq_ref[...], sq_ref[...], sub_d)
            kp = _rot2(kp, ck_ref[...], sk_ref[...], sub_d)
        kd = _blockdiag2(kp, sub_d)          # [2*bk, 128]
        s = _masked_scores2(qp, kd, i, j, scale=scale, causal=causal,
                            block_q=block_q, block_k=block_k)
        s0, s1 = s[:, :block_k], s[:, block_k:]
        m0_prev, m1_prev = m_sc[0], m_sc[1]  # [bq, 128] (col-bcast)
        m0 = jnp.maximum(m0_prev, jnp.max(s0, axis=1, keepdims=True))
        m1 = jnp.maximum(m1_prev, jnp.max(s1, axis=1, keepdims=True))
        a0 = jnp.exp(m0_prev - m0)
        a1 = jnp.exp(m1_prev - m1)
        p0 = jnp.exp(s0 - m0[:, :1])
        p1 = jnp.exp(s1 - m1[:, :1])
        l_sc[0] = l_sc[0] * a0 + jnp.sum(p0, 1, keepdims=True)
        l_sc[1] = l_sc[1] * a1 + jnp.sum(p1, 1, keepdims=True)
        pd = jnp.concatenate([p0, p1], 1).astype(vp.dtype)
        vd = _blockdiag2(vp, sub_d)          # [2*bk, 128]
        alpha = jnp.where(_half_mask(block_q, sub_d), a0, a1)
        acc_sc[:] = (acc_sc[:] * alpha
                     + jax.lax.dot_general(
                         pd, vd, (((1,), (0,)), ((), ())),
                         preferred_element_type=jnp.float32))
        m_sc[0] = m0
        m_sc[1] = m1

    @pl.when(j == num_kv - 1)
    def _finalize():
        l0 = jnp.maximum(l_sc[0][:, :1], 1e-30)
        l1 = jnp.maximum(l_sc[1][:, :1], 1e-30)
        den = jnp.where(_half_mask(block_q, sub_d), l0, l1)
        o_ref[0, 0] = (acc_sc[:] / den).astype(o_ref.dtype)
        lse0 = m_sc[0][:, :1] + jnp.log(l0)               # [bq, 1]
        lse1 = m_sc[1][:, :1] + jnp.log(l1)
        lse0_ref[0, 0, 0] = jnp.broadcast_to(lse0, lse0_ref.shape[3:])
        lse1_ref[0, 0, 0] = jnp.broadcast_to(lse1, lse1_ref.shape[3:])


def _fwd_pack2(q, k, v, *, scale: float, causal: bool, block_q: int,
               block_k: int, rope=None, sub_d: int = 64):
    """Packed q,k,v: [B, Hp, S, 2*sub_d] -> (o packed, lse0, lse1 each
    [B, Hp, S // bq, bq, STATS_LANES] f32 — per-sub-head row stats).

    ``rope``: optional packed tables (cos2 [S, 128], sinm [S, 128] —
    the D=sub_d tables duplicated along lanes)."""
    B, Hp, S, Dp = q.shape
    Sk = k.shape[2]
    bq, bk = min(block_q, S), min(block_k, Sk)
    grid = (B, Hp, S // bq, Sk // bk)
    num_kv = grid[3]

    kernel = functools.partial(
        _fwd_pack2_kernel, scale=scale, causal=causal, block_q=bq,
        block_k=bk, num_kv=num_kv, has_rope=rope is not None,
        sub_d=sub_d)
    rope_args, rope_specs = (), []
    if rope is not None:
        cos2, sinm = rope
        rope_args = (cos2, sinm, cos2, sinm)
        rope_specs = [
            pl.BlockSpec((bq, Dp), lambda b, h, i, j: (i, 0)),
            pl.BlockSpec((bq, Dp), lambda b, h, i, j: (i, 0)),
            pl.BlockSpec((bk, Dp), lambda b, h, i, j: (j, 0)),
            pl.BlockSpec((bk, Dp), lambda b, h, i, j: (j, 0)),
        ]
    stats_spec = pl.BlockSpec((1, 1, 1, bq, STATS_LANES),
                              lambda b, h, i, j: (b, h, i, 0, 0))
    stats_shape = jax.ShapeDtypeStruct((B, Hp, S // bq, bq, STATS_LANES),
                                       jnp.float32)
    o, lse0, lse1 = pl.pallas_call(
        kernel,
        grid=grid,
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        in_specs=[
            pl.BlockSpec((1, 1, bq, Dp), lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, bk, Dp), lambda b, h, i, j: (b, h, j, 0)),
            pl.BlockSpec((1, 1, bk, Dp), lambda b, h, i, j: (b, h, j, 0)),
            *rope_specs,
        ],
        out_specs=[
            pl.BlockSpec((1, 1, bq, Dp), lambda b, h, i, j: (b, h, i, 0)),
            stats_spec,
            stats_spec,
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, Hp, S, Dp), q.dtype),
            stats_shape,
            stats_shape,
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, Dp), jnp.float32),
            pltpu.VMEM((2, bq, 128), jnp.float32),
            pltpu.VMEM((2, bq, 128), jnp.float32),
        ],
        interpret=_use_interpret(),
    )(q, k, v, *rope_args)
    return o, lse0, lse1


# ---------------------------------------------------------------------------
# backward kernels
# ---------------------------------------------------------------------------

def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   dq_ref, dq_sc, *, scale: float, causal: bool,
                   block_q: int, block_k: int, num_kv: int):
    i, j = pl.program_id(2), pl.program_id(3)

    @pl.when(j == 0)
    def _init():
        dq_sc[:] = jnp.zeros_like(dq_sc)

    @pl.when(_block_live(i, j, causal=causal, block_q=block_q,
                         block_k=block_k))
    def _compute():
        k = k_ref[0, 0]
        _, ds = _grad_blocks(
            q_ref[0, 0], k, v_ref[0, 0], do_ref[0, 0],
            lse_ref[0, 0, 0][:, 0:1], delta_ref[0, 0, 0][:, 0:1], i, j,
            scale=scale, causal=causal, block_q=block_q, block_k=block_k)
        dq_sc[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(j == num_kv - 1)
    def _finalize():
        dq_ref[0, 0] = dq_sc[:].astype(dq_ref.dtype)


def _bwd_fused_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                      *rest, scale: float, causal: bool, block_q: int,
                      block_k: int, num_q: int, num_kv: int,
                      has_rope: bool):
    """Strip-mined fused backward: dq, dk, dv in one pass over (b, h, i).

    The two-kernel backward (`_bwd_dq_kernel` + `_bwd_dkv_kernel`)
    recomputes the score block and dp in each kernel — 2 extra
    K=head_dim matmuls per block pair, the expensive kind on the MXU
    (contraction = 64 runs the systolic array at half rate).  Here the
    whole kv sequence rides along as one [Sk, D] block and the kernel
    walks it in ``block_k`` strips: s/p/dp are computed once per strip
    and feed all three gradients.  Causal masking goes from "compute
    the full square then mask" to *skipping dead strips outright*
    (``_block_live``) — at bq=bk=256 over S=1024 that's 37.5% of the
    score matmuls and, just as importantly on TPU, of the VPU
    exp/mask work that otherwise rivals the MXU time at head_dim 64.
    dq accumulates in VMEM scratch per q block; dk/dv accumulate in
    [Sk, D] scratch across the sequential i sweep (VMEM-bounded: the
    `_bwd` dispatcher falls back to the two-kernel path for long Sk).

    With ``has_rope``, q/k are rotated in-kernel for the score
    recompute; score-gradients land on the *rotated* q/k, so dq takes
    the transposed rotation before its store and dk takes it at
    finalize (the rotation is per-row, so it commutes with the
    accumulation over q blocks).
    """
    if has_rope:
        (cq_ref, sq_ref, ck_ref, sk_ref,
         dq_ref, dk_ref, dv_ref, dq_sc, dk_sc, dv_sc, krot_sc) = rest
    else:
        dq_ref, dk_ref, dv_ref, dq_sc, dk_sc, dv_sc = rest
    i = pl.program_id(2)                        # q block index

    @pl.when(i == 0)
    def _init_kv():
        dk_sc[:] = jnp.zeros_like(dk_sc)
        dv_sc[:] = jnp.zeros_like(dv_sc)
        if has_rope and num_kv > 1:
            # rotate k ONCE per (b, h): every q block's strips reuse the
            # cached rotation instead of re-rotating per (i, strip)
            krot_sc[:] = _rot(k_ref[0, 0], ck_ref[...], sk_ref[...],
                              k_ref.shape[-1])

    q = q_ref[0, 0]
    do = do_ref[0, 0]
    D = q.shape[-1]
    if has_rope:
        q = _rot(q, cq_ref[...], sq_ref[...], D)
    lse = lse_ref[0, 0, 0][:, 0:1]
    delta = delta_ref[0, 0, 0][:, 0:1]

    if num_kv == 1:
        # single strip: every block pair is live under causal masking,
        # so no liveness guard — and dq/k go straight through values
        # instead of VMEM scratch round-trips (this is the exact hot
        # path of the S<=block_k case, keep it lean)
        k = k_ref[0, 0]
        if has_rope:
            k = _rot(k, ck_ref[...], sk_ref[...], D)
        p, ds = _grad_blocks(
            q, k, v_ref[0, 0], do, lse, delta, i, 0,
            scale=scale, causal=causal, block_q=block_q,
            block_k=block_k)
        dv_sc[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)          # [bk, D]
        dk_sc[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)          # [bk, D]
        dq = jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
    else:
        dq_sc[:] = jnp.zeros_like(dq_sc)
        for j in range(num_kv):
            lo, hi = j * block_k, (j + 1) * block_k

            @pl.when(_block_live(i, j, causal=causal, block_q=block_q,
                                 block_k=block_k))
            def _strip(j=j, lo=lo, hi=hi):
                if has_rope:
                    k = krot_sc[lo:hi, :]
                else:
                    k = k_ref[0, 0, lo:hi, :]
                p, ds = _grad_blocks(
                    q, k, v_ref[0, 0, lo:hi, :], do, lse, delta, i, j,
                    scale=scale, causal=causal, block_q=block_q,
                    block_k=block_k)
                dv_sc[lo:hi, :] += jax.lax.dot_general(
                    p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32)  # [bk, D]
                dk_sc[lo:hi, :] += jax.lax.dot_general(
                    ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32)  # [bk, D]
                dq_sc[:] += jax.lax.dot_general(
                    ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32)
        dq = dq_sc[:]
    if has_rope:
        dq = _rot_t(dq, cq_ref[...], sq_ref[...], D)
    dq_ref[0, 0] = dq.astype(dq_ref.dtype)

    @pl.when(i == num_q - 1)
    def _finalize():
        dk = dk_sc[:]
        if has_rope:
            dk = _rot_t(dk, ck_ref[...], sk_ref[...], D)
        dk_ref[0, 0] = dk.astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_sc[:].astype(dv_ref.dtype)


def _bwd_pack2_kernel(q_ref, k_ref, v_ref, do_ref, lse0_ref, lse1_ref,
                      delta0_ref, delta1_ref, *rest, scale: float,
                      causal: bool, block_q: int, block_k: int,
                      num_q: int, num_kv: int, has_rope: bool,
                      sub_d: int):
    """Packed strip-mined fused backward: the packed analogue of
    `_bwd_fused_kernel` (same grid, same dead-strip skipping, same
    rope-at-the-boundary structure), with every matmul full-width:

        s  = qp @ kd^T          [bq, 128] x [128, 2*bk]
        dp = do @ vd^T          [bq, 128] x [128, 2*bk]
        dv = fold(pd^T @ do)    [2*bk, bq] x [bq, 128]
        dk = fold(dsd^T @ qp)   [2*bk, bq] x [bq, 128]
        dq = dsd @ kd           [bq, 2*bk] x [2*bk, 128]

    — 5 ops per strip for a head *pair* vs 10 half-width ops on the
    unpacked schedule.  ``fold`` keeps each half's own lanes and drops
    the cross-head lanes the widened transpose matmuls produce."""
    if has_rope:
        (cq_ref, sq_ref, ck_ref, sk_ref,
         dq_ref, dk_ref, dv_ref, dq_sc, dk_sc, dv_sc, krot_sc) = rest
    else:
        dq_ref, dk_ref, dv_ref, dq_sc, dk_sc, dv_sc = rest
    i = pl.program_id(2)                        # q block index

    @pl.when(i == 0)
    def _init_kv():
        dk_sc[:] = jnp.zeros_like(dk_sc)
        dv_sc[:] = jnp.zeros_like(dv_sc)
        if has_rope and num_kv > 1:
            krot_sc[:] = _rot2(k_ref[0, 0], ck_ref[...], sk_ref[...],
                               sub_d)

    qp = q_ref[0, 0]                             # [bq, 128]
    do = do_ref[0, 0]
    if has_rope:
        qp = _rot2(qp, cq_ref[...], sq_ref[...], sub_d)
    lse0 = lse0_ref[0, 0, 0][:, 0:1]
    lse1 = lse1_ref[0, 0, 0][:, 0:1]
    delta0 = delta0_ref[0, 0, 0][:, 0:1]
    delta1 = delta1_ref[0, 0, 0][:, 0:1]

    def _strip_math(kp, vp, j):
        kd = _blockdiag2(kp, sub_d)              # [2*bk, 128]
        vd = _blockdiag2(vp, sub_d)
        s = _masked_scores2(qp, kd, i, j, scale=scale, causal=causal,
                            block_q=block_q, block_k=block_k)
        p0 = jnp.exp(s[:, :block_k] - lse0)
        p1 = jnp.exp(s[:, block_k:] - lse1)
        dp = jax.lax.dot_general(
            do, vd, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)  # [bq, 2*bk]
        ds0 = p0 * (dp[:, :block_k] - delta0) * scale
        ds1 = p1 * (dp[:, block_k:] - delta1) * scale
        pd = jnp.concatenate([p0, p1], 1)
        dsd = jnp.concatenate([ds0, ds1], 1)
        return kd, pd, dsd

    if num_kv == 1:
        kp = k_ref[0, 0]
        if has_rope:
            kp = _rot2(kp, ck_ref[...], sk_ref[...], sub_d)
        kd, pd, dsd = _strip_math(kp, v_ref[0, 0], 0)
        dv_sc[:] += _fold2(jax.lax.dot_general(
            pd.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32), block_k, sub_d)
        dk_sc[:] += _fold2(jax.lax.dot_general(
            dsd.astype(qp.dtype), qp, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32), block_k, sub_d)
        dq = jax.lax.dot_general(
            dsd.astype(kd.dtype), kd, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
    else:
        dq_sc[:] = jnp.zeros_like(dq_sc)
        for j in range(num_kv):
            lo, hi = j * block_k, (j + 1) * block_k

            @pl.when(_block_live(i, j, causal=causal, block_q=block_q,
                                 block_k=block_k))
            def _strip(j=j, lo=lo, hi=hi):
                if has_rope:
                    kp = krot_sc[lo:hi, :]
                else:
                    kp = k_ref[0, 0, lo:hi, :]
                kd, pd, dsd = _strip_math(kp, v_ref[0, 0, lo:hi, :], j)
                dv_sc[lo:hi, :] += _fold2(jax.lax.dot_general(
                    pd.astype(do.dtype), do, (((0,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32), block_k, sub_d)
                dk_sc[lo:hi, :] += _fold2(jax.lax.dot_general(
                    dsd.astype(qp.dtype), qp, (((0,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32), block_k, sub_d)
                dq_sc[:] += jax.lax.dot_general(
                    dsd.astype(kd.dtype), kd, (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32)
        dq = dq_sc[:]
    if has_rope:
        dq = _rot2_t(dq, cq_ref[...], sq_ref[...], sub_d)
    dq_ref[0, 0] = dq.astype(dq_ref.dtype)

    @pl.when(i == num_q - 1)
    def _finalize():
        dk = dk_sc[:]
        if has_rope:
            dk = _rot2_t(dk, ck_ref[...], sk_ref[...], sub_d)
        dk_ref[0, 0] = dk.astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_sc[:].astype(dv_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_sc, dv_sc, *, scale: float,
                    causal: bool, block_q: int, block_k: int,
                    num_q: int):
    j, i = pl.program_id(2), pl.program_id(3)   # kv outer, q inner

    @pl.when(i == 0)
    def _init():
        dk_sc[:] = jnp.zeros_like(dk_sc)
        dv_sc[:] = jnp.zeros_like(dv_sc)

    @pl.when(_block_live(i, j, causal=causal, block_q=block_q,
                         block_k=block_k))
    def _compute():
        q = q_ref[0, 0]
        do = do_ref[0, 0]
        p, ds = _grad_blocks(
            q, k_ref[0, 0], v_ref[0, 0], do, lse_ref[0, 0, 0][:, 0:1],
            delta_ref[0, 0, 0][:, 0:1], i, j,
            scale=scale, causal=causal, block_q=block_q, block_k=block_k)
        dv_sc[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)          # [bk, D]
        dk_sc[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)          # [bk, D]

    @pl.when(i == num_q - 1)
    def _finalize():
        dk_ref[0, 0] = dk_sc[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_sc[:].astype(dv_ref.dtype)


def _bwd(q, k, v, o, lse, do, *, scale: float, causal: bool,
         block_q: int, block_k: int, rope=None):
    B, H, S, D = q.shape
    Sk = k.shape[2]
    bq, bk = min(block_q, S), min(block_k, Sk)
    num_q, num_kv = S // bq, Sk // bk
    if lse.shape[3] != bq:
        # fwd ran with a different q block; the stats are [.., S, LANES]
        # rows underneath — regroup to this pass's blocking
        lse = lse.reshape(B, H, num_q, bq, STATS_LANES)
    delta = jnp.broadcast_to(
        jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                axis=-1).reshape(B, H, num_q, bq, 1),
        (B, H, num_q, bq, STATS_LANES))

    # strip-mined fused path: the whole kv sequence rides as one block
    # and the kernel walks it in bk strips (skipping causally-dead
    # ones).  [Sk, D] f32 scratch x2 bounds it to moderate Sk; longer
    # sequences take the two-kernel path below.
    if Sk * D * 4 * 2 <= 8 * 1024 * 1024:
        qs = pl.BlockSpec((1, 1, bq, D), lambda b, h, i: (b, h, i, 0))
        ks = pl.BlockSpec((1, 1, Sk, D), lambda b, h, i: (b, h, 0, 0))
        rs = pl.BlockSpec((1, 1, 1, bq, STATS_LANES),
                          lambda b, h, i: (b, h, i, 0, 0))
        rope_args, rope_specs = (), []
        if rope is not None:
            cos2, sinm = rope
            rope_args = (cos2, sinm, cos2, sinm)
            rope_specs = [
                pl.BlockSpec((bq, D), lambda b, h, i: (i, 0)),
                pl.BlockSpec((bq, D), lambda b, h, i: (i, 0)),
                pl.BlockSpec((Sk, D), lambda b, h, i: (0, 0)),
                pl.BlockSpec((Sk, D), lambda b, h, i: (0, 0)),
            ]
        dq, dk, dv = pl.pallas_call(
            functools.partial(_bwd_fused_kernel, scale=scale,
                              causal=causal, block_q=bq, block_k=bk,
                              num_q=num_q, num_kv=num_kv,
                              has_rope=rope is not None),
            grid=(B, H, num_q),
            compiler_params=_CompilerParams(
                dimension_semantics=("parallel", "parallel",
                                     "arbitrary")),
            in_specs=[qs, ks, ks, qs, rs, rs, *rope_specs],
            out_specs=[qs, ks, ks],
            out_shape=[jax.ShapeDtypeStruct((B, H, S, D), q.dtype),
                       jax.ShapeDtypeStruct((B, H, Sk, D), k.dtype),
                       jax.ShapeDtypeStruct((B, H, Sk, D), v.dtype)],
            scratch_shapes=(
                [pltpu.VMEM((bq, D), jnp.float32),
                 pltpu.VMEM((Sk, D), jnp.float32),
                 pltpu.VMEM((Sk, D), jnp.float32)]
                + ([pltpu.VMEM((Sk, D), q.dtype)]
                   if rope is not None else [])),
            interpret=_use_interpret(),
        )(q, k, v, do, lse, delta, *rope_args)
        return dq, dk, dv
    assert rope is None, \
        "fused rope requires the strip-mined backward (moderate Sk)"

    q_spec = pl.BlockSpec((1, 1, bq, D), lambda b, h, i, j: (b, h, i, 0))
    k_spec = pl.BlockSpec((1, 1, bk, D), lambda b, h, i, j: (b, h, j, 0))
    r_spec = pl.BlockSpec((1, 1, 1, bq, STATS_LANES),
                          lambda b, h, i, j: (b, h, i, 0, 0))

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          block_q=bq, block_k=bk, num_kv=num_kv),
        grid=(B, H, num_q, num_kv),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        in_specs=[q_spec, k_spec, k_spec, q_spec, r_spec, r_spec],
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct((B, H, S, D), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, D), jnp.float32)],
        interpret=_use_interpret(),
    )(q, k, v, do, lse, delta)

    # kv-outer grid: index maps see (b, h, j, i)
    q_spec2 = pl.BlockSpec((1, 1, bq, D), lambda b, h, j, i: (b, h, i, 0))
    k_spec2 = pl.BlockSpec((1, 1, bk, D), lambda b, h, j, i: (b, h, j, 0))
    r_spec2 = pl.BlockSpec((1, 1, 1, bq, STATS_LANES),
                           lambda b, h, j, i: (b, h, i, 0, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          block_q=bq, block_k=bk, num_q=num_q),
        grid=(B, H, num_kv, num_q),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        in_specs=[q_spec2, k_spec2, k_spec2, q_spec2, r_spec2, r_spec2],
        out_specs=[k_spec2, k_spec2],
        out_shape=[jax.ShapeDtypeStruct((B, H, Sk, D), k.dtype),
                   jax.ShapeDtypeStruct((B, H, Sk, D), v.dtype)],
        scratch_shapes=[pltpu.VMEM((bk, D), jnp.float32),
                        pltpu.VMEM((bk, D), jnp.float32)],
        interpret=_use_interpret(),
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


def _bwd_pack2(q, k, v, o, lse0, lse1, do, *, scale: float, causal: bool,
               block_q: int, block_k: int, rope=None, sub_d: int = 64):
    """Packed backward dispatcher (strip-mined fused path only — the
    `flash_attention` gate keeps pack2 off for kv sequences whose
    [Sk, 128] f32 dk/dv scratch would not fit VMEM)."""
    B, Hp, S, Dp = q.shape
    Sk = k.shape[2]
    bq, bk = min(block_q, S), min(block_k, Sk)
    num_q, num_kv = S // bq, Sk // bk
    assert Sk * Dp * 4 * 2 <= 8 * 1024 * 1024, \
        "packed backward needs the strip-mined fused path (moderate Sk)"
    if lse0.shape[3] != bq:
        lse0 = lse0.reshape(B, Hp, num_q, bq, STATS_LANES)
        lse1 = lse1.reshape(B, Hp, num_q, bq, STATS_LANES)
    # per-sub-head delta = sum(do * o) over each head's own lanes
    prod = (do.astype(jnp.float32) * o.astype(jnp.float32)).reshape(
        B, Hp, S, 2, sub_d).sum(-1)                      # [B, Hp, S, 2]
    delta0 = jnp.broadcast_to(
        prod[..., 0].reshape(B, Hp, num_q, bq, 1),
        (B, Hp, num_q, bq, STATS_LANES))
    delta1 = jnp.broadcast_to(
        prod[..., 1].reshape(B, Hp, num_q, bq, 1),
        (B, Hp, num_q, bq, STATS_LANES))

    qs = pl.BlockSpec((1, 1, bq, Dp), lambda b, h, i: (b, h, i, 0))
    ks = pl.BlockSpec((1, 1, Sk, Dp), lambda b, h, i: (b, h, 0, 0))
    rs = pl.BlockSpec((1, 1, 1, bq, STATS_LANES),
                      lambda b, h, i: (b, h, i, 0, 0))
    rope_args, rope_specs = (), []
    if rope is not None:
        cos2, sinm = rope
        rope_args = (cos2, sinm, cos2, sinm)
        rope_specs = [
            pl.BlockSpec((bq, Dp), lambda b, h, i: (i, 0)),
            pl.BlockSpec((bq, Dp), lambda b, h, i: (i, 0)),
            pl.BlockSpec((Sk, Dp), lambda b, h, i: (0, 0)),
            pl.BlockSpec((Sk, Dp), lambda b, h, i: (0, 0)),
        ]
    dq, dk, dv = pl.pallas_call(
        functools.partial(_bwd_pack2_kernel, scale=scale, causal=causal,
                          block_q=bq, block_k=bk, num_q=num_q,
                          num_kv=num_kv, has_rope=rope is not None,
                          sub_d=sub_d),
        grid=(B, Hp, num_q),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        in_specs=[qs, ks, ks, qs, rs, rs, rs, rs, *rope_specs],
        out_specs=[qs, ks, ks],
        out_shape=[jax.ShapeDtypeStruct((B, Hp, S, Dp), q.dtype),
                   jax.ShapeDtypeStruct((B, Hp, Sk, Dp), k.dtype),
                   jax.ShapeDtypeStruct((B, Hp, Sk, Dp), v.dtype)],
        scratch_shapes=(
            [pltpu.VMEM((bq, Dp), jnp.float32),
             pltpu.VMEM((Sk, Dp), jnp.float32),
             pltpu.VMEM((Sk, Dp), jnp.float32)]
            + ([pltpu.VMEM((Sk, Dp), q.dtype)]
               if rope is not None else [])),
        interpret=_use_interpret(),
    )(q, k, v, do, lse0, lse1, delta0, delta1, *rope_args)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _flash_bhsd(q, k, v, scale, causal, block_q, block_k,
                bwd_block_q, bwd_block_k):
    o, _ = _fwd(q, k, v, scale=scale, causal=causal, block_q=block_q,
                block_k=block_k)
    return o


def _flash_bhsd_fwd(q, k, v, scale, causal, block_q, block_k,
                    bwd_block_q, bwd_block_k):
    o, lse = _fwd(q, k, v, scale=scale, causal=causal, block_q=block_q,
                  block_k=block_k)
    return o, (q, k, v, o, lse)


def _flash_bhsd_bwd(scale, causal, block_q, block_k, bwd_block_q,
                    bwd_block_k, res, do):
    q, k, v, o, lse = res
    dq, dk, dv = _bwd(q, k, v, o, lse, do, scale=scale, causal=causal,
                      block_q=bwd_block_q, block_k=bwd_block_k)
    return dq, dk, dv


_flash_bhsd.defvjp(_flash_bhsd_fwd, _flash_bhsd_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9, 10))
def _flash_bhsd_rope(q, k, v, cos2, sinm, scale, causal, block_q,
                     block_k, bwd_block_q, bwd_block_k):
    o, _ = _fwd(q, k, v, scale=scale, causal=causal, block_q=block_q,
                block_k=block_k, rope=(cos2, sinm))
    return o


def _flash_bhsd_rope_fwd(q, k, v, cos2, sinm, scale, causal, block_q,
                         block_k, bwd_block_q, bwd_block_k):
    o, lse = _fwd(q, k, v, scale=scale, causal=causal, block_q=block_q,
                  block_k=block_k, rope=(cos2, sinm))
    return o, (q, k, v, cos2, sinm, o, lse)


def _flash_bhsd_rope_bwd(scale, causal, block_q, block_k, bwd_block_q,
                         bwd_block_k, res, do):
    q, k, v, cos2, sinm, o, lse = res
    dq, dk, dv = _bwd(q, k, v, o, lse, do, scale=scale, causal=causal,
                      block_q=bwd_block_q, block_k=bwd_block_k,
                      rope=(cos2, sinm))
    return dq, dk, dv, None, None


_flash_bhsd_rope.defvjp(_flash_bhsd_rope_fwd, _flash_bhsd_rope_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _flash_pack2(q, k, v, scale, causal, block_q, block_k,
                 bwd_block_q, bwd_block_k):
    o, _, _ = _fwd_pack2(q, k, v, scale=scale, causal=causal,
                         block_q=block_q, block_k=block_k)
    return o


def _flash_pack2_fwd(q, k, v, scale, causal, block_q, block_k,
                     bwd_block_q, bwd_block_k):
    o, lse0, lse1 = _fwd_pack2(q, k, v, scale=scale, causal=causal,
                               block_q=block_q, block_k=block_k)
    return o, (q, k, v, o, lse0, lse1)


def _flash_pack2_bwd(scale, causal, block_q, block_k, bwd_block_q,
                     bwd_block_k, res, do):
    q, k, v, o, lse0, lse1 = res
    dq, dk, dv = _bwd_pack2(q, k, v, o, lse0, lse1, do, scale=scale,
                            causal=causal, block_q=bwd_block_q,
                            block_k=bwd_block_k)
    return dq, dk, dv


_flash_pack2.defvjp(_flash_pack2_fwd, _flash_pack2_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9, 10))
def _flash_pack2_rope(q, k, v, cos2, sinm, scale, causal, block_q,
                      block_k, bwd_block_q, bwd_block_k):
    o, _, _ = _fwd_pack2(q, k, v, scale=scale, causal=causal,
                         block_q=block_q, block_k=block_k,
                         rope=(cos2, sinm))
    return o


def _flash_pack2_rope_fwd(q, k, v, cos2, sinm, scale, causal, block_q,
                          block_k, bwd_block_q, bwd_block_k):
    o, lse0, lse1 = _fwd_pack2(q, k, v, scale=scale, causal=causal,
                               block_q=block_q, block_k=block_k,
                               rope=(cos2, sinm))
    return o, (q, k, v, cos2, sinm, o, lse0, lse1)


def _flash_pack2_rope_bwd(scale, causal, block_q, block_k, bwd_block_q,
                          bwd_block_k, res, do):
    q, k, v, cos2, sinm, o, lse0, lse1 = res
    dq, dk, dv = _bwd_pack2(q, k, v, o, lse0, lse1, do, scale=scale,
                            causal=causal, block_q=bwd_block_q,
                            block_k=bwd_block_k, rope=(cos2, sinm))
    return dq, dk, dv, None, None


_flash_pack2_rope.defvjp(_flash_pack2_rope_fwd, _flash_pack2_rope_bwd)


def segment_attention(q, k, v, segment_ids, *, causal: bool = True,
                      scale: Optional[float] = None):
    """Packed-batch attention: block-diagonal masking by segment.

    q, k, v: ``[B, S, H, D]``; ``segment_ids``: ``[B, S]`` int32, the
    sample packer's per-row document index (1-based; ``0`` = padding).
    Position ``i`` attends to ``j`` iff ``seg[i] == seg[j]``, both are
    nonzero, and (``causal``) ``j <= i`` — co-packed documents never
    see each other, which is what makes a packed forward equal the
    per-document unpacked forward (asserted in
    ``tests/test_data_plane.py``).

    XLA formulation (f32 scores/stats, masked online-softmax-free):
    the per-batch ``[B, S, S]`` mask has no Pallas kernel yet — the
    flash/pack2 schedules decline packed batches through
    :func:`flash_attention`'s reasoned gate and land here.  Fully
    masked rows (padding queries) normalize against a floor so they
    produce zeros, not NaNs; their targets are ``-1`` so no loss or
    gradient flows through them.
    """
    B, S, H, D = q.shape
    if scale is None:
        scale = D ** -0.5
    seg = segment_ids.astype(jnp.int32)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    mask = (seg[:, None, :, None] == seg[:, None, None, :]) \
        & (seg[:, None, :, None] > 0)
    if causal:
        causal_m = jnp.tril(jnp.ones((S, S), bool))
        mask = mask & causal_m[None, None]
    s = jnp.where(mask, s, _NEG_INF)
    m = jnp.max(s, -1, keepdims=True)
    p = jnp.exp(s - m)
    p = jnp.where(mask, p, 0.0)
    l = jnp.sum(p, -1, keepdims=True)
    o = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v,
                   preferred_element_type=jnp.float32)
    l_q = jnp.swapaxes(l, 1, 2)              # [B, S, H, 1]
    return (o / jnp.maximum(l_q, 1e-30)).astype(q.dtype)


def supports(S: int, Sk: int, D: int, *, block_q: int = 1024,
             block_k: int = 1024) -> bool:
    """Shapes the kernel grid can tile (fallback to einsum otherwise)."""
    bq, bk = min(block_q, S), min(block_k, Sk)
    return (S % bq == 0 and Sk % bk == 0 and D <= 256
            and bq % 8 == 0 and bk % 128 == 0)


def _pack2_plan(S, Sk, H, D, causal, block_q, block_k, bwd_block_q,
                bwd_block_k, pack2):
    """(pbq, pbk, pbwq, pbwk) if the packed schedule applies, else None.

    The single source of the pack2 dispatch decision — shared by
    ``flash_attention`` and the reporting helper ``uses_pack2`` so the
    bench can't claim a schedule the kernel silently declined."""
    cfg = attention_config()
    if pack2 is None:
        pack2 = cfg.pack2
    if not (pack2 and D == 64 and H % 2 == 0 and H > 0):
        return None
    Dp = 2 * D
    pbq = min(block_q, cfg.pack2_block_q)
    pbk = min(block_k, cfg.pack2_block_k)
    pbwq = bwd_block_q if bwd_block_q is not None else \
        (cfg.bwd_block_q if causal else pbq)
    pbwk = bwd_block_k if bwd_block_k is not None else \
        (cfg.bwd_block_k if causal else pbk)
    pbwq, pbwk = min(pbq, pbwq), min(pbk, pbwk)
    # packed backward only has the strip-mined fused path: dk/dv ride
    # in [Sk, 128] f32 VMEM scratch
    ok = (supports(S, Sk, Dp, block_q=pbq, block_k=pbk)
          and supports(S, Sk, Dp, block_q=pbwq, block_k=pbwk)
          and Sk * Dp * 4 * 2 <= 8 * 1024 * 1024)
    return (pbq, pbk, pbwq, pbwk) if ok else None


def uses_pack2(S: int, Sk: int, H: int, D: int, *, causal: bool = True,
               block_q: int = 1024, block_k: int = 1024,
               pack2: Optional[bool] = None) -> bool:
    """Whether :func:`flash_attention` takes the packed schedule for
    this shape under the current :func:`attention_config`."""
    return _pack2_plan(S, Sk, H, D, causal, block_q, block_k, None,
                       None, pack2) is not None


def flash_attention(q, k, v, *, causal: bool = True,
                    scale: Optional[float] = None, block_q: int = 1024,
                    block_k: int = 1024,
                    bwd_block_q: Optional[int] = None,
                    bwd_block_k: Optional[int] = None,
                    positions=None,
                    rope_theta: float = 10000.0,
                    pack2: Optional[bool] = None,
                    segment_ids=None):
    """Fused causal attention.  q,k,v: [B, S, H, D] -> [B, S, H, D].

    Drop-in for ``ray_tpu.parallel.ring_attention.local_attention``;
    falls back to the einsum path for shapes the grid cannot tile.

    ``block_q``/``block_k`` tile the forward grid; ``bwd_block_q``/
    ``bwd_block_k`` (default: profiled per-shape choice) tile the
    strip-mined backward independently — the fwd likes one big block
    (per-grid-step overhead dominates any causal-skip win there) while
    the bwd walks kv strips inside the kernel and genuinely skips the
    causally-dead ones.

    ``positions`` [S] enables fused RoPE: q/k are rotated inside the
    kernels (zero extra HBM passes) when the kv sequence fits one
    block; otherwise the rotation is applied here before dispatch
    (same math as ``ray_tpu.models.gpt._rope``).

    ``pack2`` (default: :func:`attention_config`) selects the two-head
    lane-packed schedule for head_dim 64 / even head counts; odd head
    counts, other head dims and untileable shapes use the single-head
    schedule regardless.

    ``segment_ids`` [B, S] (sample-packed batches) is a reasoned
    decline of every Pallas schedule: the per-batch block-diagonal
    mask has no kernel yet, so RoPE (when ``positions`` is given) is
    applied here and the XLA :func:`segment_attention` formulation
    runs — loud in timelines as ``attn/segment_xla``.
    """
    B, S, H, D = q.shape
    Sk = k.shape[1]
    cfg = attention_config()
    if scale is None:
        scale = D ** -0.5
    if positions is not None and S != Sk:
        raise ValueError(f"rope needs q and kv positions to match: "
                         f"S={S} vs Sk={Sk}")
    if segment_ids is not None:
        if positions is not None:
            q = rope_rotate(q, positions, rope_theta)
            k = rope_rotate(k, positions, rope_theta)
        with jax.named_scope("attn/segment_xla"):
            return segment_attention(q, k, v, segment_ids,
                                     causal=causal, scale=scale)

    plan = _pack2_plan(S, Sk, H, D, causal, block_q, block_k,
                       bwd_block_q, bwd_block_k, pack2)
    if plan is not None:
        pbq, pbk, pbwq, pbwk = plan
        Dp = 2 * D
        fuse_rope = (positions is not None and S == Sk
                     and Sk * Dp * 8 <= 8 * 1024 * 1024)
        if positions is not None and not fuse_rope:
            q = rope_rotate(q, positions, rope_theta)
            k = rope_rotate(k, positions, rope_theta)
        # pairing heads (2h, 2h+1) along lanes is a pure reshape in
        # the [B, S, H, D] model layout
        qp = jnp.swapaxes(q.reshape(B, S, H // 2, Dp), 1, 2)
        kp = jnp.swapaxes(k.reshape(B, Sk, H // 2, Dp), 1, 2)
        vp = jnp.swapaxes(v.reshape(B, Sk, H // 2, Dp), 1, 2)
        with jax.named_scope("attn/pack2"):
            if fuse_rope:
                cos2, sinm = rope_tables(positions, D, rope_theta,
                                         q.dtype)
                cos2 = jnp.concatenate([cos2, cos2], -1)  # [S, 128]
                sinm = jnp.concatenate([sinm, sinm], -1)
                op = _flash_pack2_rope(qp, kp, vp, cos2, sinm, scale,
                                       causal, pbq, pbk, pbwq, pbwk)
            else:
                op = _flash_pack2(qp, kp, vp, scale, causal, pbq, pbk,
                                  pbwq, pbwk)
            return jnp.swapaxes(op, 1, 2).reshape(B, S, H, D)

    if bwd_block_q is None:
        bwd_block_q = cfg.bwd_block_q if causal else block_q
        bwd_block_q = min(block_q, bwd_block_q)
    if bwd_block_k is None:
        bwd_block_k = cfg.bwd_block_k if causal else block_k
        bwd_block_k = min(block_k, bwd_block_k)
    kernel_ok = (supports(S, Sk, D, block_q=block_q, block_k=block_k)
                 and supports(S, Sk, D, block_q=bwd_block_q,
                              block_k=bwd_block_k))
    # in-kernel rope needs the strip-mined fused backward (kv rides as
    # one block; bound matches _bwd's VMEM-scratch budget)
    fuse_rope = (positions is not None and kernel_ok
                 and S == Sk and Sk * D * 8 <= 8 * 1024 * 1024)
    if positions is not None and not fuse_rope:
        q = rope_rotate(q, positions, rope_theta)
        k = rope_rotate(k, positions, rope_theta)
    if not kernel_ok:
        from ray_tpu.parallel.ring_attention import local_attention
        with jax.named_scope("attn/xla"):
            return local_attention(q, k, v, causal=causal, scale=scale)
    with jax.named_scope("attn/flash"):
        qt = jnp.swapaxes(q, 1, 2)
        kt = jnp.swapaxes(k, 1, 2)
        vt = jnp.swapaxes(v, 1, 2)
        if fuse_rope:
            cos2, sinm = rope_tables(positions, D, rope_theta, q.dtype)
            o = _flash_bhsd_rope(qt, kt, vt, cos2, sinm, scale, causal,
                                 block_q, block_k, bwd_block_q,
                                 bwd_block_k)
        else:
            o = _flash_bhsd(qt, kt, vt, scale, causal, block_q,
                            block_k, bwd_block_q, bwd_block_k)
        return jnp.swapaxes(o, 1, 2)


# ---------------------------------------------------------------------------
# cache-aware decode attention (inference engine)
#
# One query token per sequence against a padded KV context gathered from
# the paged cache ([B, S, H, D], valid prefix per sequence given by
# ``lengths``).  The q "matrix" is a single row, which the TPU tiling
# rules cannot block — the kernel broadcasts it to 8 sublanes (every row
# computes the same result; row 0 is returned) and walks the context in
# ``block_k`` strips with the same online-softmax scratch discipline as
# ``_fwd_kernel``.  Lengths ride in scalar-prefetch SMEM so the mask is
# a per-strip iota compare, not a precomputed [B, S] tensor.
# ---------------------------------------------------------------------------

_DECODE_QROWS = 8      # sublane-pad the single query row to a tileable block


def _decode_kernel(len_ref, q_ref, k_ref, v_ref, *rest, scale: float,
                   block_k: int, num_kv: int, quantized: bool = False):
    """``quantized`` (static): K/V arrive as int8 codes plus
    per-(position, head) f32 scale refs and are dequantized *inside*
    the 128-lane context strip — the quantized cache never
    materializes in anything wider than its strip.  One body for both
    modes so the scratch discipline cannot diverge."""
    if quantized:
        ks_ref, vs_ref, o_ref, acc_sc, m_sc, l_sc = rest
    else:
        o_ref, acc_sc, m_sc, l_sc = rest
    b, j = pl.program_id(0), pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        acc_sc[:] = jnp.zeros_like(acc_sc)
        m_sc[:] = jnp.full_like(m_sc, _NEG_INF)
        l_sc[:] = jnp.zeros_like(l_sc)

    q = q_ref[0, 0]                          # [QROWS, D]
    k = k_ref[0, :, 0, :]                    # [bk, D]
    v = v_ref[0, :, 0, :]
    if quantized:
        q = q.astype(jnp.float32)
        k = k.astype(jnp.float32) * ks_ref[0, 0][:, None]
        v = v.astype(jnp.float32) * vs_ref[0, 0][:, None]
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale      # [QROWS, bk]
    col = (j * block_k
           + jax.lax.broadcasted_iota(jnp.int32,
                                      (_DECODE_QROWS, block_k), 1))
    s = jnp.where(col < len_ref[b], s, _NEG_INF)
    m_prev = m_sc[:]                          # [QROWS, 128] (col-bcast)
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
    alpha = jnp.exp(m_prev - m_new)
    p = jnp.exp(s - m_new[:, :1])
    l_sc[:] = l_sc[:] * alpha + jnp.sum(p, 1, keepdims=True)
    acc_sc[:] = (acc_sc[:] * alpha[:, :1]
                 + jax.lax.dot_general(
                     p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                     preferred_element_type=jnp.float32))
    m_sc[:] = m_new

    @pl.when(j == num_kv - 1)
    def _finalize():
        o_ref[0, 0] = (acc_sc[:]
                       / jnp.maximum(l_sc[:, :1], 1e-30)).astype(
                           o_ref.dtype)


def _decode_block(S: int, block_k: int) -> int:
    """Largest 128-multiple strip <= block_k that divides S (0: none).

    Dropping to a narrower strip beats silently leaving the kernel for
    the XLA fallback: any 128-multiple context (every paged-cache
    gather at the default page_size) stays on the Pallas path."""
    bk = min(block_k, S) // 128 * 128
    while bk >= 128 and S % bk:
        bk -= 128
    return max(bk, 0)


def decode_supports(S: int, D: int, *, block_k: int = 512) -> bool:
    """Context shapes the decode kernel grid can tile (XLA otherwise)."""
    return _decode_block(S, block_k) >= 128 and D <= 256


def decode_attention(q, k, v, lengths, *, scale: Optional[float] = None,
                     impl: str = "auto", block_k: int = 512,
                     k_scale=None, v_scale=None):
    """Single-token decode attention against a padded KV context.

    q: [B, H, D] — the current token's (already-rotated) queries;
    k, v: [B, S, H, D] — the per-sequence context gathered from the
    paged cache (positions >= ``lengths[b]`` are garbage and masked);
    lengths: [B] int32 — valid context length per sequence (including
    the current token, whose K/V the caller has already written).
    Returns [B, H, D] in q's dtype.

    ``k_scale``/``v_scale`` ([B, S, H] f32, both or neither): the
    context is block-scaled int8 (``kv_dtype="int8"`` caches) and is
    dequantized here — inside the kernel's 128-lane context strips on
    the Pallas path, as a fused ``codes * scale`` element-wise on the
    XLA path — so the int8 cache is never materialized wide.

    ``impl``: "pallas" (strip-mined online-softmax kernel; raises for
    untileable shapes), "xla" (masked einsum formulation, shards and
    runs anywhere), or "auto" (pallas on a TPU backend for lane-aligned
    shapes, xla otherwise — interpret-mode parity for the kernel lives
    in ``tests/test_ops.py``).
    """
    B, H, D = q.shape
    S = k.shape[1]
    if (k_scale is None) != (v_scale is None):
        raise ValueError("k_scale/v_scale must be passed together")
    quantized = k_scale is not None
    if scale is None:
        scale = D ** -0.5
    lengths = lengths.astype(jnp.int32)
    if impl == "pallas" and not decode_supports(S, D, block_k=block_k):
        raise ValueError(f"decode kernel cannot tile S={S}, D={D} "
                         f"(block_k={block_k})")
    block_k = _decode_block(S, block_k) or block_k
    use_pallas = impl == "pallas" or (
        impl == "auto" and jax.default_backend() == "tpu"
        and decode_supports(S, D, block_k=block_k))
    if not use_pallas:
        with jax.named_scope("attn/decode_xla"):
            if quantized:
                # masked-einsum fallback: dequantize as one fused
                # elementwise (XLA folds it into the gather consumers)
                k = (k.astype(jnp.float32)
                     * k_scale[..., None]).astype(q.dtype)
                v = (v.astype(jnp.float32)
                     * v_scale[..., None]).astype(q.dtype)
            s = jnp.einsum("bhd,bshd->bhs", q, k,
                           preferred_element_type=jnp.float32) * scale
            mask = jnp.arange(S)[None, None, :] < lengths[:, None, None]
            s = jnp.where(mask, s, _NEG_INF)
            m = jnp.max(s, -1, keepdims=True)
            p = jnp.exp(s - m)
            l = jnp.sum(p, -1, keepdims=True)
            o = jnp.einsum("bhs,bshd->bhd", p.astype(v.dtype), v,
                           preferred_element_type=jnp.float32)
            return (o / jnp.maximum(l, 1e-30)).astype(q.dtype)
    bk = min(block_k, S)
    grid = (B, H, S // bk)
    qp = jnp.broadcast_to(q[:, :, None, :], (B, H, _DECODE_QROWS, D))
    qkv_specs = [
        pl.BlockSpec((1, 1, _DECODE_QROWS, D),
                     lambda b, h, j, lens: (b, h, 0, 0)),
        pl.BlockSpec((1, bk, 1, D),
                     lambda b, h, j, lens: (b, j, h, 0)),
        pl.BlockSpec((1, bk, 1, D),
                     lambda b, h, j, lens: (b, j, h, 0)),
    ]
    common = dict(
        out_specs=pl.BlockSpec((1, 1, _DECODE_QROWS, D),
                               lambda b, h, j, lens: (b, h, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((_DECODE_QROWS, D), jnp.float32),
            pltpu.VMEM((_DECODE_QROWS, 128), jnp.float32),
            pltpu.VMEM((_DECODE_QROWS, 128), jnp.float32),
        ],
    )
    scale_in, scale_args = [], []
    if quantized:
        # scales travel [B, H, S] so the strip lands on the 128-lane
        # (trailing) dim — one [bk] vector per (b, h, j) grid cell
        scale_spec = pl.BlockSpec((1, 1, bk),
                                  lambda b, h, j, lens: (b, h, j))
        scale_in = [scale_spec, scale_spec]
        scale_args = [jnp.swapaxes(k_scale, 1, 2),
                      jnp.swapaxes(v_scale, 1, 2)]
    name = "attn/decode_pallas_int8" if quantized else \
        "attn/decode_pallas"
    with jax.named_scope(name):
        out = pl.pallas_call(
            functools.partial(_decode_kernel, scale=scale, block_k=bk,
                              num_kv=grid[2], quantized=quantized),
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=1,
                grid=grid,
                in_specs=qkv_specs + scale_in,
                **common,
            ),
            compiler_params=_CompilerParams(
                dimension_semantics=("parallel", "parallel",
                                     "arbitrary")),
            out_shape=jax.ShapeDtypeStruct((B, H, _DECODE_QROWS, D),
                                           q.dtype),
            interpret=_use_interpret(),
        )(lengths, qp, k, v, *scale_args)
        return out[:, :, 0]


def make_flash_attention_fn(mesh=None, *, causal: bool = True,
                            block_q: int = 1024, block_k: int = 1024,
                            rope_theta: Optional[float] = None,
                            pack2: Optional[bool] = None):
    """Mesh-aware flash attention (drop-in for ``make_ring_attention_fn``).

    A ``pallas_call`` has no SPMD partitioning rule, so on a >1-device
    mesh the kernel runs under ``shard_map``: batch over (dp, fsdp),
    heads over tp — each device runs the kernel on its local shard.
    Sequence stays unsharded (sp>1 uses ring attention instead).

    With ``rope_theta`` the returned fn accepts ``positions`` and
    applies RoPE inside the kernels (``fn.fused_rope`` marks this so
    the model skips its own rotation).

    ``pack2`` pins the two-head lane-packing choice (default: the
    process-wide :func:`attention_config`); note a tp-sharded mesh
    hands each device its *local* head count, which is what the
    even-head gate sees.
    """
    fn = functools.partial(flash_attention, causal=causal,
                           block_q=block_q, block_k=block_k,
                           pack2=pack2)
    if rope_theta is not None:
        fn = functools.partial(fn, rope_theta=rope_theta)
    if mesh is None or getattr(mesh, "size", 1) <= 1:
        fn.fused_rope = rope_theta is not None
        return fn

    from jax.sharding import PartitionSpec as P

    from ray_tpu.parallel.compat import shard_map
    from ray_tpu.parallel.sharding import data_axes

    tp = "tp" if mesh.shape.get("tp", 1) > 1 else None
    spec = P(data_axes(mesh), None, tp, None)
    bseq = P(data_axes(mesh), None)     # [B, S] leaves (packed batches)

    # packed (segment_ids) batches shard over batch like q/k/v; rope —
    # when fused — is applied per-shard from the per-row positions
    # before the XLA segment formulation (pallas declines anyway)
    @functools.partial(shard_map, mesh=mesh,
                       in_specs=(spec,) * 3 + (bseq, bseq),
                       out_specs=spec)
    def sharded_seg_rope(q, k, v, positions, segment_ids):
        q = rope_rotate(q, positions, rope_theta or 10000.0)
        k = rope_rotate(k, positions, rope_theta or 10000.0)
        return segment_attention(q, k, v, segment_ids, causal=causal)

    @functools.partial(shard_map, mesh=mesh,
                       in_specs=(spec,) * 3 + (bseq,), out_specs=spec)
    def sharded_seg(q, k, v, segment_ids):
        return segment_attention(q, k, v, segment_ids, causal=causal)

    if rope_theta is not None:
        @functools.partial(shard_map, mesh=mesh,
                           in_specs=(spec,) * 3 + (P(None),),
                           out_specs=spec)
        def sharded(q, k, v, positions):
            return fn(q, k, v, positions=positions)

        def wrapped(q, k, v, positions, segment_ids=None):
            if segment_ids is not None:
                if positions.ndim == 1:      # one spec: always [B, S]
                    positions = jnp.broadcast_to(
                        positions[None], segment_ids.shape)
                return sharded_seg_rope(q, k, v, positions,
                                        segment_ids)
            return sharded(q, k, v, positions)

        wrapped.fused_rope = True
        return wrapped

    @functools.partial(shard_map, mesh=mesh, in_specs=(spec,) * 3,
                       out_specs=spec)
    def sharded(q, k, v):
        return fn(q, k, v)

    def sharded_fn(q, k, v, segment_ids=None):
        if segment_ids is not None:
            return sharded_seg(q, k, v, segment_ids)
        return sharded(q, k, v)

    sharded_fn.fused_rope = False
    return sharded_fn
