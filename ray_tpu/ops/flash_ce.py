"""Flash-CE: streamed-logits Pallas cross-entropy (fused vocab matmul
+ online logsumexp).

The loss block is the largest serialized chunk of the GPT-2 step after
the r06 attention rework: the no-remat CE writes a resident 4.9 GB f32
``[24576, 50304]`` logits tensor, reads it back for the lse/true-logit
reduces (~17 ms at HBM rate), keeps it alive across the whole backward,
and reads it a third time for the grad matmuls.  None of those passes
do MXU work — they only exist because XLA cannot compute a reduction
*inside* a matmul epilogue.

This kernel can.  Forward is a blocked matmul over the vocab dimension
whose epilogue maintains flash-attention-style online row statistics:

    for each vocab tile j:                    # [block_n, block_v] VMEM
        s    = x_blk @ head_blk               # MXU, f32 accumulation
        m    = max(m, rowmax(s))              # online max
        l    = l * exp(m_prev - m) + rowsum(exp(s - m))
        true += s[row, target[row]]           # one-hot dot, VPU select

so the ``[N, V]`` logits exist only as VMEM tiles — forward emits
``(sum_nll, n_valid)`` with only ``[N]``-sized residuals (lse and the
inputs), never touching HBM with anything vocab-sized.  Backward is
strip-mined the same way: each logits tile is recomputed in the input
dtype (bf16 on chip, f32 accumulation), ``dl = (p - onehot) * g·mask``
is formed in VMEM and fused straight into *both* grad matmuls:

    dx_blk   += dl @ head_blk^T               # accumulated in VMEM
    dhead[j] += x_blk^T @ dl                  # per-(row,vocab) partial

dx accumulates across the sequential vocab sweep in VMEM scratch (the
``ops/attention.py`` strip/accumulator idiom); dhead contributions are
emitted as per-row-block partials ``[N/block_n, d, V]`` and summed in
one XLA pass — the only vocab-sized HBM tensor in the whole path, a
write-once/read-once transient at ~1/13th the traffic of the logits
residual it replaces (and it vanishes from the *resident* footprint,
which is what re-opens the batch-32 probe the r05 recipe was capped
by).  Total matmul work is 4 vocab-matmul-equivalents (fwd, bwd
recompute, dx, dhead) vs the no-remat path's 3 — the bet recorded in
``docs/PERF.md`` is that one extra matmul at MXU rate beats 17 ms of
serialized HBM-rate reduces, *iff* the Pallas matmul is competitive
with XLA's 150+ TFLOPs at ``[24576, 768] x [768, 50304]``.

Handles: masked ``-1`` targets (excluded from both loss and grads),
vocab sizes that are not a multiple of the block (lane-aligned padding
with in-kernel column masking — V=50304 pads to the block grid, padded
columns contribute exp(-inf)=0), and row counts that are not a multiple
of ``block_n`` (zero-padded rows with ``-1`` targets).

Dispatch is owned by :func:`ce_config` — the single home for CE env
knobs (the round-5 ``RAY_TPU_CE_BF16_RESID`` astype round-trip was
measured dead (+2.5 ms: XLA materializes the f32 tensor anyway) and is
removed; ``RAY_TPU_FUSED_CE`` folded in as ``RAY_TPU_CE=fused``).
Unsupported shapes fall back to the dense XLA formulation; a Mosaic
compile failure on new hardware degrades loudly via ``bench.py``'s
fallback chain (flash → no-remat → chunked).

Reference role: the loss path of the reference's torch trainers
(``F.cross_entropy`` in ``train/torch/train_loop_utils.py``); the
streamed-logits design is TPU-first.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# one home for the Pallas infrastructure shims: the jax-version
# CompilerParams rename shim, interpret-mode policy, lane-padded
# row-stats convention, block resolution and env-knob readers are
# shared with the attention / fused-norm kernels via the substrate
from ray_tpu.ops.substrate import (NEG_INF as _NEG_INF, STATS_LANES,
                                   CompilerParams as _CompilerParams,
                                   Support, env_int, env_str,
                                   resolve_blocks,
                                   stats_in as _stats_in, supported,
                                   unsupported,
                                   use_interpret as _use_interpret)


@dataclasses.dataclass(frozen=True)
class CEConfig:
    """Loss-head schedule knobs, resolved once from the environment.

    The single home for CE env flags (consolidation precedent: r06's
    ``attention_config``; the dead ``RAY_TPU_CE_BF16_RESID`` knob was
    removed and ``RAY_TPU_FUSED_CE`` folded into ``mode``):

    - ``RAY_TPU_CE`` (default ``flash``): which CE custom path the
      model's loss head dispatches to for supported shapes —
      ``flash`` (this kernel), ``fused`` (bf16-resident logits,
      ``ops/fused_ce.py``), or ``xla`` (no custom path: the
      ``ce_chunk``-driven no-remat / chunked XLA formulations).
    - ``RAY_TPU_CE_BN`` / ``RAY_TPU_CE_BV`` (default 1024/1024):
      forward row/vocab blocking.
    - ``RAY_TPU_CE_BWD_BN`` / ``RAY_TPU_CE_BWD_BV`` (default
      1024/512): backward blocking — the bwd tile also carries the
      [bn, d] f32 dx accumulator, so it wants a narrower vocab block.
    """
    mode: str = "flash"
    block_n: int = 1024
    block_v: int = 1024
    bwd_block_n: int = 1024
    bwd_block_v: int = 512


_CONFIG: Optional[CEConfig] = None


def ce_config(refresh: bool = False) -> CEConfig:
    """The process-wide :class:`CEConfig` (env read once, cached).

    ``refresh=True`` re-reads the environment — for tests and A/B
    drivers that flip flags after import."""
    global _CONFIG
    if _CONFIG is None or refresh:
        _CONFIG = CEConfig(
            mode=env_str("RAY_TPU_CE", "flash"),
            block_n=env_int("RAY_TPU_CE_BN", 1024),
            block_v=env_int("RAY_TPU_CE_BV", 1024),
            bwd_block_n=env_int("RAY_TPU_CE_BWD_BN", 1024),
            bwd_block_v=env_int("RAY_TPU_CE_BWD_BV", 512),
        )
    return _CONFIG


def supports(N: int, d: int, V: int) -> bool:
    """Shapes the kernel grid can handle (callers fall back otherwise).

    N and V are padded to the block grid by the wrappers, so the only
    hard constraints are on the model dimension: it is the contraction
    lane dimension of every tile matmul and the dx accumulator width,
    so it must be lane-aligned and VMEM-sized."""
    return d % 128 == 0 and 0 < d <= 2048 and N > 0 and V > 1


def uses_flash_ce(N: int, d: int, V: int, *,
                  mode: Optional[str] = None,
                  n_devices: int = 1) -> bool:
    """Whether the model loss head takes the flash-CE path for this
    shape under the current :func:`ce_config` (``mode`` overrides the
    config, for A/B drivers) — the reporting mirror ``bench.py`` uses
    so the JSON line can't claim a schedule the dispatch declined.
    ``n_devices`` is the mesh size the loss head will run under: the
    dispatch declines sharded meshes (a ``pallas_call`` has no SPMD
    rule), so pass it for anything but a single-chip run."""
    if mode is None:
        mode = ce_config().mode
    return mode == "flash" and n_devices <= 1 and supports(N, d, V)


# block resolution and the lane-broadcast stats layout are the
# substrate's resolve_blocks/stats_in (this module wrote the originals;
# the alias keeps the call sites unchanged)
_blocks = resolve_blocks


# ---------------------------------------------------------------------------
# forward kernel
# ---------------------------------------------------------------------------

def _fwd_kernel(x_ref, h_ref, tgt_ref, *rest, block_n: int, block_v: int,
                num_v: int, v_real: Optional[int],
                norm_eps: Optional[float] = None):
    """``norm_eps`` (static): the final-norm prologue — ``x_ref`` holds
    the *raw* residual stream and the kernel computes
    ``y = rmsnorm(x) * scale`` once per row block (at ``j == 0``, into
    VMEM scratch every vocab tile then reuses), emitting the ``rstd``
    statistics as an extra ``[N]``-sized residual.  The norm work rides
    the matmul sweep instead of running as its own XLA fusion."""
    if norm_eps is not None:
        s_ref, lse_ref, true_ref, rstd_ref, m_sc, l_sc, t_sc, y_sc = rest
    else:
        lse_ref, true_ref, m_sc, l_sc, t_sc = rest
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        m_sc[:] = jnp.full_like(m_sc, _NEG_INF)
        l_sc[:] = jnp.zeros_like(l_sc)
        t_sc[:] = jnp.zeros_like(t_sc)
        if norm_eps is not None:
            r32 = x_ref[...].astype(jnp.float32)
            rstd = jax.lax.rsqrt(
                jnp.mean(r32 * r32, -1, keepdims=True) + norm_eps)
            y_sc[...] = (r32 * rstd * s_ref[...].astype(jnp.float32)
                         ).astype(y_sc.dtype)
            rstd_ref[0] = jnp.broadcast_to(rstd, rstd_ref.shape[1:])

    x = x_ref[...] if norm_eps is None else y_sc[...]
    s = jax.lax.dot_general(
        x, h_ref[...], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)              # [bn, bv]
    col = (j * block_v
           + jax.lax.broadcasted_iota(jnp.int32, (block_n, block_v), 1))
    if v_real is not None:
        s = jnp.where(col < v_real, s, _NEG_INF)
    # true-logit gather: exactly one column matches the row's target
    # (none for masked -1 targets), so a select+rowsum is the gather
    tgt = tgt_ref[0][:, 0:1]                             # [bn, 1] int32
    t_sc[:] += jnp.sum(jnp.where(col == tgt, s, 0.0), 1, keepdims=True)
    m_prev = m_sc[:]                                     # [bn, 128]
    m_new = jnp.maximum(m_prev, jnp.max(s, 1, keepdims=True))
    alpha = jnp.exp(m_prev - m_new)
    p = jnp.exp(s - m_new[:, :1])
    l_sc[:] = l_sc[:] * alpha + jnp.sum(p, 1, keepdims=True)
    m_sc[:] = m_new

    @pl.when(j == num_v - 1)
    def _finalize():
        l = jnp.maximum(l_sc[:, :1], 1e-30)
        lse = m_sc[:, :1] + jnp.log(l)                   # [bn, 1]
        lse_ref[0] = jnp.broadcast_to(lse, lse_ref.shape[1:])
        true_ref[0] = jnp.broadcast_to(t_sc[:, :1], true_ref.shape[1:])


def _fwd_pallas(x, head, targets, *, block_n: int, block_v: int,
                norm=None):
    """x [N, d], head [d, V], targets [N] int32 (-1 = masked) ->
    (lse [N] f32, true_logit [N] f32) with no [N, V] materialization.

    ``norm``: optional ``(scale [d], eps)`` — the final-norm prologue;
    ``x`` is then the raw residual stream and the return gains
    ``rstd [N] f32``."""
    N, d = x.shape
    V = head.shape[1]
    bn, bv, Np, Vp = _blocks(N, V, block_n, block_v)
    num_n, num_v = Np // bn, Vp // bv
    if Np != N:
        x = jnp.pad(x, ((0, Np - N), (0, 0)))
        targets = jnp.pad(targets, (0, Np - N), constant_values=-1)
    if Vp != V:
        head = jnp.pad(head, ((0, 0), (0, Vp - V)))
    tstats = _stats_in(targets.astype(jnp.int32), num_n, bn)

    stats_spec = pl.BlockSpec((1, bn, STATS_LANES), lambda i, j: (i, 0, 0))
    stats_shape = jax.ShapeDtypeStruct((num_n, bn, STATS_LANES),
                                       jnp.float32)
    norm_args, norm_in, norm_out, norm_shape, norm_sc = \
        (), [], [], [], []
    if norm is not None:
        scale, eps = norm
        norm_args = (scale[None, :],)
        norm_in = [pl.BlockSpec((1, d), lambda i, j: (0, 0))]
        norm_out = [stats_spec]
        norm_shape = [stats_shape]
        norm_sc = [pltpu.VMEM((bn, d), x.dtype)]
    out = pl.pallas_call(
        functools.partial(_fwd_kernel, block_n=bn, block_v=bv,
                          num_v=num_v,
                          v_real=V if Vp != V else None,
                          norm_eps=norm[1] if norm else None),
        grid=(num_n, num_v),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        in_specs=[
            pl.BlockSpec((bn, d), lambda i, j: (i, 0)),
            pl.BlockSpec((d, bv), lambda i, j: (0, j)),
            stats_spec,
            *norm_in,
        ],
        out_specs=[stats_spec, stats_spec, *norm_out],
        out_shape=[stats_shape, stats_shape, *norm_shape],
        scratch_shapes=[
            pltpu.VMEM((bn, 128), jnp.float32),
            pltpu.VMEM((bn, 128), jnp.float32),
            pltpu.VMEM((bn, 128), jnp.float32),
            *norm_sc,
        ],
        interpret=_use_interpret(),
    )(x, head, tstats, *norm_args)
    flat = tuple(o[:, :, 0].reshape(Np)[:N] for o in out)
    return flat          # (lse, true[, rstd])


# ---------------------------------------------------------------------------
# backward kernel
# ---------------------------------------------------------------------------

def _bwd_kernel(x_ref, h_ref, tgt_ref, lse_ref, srow_ref,
                *rest, block_n: int, block_v: int,
                num_v: int, v_real: Optional[int],
                norm_eps: Optional[float] = None):
    """``norm_eps`` (static): the final-norm prologue's backward —
    ``x_ref`` holds the raw residual stream, the normed ``y`` is
    recomputed into VMEM scratch from the saved ``rstd`` (both matmuls
    contract against it), and at the end of the vocab sweep the
    accumulated ``dy`` takes the norm backward *in-kernel*: ``dx``
    becomes the residual-stream gradient and the norm-scale gradient
    is emitted as a per-row-block ``[d]`` partial (summed in one XLA
    pass by the wrapper) — no standalone ``[d]``-output reduction
    dispatch survives."""
    if norm_eps is not None:
        (s_ref, rstd_ref, dx_ref, dhp_ref, dsp_ref,
         dx_sc, y_sc) = rest
    else:
        dx_ref, dhp_ref, dx_sc = rest
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        dx_sc[:] = jnp.zeros_like(dx_sc)
        if norm_eps is not None:
            r32 = x_ref[...].astype(jnp.float32)
            rstd = rstd_ref[0][:, 0:1]
            y_sc[...] = (r32 * rstd * s_ref[...].astype(jnp.float32)
                         ).astype(y_sc.dtype)

    x = x_ref[...] if norm_eps is None else y_sc[...]    # [bn, d]
    h = h_ref[...]                                       # [d, bv]
    s = jax.lax.dot_general(
        x, h, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)              # recompute tile
    col = (j * block_v
           + jax.lax.broadcasted_iota(jnp.int32, (block_n, block_v), 1))
    if v_real is not None:
        s = jnp.where(col < v_real, s, _NEG_INF)
    p = jnp.exp(s - lse_ref[0][:, 0:1])   # padded cols: exp(-inf) = 0
    onehot = jnp.where(col == tgt_ref[0][:, 0:1], 1.0, 0.0)
    # (p - onehot) scaled by the incoming cotangent x row mask, cast to
    # the input dtype, fused straight into BOTH grad matmuls — the tile
    # never leaves VMEM
    dl = ((p - onehot) * srow_ref[0][:, 0:1]).astype(h.dtype)
    dx_sc[:] += jax.lax.dot_general(
        dl, h, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)              # [bn, d]
    dhp_ref[0] = jax.lax.dot_general(
        x, dl, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32).astype(dhp_ref.dtype)

    @pl.when(j == num_v - 1)
    def _finalize():
        if norm_eps is None:
            dx_ref[...] = dx_sc[:].astype(dx_ref.dtype)
        else:
            dy = dx_sc[:]                                # [bn, d] f32
            r32 = x_ref[...].astype(jnp.float32)
            rstd = rstd_ref[0][:, 0:1]
            xhat = r32 * rstd
            dxhat = dy * s_ref[...].astype(jnp.float32)
            m = jnp.mean(dxhat * xhat, -1, keepdims=True)
            dx_ref[...] = (rstd * (dxhat - xhat * m)).astype(dx_ref.dtype)
            dsp_ref[...] = jnp.sum(dy * xhat, 0, keepdims=True)


def _bwd_pallas(x, head, targets, lse, gs, *, block_n: int,
                block_v: int, norm=None):
    """Strip-mined backward: (residuals, d(sum_nll)) -> (dx, dhead).

    dx accumulates across the vocab sweep in VMEM scratch; dhead is
    emitted as ``[num_n, d, V]`` per-row-block partials (each written
    exactly once, at matmul rate) and summed in one XLA pass — the
    write-once/read-once analogue of attention's dk/dv scratch, sized
    for a head too large to ride along in VMEM.

    ``norm``: optional ``(scale [d], eps, rstd [N] f32)`` — the
    final-norm prologue's backward; the return gains ``dscale [d]``
    (from per-row-block partials, same one-XLA-pass sum as dhead) and
    ``dx`` is the *residual-stream* gradient."""
    N, d = x.shape
    V = head.shape[1]
    bn, bv, Np, Vp = _blocks(N, V, block_n, block_v)
    num_n, num_v = Np // bn, Vp // bv
    rstd = norm[2] if norm is not None else None
    if Np != N:
        x = jnp.pad(x, ((0, Np - N), (0, 0)))
        targets = jnp.pad(targets, (0, Np - N), constant_values=-1)
        lse = jnp.pad(lse, (0, Np - N))
        if rstd is not None:
            rstd = jnp.pad(rstd, (0, Np - N))
    if Vp != V:
        head = jnp.pad(head, ((0, 0), (0, Vp - V)))
    targets = targets.astype(jnp.int32)
    # per-row scale: the sum_nll cotangent where the target is live
    srow = jnp.where(targets >= 0, gs.astype(jnp.float32), 0.0)
    tstats = _stats_in(targets, num_n, bn)
    lstats = _stats_in(lse.astype(jnp.float32), num_n, bn)
    sstats = _stats_in(srow, num_n, bn)

    stats_spec = pl.BlockSpec((1, bn, STATS_LANES), lambda i, j: (i, 0, 0))
    norm_args, norm_in, norm_out, norm_shape, norm_sc = \
        (), [], [], [], []
    if norm is not None:
        scale, eps = norm[0], norm[1]
        norm_args = (scale[None, :], _stats_in(rstd, num_n, bn))
        norm_in = [pl.BlockSpec((1, d), lambda i, j: (0, 0)),
                   stats_spec]
        norm_out = [pl.BlockSpec((1, d), lambda i, j: (i, 0))]
        norm_shape = [jax.ShapeDtypeStruct((num_n, d), jnp.float32)]
        norm_sc = [pltpu.VMEM((bn, d), x.dtype)]
    out = pl.pallas_call(
        functools.partial(_bwd_kernel, block_n=bn, block_v=bv,
                          num_v=num_v,
                          v_real=V if Vp != V else None,
                          norm_eps=norm[1] if norm else None),
        grid=(num_n, num_v),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        in_specs=[
            pl.BlockSpec((bn, d), lambda i, j: (i, 0)),
            pl.BlockSpec((d, bv), lambda i, j: (0, j)),
            stats_spec,
            stats_spec,
            stats_spec,
            *norm_in,
        ],
        out_specs=[
            pl.BlockSpec((bn, d), lambda i, j: (i, 0)),
            pl.BlockSpec((1, d, bv), lambda i, j: (i, 0, j)),
            *norm_out,
        ],
        out_shape=[
            jax.ShapeDtypeStruct((Np, d), x.dtype),
            jax.ShapeDtypeStruct((num_n, d, Vp), head.dtype),
            *norm_shape,
        ],
        scratch_shapes=[pltpu.VMEM((bn, d), jnp.float32), *norm_sc],
        interpret=_use_interpret(),
    )(x, head, tstats, lstats, sstats, *norm_args)
    dx, dhp = out[0], out[1]
    dhead = jnp.sum(dhp.astype(jnp.float32), axis=0)[:, :V]
    if norm is None:
        return dx[:N], dhead.astype(head.dtype)
    # per-row-block dscale partials summed in ONE XLA pass — this sum
    # replaces the standalone [d]-output reduction dispatch
    dscale = jnp.sum(out[2], axis=0)
    return dx[:N], dhead.astype(head.dtype), dscale


# ---------------------------------------------------------------------------
# custom VJP + public API
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_ce(x, head, targets, block_n, block_v, bwd_block_n,
              bwd_block_v):
    out, _ = _flash_ce_fwd(x, head, targets, block_n, block_v,
                           bwd_block_n, bwd_block_v)
    return out


def _flash_ce_fwd(x, head, targets, block_n, block_v, bwd_block_n,
                  bwd_block_v):
    lse, true = _fwd_pallas(x, head, targets, block_n=block_n,
                            block_v=block_v)
    mask = (targets >= 0).astype(jnp.float32)
    out = (jnp.sum((lse - true) * mask), jnp.sum(mask))
    # residuals are [N]-sized (plus the inputs the grads contract
    # against) — nothing vocab-shaped survives the forward
    return out, (x, head, targets, lse)


def _flash_ce_bwd(block_n, block_v, bwd_block_n, bwd_block_v, res, g):
    x, head, targets, lse = res
    gs, _ = g                                  # d/d(sum_nll); n is count
    dx, dhead = _bwd_pallas(x, head, targets, lse, jnp.asarray(gs),
                            block_n=bwd_block_n, block_v=bwd_block_v)
    return dx, dhead, None


_flash_ce.defvjp(_flash_ce_fwd, _flash_ce_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def _flash_ce_norm(x, head, targets, scale, eps, block_n, block_v,
                   bwd_block_n, bwd_block_v):
    out, _ = _flash_ce_norm_fwd(x, head, targets, scale, eps, block_n,
                                block_v, bwd_block_n, bwd_block_v)
    return out


def _flash_ce_norm_fwd(x, head, targets, scale, eps, block_n, block_v,
                       bwd_block_n, bwd_block_v):
    lse, true, rstd = _fwd_pallas(x, head, targets, block_n=block_n,
                                  block_v=block_v, norm=(scale, eps))
    mask = (targets >= 0).astype(jnp.float32)
    out = (jnp.sum((lse - true) * mask), jnp.sum(mask))
    # residuals stay [N]-sized: the raw residual stream, the stats
    # (lse + rstd) and the operands the grads contract against — the
    # normed hidden is recomputed per tile, never saved
    return out, (x, head, targets, scale, lse, rstd)


def _flash_ce_norm_bwd(eps, block_n, block_v, bwd_block_n, bwd_block_v,
                       res, g):
    x, head, targets, scale, lse, rstd = res
    gs, _ = g                                  # d/d(sum_nll); n is count
    dx, dhead, dscale = _bwd_pallas(
        x, head, targets, lse, jnp.asarray(gs),
        block_n=bwd_block_n, block_v=bwd_block_v,
        norm=(scale, eps, rstd))
    return dx, dhead, None, dscale.astype(scale.dtype)


_flash_ce_norm.defvjp(_flash_ce_norm_fwd, _flash_ce_norm_bwd)


def uses_flash_ce_norm(N: int, d: int, V: int, *,
                       mode: Optional[str] = None,
                       n_devices: int = 1,
                       norm: str = "rmsnorm",
                       has_bias: bool = False,
                       enabled: Optional[bool] = None) -> Support:
    """Dispatch gate (with reason) for the final-norm-fused CE path.

    The single source of the decision ``models.gpt.loss_fn`` makes
    before skipping the XLA final norm — also the ``bench.py``
    reporting mirror.  Requires the flash-CE path itself
    (:func:`uses_flash_ce`'s conditions) plus the fused-norm knob and
    a norm the prologue can fuse."""
    from ray_tpu.ops.fused_norm import fuse_config
    if enabled is None:
        enabled = fuse_config().enabled
    if not enabled:
        return unsupported("disabled (RAY_TPU_FUSE_NORM=0)")
    if norm != "rmsnorm":
        return unsupported(f"norm={norm!r}: only rmsnorm fuses")
    if has_bias:
        return unsupported("bias norms (GPT-2 exact-architecture mode) "
                           "stay on the XLA path")
    if not uses_flash_ce(N, d, V, mode=mode, n_devices=n_devices):
        return unsupported(
            f"flash-CE path declined (mode={mode or ce_config().mode!r}, "
            f"n_devices={n_devices}, N={N}, d={d}, V={V})")
    return supported("flash-CE with fused final-norm prologue")


def flash_ce_norm_sum(x, head, targets, norm_scale, *,
                      eps: float = 1e-6,
                      block_n: Optional[int] = None,
                      block_v: Optional[int] = None,
                      bwd_block_n: Optional[int] = None,
                      bwd_block_v: Optional[int] = None):
    """Final-norm-fused streamed-logits CE: ``(sum_nll, n_valid)``.

    x [N, d] is the *raw* residual stream (the model's final hidden,
    before its last norm); the kernel computes
    ``rmsnorm(x) * norm_scale`` in the vocab matmul's prologue — the
    normed tensor is never materialized in HBM, the norm statistics
    ride as ``[N]``-sized residuals, and the norm-scale gradient comes
    back through per-row-block partials.  Differentiable in
    (x, head, norm_scale).  Shapes :func:`supports` declines fall back
    to the unfused XLA formulation (norm then dense CE, same
    numerics)."""
    cfg = ce_config()
    N, d = x.shape
    V = head.shape[1]
    if not supports(N, d, V):
        with jax.named_scope("ce/norm_xla"):
            x32 = x.astype(jnp.float32)
            x32 = x32 * jax.lax.rsqrt(
                jnp.mean(x32 * x32, -1, keepdims=True) + eps)
            y = (x32 * norm_scale.astype(jnp.float32)).astype(x.dtype)
            return _xla_ce_sum(y, head, targets)
    with jax.named_scope("ce/flash_norm"):
        return _flash_ce_norm(x, head.astype(x.dtype), targets,
                              norm_scale, eps,
                              block_n or cfg.block_n,
                              block_v or cfg.block_v,
                              bwd_block_n or cfg.bwd_block_n,
                              bwd_block_v or cfg.bwd_block_v)


def _xla_ce_sum(x, head, targets):
    """Dense XLA reference (fallback for unsupported shapes; also the
    parity oracle in tests/test_ops.py)."""
    logits = jax.lax.dot_general(
        x, head, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    lse = jax.scipy.special.logsumexp(logits, axis=-1)
    true = jnp.take_along_axis(
        logits, jnp.maximum(targets, 0)[:, None], axis=-1)[:, 0]
    mask = (targets >= 0).astype(jnp.float32)
    return jnp.sum((lse - true) * mask), jnp.sum(mask)


def flash_ce_sum(x, head, targets, *, block_n: Optional[int] = None,
                 block_v: Optional[int] = None,
                 bwd_block_n: Optional[int] = None,
                 bwd_block_v: Optional[int] = None):
    """Streamed-logits cross-entropy: ``(sum_nll, n_valid)``.

    x [N, d] (bf16 ok), head [d, V], targets [N] int32 (-1 = masked).
    Differentiable in (x, head); the [N, V] logits are never
    materialized in either pass.  Blocks default to :func:`ce_config`;
    shapes :func:`supports` declines fall back to the dense XLA
    formulation (same numerics, no streaming)."""
    cfg = ce_config()
    N, d = x.shape
    V = head.shape[1]
    if not supports(N, d, V):
        with jax.named_scope("ce/xla"):
            return _xla_ce_sum(x, head, targets)
    with jax.named_scope("ce/flash"):
        return _flash_ce(x, head, targets,
                         block_n or cfg.block_n,
                         block_v or cfg.block_v,
                         bwd_block_n or cfg.bwd_block_n,
                         bwd_block_v or cfg.bwd_block_v)
