from ray_tpu.scripts.scripts import main

main()
