"""CLI (parity: ``python/ray/scripts/scripts.py``): status, list, summary,
timeline, memory, microbenchmark, dashboard against a live session.

Usage: ``python -m ray_tpu.scripts <command> [...]`` (also installed as
the ``ray-tpu`` entrypoint).  Commands attach to the newest live session's
control-plane socket, so they work from any terminal on the node.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import tempfile
import time
from typing import Optional


def _find_session_cp_sock() -> Optional[str]:
    import getpass
    root = os.path.join(tempfile.gettempdir(),
                        f"ray_tpu_{getpass.getuser()}")
    sessions = sorted(glob.glob(os.path.join(root, "session_*")),
                      key=os.path.getmtime, reverse=True)
    for session in sessions:
        # TCP sessions advertise their address in a file; UDS sessions
        # are found by the socket path itself.
        addr_file = os.path.join(session, "cp_address")
        if os.path.exists(addr_file):
            with open(addr_file) as f:
                return f.read().strip()
        sock = os.path.join(session, "sockets", "cp.sock")
        if os.path.exists(sock):
            return sock
    return None


def _connect_cp():
    from ray_tpu._private.protocol import RpcClient
    sock = _find_session_cp_sock()
    if sock is None:
        print("No live ray_tpu session found on this node.",
              file=sys.stderr)
        sys.exit(1)
    client = RpcClient(sock)
    try:
        client.call("ping")
    except (OSError, ConnectionError):
        print("Session socket exists but the control plane is not "
              "responding.", file=sys.stderr)
        sys.exit(1)
    return client


def cmd_status(args):
    cp = _connect_cp()
    nodes = cp.call("list_nodes")
    print(f"{'NODE':34} {'STATE':8} {'CPU':>10} {'TPU':>8} PENDING")
    for n in nodes:
        total = n.get("resources_total", {})
        avail = n.get("resources_available", {})
        cpu = f"{avail.get('CPU', 0):.0f}/{total.get('CPU', 0):.0f}"
        tpu = f"{avail.get('TPU', 0):.0f}/{total.get('TPU', 0):.0f}" \
            if total.get("TPU") else "-"
        load = n.get("load", {}).get("num_pending", 0)
        print(f"{n['node_id'].hex():34} {n['state']:8} {cpu:>10} "
              f"{tpu:>8} {load}")
    counters = cp.call("counters")
    if counters:
        print("\ncounters:")
        for k, v in sorted(counters.items())[:20]:
            print(f"  {k}: {v}")


def _parse_filter(expr: str):
    """``key<op>value`` -> (key, op, value); ops: != >= <= = > <``."""
    for op in ("!=", ">=", "<=", "=", ">", "<"):
        if op in expr:
            key, val = expr.split(op, 1)
            return (key.strip(), op, val.strip())
    raise SystemExit(f"bad --filter {expr!r} (want key=value)")


def cmd_list(args):
    cp = _connect_cp()
    kind = args.kind
    if kind == "nodes":
        rows = [{**n, "node_id": n["node_id"].hex()}
                for n in cp.call("list_nodes")]
    elif kind == "actors":
        rows = []
        for a in cp.call("list_actors"):
            rows.append({"actor_id": a["actor_id"].hex(),
                         "class": a.get("class_name"),
                         "state": a.get("state"),
                         "name": a.get("name"),
                         "pid": a.get("pid")})
    elif kind == "tasks":
        events = cp.call("list_task_events", 1000)
        latest = {}
        for ev in events:
            latest[ev["task_id"]] = ev
        rows = list(latest.values())
    elif kind == "objects":
        rows = cp.call("list_objects")[:100]
    elif kind == "placement-groups":
        rows = [{**p, "pg_id": p["pg_id"].hex()}
                for p in cp.call("list_placement_groups")]
    else:
        print(f"unknown kind {kind}", file=sys.stderr)
        sys.exit(1)
    if getattr(args, "filter", None):
        from ray_tpu.util.state import _match
        for expr in args.filter:
            key, op, val = _parse_filter(expr)
            rows = [r for r in rows if _match(r, key, op, val)]
    limit = getattr(args, "limit", None)
    if limit is not None:
        rows = rows[:limit]
    for row in rows:
        print(json.dumps(row, default=str))


def cmd_logs(args):
    """``ray-tpu logs`` — list worker/daemon log files across nodes;
    ``ray-tpu logs <name>`` tails one (parity: ``ray logs``,
    ``util/state/state_cli.py`` logs subcommand)."""
    from ray_tpu._private.protocol import RpcClient
    cp = _connect_cp()
    nodes = [n for n in cp.call("list_nodes")
             if n.get("state") == "ALIVE"]
    if args.node:
        nodes = [n for n in nodes
                 if n["node_id"].hex().startswith(args.node)]
        if not nodes:
            raise SystemExit(f"no alive node matches {args.node!r}")
    if not args.name:
        for n in nodes:
            nid = n["node_id"].hex()
            try:
                logs = RpcClient(n["sock_path"]).call("list_logs")
            except (OSError, ConnectionError) as e:
                print(f"[{nid[:12]}] unreachable: {e}", file=sys.stderr)
                continue
            for entry in logs:
                print(f"{nid[:12]}  {entry['size']:>10}  "
                      f"{entry['name']}")
        return
    for n in nodes:
        try:
            data = RpcClient(n["sock_path"]).call(
                "tail_log", args.name, args.tail)
        except (OSError, ConnectionError):
            continue  # node unreachable: try the rest
        if data is None:
            continue  # this node doesn't have the file
        sys.stdout.write(data.decode(errors="replace"))
        return
    raise SystemExit(f"log {args.name!r} not found on any node")


def cmd_summary(args):
    cp = _connect_cp()
    events = cp.call("list_task_events", 100000)
    states = {}
    for ev in events:
        states[ev.get("state")] = states.get(ev.get("state"), 0) + 1
    actors = cp.call("list_actors")
    astates = {}
    for a in actors:
        astates[a.get("state")] = astates.get(a.get("state"), 0) + 1
    print("task events:", json.dumps(states))
    print("actors:", json.dumps(astates))
    print("objects:", json.dumps(cp.call("objects_summary")))


def cmd_timeline(args):
    cp = _connect_cp()
    from ray_tpu._private.profiling import chrome_tracing_dump
    events = cp.call("list_task_events", 100000)
    out = args.output or "timeline.json"
    chrome_tracing_dump(events, out)
    print(f"wrote {out} ({len(events)} events); open in "
          "chrome://tracing or https://ui.perfetto.dev")


def cmd_memory(args):
    cp = _connect_cp()
    objs = cp.call("list_objects")
    total = sum(o.get("size", 0) for o in objs)
    print(f"{len(objs)} objects, {total / 2**20:.1f} MiB")
    for o in sorted(objs, key=lambda o: -o.get("size", 0))[:20]:
        print(f"  {o['object_id'][:16]}  {o.get('size', 0):>12}  "
              f"{o.get('where')}")


def cmd_stack(args):
    """Dump python stacks of every worker on every node (reference:
    ``ray stack`` via py-spy; here workers' registered faulthandlers
    write to their session log files on SIGUSR1)."""
    import glob
    import os
    import time

    from ray_tpu._private import protocol
    cp = _connect_cp()
    total = []
    session_dirs = set()
    for info in cp.call("list_nodes"):
        if info.get("state") != "ALIVE":
            continue
        session_dirs.add(info.get("session_dir", ""))
        try:
            pids = protocol.RpcClient(info["sock_path"]).call(
                "signal_stack_dump")
            total.extend(pids)
            print(f"node {info['node_id'].hex()[:12]}: signalled "
                  f"{len(pids)} workers")
        except (OSError, ConnectionError) as e:
            print(f"node {info['node_id'].hex()[:12]}: unreachable ({e})")
    time.sleep(0.7)          # give faulthandler time to write
    shown = 0
    for sdir in session_dirs:
        for log in sorted(glob.glob(os.path.join(sdir, "logs",
                                                 "worker-*.log"))):
            try:
                with open(log) as f:
                    tail = f.readlines()[-120:]
            except OSError:
                continue
            # show from the LAST dump onward (one "Current thread"
            # header per faulthandler dump; older dumps are stale)
            start = None
            for i, line in enumerate(tail):
                if "Current thread" in line:
                    start = i
                elif start is None and "Thread 0x" in line:
                    start = i
            if start is not None:
                print(f"\n===== {os.path.basename(log)} =====")
                print("".join(tail[start:]).rstrip())
                shown += 1
    print(f"\n{len(total)} workers signalled, {shown} stack dumps shown")


def cmd_microbenchmark(args):
    import ray_tpu
    from ray_tpu._private import ray_perf
    ray_tpu.init()
    try:
        ray_perf.main(duration=args.duration)
    finally:
        ray_tpu.shutdown()


def cmd_dashboard(args):
    import ray_tpu
    ray_tpu.init(ignore_reinit_error=True)
    from ray_tpu.dashboard.app import Dashboard
    port = Dashboard(args.port).start()
    print(f"dashboard at http://127.0.0.1:{port}")
    import time
    while True:
        time.sleep(3600)


_HEAD_DAEMON = """
import signal
# block BEFORE sigwait: with the default disposition unblocked SIGTERM
# would kill the process and skip the graceful shutdown
signal.pthread_sigmask(signal.SIG_BLOCK, {{signal.SIGTERM,
                                           signal.SIGINT}})
import ray_tpu
ray_tpu.init(_system_config={system_config!r}, **{kwargs!r})
from ray_tpu._private.worker import global_node
print("ray_tpu head up:", global_node().cp_sock_path, flush=True)
signal.sigwait({{signal.SIGTERM, signal.SIGINT}})
ray_tpu.shutdown()
"""


def _pidfile() -> str:
    import getpass
    return os.path.join(tempfile.gettempdir(),
                        f"ray_tpu_{getpass.getuser()}", "daemons.pids")


def _record_pid(pid: int) -> None:
    path = _pidfile()
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "a") as f:
        f.write(f"{pid}\n")


def cmd_start(args):
    """``ray-tpu start --head`` / ``--address`` — standalone daemons
    (parity: ``ray start``).  The head runs as its own process; drivers
    attach with ``init(address='auto')``; worker nodes on any host join
    a TCP head with --address."""
    import subprocess
    import uuid
    if args.head:
        system_config = {}
        if args.tcp:
            system_config["use_tcp"] = True
            if args.node_ip:
                system_config["node_ip"] = args.node_ip
        if args.persist:
            system_config["cp_persistence"] = True
        kwargs = {}
        if args.num_cpus is not None:
            kwargs["num_cpus"] = args.num_cpus
        if args.num_tpus is not None:
            kwargs["num_tpus"] = args.num_tpus
        code = _HEAD_DAEMON.format(kwargs=kwargs,
                                   system_config=system_config)
        log_dir = os.path.dirname(_pidfile())
        os.makedirs(log_dir, exist_ok=True)
        log_path = os.path.join(log_dir, "head.log")
        log = open(log_path, "ab")
        # log file, not a pipe: the daemon outlives this CLI, and later
        # stdout writes to an abandoned pipe would BrokenPipeError it
        proc = subprocess.Popen([sys.executable, "-c", code],
                                stdout=log, stderr=subprocess.STDOUT,
                                start_new_session=True)
        log.close()
        deadline = time.time() + 60
        addr = None
        while time.time() < deadline:
            if proc.poll() is not None:
                with open(log_path) as f:
                    tail = f.read()[-2000:]
                print(f"head daemon exited rc={proc.returncode}:\n"
                      f"{tail}", file=sys.stderr)
                sys.exit(1)
            from ray_tpu._private.node import find_session_cp_address
            found = find_session_cp_address()
            if found:
                try:
                    from ray_tpu._private.protocol import RpcClient
                    RpcClient(found[0], connect_timeout=2.0).ping()
                    addr = found[0]
                    break
                except Exception:  # noqa: BLE001 — not up yet
                    pass
            time.sleep(0.3)
        if addr is None:
            print("head did not come up within 60s; see "
                  f"{log_path}", file=sys.stderr)
            sys.exit(1)
        _record_pid(proc.pid)
        print(f"ray_tpu head up: {addr} (pid {proc.pid}, "
              f"log {log_path})")
        print("attach drivers with: ray_tpu.init(address='auto')")
        return
    if not args.address:
        print("start needs --head or --address <cp_addr>",
              file=sys.stderr)
        sys.exit(2)
    # worker node daemon joining an existing (TCP) head
    from ray_tpu._private.protocol import RpcClient
    cp = RpcClient(args.address)
    cp.ping()
    node_id = uuid.uuid4().bytes[:16]
    local_dir = os.path.join(tempfile.gettempdir(),
                             f"ray_tpu_node_{node_id.hex()[:12]}")
    os.makedirs(os.path.join(local_dir, "sockets"), exist_ok=True)
    os.makedirs(os.path.join(local_dir, "logs"), exist_ok=True)
    shm_base = "/dev/shm" if os.path.isdir("/dev/shm") \
        else tempfile.gettempdir()
    res = {"CPU": float(args.num_cpus or os.cpu_count() or 1)}
    if args.num_tpus:
        res["TPU"] = float(args.num_tpus)
    from ray_tpu._private.node_proc import build_env
    env = dict(os.environ)
    env.update(build_env(
        session_dir=local_dir, cp_addr=args.address, node_id=node_id,
        shm_root=os.path.join(shm_base,
                              f"ray_tpu_node_{node_id.hex()[:12]}"),
        spill_dir=os.path.join(local_dir, "spill"), resources=res,
        use_tcp=args.address.startswith("tcp://"),
        node_ip=args.node_ip or "127.0.0.1"))
    log = open(os.path.join(local_dir, "logs", "node.log"), "ab")
    proc = subprocess.Popen(
        [sys.executable, "-m", "ray_tpu._private.node_proc"],
        env=env, stdout=log, stderr=subprocess.STDOUT,
        start_new_session=True)
    log.close()
    _record_pid(proc.pid)
    print(f"node {node_id.hex()[:12]} joining {args.address} "
          f"(pid {proc.pid}, logs {local_dir}/logs/node.log)")


def cmd_stop(args):
    """Kill daemons started by ``ray-tpu start`` on this host."""
    import signal
    path = _pidfile()
    if not os.path.exists(path):
        print("no ray_tpu daemons recorded")
        return
    with open(path) as f:
        pids = [int(ln) for ln in f.read().split() if ln.strip()]
    stopped = 0
    for pid in pids:
        try:
            os.kill(pid, signal.SIGTERM)
            stopped += 1
        except ProcessLookupError:
            pass
    os.unlink(path)
    print(f"stopped {stopped} daemon(s)")


def cmd_jobs(args):
    """``ray-tpu jobs ...`` against the live session's job table
    (parity: ``ray job submit/status/logs/list/stop``)."""
    import json
    if args.jobs_command == "submit":
        # submission starts a runtime in this shell, so the CLI always
        # waits for completion: exiting earlier would tear the runtime
        # (and the job's supervisor) down with it
        import ray_tpu
        from ray_tpu.job import JobSubmissionClient
        try:
            # a standing `ray-tpu start --head` session: attach so the
            # job runs on it and lands in the session's job table
            ray_tpu.init(address="auto", ignore_reinit_error=True)
        except Exception:  # noqa: BLE001 — no live session
            ray_tpu.init(ignore_reinit_error=True)
        c = JobSubmissionClient()
        jid = c.submit_job(entrypoint=args.entrypoint)
        print(jid)
        status = c.wait_until_finished(jid, timeout=args.timeout)
        print(status)
        print(c.get_job_logs(jid), end="")
        sys.exit(0 if status == "SUCCEEDED" else 1)
    client = _connect_cp()
    # read-only commands ride the CP KV of the running session
    if args.jobs_command == "list":
        for key in client.call("kv_keys", b"", "_jobs"):
            raw = client.call("kv_get", key, "_jobs")
            info = json.loads(raw.decode())
            print(f"{info['submission_id']}  {info['status']:9s}  "
                  f"{info['entrypoint'][:60]}")
    elif args.jobs_command == "status":
        raw = client.call("kv_get", args.job_id.encode(), "_jobs")
        if raw is None:
            print(f"no job {args.job_id}", file=sys.stderr)
            sys.exit(1)
        print(json.loads(raw.decode())["status"])


def main(argv=None):
    parser = argparse.ArgumentParser(prog="ray-tpu")
    sub = parser.add_subparsers(dest="command", required=True)
    sub.add_parser("status")
    p_list = sub.add_parser("list")
    p_list.add_argument("kind", choices=["nodes", "actors", "tasks",
                                         "objects", "placement-groups"])
    p_list.add_argument("--filter", action="append", default=[],
                        help="key<op>value predicate (= != < <= > >=); "
                             "repeatable, ANDed")
    p_list.add_argument("--limit", type=int, default=None)
    p_logs = sub.add_parser("logs")
    p_logs.add_argument("name", nargs="?", default=None,
                        help="log file to tail (omit to list)")
    p_logs.add_argument("--node", default=None,
                        help="node id prefix to restrict to")
    p_logs.add_argument("--tail", type=int, default=65536,
                        help="bytes from the end to print")
    sub.add_parser("summary")
    p_tl = sub.add_parser("timeline")
    p_tl.add_argument("--output", "-o", default=None)
    sub.add_parser("memory")
    sub.add_parser("stack")
    p_mb = sub.add_parser("microbenchmark")
    p_mb.add_argument("--duration", type=float, default=2.0)
    p_db = sub.add_parser("dashboard")
    p_db.add_argument("--port", type=int, default=8265)
    p_start = sub.add_parser("start")
    p_start.add_argument("--head", action="store_true")
    p_start.add_argument("--address", default=None)
    p_start.add_argument("--num-cpus", type=float, default=None,
                         dest="num_cpus")
    p_start.add_argument("--num-tpus", type=float, default=None,
                         dest="num_tpus")
    p_start.add_argument("--tcp", action="store_true",
                         help="bind the head on TCP (multi-host)")
    p_start.add_argument("--node-ip", default=None, dest="node_ip")
    p_start.add_argument("--persist", action="store_true",
                         help="journal the control plane (restartable)")
    sub.add_parser("stop")
    p_jobs = sub.add_parser("jobs")
    jobs_sub = p_jobs.add_subparsers(dest="jobs_command", required=True)
    p_submit = jobs_sub.add_parser("submit")
    p_submit.add_argument("entrypoint")
    p_submit.add_argument("--timeout", type=float, default=600.0)
    jobs_sub.add_parser("list")
    p_jstat = jobs_sub.add_parser("status")
    p_jstat.add_argument("job_id")
    args = parser.parse_args(argv)
    {"status": cmd_status, "list": cmd_list, "summary": cmd_summary,
     "timeline": cmd_timeline, "memory": cmd_memory,
     "stack": cmd_stack, "logs": cmd_logs,
     "microbenchmark": cmd_microbenchmark,
     "dashboard": cmd_dashboard, "jobs": cmd_jobs,
     "start": cmd_start, "stop": cmd_stop}[args.command](args)


if __name__ == "__main__":
    main()
