"""Compiled DAG execution over mutable channels.

Parity: ``python/ray/dag/compiled_dag_node.py:174`` — compiling an actor
DAG replaces per-call task submission (control-plane round trips, object
commits, scheduling) with standing *executor loops*: each actor blocks
in a loop reading its input channels, invoking its bound method, and
writing its output channel.  After compilation a call is just shm
writes/reads — the mechanism for tight same-host actor pipelines (on a
TPU VM: the host-side step loop around device computation).

Supported graph shape: ``MethodNode``s over distinct actors whose args
are the ``InputNode``, other MethodNodes, or constants; single output
node (or ``MultiOutputNode`` of MethodNodes).  ``experimental_compile``
on such a DAG returns a :class:`CompiledDAG`.
"""

from __future__ import annotations

import os
import uuid
from typing import Any, Dict, List, Optional

import ray_tpu
from ray_tpu.experimental.channel import Channel, ChannelClosed


class _DagError:
    """An exception captured in one stage, forwarded through channels so
    the driver (not a 60s channel timeout) surfaces it."""

    def __init__(self, exc: BaseException, tb: str):
        self.exc = exc
        self.tb = tb


def _executor_loop(instance, method_name: str, in_channels,
                   in_kinds, consts, out_channel, reader_indices):
    """Standing loop run inside the actor via ``__ray_call__``."""
    import traceback
    method = getattr(instance, method_name)
    try:
        while True:
            args = []
            failed = None
            for ch, kind, idx in zip(in_channels, in_kinds,
                                     reader_indices):
                if kind == "const":
                    args.append(ch)     # ch is the constant itself
                else:
                    value = ch.read(reader_index=idx, timeout=None)
                    if isinstance(value, _DagError) and failed is None:
                        failed = value
                    args.append(value)
            if failed is not None:
                out_channel.write(failed, timeout=None)
                continue        # poisoned input: forward, stay alive
            try:
                result = method(*args)
            except BaseException as e:  # noqa: BLE001 — ship to driver
                result = _DagError(e, traceback.format_exc())
            out_channel.write(result, timeout=None)
    except ChannelClosed:
        out_channel.close()     # propagate shutdown downstream
        return "closed"


class CompiledDAGFuture:
    """Result handle for one ``execute`` (read once, in order)."""

    def __init__(self, dag: "CompiledDAG", seq: int):
        self._dag = dag
        self._seq = seq

    def get(self, timeout: Optional[float] = 60.0):
        return self._dag._read_result(self._seq, timeout)


class CompiledDAG:
    def __init__(self, output_node, channel_capacity: int = 1 << 20):
        from ray_tpu.dag import (InputNode, MethodNode, MultiOutputNode)
        self._chan_dir = None
        self._channels: List[Channel] = []
        self._loop_refs = []
        self._submitted = 0
        self._read = 0
        self._results: Dict[int, Any] = {}

        if isinstance(output_node, MultiOutputNode):
            outputs = list(output_node.outputs)
        else:
            outputs = [output_node]
        if not all(isinstance(o, MethodNode) for o in outputs):
            raise TypeError("compiled DAG outputs must be actor method "
                            "nodes")

        # ---- walk the graph: topological order over MethodNodes ------
        order: List[Any] = []
        seen: Dict[int, bool] = {}

        def visit(node):
            if id(node) in seen:
                return
            seen[id(node)] = True
            if node.kwargs:
                raise TypeError(
                    "compiled DAGs support positional args only "
                    f"(node {node.method!r} binds kwargs "
                    f"{sorted(node.kwargs)})")
            for a in node.args:
                if isinstance(a, MethodNode):
                    visit(a)
                elif isinstance(a, InputNode):
                    pass
                elif isinstance(a, (list, dict, set)):
                    raise TypeError(
                        "compiled DAGs take leaf args only")
            order.append(node)

        for o in outputs:
            visit(o)

        # one actor per node (an actor's loop serves exactly one node)
        actors = {}
        for node in order:
            handle = node.class_node._get_handle({}, ())
            if id(node.class_node) in actors:
                raise ValueError(
                    "compiled DAGs currently bind one method per actor")
            actors[id(node.class_node)] = handle

        # ---- channels -------------------------------------------------
        session_tmp = os.environ.get("TMPDIR", "/dev/shm")
        self._chan_dir = os.path.join(
            session_tmp, f"ray_tpu_dag_{uuid.uuid4().hex[:8]}")
        os.makedirs(self._chan_dir, exist_ok=True)

        def new_channel(name: str, num_readers: int) -> Channel:
            ch = Channel(os.path.join(self._chan_dir, name),
                         capacity=channel_capacity,
                         num_readers=num_readers)
            self._channels.append(ch)
            return ch

        # readers per producer: downstream nodes + driver (for outputs)
        consumers: Dict[int, int] = {}
        for node in order:
            for a in node.args:
                if isinstance(a, MethodNode):
                    consumers[id(a)] = consumers.get(id(a), 0) + 1
        input_consumers = sum(
            1 for node in order for a in node.args
            if isinstance(a, InputNode))
        for o in outputs:
            consumers[id(o)] = consumers.get(id(o), 0) + 1

        self._input_channel = new_channel("input", max(input_consumers,
                                                       1))
        out_channels: Dict[int, Channel] = {}
        for i, node in enumerate(order):
            out_channels[id(node)] = new_channel(
                f"node{i}", consumers.get(id(node), 1))

        # ---- start executor loops ------------------------------------
        input_reader_next = [0]

        def claim_input_reader() -> int:
            idx = input_reader_next[0]
            input_reader_next[0] += 1
            return idx

        reader_claims: Dict[int, int] = {}   # producer id -> next index

        for node in order:
            in_chs, kinds, idxs = [], [], []
            for a in node.args:
                from ray_tpu.dag import InputNode, MethodNode
                if isinstance(a, InputNode):
                    in_chs.append(self._input_channel)
                    kinds.append("chan")
                    idxs.append(claim_input_reader())
                elif isinstance(a, MethodNode):
                    producer = out_channels[id(a)]
                    nxt = reader_claims.get(id(a), 0)
                    reader_claims[id(a)] = nxt + 1
                    in_chs.append(producer)
                    kinds.append("chan")
                    idxs.append(nxt)
                else:
                    in_chs.append(a)
                    kinds.append("const")
                    idxs.append(0)
            handle = actors[id(node.class_node)]
            ref = handle.__ray_call__.remote(
                _executor_loop, node.method, in_chs, kinds, None,
                out_channels[id(node)], idxs)
            self._loop_refs.append(ref)

        # driver reads each output with the producer's last reader index
        self._output_readers = []
        for o in outputs:
            nxt = reader_claims.get(id(o), 0)
            reader_claims[id(o)] = nxt + 1
            self._output_readers.append((out_channels[id(o)], nxt))
        self._multi = isinstance(output_node, MultiOutputNode)

    # ------------------------------------------------------------------
    def execute(self, value: Any) -> CompiledDAGFuture:
        self._input_channel.write(value)
        fut = CompiledDAGFuture(self, self._submitted)
        self._submitted += 1
        return fut

    def _read_result(self, seq: int, timeout: Optional[float]):
        if seq in self._results:
            out = self._results.pop(seq)
        else:
            out = None
            while self._read <= seq:
                vals = [ch.read(reader_index=idx, timeout=timeout)
                        for ch, idx in self._output_readers]
                got = vals if self._multi else vals[0]
                if self._read == seq:
                    self._read += 1
                    out = got
                    break
                self._results[self._read] = got
                self._read += 1
            else:
                raise RuntimeError(f"result {seq} already consumed")
        errs = out if isinstance(out, list) else [out]
        for e in errs:
            if isinstance(e, _DagError):
                raise RuntimeError(
                    f"compiled DAG stage raised:\n{e.tb}") from e.exc
        return out

    def teardown(self) -> None:
        for ch in self._channels:
            ch.close()
        # loops observe the poison and return; collect them briefly
        for ref in self._loop_refs:
            try:
                ray_tpu.get(ref, timeout=10)
            except Exception:  # noqa: BLE001
                pass
        for ch in self._channels:
            ch.unlink()
        try:
            if self._chan_dir:
                os.rmdir(self._chan_dir)
        except OSError:
            pass

    def __del__(self):
        # close (unblocks loops) AND unlink: a dropped CompiledDAG must
        # not leak nodes+1 shm files per compile
        try:
            for ch in self._channels:
                ch.unlink()
            if self._chan_dir:
                os.rmdir(self._chan_dir)
        except Exception:  # noqa: BLE001
            pass
