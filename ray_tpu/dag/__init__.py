"""DAG building (parity: ``python/ray/dag``): ``fn.bind(...)`` /
``Cls.bind(...)`` build a lazy graph; ``.execute()`` submits it as
regular tasks, ``.experimental_compile()`` turns an actor DAG into a
standing pipeline over mutable shm channels
(``ray_tpu.dag.compiled``, parity: ``compiled_dag_node.py``).
"""

from __future__ import annotations

from typing import Any, Dict, List, Tuple


class DAGNode:
    def execute(self, *args):
        refs = self._resolve({}, args)
        return refs

    def _resolve(self, cache: Dict[int, Any], exec_args: Tuple):
        raise NotImplementedError

    def experimental_compile(self, channel_capacity: int = 1 << 20):
        """Compile an actor DAG into a standing channel pipeline."""
        from ray_tpu.dag.compiled import CompiledDAG
        return CompiledDAG(self, channel_capacity=channel_capacity)


class InputNode(DAGNode):
    """Placeholder for execute()-time arguments: ``with InputNode() as x``."""

    _CURRENT: List["InputNode"] = []

    def __init__(self, index: int = 0):
        self.index = index

    def __enter__(self):
        InputNode._CURRENT.append(self)
        return self

    def __exit__(self, *exc):
        InputNode._CURRENT.pop()

    def _resolve(self, cache, exec_args):
        return exec_args[self.index]


class FunctionNode(DAGNode):
    def __init__(self, remote_fn, args: Tuple, kwargs: Dict[str, Any]):
        self.remote_fn = remote_fn
        self.args = args
        self.kwargs = kwargs

    def _resolve(self, cache, exec_args):
        key = id(self)
        if key in cache:
            return cache[key]

        def value_of(a):
            return (a._resolve(cache, exec_args)
                    if isinstance(a, DAGNode) else a)

        ref = self.remote_fn.remote(
            *[value_of(a) for a in self.args],
            **{k: value_of(v) for k, v in self.kwargs.items()})
        cache[key] = ref
        return ref

    def execute(self, *args):
        return self._resolve({}, args)


class ClassNode(DAGNode):
    def __init__(self, actor_cls, args: Tuple, kwargs: Dict[str, Any]):
        self.actor_cls = actor_cls
        self.args = args
        self.kwargs = kwargs
        self._handle = None

    def _get_handle(self, cache, exec_args):
        if self._handle is None:
            def value_of(a):
                return (a._resolve(cache, exec_args)
                        if isinstance(a, DAGNode) else a)
            self._handle = self.actor_cls.remote(
                *[value_of(a) for a in self.args],
                **{k: value_of(v) for k, v in self.kwargs.items()})
        return self._handle

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        return _MethodNodeFactory(self, name)

    def _resolve(self, cache, exec_args):
        return self._get_handle(cache, exec_args)


class _MethodNodeFactory:
    def __init__(self, class_node: ClassNode, method: str):
        self.class_node = class_node
        self.method = method

    def bind(self, *args, **kwargs) -> "MethodNode":
        return MethodNode(self.class_node, self.method, args, kwargs)


class MethodNode(DAGNode):
    def __init__(self, class_node: ClassNode, method: str, args, kwargs):
        self.class_node = class_node
        self.method = method
        self.args = args
        self.kwargs = kwargs

    def _resolve(self, cache, exec_args):
        key = id(self)
        if key in cache:
            return cache[key]
        handle = self.class_node._get_handle(cache, exec_args)

        def value_of(a):
            return (a._resolve(cache, exec_args)
                    if isinstance(a, DAGNode) else a)

        ref = getattr(handle, self.method).remote(
            *[value_of(a) for a in self.args],
            **{k: value_of(v) for k, v in self.kwargs.items()})
        cache[key] = ref
        return ref

    def execute(self, *args):
        return self._resolve({}, args)


class MultiOutputNode(DAGNode):
    """Bundle several leaves into one executable DAG (parity:
    ``python/ray/dag/output_node.py``): ``execute()`` resolves every
    branch against one shared cache, so diamond dependencies submit
    each upstream task exactly once, and returns one ref per output."""

    def __init__(self, outputs):
        self.outputs = list(outputs)

    def __iter__(self):
        return iter(self.outputs)

    def __len__(self):
        return len(self.outputs)

    def __getitem__(self, i):
        return self.outputs[i]

    def _resolve(self, cache, exec_args):
        return [o._resolve(cache, exec_args) if isinstance(o, DAGNode)
                else o for o in self.outputs]
