"""Multi-tenant adapter serving (r25): LoRA factors as call args.

"Millions of users" is not one model — it is one base model plus
thousands of per-tenant low-rank adapters.  This package is the
multi-tenant seam across the stack:

- :mod:`~ray_tpu.adapters.lora` — the math: A/B factor initialization,
  merged-weights construction (the parity oracle), and the device-side
  **adapter bank** (``[N, L, in, r]`` stacked factors, slot 0 the
  all-zeros identity) that rides every AOT inference executable as a
  call argument — the r14 ``set_params`` lesson applied to tenants:
  hot-swap must be recompile-free, so adapters are *data*, never
  constants.
- :mod:`~ray_tpu.adapters.store` — :class:`AdapterStore`, the
  fleet-shared content-addressed publication point (object-store
  backed like ``WeightStore``/``KVPageStore``), keyed
  ``(model_id, version)`` with a monotonic per-model latest pointer.
- :mod:`~ray_tpu.adapters.registry` — :class:`AdapterRegistry`, the
  per-engine resident-adapter bookkeeping: which ``(model_id,
  version)`` sits in which bank slot, LRU over unpinned residents,
  pins from in-flight requests so factors mid-decode can never be
  evicted or rewritten under the requests using them (a republish
  lands in a fresh row until the old version's pins drain).
- :mod:`~ray_tpu.adapters.config` — :class:`LoraConfig` and the
  ``RAY_TPU_LORA_*`` / ``RAY_TPU_ADAPTER_CACHE`` env knobs.

The engine applies per-slot adapters inside the batched decode step
via a grouped matmul (gather factors by slot id, two skinny einsums),
so co-batched tenants share one tick; requests without a ``model_id``
ride bank slot 0 and are bit-identical to an adapter-free engine.
"""

from ray_tpu.adapters.config import LoraConfig, lora_config
from ray_tpu.adapters.lora import (adapter_nbytes, bank_install,
                                   bank_zeros, init_adapter,
                                   merge_adapter, salt_bytes,
                                   target_dims)
from ray_tpu.adapters.registry import AdapterRegistry
from ray_tpu.adapters.store import AdapterStore, AdapterUnavailableError

__all__ = [
    "LoraConfig", "lora_config", "target_dims", "init_adapter",
    "merge_adapter", "bank_zeros", "bank_install", "adapter_nbytes",
    "salt_bytes", "AdapterStore", "AdapterUnavailableError",
    "AdapterRegistry",
]
