"""AdapterRegistry: per-engine resident-adapter bookkeeping.

The engine's bank has ``cache_slots`` writable rows (slot 0 is the
identity).  This registry decides which factors occupy which row.
Residency is keyed by **(model_id, version)**: a republished version
is a *different* entry, so new factors land in a fresh row while
requests pinned to the old version keep decoding over untouched
factors — the "decode under the EXACT factors the prefill used"
invariant survives mid-traffic republishes and co-batched
version-pinned handoff imports.

- ``lookup``/``touch`` — LRU order over resident (tenant, version)
  pairs; an unversioned lookup resolves to the tenant's newest
  resident version;
- ``pin``/``unpin`` — every admitted request pins its exact
  (tenant, version) for its lifetime, so factors mid-decode can never
  be evicted *or overwritten* under the requests using them (the
  page-allocator hold discipline, applied to bank rows);
- ``place`` — allocate a row for a (tenant, version): the tenant's
  stale unpinned versions retire first (the publish supersedes them),
  then a free row, then the LRU unpinned entry is evicted; all rows
  pinned by in-flight requests is a typed
  :class:`~ray_tpu.adapters.store.AdapterUnavailableError`
  (the router re-routes), never a hang and never an in-place swap.

Leak-audit contract: ``pinned_total == 0`` after a drain.
"""

from __future__ import annotations

import collections
from typing import Dict, Optional, Tuple

from ray_tpu.adapters.store import AdapterUnavailableError


class AdapterRegistry:
    def __init__(self, cache_slots: int):
        if cache_slots < 1:
            raise ValueError(f"cache_slots must be >= 1, got {cache_slots}")
        self.cache_slots = cache_slots
        # (model_id, version) -> bank slot; insertion order is LRU
        # order (move_to_end on touch)
        self._resident: "collections.OrderedDict[Tuple[str, int], int]" = \
            collections.OrderedDict()
        self._free = list(range(cache_slots, 0, -1))  # pop() yields slot 1 first
        self._pins: Dict[Tuple[str, int], int] = {}
        self.hits = 0
        self.misses = 0
        self.loads = 0
        self.evictions = 0
        self.load_seconds = 0.0

    def lookup(self, model_id: str,
               version: Optional[int] = None) -> Optional[Tuple[int, int]]:
        """Resident ``(slot, version)`` for ``model_id`` — the exact
        ``version`` when given, else the tenant's newest resident
        version (latest-tracking traffic with no store to consult)."""
        if version is not None:
            slot = self._resident.get((model_id, version))
            return None if slot is None else (slot, version)
        best: Optional[Tuple[int, int]] = None
        for (mid, v), slot in self._resident.items():
            if mid == model_id and (best is None or v > best[1]):
                best = (slot, v)
        return best

    def touch(self, model_id: str, version: int) -> None:
        self._resident.move_to_end((model_id, version))

    def pin(self, model_id: str, version: int) -> None:
        key = (model_id, version)
        self._pins[key] = self._pins.get(key, 0) + 1

    def unpin(self, model_id: str, version: int) -> None:
        key = (model_id, version)
        n = self._pins.get(key, 0) - 1
        if n < 0:
            raise RuntimeError(
                f"unpin of {model_id!r} v{version} without a pin")
        if n == 0:
            self._pins.pop(key)
        else:
            self._pins[key] = n

    def place(self, model_id: str, version: int) -> Tuple[int, Optional[str]]:
        """Allocate a bank row for ``(model_id, version)`` ->
        ``(slot, evicted)``.

        The exact pair resident keeps its row *unless pinned* — the
        store is content-addressed, so an unpinned same-version
        re-place is a benign reinstall, but rewriting a pinned row
        would swap factors under active decodes and is refused with
        the typed error.  On a miss, the tenant's stale unpinned
        versions retire first, then a free row is taken, then the LRU
        unpinned entry of any tenant is evicted; if every row is
        pinned by in-flight requests the bank is genuinely full and
        the caller gets the typed error.  ``evicted`` names a tenant
        that fully left residency (None when only a stale version of
        a still-resident tenant retired)."""
        key = (model_id, version)
        slot = self._resident.get(key)
        if slot is not None:
            if key in self._pins:
                raise AdapterUnavailableError(
                    model_id,
                    f"version {version} is pinned by in-flight "
                    "requests — its bank row cannot be rewritten")
            self._resident.move_to_end(key)
            return slot, None
        # retire the tenant's stale unpinned versions: their factors
        # are superseded by this publish and nothing references them.
        # Strictly older only — a version-pinned handoff import of an
        # old version must not displace the tenant's latest.
        for stale in [k for k in self._resident
                      if k[0] == model_id and k[1] < version
                      and k not in self._pins]:
            self._free.append(self._resident.pop(stale))
        evicted = None
        if self._free:
            slot = self._free.pop()
        else:
            victim = next((k for k in self._resident
                           if k not in self._pins), None)
            if victim is None:
                raise AdapterUnavailableError(
                    model_id,
                    f"all {self.cache_slots} resident adapters are "
                    "pinned by in-flight requests")
            slot = self._resident.pop(victim)
            self.evictions += 1
            if not any(k[0] == victim[0] for k in self._resident):
                evicted = victim[0]
        self._resident[key] = slot
        return slot, evicted

    @property
    def resident_ids(self) -> Tuple[str, ...]:
        out = []
        for mid, _v in self._resident:
            if mid not in out:
                out.append(mid)
        return tuple(out)

    @property
    def pinned_total(self) -> int:
        return sum(self._pins.values())

    def digest(self) -> frozenset:
        """Resident tenant model_ids the router composes into
        affinity scores (version-blind: any resident version skips
        the cold store fetch)."""
        return frozenset(mid for mid, _v in self._resident)

    def stats(self) -> Dict[str, float]:
        return {
            "resident": len(self._resident),
            "tenants": len(self.resident_ids),
            "cache_slots": self.cache_slots,
            "hits": self.hits,
            "misses": self.misses,
            "loads": self.loads,
            "evictions": self.evictions,
            "pins": self.pinned_total,
            "load_seconds": round(self.load_seconds, 6),
        }
