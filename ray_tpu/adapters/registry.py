"""AdapterRegistry: per-engine resident-adapter bookkeeping.

The engine's bank has ``cache_slots`` writable rows (slot 0 is the
identity).  This registry decides which tenant occupies which row:

- ``lookup``/``touch`` — LRU order over residents;
- ``pin``/``unpin`` — every admitted request pins its tenant for its
  lifetime, so an adapter mid-decode can never be evicted under the
  requests using it (the page-allocator hold discipline, applied to
  bank rows);
- ``place`` — allocate a row for a new tenant, evicting the
  least-recently-used *unpinned* resident when full; all-pinned is a
  typed :class:`~ray_tpu.adapters.store.AdapterUnavailableError`
  (the router re-routes), never a hang.

Leak-audit contract: ``pinned_total == 0`` after a drain.
"""

from __future__ import annotations

import collections
from typing import Dict, Optional, Tuple

from ray_tpu.adapters.store import AdapterUnavailableError


class AdapterRegistry:
    def __init__(self, cache_slots: int):
        if cache_slots < 1:
            raise ValueError(f"cache_slots must be >= 1, got {cache_slots}")
        self.cache_slots = cache_slots
        # model_id -> (bank slot, installed version); insertion order
        # is LRU order (move_to_end on touch)
        self._resident: "collections.OrderedDict[str, Tuple[int, int]]" = \
            collections.OrderedDict()
        self._free = list(range(cache_slots, 0, -1))  # pop() yields slot 1 first
        self._pins: Dict[str, int] = {}
        self.hits = 0
        self.misses = 0
        self.loads = 0
        self.evictions = 0
        self.load_seconds = 0.0

    def lookup(self, model_id: str) -> Optional[Tuple[int, int]]:
        return self._resident.get(model_id)

    def touch(self, model_id: str) -> None:
        self._resident.move_to_end(model_id)

    def pin(self, model_id: str) -> None:
        self._pins[model_id] = self._pins.get(model_id, 0) + 1

    def unpin(self, model_id: str) -> None:
        n = self._pins.get(model_id, 0) - 1
        if n < 0:
            raise RuntimeError(f"unpin of {model_id!r} without a pin")
        if n == 0:
            self._pins.pop(model_id)
        else:
            self._pins[model_id] = n

    def place(self, model_id: str, version: int) -> Tuple[int, Optional[str]]:
        """Allocate a bank row for ``model_id`` -> ``(slot, evicted)``.

        A stale resident (version bump) keeps its row.  Otherwise take
        a free row, else evict the LRU unpinned resident; if every
        resident is pinned by in-flight requests the bank is genuinely
        full and the caller gets the typed error."""
        ent = self._resident.get(model_id)
        if ent is not None:
            slot = ent[0]
            self._resident[model_id] = (slot, version)
            self._resident.move_to_end(model_id)
            return slot, None
        evicted = None
        if self._free:
            slot = self._free.pop()
        else:
            victim = next((m for m in self._resident if m not in self._pins),
                          None)
            if victim is None:
                raise AdapterUnavailableError(
                    model_id,
                    f"all {self.cache_slots} resident adapters are "
                    "pinned by in-flight requests")
            slot = self._resident.pop(victim)[0]
            self.evictions += 1
            evicted = victim
        self._resident[model_id] = (slot, version)
        return slot, evicted

    @property
    def resident_ids(self) -> Tuple[str, ...]:
        return tuple(self._resident)

    @property
    def pinned_total(self) -> int:
        return sum(self._pins.values())

    def digest(self) -> frozenset:
        """Residency digest the router composes into affinity scores."""
        return frozenset(self._resident)

    def stats(self) -> Dict[str, float]:
        return {
            "resident": len(self._resident),
            "cache_slots": self.cache_slots,
            "hits": self.hits,
            "misses": self.misses,
            "loads": self.loads,
            "evictions": self.evictions,
            "pins": self.pinned_total,
            "load_seconds": round(self.load_seconds, 6),
        }
