"""LoRA factor math: init, merged-weights oracle, and the device bank.

Canonical adapter layout (host or device, per adapter):

    {"<t>_a": [L, in_t, r], "<t>_b": [L, r, out_t]}   for t in targets

with every target expressed as a flattened 2-D matmul:

    target   in       out      stacked base weight
    wq/wk/wv d        H*hd     [L, d, H, hd]
    wo       H*hd     d        [L, H, hd, d]
    w1/w3    d        f        [L, d, f]
    w2       f        d        [L, f, d]

The engine-side **bank** stacks ``N = cache_slots + 1`` adapters along
a new leading axis (``[N, L, in, r]`` / ``[N, L, r, out]``) plus a
per-slot f32 ``scale`` vector.  Slot 0 is all-zeros with scale 0 — the
exact identity every adapter-free request rides.  The bank is a plain
pytree of device arrays, so it travels through AOT executables as a
call argument (like params) and is hot-swapped with eager ``.at[].set``
updates: zero recompiles on load, evict, or version republish.
"""

from __future__ import annotations

import hashlib
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.adapters.config import LoraConfig


def effective_targets(cfg, lcfg: LoraConfig) -> Tuple[str, ...]:
    """``lcfg.targets`` minus targets the architecture doesn't have."""
    drop = set()
    if cfg.act != "swiglu":
        drop.add("w3")
    if cfg.n_experts > 0:
        raise ValueError("LoRA adapters are dense-FFN only (MoE layers "
                         "route tokens per-expert; a per-slot delta on "
                         "the expert matmuls is not yet supported)")
    return tuple(t for t in lcfg.targets if t not in drop)


def target_dims(cfg) -> Dict[str, Tuple[int, int]]:
    """``{target: (in_dim, out_dim)}`` in the flattened 2-D view."""
    d, hk, f = cfg.d_model, cfg.n_heads * cfg.head_dim, cfg.ff_dim
    dims = {"wq": (d, hk), "wk": (d, hk), "wv": (d, hk), "wo": (hk, d),
            "w1": (d, f), "w2": (f, d)}
    if cfg.act == "swiglu":
        dims["w3"] = (d, f)
    return dims


def init_adapter(cfg, lcfg: LoraConfig, key, *, random_b: bool = False,
                 dtype=None) -> Dict[str, Any]:
    """One adapter's host factors.  Standard LoRA init (A gaussian,
    B zeros → the fresh adapter is an exact no-op); ``random_b=True``
    gives a non-identity adapter for parity tests and benchmarks."""
    dt = dtype or cfg.dtype
    L, r = cfg.n_layers, lcfg.rank
    out: Dict[str, Any] = {}
    for t in effective_targets(cfg, lcfg):
        i, o = target_dims(cfg)[t]
        key, ka, kb = jax.random.split(key, 3)
        out[f"{t}_a"] = (jax.random.normal(ka, (L, i, r)) * i ** -0.5) \
            .astype(dt)
        b = jax.random.normal(kb, (L, r, o)) * r ** -0.5 if random_b \
            else jnp.zeros((L, r, o))
        out[f"{t}_b"] = b.astype(dt)
    return out


def merge_adapter(params: Dict[str, Any], adapter: Dict[str, Any],
                  cfg, *, scale: float = 1.0) -> Dict[str, Any]:
    """The parity oracle: new params with ``W += scale * A @ B`` folded
    into every adapted matmul (f32 accumulation, cast back to the
    param dtype).  An engine serving ``adapter`` must match an engine
    serving these merged weights."""
    layers = dict(params["layers"])
    for name in ("wq", "wk", "wv", "wo", "w1", "w3", "w2"):
        a = adapter.get(f"{name}_a")
        if a is None or name not in layers:
            continue
        b = adapter[f"{name}_b"]
        w = layers[name]
        delta = scale * jnp.einsum(
            "lir,lro->lio", a.astype(jnp.float32), b.astype(jnp.float32))
        layers[name] = (w.astype(jnp.float32)
                        + delta.reshape(w.shape)).astype(w.dtype)
    out = dict(params)
    out["layers"] = layers
    return out


def bank_zeros(cfg, lcfg: LoraConfig, *, dtype=None) -> Dict[str, Any]:
    """Fresh all-identity bank: every slot zeroed, scale 0."""
    dt = dtype or cfg.dtype
    N, L, r = lcfg.bank_slots, cfg.n_layers, lcfg.rank
    bank: Dict[str, Any] = {"scale": jnp.zeros((N,), jnp.float32)}
    for t in effective_targets(cfg, lcfg):
        i, o = target_dims(cfg)[t]
        bank[f"{t}_a"] = jnp.zeros((N, L, i, r), dt)
        bank[f"{t}_b"] = jnp.zeros((N, L, r, o), dt)
    return bank


def bank_install(bank: Dict[str, Any], slot: int, adapter: Dict[str, Any],
                 *, scale: float = 1.0) -> Dict[str, Any]:
    """Functionally overwrite one bank slot with an adapter's factors.

    Eager ``.at[].set`` — dispatches a handful of device updates, never
    touches the compile cache.  Targets absent from ``adapter`` are
    zeroed (the slot must not leak a previous tenant's factors)."""
    if slot <= 0:
        raise ValueError(f"bank slot {slot} is not writable (slot 0 is "
                         "the reserved identity)")
    out = dict(bank)
    for k, v in bank.items():
        if k == "scale":
            out[k] = v.at[slot].set(np.float32(scale))
            continue
        src = adapter.get(k)
        if src is None:
            out[k] = v.at[slot].set(0)
        else:
            out[k] = v.at[slot].set(jnp.asarray(src, v.dtype))
    return out


def bank_mismatch(bank: Dict[str, Any],
                  adapter: Any) -> Optional[str]:
    """Reason ``adapter``'s factors cannot install into ``bank``
    (wrong rank / target set / layer dims), else None.

    The serving engine gates every store fetch through this before
    ``bank_install``: a tenant publishing factors of a different
    geometry must surface as a typed per-request
    ``AdapterUnavailableError``, not as a jax shape error escaping the
    replica's step loop.  Targets *absent* from the adapter are fine
    (the install zeroes them); targets the bank does not carry are a
    mismatch — silently dropping them would diverge from the merged
    oracle."""
    if not isinstance(adapter, dict):
        return (f"payload is {type(adapter).__name__}, "
                "expected a factor dict")
    targets = tuple(k for k in bank if k != "scale")
    for k, v in adapter.items():
        if k == "scale":
            continue
        ref = bank.get(k)
        if ref is None:
            return (f"factor {k!r} has no matching bank target "
                    f"(bank carries {targets})")
        shape = tuple(getattr(v, "shape", None)
                      or np.asarray(v).shape)
        row = tuple(ref.shape[1:])
        if shape != row:
            return (f"factor {k!r} shape {shape} != bank row "
                    f"shape {row}")
    return None


def bank_clear(bank: Dict[str, Any], slot: int) -> Dict[str, Any]:
    """Zero a slot back to identity (evict without replacement)."""
    out = dict(bank)
    for k, v in bank.items():
        out[k] = v.at[slot].set(0)
    return out


def adapter_nbytes(adapter: Dict[str, Any]) -> int:
    """Publish payload size — the rank·(in+out)·L·itemsize sum that
    docs/PERF.md's r25 math quotes against full-params publishes."""
    total = 0
    for leaf in jax.tree.leaves(adapter):
        arr = np.asarray(jax.device_get(leaf)) if hasattr(leaf, "dtype") \
            else np.asarray(leaf)
        total += arr.nbytes
    return total


def salt_bytes(model_id: Optional[str], version: int) -> bytes:
    """Prefix-cache chain-root salt for an (adapter, version) pair.

    Adapter K/V differs from base K/V for identical token prefixes, so
    salted chains keep the r16 prefix index and the r23 tiered store
    from ever aliasing tenants; a version republish changes the salt,
    so stale entries simply miss and age out of the LRU — no flush."""
    if not model_id:
        return b""
    return hashlib.blake2b(f"{model_id}@{version}".encode(),
                           digest_size=16).digest()
