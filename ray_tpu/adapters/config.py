"""LoRA / adapter-serving knobs (``RAY_TPU_LORA_*``, ``RAY_TPU_ADAPTER_CACHE``).

Follows the frozen-dataclass + cached ``*_config(refresh=...)`` pattern
of :mod:`ray_tpu.inference.config`: every knob validates with a warning
and falls back to its default rather than crashing the process.
"""

from __future__ import annotations

import dataclasses
import os
import sys
from typing import Optional, Tuple

# Every matmul in layer_apply that can carry a low-rank delta.  ``w3``
# only exists under swiglu activation and is dropped at bank-build time
# for other activations.
ALL_TARGETS: Tuple[str, ...] = ("wq", "wk", "wv", "wo", "w1", "w3", "w2")


def _warn(msg: str) -> None:
    print(f"ray_tpu.adapters: {msg}", file=sys.stderr)


def _pos_int(name: str, default: int) -> int:
    raw = os.environ.get(name)
    if raw is None:
        return default
    try:
        val = int(raw)
    except ValueError:
        _warn(f"{name}={raw!r} is not an integer; using {default}")
        return default
    if val <= 0:
        _warn(f"{name}={val} must be positive; using {default}")
        return default
    return val


@dataclasses.dataclass(frozen=True)
class LoraConfig:
    """Geometry of the per-engine adapter bank.

    ``rank`` and ``targets`` are part of the engine's executable key:
    changing them recompiles (once); loading/republishing adapters
    never does, because the bank is a call argument.
    ``cache_slots`` is the per-replica LRU capacity — the bank holds
    ``cache_slots + 1`` rows, with slot 0 reserved as the all-zeros
    identity that base (adapter-free) traffic rides.
    """

    enabled: bool = False
    rank: int = 8
    scale: float = 1.0
    targets: Tuple[str, ...] = ALL_TARGETS
    cache_slots: int = 8

    @property
    def bank_slots(self) -> int:
        """Total bank rows, including the identity slot 0."""
        return self.cache_slots + 1


_CACHED: Optional[LoraConfig] = None


def lora_config(refresh: bool = False) -> LoraConfig:
    """Read ``RAY_TPU_LORA`` (enable), ``RAY_TPU_LORA_RANK``,
    ``RAY_TPU_LORA_TARGETS`` (csv subset of matmul names) and
    ``RAY_TPU_ADAPTER_CACHE`` (resident adapters per replica)."""
    global _CACHED
    if _CACHED is not None and not refresh:
        return _CACHED

    enabled = os.environ.get("RAY_TPU_LORA", "0").lower() in ("1", "true", "yes")
    rank = _pos_int("RAY_TPU_LORA_RANK", 8)
    cache_slots = _pos_int("RAY_TPU_ADAPTER_CACHE", 8)

    targets: Tuple[str, ...] = ALL_TARGETS
    raw = os.environ.get("RAY_TPU_LORA_TARGETS")
    if raw:
        picked = tuple(t.strip() for t in raw.split(",") if t.strip())
        bad = [t for t in picked if t not in ALL_TARGETS]
        if bad or not picked:
            _warn(f"RAY_TPU_LORA_TARGETS={raw!r} has unknown targets "
                  f"{bad} (valid: {ALL_TARGETS}); using all targets")
        else:
            # canonical order keeps the executable key stable across
            # permuted csv spellings
            targets = tuple(t for t in ALL_TARGETS if t in picked)

    _CACHED = LoraConfig(enabled=enabled, rank=rank, targets=targets,
                         cache_slots=cache_slots)
    return _CACHED
