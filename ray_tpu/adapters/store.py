"""AdapterStore: the fleet-shared, content-addressed adapter shelf.

Structurally the :class:`~ray_tpu.rl.replay.WeightStore` for adapters,
but **multi-tenant and multi-version**: entries are keyed
``(model_id, version)`` with a monotonic per-model latest pointer.
Snapshots go through the object store when a ray_tpu session is up
(``ray_tpu.put`` — N replicas share one copy), else an in-process dict
serves host-sim and tests.  Replicas *fetch* through it on cache miss
(including the r20 disagg import path: a decode replica that receives
a handoff for an adapter it has never seen pulls the exact pinned
version here — never recompiles, because the bank is a call arg).

Leak-audit contract: ``in_flight`` counts checked-out fetches and must
be 0 after a fleet drain, exactly like ``KVPageStore.in_flight``.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional, Tuple

from ray_tpu.adapters.lora import adapter_nbytes, salt_bytes


class AdapterUnavailableError(RuntimeError):
    """Typed miss/load failure for a per-request ``model_id``.

    Raised by ``engine.submit`` (unknown tenant), by the adapter load
    path (store miss, injected ``serve.adapter_load`` fault) and by a
    full-of-pinned-adapters bank.  The router treats it as a
    re-routable condition; a client sees it as a terminal typed error
    — never a hang.  ``__reduce__`` rebuilds from constructor args so
    it survives the object store (the HandoffContentMissing idiom)."""

    def __init__(self, model_id: Optional[str], reason: str):
        super().__init__(
            f"adapter {model_id!r} unavailable: {reason}")
        self.model_id = model_id
        self.reason = reason

    def __reduce__(self):
        return (AdapterUnavailableError, (self.model_id, self.reason))


class AdapterStore:
    """Versioned per-tenant adapter snapshots + scales."""

    def __init__(self, use_object_store: Optional[bool] = None):
        if use_object_store is None:
            from ray_tpu._private.worker import is_initialized
            use_object_store = is_initialized()
        self._use_ray = use_object_store
        self._lock = threading.Lock()
        # (model_id, version) -> (payload, scale, nbytes); payload is a
        # host pytree or an ObjectRef
        self._entries: Dict[Tuple[str, int], Tuple[Any, float, int]] = {}
        self._latest: Dict[str, int] = {}
        # materialization memo per key (N replicas syncing one
        # publication must not pay N deserializations)
        self._mat: Dict[Tuple[str, int], Any] = {}
        self.in_flight = 0
        self.puts = 0
        self.gets = 0
        self.misses = 0
        self.bytes_published = 0

    def put(self, model_id: str, adapter, *, scale: float = 1.0,
            version: Optional[int] = None) -> int:
        """Publish an adapter snapshot; returns its version (monotonic
        per model_id unless pinned explicitly).  ``adapter`` may be a
        host pytree or an ``ObjectRef`` (LearnerGroup hands
        ``get_params_ref()`` straight through)."""
        if not model_id:
            raise ValueError("model_id must be a non-empty string")
        from ray_tpu.object_ref import ObjectRef
        nbytes = 0
        if isinstance(adapter, ObjectRef):
            if self._use_ray:
                import ray_tpu
                ray_tpu.wait([adapter], num_returns=1)
        else:
            nbytes = adapter_nbytes(adapter)
            if self._use_ray:
                import ray_tpu
                adapter = ray_tpu.put(adapter)
        with self._lock:
            if version is None:
                version = self._latest.get(model_id, 0) + 1
            version = int(version)
            self._entries[(model_id, version)] = (adapter, float(scale),
                                                  nbytes)
            if version >= self._latest.get(model_id, 0):
                self._latest[model_id] = version
            self.puts += 1
            self.bytes_published += nbytes
        return version

    def __contains__(self, model_id: str) -> bool:
        with self._lock:
            return model_id in self._latest

    def latest_version(self, model_id: str) -> Optional[int]:
        with self._lock:
            return self._latest.get(model_id)

    def salt_for(self, model_id: Optional[str],
                 version: Optional[int] = None) -> bytes:
        """Prefix-chain salt for routing-side hash computation; b"" for
        base traffic or tenants this store has never seen (a salted
        hash that matches nothing degrades to a plain affinity miss)."""
        if not model_id:
            return b""
        v = version if version is not None else self.latest_version(model_id)
        if v is None:
            return b""
        return salt_bytes(model_id, v)

    def checkout(self, model_id: str,
                 version: Optional[int] = None) -> Tuple[int, Any, float]:
        """-> ``(version, host adapter pytree, scale)``; pins the fetch
        in ``in_flight`` until :meth:`checkin`.  Raises
        :class:`AdapterUnavailableError` on a miss (unknown tenant or
        unknown pinned version)."""
        with self._lock:
            if version is None:
                version = self._latest.get(model_id)
            if version is None or (model_id, version) not in self._entries:
                self.misses += 1
                raise AdapterUnavailableError(
                    model_id,
                    "never published" if version is None
                    else f"version {version} not in store")
            payload, scale, _ = self._entries[(model_id, version)]
            self.in_flight += 1
            self.gets += 1
            mat = self._mat.get((model_id, version))
        if mat is not None:
            return version, mat, scale
        from ray_tpu.object_ref import ObjectRef
        try:
            if isinstance(payload, ObjectRef):
                import ray_tpu
                payload = ray_tpu.get(payload)
        except Exception as err:
            # a failed materialization must not strand the pin:
            # in_flight is the leak-audit counter, and a fetch that
            # raised has nothing to check in later
            with self._lock:
                self.in_flight -= 1
            raise AdapterUnavailableError(
                model_id, f"object-store fetch of version {version} "
                f"failed: {err}") from err
        with self._lock:
            self._mat[(model_id, version)] = payload
        return version, payload, scale

    def checkin(self) -> None:
        with self._lock:
            if self.in_flight <= 0:
                raise RuntimeError("AdapterStore.checkin without a "
                                   "matching checkout")
            self.in_flight -= 1

    def get(self, model_id: str,
            version: Optional[int] = None) -> Tuple[int, Any, float]:
        """Unpinned convenience fetch (checkout + immediate checkin)."""
        out = self.checkout(model_id, version)
        self.checkin()
        return out

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "models": len(self._latest),
                "entries": len(self._entries),
                "puts": self.puts,
                "gets": self.gets,
                "misses": self.misses,
                "in_flight": self.in_flight,
                "bytes_published": self.bytes_published,
            }
