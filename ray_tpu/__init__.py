"""ray_tpu — a TPU-native distributed computing framework.

Same capability surface as the reference (tasks, actors, objects, placement
groups, Train/Tune/Data/Serve/RLlib) with the tensor plane re-based on
JAX/XLA: device meshes + pjit/shard_map collectives over ICI/DCN instead of
NCCL, Pallas kernels for the hot ops, and host-side objects in a
shared-memory store.

Public API parity target: ``python/ray/_private/worker.py`` (init, remote,
get, put, wait, ...), ``python/ray/actor.py``, ``python/ray/exceptions.py``.
"""

from __future__ import annotations

import os
import threading
from typing import Any, Dict, List, Optional, Sequence, Union

from ray_tpu import exceptions
from ray_tpu._private import worker as _worker_mod
from ray_tpu._private.config import GLOBAL_CONFIG
from ray_tpu._private.worker import global_worker, is_initialized
from ray_tpu.actor import (ActorClass, ActorHandle, get_actor, method)
from ray_tpu.object_ref import ObjectRef, ObjectRefGenerator
from ray_tpu.remote_function import RemoteFunction

__version__ = "0.1.0"

_init_lock = threading.RLock()


def init(address: Optional[str] = None,
         num_cpus: Optional[float] = None,
         num_tpus: Optional[float] = None,
         resources: Optional[Dict[str, float]] = None,
         namespace: str = "default",
         ignore_reinit_error: bool = False,
         _system_config: Optional[Dict[str, Any]] = None,
         **kwargs) -> "RuntimeContext":
    """Start a ray_tpu runtime — or, with ``address``, connect to a
    running one as an additional driver ("auto", a session directory,
    or a control-plane address; parity: ``ray.init(address=...)``)."""
    with _init_lock:
        if is_initialized():
            if ignore_reinit_error:
                return get_runtime_context()
            raise RuntimeError(
                "ray_tpu.init() called twice; pass "
                "ignore_reinit_error=True to ignore")
        if address is None:
            # job entrypoints etc. inherit the cluster via env
            # (parity: RAY_ADDRESS)
            address = os.environ.get("RAY_TPU_ADDRESS") or None
        if address is not None:
            if any(v is not None for v in (num_cpus, num_tpus,
                                           resources, _system_config)):
                import warnings
                warnings.warn(
                    "init(address=...) attaches to an existing cluster; "
                    "num_cpus/num_tpus/resources/_system_config are "
                    "ignored (reference parity: ray.init warns too)",
                    stacklevel=2)
            from ray_tpu._private.node import AttachedNode
            node = AttachedNode(address, namespace=namespace)
        else:
            from ray_tpu._private.node import HeadNode
            node = HeadNode(num_cpus=num_cpus, num_tpus=num_tpus,
                            resources=resources, namespace=namespace,
                            system_config=_system_config,
                            session_name=kwargs.pop("session_name",
                                                    None))
        _worker_mod.set_global_worker(node.worker, node)
        return get_runtime_context()


def shutdown() -> None:
    with _init_lock:
        node = _worker_mod.global_node()
        _worker_mod.set_global_worker(None, None)
        if node is not None:
            node.shutdown()
        GLOBAL_CONFIG.reset()


def remote(*args, **kwargs):
    """``@remote`` decorator for functions and classes.

    Usage: ``@ray_tpu.remote`` or ``@ray_tpu.remote(num_cpus=2, ...)``.
    """
    def make(target):
        import inspect
        if inspect.isclass(target):
            return ActorClass(target, **kwargs)
        return RemoteFunction(target, **kwargs)

    if len(args) == 1 and not kwargs and callable(args[0]):
        return make(args[0])
    if args:
        raise TypeError("remote() takes keyword arguments only")
    return make


def get(refs: Union[ObjectRef, Sequence[ObjectRef]],
        *, timeout: Optional[float] = None) -> Any:
    return global_worker().get(refs, timeout=timeout)


def put(value: Any) -> ObjectRef:
    return global_worker().put(value)


def wait(refs: Sequence[ObjectRef], *, num_returns: int = 1,
         timeout: Optional[float] = None, fetch_local: bool = True):
    return global_worker().wait(refs, num_returns=num_returns,
                                timeout=timeout, fetch_local=fetch_local)


def kill(actor: ActorHandle, *, no_restart: bool = True) -> None:
    global_worker().kill_actor(actor._actor_id, no_restart)


def cancel(ref: ObjectRef, *, force: bool = False,
           recursive: bool = True) -> None:
    global_worker().cancel_task(ref)


def timeline(filename: Optional[str] = None) -> str:
    """Chrome-trace of task events (parity: ``ray.timeline``): returns
    the JSON string; also writes it to ``filename`` when given."""
    from ray_tpu._private.profiling import timeline as _tl
    return _tl(filename)


def nodes() -> List[Dict[str, Any]]:
    out = []
    for info in global_worker().cp.list_nodes():
        out.append({
            "NodeID": info["node_id"].hex(),
            "Alive": info["state"] == "ALIVE",
            "NodeManagerAddress": info.get("ip", "127.0.0.1"),
            "Resources": info.get("resources_total", {}),
            "Labels": info.get("labels", {}),
        })
    return out


def cluster_resources() -> Dict[str, float]:
    total: Dict[str, float] = {}
    for info in global_worker().cp.list_nodes():
        if info["state"] != "ALIVE":
            continue
        for k, v in info.get("resources_total", {}).items():
            total[k] = total.get(k, 0.0) + v
    return total


def available_resources() -> Dict[str, float]:
    total: Dict[str, float] = {}
    for info in global_worker().cp.list_nodes():
        if info["state"] != "ALIVE":
            continue
        for k, v in info.get("resources_available", {}).items():
            total[k] = total.get(k, 0.0) + v
    return total


class RuntimeContext:
    """Parity: ``python/ray/runtime_context.py``."""

    @property
    def worker(self):
        return global_worker()

    def get_node_id(self) -> str:
        return global_worker().node_id.hex()

    def get_job_id(self) -> str:
        return global_worker().job_id.hex()

    def get_worker_id(self) -> str:
        return global_worker().worker_id.hex()

    def get_actor_id(self) -> Optional[str]:
        aid = global_worker().current_actor_id
        return aid.hex() if aid else None

    def get_task_id(self) -> Optional[str]:
        tid = global_worker().current_task_id
        return tid.hex() if tid else None

    @property
    def namespace(self) -> str:
        return global_worker().namespace

    def get_assigned_resources(self) -> Dict[str, float]:
        return {}


def get_runtime_context() -> RuntimeContext:
    return RuntimeContext()


def _lazy_submodules():
    return {"data", "train", "tune", "serve", "rllib", "util", "workflow",
            "dag", "air"}


def __getattr__(name: str):
    if name in _lazy_submodules():
        import importlib
        mod = importlib.import_module(f"ray_tpu.{name}")
        globals()[name] = mod
        return mod
    raise AttributeError(f"module 'ray_tpu' has no attribute {name!r}")


__all__ = [
    "init", "shutdown", "is_initialized", "remote", "get", "put", "wait",
    "kill", "cancel", "get_actor", "method", "nodes", "timeline",
    "cluster_resources",
    "available_resources", "get_runtime_context", "ObjectRef",
    "ObjectRefGenerator", "ActorClass", "ActorHandle", "RemoteFunction",
    "exceptions", "__version__",
]
