"""Logical-axis sharding rules.

The flax-style "logical axis name → mesh axis" indirection: model code
annotates arrays with logical names (``("batch", "seq", "embed")``); a rule
table maps those to mesh axes, producing ``PartitionSpec`` /
``NamedSharding``.  This is how DP/FSDP/TP/SP become *config*, not code —
the reference needed a different wrapper per strategy
(``train_loop_utils.py`` prepare_model ddp/fsdp); here the same model runs
under any mesh by swapping rules.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple, Union

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

Rules = Tuple[Tuple[str, Union[str, Tuple[str, ...], None]], ...]

# Default rule table for transformer training on a (pp, dp, fsdp, sp, ep,
# tp) mesh.  fsdp shards parameters along their largest dim (ZeRO-3); tp
# follows megatron sharding; activations shard batch over (dp, fsdp) and
# sequence over sp.
DEFAULT_RULES: Rules = (
    ("batch", ("dcn", "dp", "fsdp")),
    ("seq", "sp"),
    ("kv_seq", None),
    ("embed", None),
    ("embed_fsdp", "fsdp"),
    ("vocab", "tp"),
    ("heads", "tp"),
    ("head_dim", None),
    ("mlp", "tp"),
    ("experts", "ep"),
    ("expert_mlp", "tp"),
    ("stage", "pp"),
    ("conv_in", None),
    ("conv_out", "tp"),
)


def rules_dict(rules: Optional[Rules] = None) -> Dict[str, object]:
    return dict(rules if rules is not None else DEFAULT_RULES)


def logical_to_spec(logical_axes: Sequence[Optional[str]],
                    rules: Optional[Rules] = None,
                    mesh=None) -> P:
    """Map logical axis names to a PartitionSpec.

    Axes mapped to mesh axes that don't exist in ``mesh`` (or have size 1)
    degrade to replication, so one rule table serves every mesh shape.
    """
    table = rules_dict(rules)
    out = []
    for name in logical_axes:
        if name is None:
            out.append(None)
            continue
        target = table.get(name)
        if target is None:
            out.append(None)
            continue
        if mesh is not None:
            if isinstance(target, tuple):
                target = tuple(a for a in target
                               if mesh.shape.get(a, 1) > 1) or None
                if isinstance(target, tuple) and len(target) == 1:
                    target = target[0]
            elif mesh.shape.get(target, 1) <= 1:
                target = None
        out.append(target)
    while out and out[-1] is None:
        out.pop()
    return P(*out)


def named_sharding(mesh, logical_axes: Sequence[Optional[str]],
                   rules: Optional[Rules] = None) -> NamedSharding:
    return NamedSharding(mesh, logical_to_spec(logical_axes, rules, mesh))


def _is_axes_leaf(x) -> bool:
    return isinstance(x, tuple) and all(
        isinstance(a, (str, type(None))) for a in x)


def tree_shardings(mesh, logical_tree, rules: Optional[Rules] = None):
    """Map a pytree of logical-axis tuples to a pytree of NamedShardings."""
    return jax.tree.map(
        lambda axes: named_sharding(mesh, axes, rules),
        logical_tree, is_leaf=_is_axes_leaf)


def tree_specs(mesh, logical_tree, rules: Optional[Rules] = None):
    """Map a pytree of logical-axis tuples to bare PartitionSpecs —
    the shard_map-facing sibling of :func:`tree_shardings`, so manual
    paths (``parallel/overlap.py``) and GSPMD in_shardings resolve from
    one rule table and cannot disagree."""
    return jax.tree.map(
        lambda axes: logical_to_spec(axes, rules, mesh),
        logical_tree, is_leaf=_is_axes_leaf)


def constrain(x, logical_axes: Sequence[Optional[str]],
              rules: Optional[Rules] = None, mesh=None):
    """``with_sharding_constraint`` by logical axes (inside jit)."""
    from jax.lax import with_sharding_constraint
    if mesh is None:
        try:
            mesh = jax.sharding.get_abstract_mesh()
            if mesh is None or not mesh.axis_names:
                return x
        except Exception:  # noqa: BLE001
            return x
    try:
        shaped = mesh if mesh.shape else None
    except Exception:  # noqa: BLE001 — AbstractMesh may refuse attributes
        shaped = None
    spec = logical_to_spec(logical_axes, rules, shaped)
    concrete = isinstance(mesh, jax.sharding.Mesh)
    return with_sharding_constraint(
        x, NamedSharding(mesh, spec) if concrete else spec)


def shard_params(params, mesh, logical_tree, rules: Optional[Rules] = None):
    """Device_put a param pytree according to its logical axes."""
    shardings = tree_shardings(mesh, logical_tree, rules)
    return jax.device_put(params, shardings)


def data_axes(mesh):
    """The mesh axes a batch dimension shards over: (dcn, dp, fsdp)
    present in the mesh with size > 1, collapsed to a single name when
    alone, or ``None``.  ``dcn`` leads: across pods the model is pure
    data parallelism, so the batch splits over the slow tier first.
    Shared by batch shardings and shard_map in_specs so the two
    conventions cannot diverge."""
    axes = tuple(a for a in ("dcn", "dp", "fsdp")
                 if mesh.shape.get(a, 1) > 1)
    if not axes:
        return None
    return axes[0] if len(axes) == 1 else axes
