"""Ulysses sequence parallelism — all-to-all head resharding.

Parity target: DeepSpeed-Ulysses as integrated by the reference's
long-context stacks (SURVEY.md §2.4 row "Ulysses / all-to-all").  The
alternative to ring attention (``ring_attention.py``): instead of
rotating K/V blocks around the ``sp`` ring, one ``all_to_all`` trades
the sequence shard for a head shard, every device runs *full-sequence*
attention on ``H/sp`` heads, and a second ``all_to_all`` restores the
sequence sharding.  Two collectives per layer instead of ``sp`` ring
steps — better when heads are plentiful and ICI all-to-all is cheap;
ring wins when S is huge and overlap matters.

Composes with tp (heads are split over ``(tp, sp)``) via partial-manual
shard_map: only ``sp`` is manual here.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
from jax import lax
from jax.sharding import PartitionSpec as P

from ray_tpu.parallel.compat import shard_map, supports_partial_manual
from ray_tpu.parallel.ring_attention import local_attention
from ray_tpu.parallel.sharding import data_axes


def make_ulysses_attention_fn(mesh, *, causal: bool = True,
                              scale: Optional[float] = None,
                              attn_impl=None):
    """Returns ``fn(q, k, v) -> out`` for [B, S, H, D] inputs whose seq
    dim is sharded over ``sp``.  Drop-in for
    ``make_ring_attention_fn`` / ``make_flash_attention_fn``.

    ``attn_impl(q, k, v, causal=..., scale=...)`` runs the local
    full-sequence attention (default: the einsum path; pass
    ``ops.attention.flash_attention`` on real TPU).
    """
    sp = mesh.shape.get("sp", 1)
    inner = attn_impl or local_attention
    if sp <= 1:
        return functools.partial(inner, causal=causal, scale=scale)

    if supports_partial_manual():
        # partial-manual: specs name only the manual axis; dp/tp
        # shardings propagate automatically through the auto axes
        spec = P(None, "sp", None, None)
        manual = {"sp"}
    else:
        batch = data_axes(mesh)
        tp = "tp" if mesh.shape.get("tp", 1) > 1 else None
        spec = P(batch, "sp", tp, None)
        manual = None

    @functools.partial(shard_map, mesh=mesh, in_specs=(spec,) * 3,
                       out_specs=spec, axis_names=manual)
    def fn(q, k, v):
        H = q.shape[2]
        if H % sp:
            raise ValueError(f"heads={H} not divisible by sp={sp}")
        # [B, S/sp, H, D] -> [B, S, H/sp, D]: trade seq shard for heads
        q, k, v = (lax.all_to_all(t, "sp", split_axis=2, concat_axis=1,
                                  tiled=True) for t in (q, k, v))
        out = inner(q, k, v, causal=causal, scale=scale)
        # [B, S, H/sp, D] -> [B, S/sp, H, D]
        return lax.all_to_all(out, "sp", split_axis=1, concat_axis=2,
                              tiled=True)

    return fn
