"""Device-mesh construction — the substrate of every parallelism strategy.

TPU-native replacement for the reference's NCCL communicator world: instead
of process groups + communicator objects (reference
``python/ray/util/collective/collective_group/nccl_collective_group.py``),
parallelism is expressed as named axes of a ``jax.sharding.Mesh`` and XLA
inserts the collectives.  Axis convention (see scaling-book recipe):

    dcn   the inter-pod tier (data-center network): pure data
          parallelism across pods — params replicated per pod, grads
          all-reduced over the slow links
    dp    data parallelism (gradient psum)
    fsdp  parameter/optimizer sharding (ZeRO-3-style)
    tp    tensor parallelism (megatron-style sharded matmuls)
    sp    sequence/context parallelism (ring attention)
    pp    pipeline stages
    ep    expert parallelism (MoE all-to-all), usually folded over dp

ICI topology note: axes earlier in the tuple change slowest; put the axis
with the heaviest collective traffic (tp) innermost so it rides the
densest ICI links.  ``dcn`` is outermost by construction — it is the
slowest tier, and the hierarchical collectives in ``parallel/overlap.py``
depend on every ICI axis being contiguous *inside* one dcn slice.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

AXIS_ORDER = ("dcn", "pp", "dp", "fsdp", "sp", "ep", "tp")

# Axes that live on the intra-pod ICI fabric; "dcn" is the only
# cross-pod axis.  ``mesh_tiers`` buckets a live mesh by this split.
ICI_AXES = tuple(a for a in AXIS_ORDER if a != "dcn")


class MeshAxisError(ValueError):
    """A mesh-axis string was malformed; ``axis`` names the offender.

    Raised by :func:`parse_mesh_axes` (and ``MeshSpec.create``) with the
    offending axis attached so CLI surfaces (``bench.py --mesh``,
    scratch drivers) can point at the exact token instead of the whole
    argument."""

    def __init__(self, msg: str, *, axis: Optional[str] = None):
        super().__init__(msg)
        self.axis = axis


@dataclass(frozen=True)
class MeshSpec:
    """A named logical mesh shape, resolvable against any device set."""

    axes: Tuple[Tuple[str, int], ...]

    @classmethod
    def create(cls, **sizes: int) -> "MeshSpec":
        unknown = set(sizes) - set(AXIS_ORDER)
        if unknown:
            bad = sorted(unknown)[0]
            raise MeshAxisError(
                f"unknown mesh axis {bad!r}; valid: {AXIS_ORDER}",
                axis=bad)
        axes = tuple((a, int(sizes[a])) for a in AXIS_ORDER if a in sizes)
        return cls(axes)

    @classmethod
    def from_mesh(cls, mesh) -> "MeshSpec":
        """The logical shape of a live ``jax.sharding.Mesh`` (or a
        MeshSpec, passed through) — what a checkpoint sidecar records
        as the *writing* topology so a restore onto a different mesh
        can be refused or resharded deliberately."""
        if isinstance(mesh, cls):
            return mesh
        return cls(tuple((str(a), int(s))
                         for a, s in dict(mesh.shape).items()))

    # ----------------------------------------------- sidecar (de)serialization
    def to_dict(self) -> Dict[str, int]:
        """JSON-safe image for checkpoint sidecars (axis order is the
        identity: ``{"fsdp": 8}`` and ``{"fsdp": 4, "tp": 2}`` are
        different topologies even at equal size)."""
        return {a: s for a, s in self.axes}

    @classmethod
    def from_dict(cls, d: Dict[str, int]) -> "MeshSpec":
        # not create(): a sidecar written by a future axis set must
        # still round-trip for the mismatch report instead of raising
        # an unknown-axis error before the real message
        return cls(tuple((str(a), int(s)) for a, s in d.items()))

    def describe(self) -> str:
        return ",".join(f"{a}={s}" for a, s in self.axes) or "dp=1"

    @property
    def size(self) -> int:
        return math.prod(s for _, s in self.axes) if self.axes else 1

    @property
    def axis_names(self) -> Tuple[str, ...]:
        return tuple(a for a, _ in self.axes)

    def resolve(self, num_devices: int) -> "MeshSpec":
        """Fill at most one ``-1`` axis from the device count."""
        wild = [a for a, s in self.axes if s == -1]
        if len(wild) > 1:
            raise ValueError("at most one mesh axis may be -1")
        fixed = math.prod(s for _, s in self.axes if s != -1)
        if wild:
            if num_devices % fixed:
                raise ValueError(
                    f"{num_devices} devices not divisible by fixed axes "
                    f"product {fixed}")
            fill = num_devices // fixed
            return MeshSpec(tuple((a, fill if s == -1 else s)
                                  for a, s in self.axes))
        if fixed > num_devices:
            raise ValueError(
                f"mesh size {fixed} exceeds device count {num_devices}")
        return self  # smaller meshes use the first `fixed` devices

    # ----------------------------------------------------------- tier split
    def tier_split(self) -> Tuple[int, int]:
        """``(dcn_size, pod_size)`` — the cross-pod tier and the per-pod
        ICI product.  A flat (single-pod) spec is ``(1, size)``.  This
        is what the checkpoint sidecar round-trips so an r18 cross-mesh
        restore can tell ``dcn=2,fsdp=4`` from flat ``fsdp=8`` even at
        equal device count."""
        d = dict(self.axes)
        dcn = int(d.get("dcn", 1))
        return dcn, self.size // max(dcn, 1)


def mesh_tiers(mesh) -> Dict[str, Tuple[str, ...]]:
    """Bucket a live mesh's >1-sized axes by fabric tier:
    ``{"ici": (...), "dcn": (...)}``.  The hierarchical collectives and
    the per-tier byte accounting share this split so they cannot
    disagree about which wire a collective rides."""
    shape = dict(mesh.shape)
    return {
        "ici": tuple(a for a in ICI_AXES if shape.get(a, 1) > 1),
        "dcn": tuple(a for a in ("dcn",) if shape.get(a, 1) > 1),
    }


def parse_mesh_axes(arg: str) -> Dict[str, int]:
    """``"dcn=2,fsdp=4"`` -> ``{"dcn": 2, "fsdp": 4}`` (CLI mesh syntax
    shared by ``bench.py --mesh`` and the scratch drivers).

    Rejections all raise :class:`MeshAxisError` naming the offending
    axis: unknown names, duplicates, non-positive sizes (``-1`` is the
    one allowed wildcard), and ``dcn`` anywhere but first — the slow
    tier must be the outermost (slowest-varying) axis or the per-pod
    device blocks ``make_mesh`` carves would interleave pods."""
    sizes: Dict[str, int] = {}
    order: List[str] = []
    for part in arg.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise MeshAxisError(
                f"bad mesh axis {part!r} (want e.g. 'dcn=2,fsdp=4')",
                axis=part)
        name, _, value = part.partition("=")
        name = name.strip()
        try:
            size = int(value)
        except ValueError:
            raise MeshAxisError(
                f"mesh axis {name!r} has non-integer size {value!r}",
                axis=name) from None
        if name in sizes:
            raise MeshAxisError(
                f"duplicate mesh axis {name!r}", axis=name)
        if size == 0 or size < -1:
            raise MeshAxisError(
                f"mesh axis {name!r} has non-positive size {size} "
                "(only -1 is allowed as a wildcard)", axis=name)
        sizes[name] = size
        order.append(name)
    if "dcn" in order and order.index("dcn") != 0:
        raise MeshAxisError(
            "mesh axis 'dcn' must be outermost (first): the cross-pod "
            f"tier is the slowest axis, got order {order}", axis="dcn")
    MeshSpec.create(**sizes)   # validates axis names
    return sizes


def make_mesh(spec: Optional[MeshSpec] = None, devices=None,
              **sizes: int):
    """Build a ``jax.sharding.Mesh`` from a spec or axis sizes.

    ``make_mesh(dp=2, tp=4)``; pass one ``-1`` to absorb remaining devices:
    ``make_mesh(dp=-1, tp=2)``.
    """
    import jax
    from jax.sharding import Mesh

    if spec is None:
        if not sizes:
            sizes = {"dp": -1}
        spec = MeshSpec.create(**sizes)
    if devices is None:
        devices = jax.devices()
    spec = spec.resolve(len(devices))
    shape = [s for _, s in spec.axes]
    import numpy as np
    dev_array = np.asarray(devices[: spec.size]).reshape(shape)
    return Mesh(dev_array, spec.axis_names)


def single_device_mesh(device=None):
    import jax
    from jax.sharding import Mesh
    import numpy as np
    if device is None:
        device = jax.devices()[0]
    return Mesh(np.asarray([device]).reshape(1), ("dp",))


def mesh_axis_size(mesh, axis: str) -> int:
    return mesh.shape.get(axis, 1) if hasattr(mesh, "shape") else 1


def suggest_accum_steps(batch: int, div: int,
                        prefer: int = 1) -> Optional[int]:
    """The gradient-accumulation factor that would make ``batch``
    legal on a mesh whose data axes multiply to ``div``: each of the
    ``k`` microbatches (``batch / k`` rows) must be whole AND divide
    evenly over the data axes, so legal ``k`` are exactly the divisors
    of ``batch // div``.  Returns the legal ``k`` closest to
    ``prefer`` (ties go up — more microbatches, less memory), or
    ``None`` when ``div`` does not divide ``batch`` at all: no
    accumulation factor can fix plain indivisibility, only a batch or
    mesh change can."""
    if div <= 0 or batch % div:
        return None
    per = batch // div
    legal = [k for k in range(1, per + 1) if per % k == 0]
    return min(legal, key=lambda k: (abs(k - prefer), -k))


def validate_divisibility(mesh, *, batch: Optional[int] = None,
                          seq: Optional[int] = None,
                          d_model: Optional[int] = None,
                          n_heads: Optional[int] = None,
                          accum_steps: int = 1) -> None:
    """Fail fast on shape/axis mismatches instead of inside XLA.

    ``accum_steps``: gradient-accumulation microbatch count — the
    batch check then validates the *microbatch* (``batch /
    accum_steps`` must be whole and divide the data axes), and a
    failure names the failing axes with their sizes and suggests the
    ``accum_steps`` that would make this mesh legal (the elastic
    degraded-restore path: an 8->4 shrink keeps the global batch by
    doubling accumulation instead of dying here)."""
    accum_steps = int(accum_steps)
    if accum_steps < 1:
        raise ValueError(f"accum_steps={accum_steps} must be >= 1")
    checks = [
        (seq, ("sp",), "sequence length"),
        (n_heads, ("tp",), "attention heads"),
        (d_model, ("tp",), "d_model"),
    ]
    for value, axes, label in checks:
        if value is None:
            continue
        div = math.prod(mesh.shape.get(a, 1) for a in axes)
        if value % div:
            present = ", ".join(
                f"{a}={mesh.shape.get(a, 1)}" for a in axes
                if mesh.shape.get(a, 1) > 1) or "all size 1"
            raise ValueError(
                f"{label}={value} not divisible by mesh axes {axes} "
                f"({present}; product {div})")
    if batch is None:
        return
    axes = ("dcn", "dp", "fsdp")
    div = math.prod(mesh.shape.get(a, 1) for a in axes)
    if batch % (div * accum_steps) == 0:
        return
    present = ", ".join(f"{a}={mesh.shape.get(a, 1)}" for a in axes
                        if mesh.shape.get(a, 1) > 1) or "all size 1"
    suggestion = suggest_accum_steps(batch, div, prefer=accum_steps)
    if suggestion is None:
        hint = (f"no accum_steps can fix this — the data axes "
                f"(product {div}) do not divide the global batch; "
                "change the batch or the mesh")
    else:
        hint = (f"accum_steps={suggestion} would make this mesh "
                f"legal (microbatch {batch // suggestion})")
    raise ValueError(
        f"batch={batch} with accum_steps={accum_steps} not divisible "
        f"by mesh data axes {axes} ({present}; product {div}): each "
        f"microbatch must be whole and shard evenly — {hint}")
