"""Ring attention — sequence/context parallelism over an ICI ring.

The reference has NO native sequence parallelism (verified in SURVEY.md
§2.4: Ray delegates long-context to DeepSpeed/Lightning inside the user
fn).  Here it is first-class: K/V shards rotate around the ``sp`` mesh
axis via ``ppermute`` while each device accumulates blockwise attention
for its resident Q shard with an online (streaming) softmax — attention
over sequences of length ``sp * S_local`` with O(S_local^2) memory.

Design (Liu et al. ring attention + flash-attention online softmax):
- one ring step per sp-rank; compute for the resident block overlaps the
  ppermute of the next K/V block (XLA schedules the collective async);
- numerics: scores/stats accumulate in f32 regardless of input dtype;
  masked logits use a large-negative finite value so fully-masked blocks
  stay NaN-free (every causal row owns its diagonal, so the final result
  is exact);
- the per-block kernel is pluggable: defaults to an einsum path XLA fuses
  well; ``ray_tpu.ops.attention`` provides the Pallas flash kernel for the
  resident-block case.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax

_NEG_INF = -1e9


def _block_attn(q, k, v, mask, scale):
    """One blockwise attention step returning (out, row_max, row_sum).

    q: [B, Sq, H, D]  k/v: [B, Sk, H, D]  mask: [Sq, Sk] bool or None.
    Stats in f32: out [B, Sq, H, D], m/l [B, Sq, H].
    """
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if mask is not None:
        scores = jnp.where(mask[None, None, :, :], scores, _NEG_INF)
    m = jnp.max(scores, axis=-1)                      # [B, H, Sq]
    p = jnp.exp(scores - m[..., None])                # [B, H, Sq, Sk]
    l = jnp.sum(p, axis=-1)                           # [B, H, Sq]
    o = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v,
                   preferred_element_type=jnp.float32)
    # reshape stats to [B, Sq, H]
    return o, jnp.transpose(m, (0, 2, 1)), jnp.transpose(l, (0, 2, 1))


def zigzag_positions(rank, n, s_local):
    """Global token positions of rank ``rank``'s shard under the ZIGZAG
    layout: the sequence is cut into ``2n`` chunks and rank i holds
    chunks ``(i, 2n-1-i)`` — one early + one late chunk, so every rank
    carries the same share of the causal triangle (reference idea:
    striped/zigzag context parallelism; the plain contiguous layout
    gives rank n-1 the whole triangle while rank 0 sits masked).
    """
    c = s_local // 2
    early = rank * c + jnp.arange(c)
    late = (2 * n - 1 - rank) * c + jnp.arange(c)
    return jnp.concatenate([early, late])


def zigzag_permutation(seq_len: int, n: int):
    """Host-side index map: ``x[:, perm]`` reorders a ``[B, S, ...]``
    global sequence so an even split over ``n`` ranks gives each rank
    its zigzag shard.  Returns (perm, inverse_perm) as numpy arrays."""
    import numpy as np
    if seq_len % (2 * n):
        raise ValueError(
            f"zigzag needs seq_len divisible by 2*sp (got seq_len="
            f"{seq_len}, sp={n})")
    s_local = seq_len // n
    perm = np.concatenate([
        np.asarray(zigzag_positions(r, n, s_local)) for r in range(n)])
    inv = np.empty_like(perm)
    inv[perm] = np.arange(seq_len)
    return perm, inv


def ring_attention(q, k, v, *, axis_name: str = "sp", causal: bool = True,
                   scale: Optional[float] = None,
                   block_attn: Callable = _block_attn,
                   layout: str = "contiguous"):
    """Ring attention over a sharded sequence axis.

    Must run inside ``shard_map`` (or pjit-manual) with ``axis_name``
    bound.  q, k, v: ``[B, S_local, H, D]`` — the local sequence shard.
    Returns ``[B, S_local, H, D]`` in q's dtype.

    ``layout="zigzag"``: shards follow :func:`zigzag_positions` (feed
    the model a :func:`zigzag_permutation`-reordered sequence).  With
    chunks ``(r, 2n-1-r)`` every off-diagonal ring step reduces to an
    UNMASKED half-block — ``src < my``: all of q attends only the
    source's early chunk; ``src > my``: only q's late chunk attends the
    full source — so each step costs half the contiguous layout's
    block, identical on every rank: causal work is balanced AND ~halved
    (striped/zigzag context parallelism).
    """
    from ray_tpu.parallel.compat import axis_size
    n = axis_size(axis_name)
    my = lax.axis_index(axis_name)
    B, S, H, D = q.shape
    if scale is None:
        scale = D ** -0.5
    if layout == "zigzag" and S % 2:
        raise ValueError(
            f"zigzag layout needs an even local shard, got S_local={S} "
            "(global seq_len must divide by 2*sp)")

    if layout == "zigzag":
        q_pos = zigzag_positions(my, n, S)
    else:
        q_pos = my * S + jnp.arange(S)                # global q positions

    c = S // 2

    def zz_diag(q, k_blk, v_blk, src):
        # own block: the zigzag causal mask (half true by structure)
        kv_pos = zigzag_positions(src, n, S)
        mask = q_pos[:, None] >= kv_pos[None, :]
        return block_attn(q, k_blk, v_blk, mask, scale)

    def zz_lower(q, k_blk, v_blk, src):
        # src strictly "earlier": every q position sees the source's
        # EARLY chunk completely and its late chunk not at all
        bo, bm, bl = block_attn(q, k_blk[:, :c], v_blk[:, :c], None,
                                scale)
        return bo, bm, bl

    def zz_upper(q, k_blk, v_blk, src):
        # src strictly "later": only q's LATE chunk sees the source
        # (all of it); early q rows contribute nothing this step
        bo, bm, bl = block_attn(q[:, c:], k_blk, v_blk, None, scale)
        pad_o = jnp.zeros((B, c, H, D), jnp.float32)
        pad_m = jnp.full((B, c, H), _NEG_INF, jnp.float32)
        pad_l = jnp.zeros((B, c, H), jnp.float32)
        return (jnp.concatenate([pad_o, bo], axis=1),
                jnp.concatenate([pad_m, bm], axis=1),
                jnp.concatenate([pad_l, bl], axis=1))

    def step(carry, step_idx):
        o, m, l, k_blk, v_blk = carry
        src = (my - step_idx) % n
        if causal and layout == "zigzag":
            # per-rank branch (no collective inside): each step costs
            # one half-block on every rank
            bo, bm, bl = lax.cond(
                src == my,
                lambda args: zz_diag(*args),
                lambda args: lax.cond(
                    args[3] < my,
                    lambda a: zz_lower(*a),
                    lambda a: zz_upper(*a),
                    args),
                (q, k_blk, v_blk, src))
        elif causal:
            kv_pos = src * S + jnp.arange(S)
            mask = q_pos[:, None] >= kv_pos[None, :]
            bo, bm, bl = block_attn(q, k_blk, v_blk, mask, scale)
        else:
            bo, bm, bl = block_attn(q, k_blk, v_blk, None, scale)
        m_new = jnp.maximum(m, bm)
        alpha = jnp.exp(m - m_new)                    # rescale old state
        beta = jnp.exp(bm - m_new)                    # rescale new block
        l_new = l * alpha + bl * beta
        o_new = (o * alpha[..., None]
                 + bo * beta[..., None])
        perm = [(i, (i + 1) % n) for i in range(n)]
        k_next = lax.ppermute(k_blk, axis_name, perm)
        v_next = lax.ppermute(v_blk, axis_name, perm)
        return (o_new, m_new, l_new, k_next, v_next), None

    o0 = jnp.zeros((B, S, H, D), jnp.float32)
    m0 = jnp.full((B, S, H), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, S, H), jnp.float32)
    (o, m, l, _, _), _ = lax.scan(step, (o0, m0, l0, k, v),
                                  jnp.arange(n))
    out = o / jnp.maximum(l[..., None], 1e-30)
    return out.astype(q.dtype)


def local_attention(q, k, v, *, causal: bool = True,
                    scale: Optional[float] = None, segment_ids=None):
    """Single-device reference attention (same signature, no ring).

    ``segment_ids`` [B, S] (sample-packed batches, 0 = pad) delegates
    to the block-diagonal-masked formulation — co-packed documents
    never attend to each other."""
    if segment_ids is not None:
        from ray_tpu.ops.attention import segment_attention
        return segment_attention(q, k, v, segment_ids, causal=causal,
                                 scale=scale)
    B, S, H, D = q.shape
    if scale is None:
        scale = D ** -0.5
    mask = (jnp.tril(jnp.ones((S, S), bool)) if causal else None)
    o, m, l = _block_attn(q, k, v, mask, scale)
    return (o / jnp.maximum(l[..., None], 1e-30)).astype(q.dtype)


def make_ring_attention_fn(mesh, *, causal: bool = True,
                           rules=None, layout: str = "contiguous"):
    """shard_map-wrapped ring attention for a given mesh.

    Shards: batch over (dp, fsdp), seq over sp, heads over tp.  Falls back
    to plain local attention when the mesh has no sp axis.
    ``layout="zigzag"`` enables causal load balancing — the caller feeds
    sequences pre-permuted with :func:`zigzag_permutation`.
    """
    from jax.sharding import PartitionSpec as P

    from ray_tpu.parallel.compat import shard_map

    sp = mesh.shape.get("sp", 1)
    if sp <= 1:
        return functools.partial(local_attention, causal=causal)

    def drop_missing(spec_axes):
        out = []
        for a in spec_axes:
            if isinstance(a, tuple):
                a = tuple(x for x in a if mesh.shape.get(x, 1) >= 1
                          and x in mesh.axis_names) or None
            elif a is not None and a not in mesh.axis_names:
                a = None
            out.append(a)
        return P(*out)

    spec = drop_missing([("dp", "fsdp"), "sp", "tp", None])

    @functools.partial(shard_map, mesh=mesh,
                       in_specs=(spec, spec, spec), out_specs=spec)
    def fn(q, k, v):
        return ring_attention(q, k, v, axis_name="sp", causal=causal,
                              layout=layout)

    return fn
