"""Ring attention — sequence/context parallelism over an ICI ring.

The reference has NO native sequence parallelism (verified in SURVEY.md
§2.4: Ray delegates long-context to DeepSpeed/Lightning inside the user
fn).  Here it is first-class: K/V shards rotate around the ``sp`` mesh
axis via ``ppermute`` while each device accumulates blockwise attention
for its resident Q shard with an online (streaming) softmax — attention
over sequences of length ``sp * S_local`` with O(S_local^2) memory.

Design (Liu et al. ring attention + flash-attention online softmax):
- one ring step per sp-rank; compute for the resident block overlaps the
  ppermute of the next K/V block (XLA schedules the collective async);
- numerics: scores/stats accumulate in f32 regardless of input dtype;
  masked logits use a large-negative finite value so fully-masked blocks
  stay NaN-free (every causal row owns its diagonal, so the final result
  is exact);
- the per-block kernel is pluggable: defaults to an einsum path XLA fuses
  well; ``ray_tpu.ops.attention`` provides the Pallas flash kernel for the
  resident-block case.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax

_NEG_INF = -1e9


def _block_attn(q, k, v, mask, scale):
    """One blockwise attention step returning (out, row_max, row_sum).

    q: [B, Sq, H, D]  k/v: [B, Sk, H, D]  mask: [Sq, Sk] bool or None.
    Stats in f32: out [B, Sq, H, D], m/l [B, Sq, H].
    """
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if mask is not None:
        scores = jnp.where(mask[None, None, :, :], scores, _NEG_INF)
    m = jnp.max(scores, axis=-1)                      # [B, H, Sq]
    p = jnp.exp(scores - m[..., None])                # [B, H, Sq, Sk]
    l = jnp.sum(p, axis=-1)                           # [B, H, Sq]
    o = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v,
                   preferred_element_type=jnp.float32)
    # reshape stats to [B, Sq, H]
    return o, jnp.transpose(m, (0, 2, 1)), jnp.transpose(l, (0, 2, 1))


def ring_attention(q, k, v, *, axis_name: str = "sp", causal: bool = True,
                   scale: Optional[float] = None,
                   block_attn: Callable = _block_attn):
    """Ring attention over a sharded sequence axis.

    Must run inside ``shard_map`` (or pjit-manual) with ``axis_name``
    bound.  q, k, v: ``[B, S_local, H, D]`` — the local sequence shard.
    Returns ``[B, S_local, H, D]`` in q's dtype.
    """
    n = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    B, S, H, D = q.shape
    if scale is None:
        scale = D ** -0.5

    q_pos = my * S + jnp.arange(S)                    # global q positions

    def step(carry, step_idx):
        o, m, l, k_blk, v_blk = carry
        src = (my - step_idx) % n
        if causal:
            kv_pos = src * S + jnp.arange(S)
            mask = q_pos[:, None] >= kv_pos[None, :]
        else:
            mask = None
        bo, bm, bl = block_attn(q, k_blk, v_blk, mask, scale)
        m_new = jnp.maximum(m, bm)
        alpha = jnp.exp(m - m_new)                    # rescale old state
        beta = jnp.exp(bm - m_new)                    # rescale new block
        l_new = l * alpha + bl * beta
        o_new = (o * alpha[..., None]
                 + bo * beta[..., None])
        perm = [(i, (i + 1) % n) for i in range(n)]
        k_next = lax.ppermute(k_blk, axis_name, perm)
        v_next = lax.ppermute(v_blk, axis_name, perm)
        return (o_new, m_new, l_new, k_next, v_next), None

    o0 = jnp.zeros((B, S, H, D), jnp.float32)
    m0 = jnp.full((B, S, H), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, S, H), jnp.float32)
    (o, m, l, _, _), _ = lax.scan(step, (o0, m0, l0, k, v),
                                  jnp.arange(n))
    out = o / jnp.maximum(l[..., None], 1e-30)
    return out.astype(q.dtype)


def local_attention(q, k, v, *, causal: bool = True,
                    scale: Optional[float] = None):
    """Single-device reference attention (same signature, no ring)."""
    B, S, H, D = q.shape
    if scale is None:
        scale = D ** -0.5
    mask = (jnp.tril(jnp.ones((S, S), bool)) if causal else None)
    o, m, l = _block_attn(q, k, v, mask, scale)
    return (o / jnp.maximum(l[..., None], 1e-30)).astype(q.dtype)


def make_ring_attention_fn(mesh, *, causal: bool = True,
                           rules=None):
    """shard_map-wrapped ring attention for a given mesh.

    Shards: batch over (dp, fsdp), seq over sp, heads over tp.  Falls back
    to plain local attention when the mesh has no sp axis.
    """
    from jax.sharding import PartitionSpec as P

    from ray_tpu.parallel.compat import shard_map

    sp = mesh.shape.get("sp", 1)
    if sp <= 1:
        return functools.partial(local_attention, causal=causal)

    def drop_missing(spec_axes):
        out = []
        for a in spec_axes:
            if isinstance(a, tuple):
                a = tuple(x for x in a if mesh.shape.get(x, 1) >= 1
                          and x in mesh.axis_names) or None
            elif a is not None and a not in mesh.axis_names:
                a = None
            out.append(a)
        return P(*out)

    spec = drop_missing([("dp", "fsdp"), "sp", "tp", None])

    @functools.partial(shard_map, mesh=mesh,
                       in_specs=(spec, spec, spec), out_specs=spec)
    def fn(q, k, v):
        return ring_attention(q, k, v, axis_name="sp", causal=causal)

    return fn
