"""Mixture-of-Experts with expert parallelism over an ``ep`` mesh axis.

Absent natively in the reference (SURVEY.md §2.4).  TPU-native design:
top-k token routing with a static capacity (XLA needs static shapes — no
ragged dispatch), expressed as one-hot einsums the compiler turns into
MXU-friendly matmuls; under an ``ep`` axis the dispatched tokens move to
their experts with ``lax.all_to_all`` and return the same way.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ray_tpu.parallel.compat import shard_map


class MoEParams(NamedTuple):
    wg: jnp.ndarray   # [d, E] router
    w1: jnp.ndarray   # [E, d, h]
    w2: jnp.ndarray   # [E, h, d]


def init_moe_params(key, d_model: int, hidden: int, n_experts: int,
                    dtype=jnp.float32) -> MoEParams:
    k1, k2, k3 = jax.random.split(key, 3)
    scale = d_model ** -0.5
    return MoEParams(
        wg=(jax.random.normal(k1, (d_model, n_experts)) * scale
            ).astype(dtype),
        w1=(jax.random.normal(k2, (n_experts, d_model, hidden)) * scale
            ).astype(dtype),
        w2=(jax.random.normal(k3, (n_experts, hidden, d_model))
            * hidden ** -0.5).astype(dtype),
    )


def _route(x, wg, top_k: int, capacity: int):
    """Compute dispatch/combine tensors.

    x: [T, d] tokens.  Returns dispatch [T, E, C] (0/1), combine [T, E, C]
    (gate weights), aux_loss (load-balance).
    """
    T = x.shape[0]
    E = wg.shape[1]
    logits = jnp.einsum("td,de->te", x.astype(jnp.float32),
                        wg.astype(jnp.float32))
    probs = jax.nn.softmax(logits, axis=-1)
    gate_vals, expert_idx = lax.top_k(probs, top_k)          # [T, k]
    # normalize the selected gates
    gate_vals = gate_vals / jnp.maximum(
        gate_vals.sum(-1, keepdims=True), 1e-9)
    # position of each token within its expert's buffer, per k-slot
    dispatch = jnp.zeros((T, E, capacity), jnp.float32)
    combine = jnp.zeros((T, E, capacity), jnp.float32)
    # fill slot by slot so capacity is consumed in priority order
    used = jnp.zeros((E,), jnp.int32)
    for slot in range(top_k):
        e = expert_idx[:, slot]                               # [T]
        onehot = jax.nn.one_hot(e, E, dtype=jnp.int32)        # [T, E]
        pos_in_e = (jnp.cumsum(onehot, axis=0) - onehot) + used[None, :]
        pos = jnp.sum(pos_in_e * onehot, axis=1)              # [T]
        keep = pos < capacity
        pos_oh = jax.nn.one_hot(pos, capacity, dtype=jnp.float32)
        sel = (onehot.astype(jnp.float32) * keep[:, None].astype(
            jnp.float32))
        dispatch = dispatch + sel[:, :, None] * pos_oh[:, None, :]
        combine = combine + (sel * gate_vals[:, slot:slot + 1]
                             )[:, :, None] * pos_oh[:, None, :]
        used = used + jnp.sum(sel, axis=0).astype(jnp.int32)
    # load-balance auxiliary loss (Switch-style)
    me = probs.mean(axis=0)
    ce = (dispatch.sum(axis=2) > 0).astype(jnp.float32).mean(axis=0)
    aux = E * jnp.sum(me * ce)
    return dispatch, combine, aux


def _expert_ffn(w1, w2, tokens):
    """tokens: [E, C, d] -> [E, C, d] through each expert's FFN."""
    h = jnp.einsum("ecd,edh->ech", tokens, w1)
    h = jax.nn.gelu(h)
    return jnp.einsum("ech,ehd->ecd", h, w2)


def moe_layer(params: MoEParams, x, *, top_k: int = 2,
              capacity_factor: float = 1.5,
              axis_name: Optional[str] = None,
              expert_ffn=None):
    """Apply an MoE FFN to ``x`` ``[T, d]`` (flatten batch*seq first).

    With ``axis_name`` set, runs the expert-parallel path: tokens are local
    to each device, experts sharded over the axis; dispatched tokens
    all_to_all to their expert's device and back.
    """
    if expert_ffn is None:
        expert_ffn = _expert_ffn
    T, d = x.shape
    E = params.wg.shape[1]
    if axis_name is None:
        capacity = max(top_k, int(capacity_factor * T * top_k / E))
        dispatch, combine, aux = _route(x, params.wg, top_k, capacity)
        expert_in = jnp.einsum("tec,td->ecd", dispatch, x)
        expert_out = expert_ffn(params.w1, params.w2, expert_in)
        out = jnp.einsum("tec,ecd->td", combine, expert_out)
        return out.astype(x.dtype), aux

    # ---- expert-parallel: params.w1/w2 are the LOCAL expert shard ----
    from ray_tpu.parallel.compat import axis_size
    n = axis_size(axis_name)
    E_local = params.w1.shape[0]
    E_global = E_local * n
    assert params.wg.shape[1] == E_global, (
        "router must score all global experts")
    capacity = max(top_k, int(capacity_factor * T * top_k / E_global))
    dispatch, combine, aux = _route(x, params.wg, top_k, capacity)
    expert_in = jnp.einsum("tec,td->ecd", dispatch, x)   # [E_glob, C, d]
    # send each expert's tokens to the device owning it:
    # [E_glob, C, d] -> [E_local, n*C, d]
    expert_in = lax.all_to_all(
        expert_in.reshape(n, E_local, capacity, d), axis_name,
        split_axis=0, concat_axis=1).reshape(E_local, n * capacity, d)
    expert_out = expert_ffn(params.w1, params.w2, expert_in)
    # route back: [E_local, n*C, d] -> [E_glob, C, d]
    expert_out = lax.all_to_all(
        expert_out.reshape(E_local, n, capacity, d), axis_name,
        split_axis=1, concat_axis=0).reshape(E_global, capacity, d)
    out = jnp.einsum("tec,ecd->td", combine, expert_out)
    return out.astype(x.dtype), lax.pmean(aux, axis_name)


def make_moe_fn(mesh, *, top_k: int = 2, capacity_factor: float = 1.5):
    """shard_map-wrapped expert-parallel MoE for a mesh with an ep axis.

    Token batch sharded over (dp, fsdp, ep is folded over tokens too);
    experts sharded over ep.
    """
    ep = mesh.shape.get("ep", 1)
    if ep <= 1:
        def dense(params, x):
            return moe_layer(params, x, top_k=top_k,
                             capacity_factor=capacity_factor)
        return dense

    pspec = MoEParams(wg=P(None, None), w1=P("ep", None, None),
                      w2=P("ep", None, None))

    @functools.partial(shard_map, mesh=mesh,
                       in_specs=(pspec, P("ep", None)),
                       out_specs=(P("ep", None), P()))
    def fn(params, x):
        out, aux = moe_layer(params, x, top_k=top_k,
                             capacity_factor=capacity_factor,
                             axis_name="ep")
        return out, aux

    return fn
