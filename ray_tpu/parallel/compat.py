"""JAX API compatibility shims (jax.shard_map moved/renamed across 0.4→0.9)."""

from __future__ import annotations

import functools
from typing import Any


def shard_map(f=None, *, mesh=None, in_specs=None, out_specs=None,
              check: bool = False):
    """Uniform shard_map wrapper with replication checking disabled.

    The manual collectives here (ppermute rings, all_to_all) confuse the
    replication checker on some jax versions; numerical tests cover
    correctness instead.
    """
    import jax

    def wrap(fn):
        if hasattr(jax, "shard_map"):
            return jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs, check_vma=check)
        from jax.experimental.shard_map import shard_map as _sm
        return _sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                   check_rep=check)

    if f is None:
        return wrap
    return wrap(f)
