"""JAX API compatibility shims (jax.shard_map moved/renamed across 0.4→0.9)."""

from __future__ import annotations

import functools
from typing import Any


def shard_map(f=None, *, mesh=None, in_specs=None, out_specs=None,
              check: bool = False, axis_names=None):
    """Uniform shard_map wrapper with replication checking disabled.

    The manual collectives here (ppermute rings, all_to_all) confuse the
    replication checker on some jax versions; numerical tests cover
    correctness instead.

    ``axis_names`` (jax >= 0.8): partial-manual mode — only the named mesh
    axes are manual inside the body; the rest stay automatic, so sharding
    constraints on them still propagate (used by the pipeline layer to be
    manual over ``pp`` while dp/fsdp/tp compose automatically).
    """
    import jax

    def wrap(fn):
        if hasattr(jax, "shard_map"):
            kw = {}
            if axis_names is not None:
                kw["axis_names"] = frozenset(axis_names)
            return jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs, check_vma=check,
                                 **kw)
        if axis_names is not None:
            raise NotImplementedError(
                "partial-manual shard_map needs jax.shard_map (jax>=0.8)")
        from jax.experimental.shard_map import shard_map as _sm
        return _sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                   check_rep=check)

    if f is None:
        return wrap
    return wrap(f)


def axis_size(axis_name):
    """Size of a bound mesh axis inside shard_map.

    ``lax.axis_size`` appeared in jax 0.5; ``psum(1)`` is the 0.4.x
    spelling (constant-folded to a static int).  One home for the shim
    — ring_attention, moe and overlap all need it."""
    from jax import lax
    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis_name)
    return lax.psum(1, axis_name)


def supports_partial_manual() -> bool:
    import inspect

    import jax
    if not hasattr(jax, "shard_map"):
        return False
    return "axis_names" in inspect.signature(jax.shard_map).parameters
