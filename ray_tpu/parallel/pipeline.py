"""Pipeline parallelism over a ``pp`` mesh axis.

Absent natively in the reference (SURVEY.md §2.4 — delegated to DeepSpeed
et al.).  TPU-native design: every stage is the *same* jitted SPMD program
(one shard_map over ``pp``); stage weights are the per-device shard of a
stacked param tree; activations move stage-to-stage with ``ppermute`` in a
GPipe schedule.  Autodiff differentiates straight through the scan +
ppermute, so the backward pipeline falls out of the forward one.

This composes with the other axes: within a stage the layer math can be
tp/fsdp-sharded as usual (the shard_map here only manages ``pp``).
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ray_tpu.parallel.compat import shard_map, supports_partial_manual


def pipeline_apply(stage_fn: Callable, stacked_params, x, *, mesh,
                   num_microbatches: int, params_spec=None):
    """Run a GPipe pipeline.

    Args:
      stage_fn: ``(params_slice, activation) -> activation`` for ONE stage;
        activation shapes must match across stages.
      stacked_params: pytree whose leaves have leading dim ``pp`` (stage).
      x: ``[M, mb, ...]`` microbatched input (M = num_microbatches).
      mesh: mesh containing a ``pp`` axis.
      params_spec: optional pytree of PartitionSpecs for stacked_params
        (defaults to sharding dim 0 over pp, rest replicated).

    Returns the last stage's outputs, ``[M, mb, ...]``.

    On jax>=0.8 the shard_map is *partial-manual*: only ``pp`` is manual,
    so dp/fsdp/tp shardings inside ``stage_fn`` compose automatically
    (XLA partitions the within-stage math as usual).
    """
    pp = mesh.shape["pp"]
    xs_m = jax.tree.leaves(x)[0].shape[0]
    if xs_m != num_microbatches:
        raise ValueError(f"x leading dim {xs_m} != "
                         f"num_microbatches {num_microbatches}")
    partial_manual = supports_partial_manual()
    if params_spec is None:
        params_spec = jax.tree.map(
            lambda leaf: P("pp", *([None] * (leaf.ndim - 1))),
            stacked_params)

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(params_spec, P()), out_specs=P(),
        axis_names={"pp"} if partial_manual else None)
    def run(params, xs):
        # params leaves: [1, ...] local stage slice -> squeeze
        params = jax.tree.map(lambda p: jnp.squeeze(p, 0), params)
        my = lax.axis_index("pp")
        M = xs.shape[0]
        T = M + pp - 1
        act0 = jnp.zeros_like(xs[0])
        out0 = jnp.zeros_like(xs)
        perm_fwd = [(i, (i + 1) % pp) for i in range(pp)]

        def tick(carry, t):
            act, outs = carry
            # receive from previous stage (stage 0 receives garbage ring
            # wrap, replaced by injection below)
            received = lax.ppermute(act, "pp", perm_fwd)
            inject = xs[jnp.minimum(t, M - 1)]
            act_in = jnp.where(my == 0, inject, received)
            act_out = stage_fn(params, act_in)
            out_idx = t - (pp - 1)
            write = jnp.logical_and(my == pp - 1, out_idx >= 0)
            idx = jnp.maximum(out_idx, 0)
            updated = lax.dynamic_update_index_in_dim(
                outs, jnp.where(write, act_out, outs[idx]), idx, 0)
            return (act_out, updated), None

        (act, outs), _ = lax.scan(tick, (act0, out0), jnp.arange(T))
        # broadcast the last stage's buffer to all stages
        mask = (my == pp - 1).astype(outs.dtype)
        outs = lax.psum(outs * mask, "pp")
        return outs

    return run(stacked_params, x)


def pipeline_loss_fn(stage_fn: Callable, loss_fn: Callable):
    """Compose a pipeline forward with a loss on the final activations."""
    def fn(stacked_params, x, targets, *, mesh, num_microbatches):
        out = pipeline_apply(stage_fn, stacked_params, x, mesh=mesh,
                             num_microbatches=num_microbatches)
        return loss_fn(out, targets)
    return fn


def stack_stage_params(per_stage_params):
    """[{...}, {...}] -> single pytree with leading stage dim."""
    return jax.tree.map(lambda *leaves: jnp.stack(leaves),
                        *per_stage_params)
