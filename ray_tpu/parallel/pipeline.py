"""Pipeline parallelism over a ``pp`` (or ``dcn``) mesh axis.

Absent natively in the reference (SURVEY.md §2.4 — delegated to DeepSpeed
et al.).  TPU-native design: every stage is the *same* jitted SPMD program
(one shard_map over the stage axis); stage weights are the per-device
shard of a stacked param tree; activations move stage-to-stage with
``ppermute``.  Two schedules:

* GPipe (:func:`pipeline_apply`): all forwards, then autodiff's mirrored
  backward sweep.  Simple, but every microbatch's activations are live at
  the steady-state peak (in-flight = M).
* 1F1B (:func:`pipeline_1f1b_value_and_grad`, arXiv:2011.03641): each
  stage alternates one forward with one backward once warm, so at most
  ``2*pp - 1`` microbatches are in flight regardless of M — the
  activation footprint is bounded by the *depth*, not the *batch*.  The
  backward is hand-scheduled (recompute + ``jax.vjp`` per tick) because
  autodiff of a scan cannot interleave ticks.

Both compose with the other axes: within a stage the layer math can be
tp/fsdp-sharded as usual (the shard_map here only manages the stage
axis).  Staging over ``dcn`` is the natural multi-pod layout: one stage
per pod, only the microbatch activation boundary crossing the slow tier
per tick instead of a gradient all-reduce of the whole model.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ray_tpu.parallel.compat import shard_map, supports_partial_manual


def pipeline_schedule_stats(pp: int, num_microbatches: int,
                            schedule: str = "1f1b") -> Dict[str, Any]:
    """Analytic schedule figures: bubble fraction and peak in-flight
    microbatches (the activation-memory driver).

    GPipe idles ``pp - 1`` of ``M + pp - 1`` ticks per sweep and holds
    all ``M`` microbatches' activations at peak; 1F1B idles
    ``2*pp - 2`` of ``M + 2*pp - 2`` ticks (same asymptotic fraction)
    but holds at most ``2*pp - 1``.  Reported by ``build_gpt_train_pp``
    and the r22 scratch driver so the bubble is a number in the run
    record, not a vibe."""
    M = int(num_microbatches)
    pp = int(pp)
    if schedule == "gpipe":
        ticks = M + pp - 1
        bubble = (pp - 1) / ticks
        in_flight = M
    elif schedule == "1f1b":
        ticks = M + 2 * pp - 2
        bubble = (2 * pp - 2) / max(ticks, 1)
        in_flight = min(M, 2 * pp - 1)
    else:
        raise ValueError(
            f"unknown pipeline schedule {schedule!r} "
            "(want 'gpipe' or '1f1b')")
    return {"schedule": schedule, "stages": pp, "num_microbatches": M,
            "ticks": ticks, "bubble_fraction": bubble,
            "in_flight_microbatches": in_flight}


def pipeline_apply(stage_fn: Callable, stacked_params, x, *, mesh,
                   num_microbatches: int, params_spec=None,
                   axis: str = "pp"):
    """Run a GPipe pipeline.

    Args:
      stage_fn: ``(params_slice, activation) -> activation`` for ONE stage;
        activation shapes must match across stages.
      stacked_params: pytree whose leaves have leading dim ``pp`` (stage).
      x: ``[M, mb, ...]`` microbatched input (M = num_microbatches).
      mesh: mesh containing the stage axis.
      params_spec: optional pytree of PartitionSpecs for stacked_params
        (defaults to sharding dim 0 over the stage axis, rest replicated).
      axis: mesh axis to stage over (``"pp"``, or ``"dcn"`` for
        one-stage-per-pod layouts).

    Returns the last stage's outputs, ``[M, mb, ...]``.

    On jax>=0.8 the shard_map is *partial-manual*: only the stage axis is
    manual, so dp/fsdp/tp shardings inside ``stage_fn`` compose
    automatically (XLA partitions the within-stage math as usual).
    """
    pp = mesh.shape[axis]
    xs_m = jax.tree.leaves(x)[0].shape[0]
    if xs_m != num_microbatches:
        raise ValueError(f"x leading dim {xs_m} != "
                         f"num_microbatches {num_microbatches}")
    partial_manual = supports_partial_manual()
    if params_spec is None:
        params_spec = jax.tree.map(
            lambda leaf: P(axis, *([None] * (leaf.ndim - 1))),
            stacked_params)

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(params_spec, P()), out_specs=P(),
        axis_names={axis} if partial_manual else None)
    def run(params, xs):
        # params leaves: [1, ...] local stage slice -> squeeze
        params = jax.tree.map(lambda p: jnp.squeeze(p, 0), params)
        my = lax.axis_index(axis)
        M = xs.shape[0]
        T = M + pp - 1
        act0 = jnp.zeros_like(xs[0])
        out0 = jnp.zeros_like(xs)
        perm_fwd = [(i, (i + 1) % pp) for i in range(pp)]

        def tick(carry, t):
            act, outs = carry
            # receive from previous stage (stage 0 receives garbage ring
            # wrap, replaced by injection below)
            received = lax.ppermute(act, axis, perm_fwd)
            inject = xs[jnp.minimum(t, M - 1)]
            act_in = jnp.where(my == 0, inject, received)
            act_out = stage_fn(params, act_in)
            out_idx = t - (pp - 1)
            write = jnp.logical_and(my == pp - 1, out_idx >= 0)
            idx = jnp.maximum(out_idx, 0)
            updated = lax.dynamic_update_index_in_dim(
                outs, jnp.where(write, act_out, outs[idx]), idx, 0)
            return (act_out, updated), None

        (act, outs), _ = lax.scan(tick, (act0, out0), jnp.arange(T))
        # broadcast the last stage's buffer to all stages
        mask = (my == pp - 1).astype(outs.dtype)
        outs = lax.psum(outs * mask, axis)
        return outs

    return run(stacked_params, x)


def pipeline_1f1b_value_and_grad(
        stage_fn: Callable, stage_params, shared_params, mb_inputs, *,
        mesh, num_microbatches: int, act_example,
        axis: str = "pp", cot_weights=None, stage_spec=None):
    """One-forward-one-backward pipeline step: loss AND grads in a
    single hand-scheduled sweep (arXiv:2011.03641).

    The schedule: microbatch ``u`` runs forward on stage ``s`` at tick
    ``u + s`` and backward at tick ``u + 2*pp - 2 - s`` — the last
    stage's forward and backward of the same microbatch share a tick,
    which is what bounds in-flight activations at ``2*pp - 1``.  Each
    stage keeps a ring buffer of its ``min(M, 2*pp - 1)`` most recent
    stage *inputs*; the backward recomputes the stage forward from the
    saved input under ``jax.vjp`` (remat — the same memory/flops trade
    the non-pipelined path makes) and ppermutes the input-cotangent
    upstream.  Bubble ticks compute on zeros/clamped indices and are
    masked out of every accumulator with ``where`` *selects* (never
    multiplies), so garbage — even a NaN — cannot reach a live value.

    Args:
      stage_fn: ``(stage_params_local, shared_params, act_in, mb) ->
        (act_out, loss)`` for ONE stage, uniform across stages (mask
        internally on the stage index: first stage ignores ``act_in``
        and embeds from ``mb``; ``loss`` is read only on the last
        stage).  ``loss`` must be this microbatch's *mean* over its own
        valid tokens — the runner weights it by ``cot_weights[u]``.
      stage_params: pytree, leaves ``[pp, ...]`` (stage-stacked).
      shared_params: pytree replicated across stages (embedding table,
        final norm, head); grads are psum'd over the stage axis.
      mb_inputs: pytree, leaves ``[M, ...]`` — per-microbatch inputs
        (tokens, targets), replicated over the stage axis (the last
        stage needs every microbatch's targets).
      act_example: activation template (``[mb_rows, ...]``) used to
        shape the carries; zeros of it must be a legal stage input.
      cot_weights: ``[M]`` f32 loss weights (default uniform ``1/M``).
        For masked targets pass ``n_u / n_total`` so the weighted sum
        equals the global masked mean exactly.
      stage_spec: PartitionSpec tree for ``stage_params`` (default: dim
        0 over ``axis``, rest replicated).

    Returns ``(loss, stage_grads, shared_grads)``; grads are f32,
    ``stage_grads`` stage-stacked like ``stage_params``.
    """
    pp = int(mesh.shape[axis])
    M = int(num_microbatches)
    if M < 1:
        raise ValueError(f"num_microbatches={M} must be >= 1")
    for leaf in jax.tree.leaves(mb_inputs):
        if leaf.shape[0] != M:
            raise ValueError(
                f"mb_inputs leading dim {leaf.shape[0]} != "
                f"num_microbatches {M}")
    partial_manual = supports_partial_manual()
    if not partial_manual and any(
            int(v) > 1 for a, v in dict(mesh.shape).items() if a != axis):
        raise ValueError(
            f"1F1B over axis {axis!r} with other sharded mesh axes "
            "requires partial-manual shard_map (jax >= 0.8)")
    if stage_spec is None:
        stage_spec = jax.tree.map(
            lambda leaf: P(axis, *([None] * (leaf.ndim - 1))),
            stage_params)
    if cot_weights is None:
        cot_weights = jnp.full((M,), 1.0 / M, jnp.float32)

    T = M + 2 * pp - 2
    K = min(M, 2 * pp - 1)     # ring-buffer depth = peak in-flight

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(stage_spec, P(), P(), P(), P()),
        out_specs=(P(), stage_spec, P()),
        axis_names={axis} if partial_manual else None)
    def run(p_stage, p_shared, mbs, w, act0):
        p_stage = jax.tree.map(lambda p: jnp.squeeze(p, 0), p_stage)
        s = lax.axis_index(axis)
        is_last = s == pp - 1
        perm_fwd = [(i, (i + 1) % pp) for i in range(pp)]
        perm_bwd = [(i, (i - 1) % pp) for i in range(pp)]

        zero_act = jnp.zeros_like(act0)
        saved0 = jnp.zeros((K,) + act0.shape, act0.dtype)
        gs0 = jax.tree.map(
            lambda p: jnp.zeros(p.shape, jnp.float32), p_stage)
        gh0 = jax.tree.map(
            lambda p: jnp.zeros(p.shape, jnp.float32), p_shared)

        def mb_at(u):
            return jax.tree.map(
                lambda leaf: lax.dynamic_index_in_dim(
                    leaf, u, 0, keepdims=False), mbs)

        def tick(carry, t):
            act_fwd, cot_bwd, saved, gs, gh, loss_acc = carry
            received = lax.ppermute(act_fwd, axis, perm_fwd)
            cot_recv = lax.ppermute(cot_bwd, axis, perm_bwd)

            # ---- forward: microbatch u_f = t - s
            u_f = t - s
            f_valid = jnp.logical_and(u_f >= 0, u_f < M)
            u_fc = jnp.clip(u_f, 0, M - 1)
            act_in = jnp.where(f_valid, received, zero_act)
            # save the stage INPUT for the remat backward; the slot is
            # free again by construction (K = 2*pp - 1 covers the
            # longest fwd->bwd gap, at stage 0)
            slot_f = jnp.mod(u_fc, K)
            prev = lax.dynamic_index_in_dim(saved, slot_f, 0,
                                            keepdims=False)
            saved = lax.dynamic_update_index_in_dim(
                saved, jnp.where(f_valid, act_in, prev), slot_f, 0)
            act_out, loss_u = stage_fn(p_stage, p_shared, act_in,
                                       mb_at(u_fc))
            act_fwd_next = jnp.where(f_valid, act_out, zero_act)
            loss_acc = loss_acc + jnp.where(
                jnp.logical_and(is_last, f_valid),
                loss_u.astype(jnp.float32) * w[u_fc], 0.0)

            # ---- backward: microbatch u_b = t - (2*pp - 2 - s).
            # The last stage's same-tick read of `saved` happens after
            # the write above, so u_b == u_f there is safe.
            u_b = t - (2 * pp - 2 - s)
            b_valid = jnp.logical_and(u_b >= 0, u_b < M)
            u_bc = jnp.clip(u_b, 0, M - 1)
            act_in_b = lax.dynamic_index_in_dim(
                saved, jnp.mod(u_bc, K), 0, keepdims=False)
            mb_b = mb_at(u_bc)

            def fwd(ps, ph, a):
                return stage_fn(ps, ph, a, mb_b)

            (out_b, loss_b), vjp_fn = jax.vjp(fwd, p_stage, p_shared,
                                              act_in_b)
            # cotangent seeds: downstream act-cotangent everywhere but
            # the last stage (whose act_out feeds nothing); the loss
            # seed w[u] only there
            cot_act = jnp.where(is_last, zero_act,
                                cot_recv).astype(out_b.dtype)
            cot_loss = jnp.where(is_last, w[u_bc],
                                 0.0).astype(loss_b.dtype)
            g_stage, g_shared, cot_in = vjp_fn((cot_act, cot_loss))
            gs = jax.tree.map(
                lambda acc, g: acc + jnp.where(
                    b_valid, g.astype(jnp.float32), 0.0), gs, g_stage)
            gh = jax.tree.map(
                lambda acc, g: acc + jnp.where(
                    b_valid, g.astype(jnp.float32), 0.0), gh, g_shared)
            cot_next = jnp.where(b_valid, cot_in,
                                 jnp.zeros_like(cot_in))
            return (act_fwd_next, cot_next, saved, gs, gh,
                    loss_acc), None

        carry0 = (zero_act, zero_act, saved0, gs0, gh0,
                  jnp.zeros((), jnp.float32))
        (_, _, _, gs, gh, loss_acc), _ = lax.scan(
            tick, carry0, jnp.arange(T))
        loss = lax.psum(loss_acc, axis)
        gh = lax.psum(gh, axis)
        gs = jax.tree.map(lambda g: jnp.expand_dims(g, 0), gs)
        return loss, gs, gh

    return run(stage_params, shared_params, mb_inputs,
               jnp.asarray(cot_weights, jnp.float32), act_example)


def pipeline_loss_fn(stage_fn: Callable, loss_fn: Callable):
    """Compose a pipeline forward with a loss on the final activations."""
    def fn(stacked_params, x, targets, *, mesh, num_microbatches):
        out = pipeline_apply(stage_fn, stacked_params, x, mesh=mesh,
                             num_microbatches=num_microbatches)
        return loss_fn(out, targets)
    return fn


def stack_stage_params(per_stage_params):
    """[{...}, {...}] -> single pytree with leading stage dim."""
    return jax.tree.map(lambda *leaves: jnp.stack(leaves),
                        *per_stage_params)
