"""LearnerGroup — data-parallel learner actors with gradient allreduce.

Parity: reference ``rllib/core/learner/learner_group.py:1`` (new stack):
N learner actors each hold a full copy of module + optimizer state and
update on their shard of the train batch; per-minibatch gradients are
ring-allreduced through ``ray_tpu.util.collective`` (the reference uses
torch DDP over NCCL), so every learner takes identical optimizer steps
and params never diverge.

TPU note: each learner actor can also pin its own chip slice and build a
local mesh (``num_tpus_per_learner``); gradients then move intra-learner
over ICI inside jit and inter-learner through the collective ring.

The group is also the learner host of the ``ray_tpu.rl`` actor/learner
loop (``learner_cls="ray_tpu.rl.learner.GPTPolicyLearner"``): batches
there are trajectory batches (``tokens``/``targets``/``rewards``, no
``obs``), and :meth:`LearnerGroup.publish_params` hands out the
versioned object-store weight snapshots the rollout actors hot-swap.
"""

from __future__ import annotations

import uuid
from typing import Any, Dict, List

import numpy as np

import ray_tpu


@ray_tpu.remote
class _LearnerActor:
    def __init__(self, module_blob: bytes, config, rank: int, world: int,
                 group_name: str, backend: str = "host",
                 learner_cls: str = "ray_tpu.rllib.algorithms.ppo."
                                    "PPOLearner"):
        import importlib

        import cloudpickle
        import jax

        mod_path, cls_name = learner_cls.rsplit(".", 1)
        cls = getattr(importlib.import_module(mod_path), cls_name)
        module = cloudpickle.loads(module_blob)
        self.learner = cls(module, config)
        self.rank, self.world = rank, world
        if world > 1:
            from ray_tpu.util import collective
            collective.init_collective_group(world, rank,
                                             backend=backend,
                                             group_name=group_name)
            self._group_name = group_name
        # identical seed everywhere: params start in sync and stay in
        # sync because every step applies the same allreduced gradient
        self.params, self.opt_state = self.learner.init_state(
            jax.random.PRNGKey(config.seed))
        from jax.flatten_util import ravel_pytree
        _, self._unravel = ravel_pytree(self.params)

    def _allreduce(self, grads):
        from jax.flatten_util import ravel_pytree

        from ray_tpu.util import collective
        flat, _ = ravel_pytree(grads)
        summed = collective.allreduce(np.asarray(flat),
                                      group_name=self._group_name)
        return self._unravel(summed / self.world)

    def update(self, shard: Dict[str, np.ndarray]) -> Dict[str, float]:
        allreduce = self._allreduce if self.world > 1 else None
        self.params, self.opt_state, metrics = self.learner.update(
            self.params, self.opt_state, shard, allreduce=allreduce)
        return metrics

    def get_params(self):
        import jax
        return jax.tree.map(np.asarray, self.params)

    def ping(self):
        return self.rank


class LearnerGroup:
    """Driver-side fan-out over N learner actors."""

    def __init__(self, module, config, num_learners: int = 2,
                 num_cpus_per_learner: float = 1.0,
                 num_tpus_per_learner: float = 0.0,
                 backend: str = "host",
                 learner_cls: str = "ray_tpu.rllib.algorithms.ppo."
                                    "PPOLearner"):
        import cloudpickle
        blob = cloudpickle.dumps(module)
        group = f"learner_{uuid.uuid4().hex[:8]}"
        self._group = group
        self._backend = backend
        opts: Dict[str, Any] = {"num_cpus": num_cpus_per_learner}
        if num_tpus_per_learner:
            opts["num_tpus"] = num_tpus_per_learner
        self.world = num_learners
        self.actors = [
            _LearnerActor.options(**opts).remote(
                blob, config, rank, num_learners, group,
                backend, learner_cls)
            for rank in range(num_learners)]
        ray_tpu.get([a.ping.remote() for a in self.actors], timeout=300)
        self._param_version = 0

    @staticmethod
    def _batch_len(train_batch: Dict[str, np.ndarray]) -> int:
        """Leading batch dimension: ``obs`` for env batches (the PPO
        family), else the first array leaf — RL trajectory batches
        carry ``tokens``/``targets``/``rewards`` and no ``obs``."""
        if "obs" in train_batch:
            return len(train_batch["obs"])
        for v in train_batch.values():
            if getattr(v, "ndim", 0) >= 1:
                return v.shape[0]
        raise ValueError("train batch has no array leaves to shard")

    def update(self, train_batch: Dict[str, np.ndarray]
               ) -> Dict[str, float]:
        """Shard the batch on axis 0 across learners; every learner must
        see the same number of minibatch steps (collective lockstep), so
        the batch is trimmed to a multiple of the world size.  Arrays
        whose leading dim differs from the batch's (e.g. PPO's scalar
        bootstrap_value) are dropped from the shards."""
        n = self._batch_len(train_batch)
        usable = n - n % self.world
        per = usable // self.world
        if per == 0:
            raise ValueError(
                f"train batch of {n} rows cannot feed {self.world} "
                "learners — reduce num_learners or sample more")
        shards: List[Dict[str, np.ndarray]] = []
        for r in range(self.world):
            sl = slice(r * per, (r + 1) * per)
            shards.append({k: v[sl] for k, v in train_batch.items()
                           if getattr(v, "ndim", 0) >= 1
                           and v.shape[0] == n})
        metrics = ray_tpu.get(
            [a.update.remote(shard)
             for a, shard in zip(self.actors, shards)], timeout=600)
        out: Dict[str, float] = {}
        for key in metrics[0]:
            out[key] = float(np.mean([m[key] for m in metrics]))
        return out

    def get_params(self):
        return ray_tpu.get(self.actors[0].get_params.remote(),
                           timeout=120)

    def get_all_params(self):
        """Every learner's params (tests assert they stay identical)."""
        return ray_tpu.get([a.get_params.remote() for a in self.actors],
                           timeout=120)

    def get_params_ref(self):
        """ObjectRef of rank-0 params — pass straight into downstream
        task args (auto-dereferenced) to skip a driver round-trip."""
        return self.actors[0].get_params.remote()

    def publish_params(self):
        """-> ``(version, ObjectRef)``: a *versioned* weight snapshot
        through the object store — the RL weight-publication contract.
        Learners stay in lockstep (identical allreduced steps), so
        rank 0's params ARE the group's params; the monotonic version
        is what rollout actors pin via ``engine.set_params(...,
        version=...)`` and what the staleness bound prices lag in."""
        self._param_version += 1
        return self._param_version, self.get_params_ref()

    def stop(self):
        for a in self.actors:
            try:
                ray_tpu.kill(a)
            except Exception:  # noqa: BLE001
                pass
        if self.world > 1:
            # the ring's rendezvous mailbox is a detached actor; kill it
            # or every LearnerGroup leaks one forever
            try:
                ray_tpu.kill(ray_tpu.get_actor(
                    f"__collective_{self._group}"))
            except Exception:  # noqa: BLE001
                pass
