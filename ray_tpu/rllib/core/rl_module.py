"""RLModule — the neural policy/value abstraction (JAX).

Parity: reference new-stack ``rllib/core/rl_module/rl_module.py``: one
object owning forward passes for exploration/inference/training.  Pure
functional JAX: params are a pytree, forward fns are jittable.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np


@dataclass
class MLPModuleConfig:
    obs_dim: int = 4
    num_actions: int = 2
    hidden: Tuple[int, ...] = (64, 64)
    dtype: Any = jnp.float32


class DiscreteMLPModule:
    """Categorical policy + value MLP (CartPole-class tasks)."""

    def __init__(self, config: MLPModuleConfig):
        self.config = config

    def init_params(self, key) -> Dict[str, Any]:
        cfg = self.config
        sizes = (cfg.obs_dim,) + tuple(cfg.hidden)
        params: Dict[str, Any] = {"layers": []}
        keys = jax.random.split(key, len(sizes) + 1)
        layers = []
        for i in range(len(sizes) - 1):
            w = jax.random.normal(keys[i], (sizes[i], sizes[i + 1])) * \
                (2.0 / sizes[i]) ** 0.5
            layers.append({"w": w.astype(cfg.dtype),
                           "b": jnp.zeros(sizes[i + 1], cfg.dtype)})
        params["layers"] = layers
        params["pi"] = {
            "w": (jax.random.normal(keys[-2],
                                    (sizes[-1], cfg.num_actions))
                  * 0.01).astype(cfg.dtype),
            "b": jnp.zeros(cfg.num_actions, cfg.dtype)}
        params["vf"] = {
            "w": (jax.random.normal(keys[-1], (sizes[-1], 1))
                  * 1.0).astype(cfg.dtype),
            "b": jnp.zeros(1, cfg.dtype)}
        return params

    def _trunk(self, params, obs):
        x = obs
        for layer in params["layers"]:
            x = jnp.tanh(x @ layer["w"] + layer["b"])
        return x

    def forward(self, params, obs):
        """obs [B, obs_dim] -> (logits [B, A], value [B])."""
        x = self._trunk(params, obs)
        logits = x @ params["pi"]["w"] + params["pi"]["b"]
        value = (x @ params["vf"]["w"] + params["vf"]["b"])[..., 0]
        return logits, value

    def action_dist(self, logits):
        return jax.nn.log_softmax(logits, axis=-1)

    def sample_actions(self, params, obs, key):
        logits, value = self.forward(params, obs)
        actions = jax.random.categorical(key, logits)
        logp = jnp.take_along_axis(
            jax.nn.log_softmax(logits), actions[..., None], -1)[..., 0]
        return actions, logp, value
