"""RLModule — the neural policy/value abstraction (JAX).

Parity: reference new-stack ``rllib/core/rl_module/rl_module.py``: one
object owning forward passes for exploration/inference/training.  Pure
functional JAX: params are a pytree, forward fns are jittable.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np


@dataclass
class MLPModuleConfig:
    obs_dim: int = 4
    num_actions: int = 2
    hidden: Tuple[int, ...] = (64, 64)
    dtype: Any = jnp.float32


class DiscreteMLPModule:
    """Categorical policy + value MLP (CartPole-class tasks)."""

    def __init__(self, config: MLPModuleConfig):
        self.config = config

    def init_params(self, key) -> Dict[str, Any]:
        cfg = self.config
        sizes = (cfg.obs_dim,) + tuple(cfg.hidden)
        params: Dict[str, Any] = {"layers": []}
        keys = jax.random.split(key, len(sizes) + 1)
        layers = []
        for i in range(len(sizes) - 1):
            w = jax.random.normal(keys[i], (sizes[i], sizes[i + 1])) * \
                (2.0 / sizes[i]) ** 0.5
            layers.append({"w": w.astype(cfg.dtype),
                           "b": jnp.zeros(sizes[i + 1], cfg.dtype)})
        params["layers"] = layers
        params["pi"] = {
            "w": (jax.random.normal(keys[-2],
                                    (sizes[-1], cfg.num_actions))
                  * 0.01).astype(cfg.dtype),
            "b": jnp.zeros(cfg.num_actions, cfg.dtype)}
        params["vf"] = {
            "w": (jax.random.normal(keys[-1], (sizes[-1], 1))
                  * 1.0).astype(cfg.dtype),
            "b": jnp.zeros(1, cfg.dtype)}
        return params

    def _trunk(self, params, obs):
        x = obs
        for layer in params["layers"]:
            x = jnp.tanh(x @ layer["w"] + layer["b"])
        return x

    def forward(self, params, obs):
        """obs [B, obs_dim] -> (logits [B, A], value [B])."""
        x = self._trunk(params, obs)
        logits = x @ params["pi"]["w"] + params["pi"]["b"]
        value = (x @ params["vf"]["w"] + params["vf"]["b"])[..., 0]
        return logits, value

    def action_dist(self, logits):
        return jax.nn.log_softmax(logits, axis=-1)

    def sample_actions(self, params, obs, key):
        logits, value = self.forward(params, obs)
        actions = jax.random.categorical(key, logits)
        logp = jnp.take_along_axis(
            jax.nn.log_softmax(logits), actions[..., None], -1)[..., 0]
        return actions, logp, value


@dataclass
class ContinuousModuleConfig:
    obs_dim: int = 3
    act_dim: int = 1
    act_low: Tuple[float, ...] = (-1.0,)
    act_high: Tuple[float, ...] = (1.0,)
    hidden: Tuple[int, ...] = (256, 256)
    log_std_bounds: Tuple[float, float] = (-10.0, 2.0)
    dtype: Any = jnp.float32


def _mlp_init(key, sizes, dtype, out_scale=0.01, out_dim=None):
    layers = []
    keys = jax.random.split(key, len(sizes))
    for i in range(len(sizes) - 1):
        w = jax.random.normal(keys[i], (sizes[i], sizes[i + 1])) * \
            (2.0 / sizes[i]) ** 0.5
        layers.append({"w": w.astype(dtype),
                       "b": jnp.zeros(sizes[i + 1], dtype)})
    if out_dim is not None:
        w = jax.random.normal(keys[-1], (sizes[-1], out_dim)) * out_scale
        layers.append({"w": w.astype(dtype),
                       "b": jnp.zeros(out_dim, dtype)})
    return layers


def _mlp_apply(layers, x, final_linear: bool):
    n = len(layers)
    for i, layer in enumerate(layers):
        x = x @ layer["w"] + layer["b"]
        if i < n - 1 or not final_linear:
            x = jax.nn.relu(x)
    return x


class SquashedGaussianModule:
    """Tanh-squashed Gaussian actor (SAC policy; reference:
    ``rllib/algorithms/sac/sac_rl_module.py`` action dist)."""

    def __init__(self, config: ContinuousModuleConfig):
        self.config = config
        self._low = np.asarray(config.act_low, np.float32)
        self._high = np.asarray(config.act_high, np.float32)

    def init_params(self, key):
        cfg = self.config
        sizes = (cfg.obs_dim,) + tuple(cfg.hidden)
        return {"trunk": _mlp_init(key, sizes, cfg.dtype,
                                   out_scale=0.01,
                                   out_dim=2 * cfg.act_dim)}

    def dist_params(self, params, obs):
        out = _mlp_apply(params["trunk"], obs, final_linear=True)
        mean, log_std = jnp.split(out, 2, axis=-1)
        lo, hi = self.config.log_std_bounds
        log_std = lo + 0.5 * (hi - lo) * (jnp.tanh(log_std) + 1.0)
        return mean, log_std

    def sample(self, params, obs, key):
        """-> (action in env bounds, log_prob)."""
        mean, log_std = self.dist_params(params, obs)
        std = jnp.exp(log_std)
        eps = jax.random.normal(key, mean.shape)
        pre = mean + std * eps
        logp = (-0.5 * (eps ** 2 + 2 * log_std
                        + jnp.log(2 * jnp.pi))).sum(-1)
        a = jnp.tanh(pre)
        # tanh change-of-variables
        logp -= jnp.log(jnp.clip(1 - a ** 2, 1e-6)).sum(-1)
        scale = (self._high - self._low) / 2.0
        act = self._low + (a + 1.0) * scale
        logp -= jnp.log(scale).sum()
        return act, logp

    def deterministic(self, params, obs):
        mean, _ = self.dist_params(params, obs)
        a = jnp.tanh(mean)
        return self._low + (a + 1.0) * (self._high - self._low) / 2.0


class TwinQModule:
    """Clipped double-Q critics (reference: SAC twin Q)."""

    def __init__(self, config: ContinuousModuleConfig):
        self.config = config

    def init_params(self, key):
        cfg = self.config
        k1, k2 = jax.random.split(key)
        sizes = (cfg.obs_dim + cfg.act_dim,) + tuple(cfg.hidden)
        return {"q1": _mlp_init(k1, sizes, cfg.dtype, out_scale=1.0,
                                out_dim=1),
                "q2": _mlp_init(k2, sizes, cfg.dtype, out_scale=1.0,
                                out_dim=1)}

    def forward(self, params, obs, act):
        x = jnp.concatenate([obs, act], axis=-1)
        q1 = _mlp_apply(params["q1"], x, final_linear=True)[..., 0]
        q2 = _mlp_apply(params["q2"], x, final_linear=True)[..., 0]
        return q1, q2
