"""Multi-agent environments + runner (parity: ``rllib/env/
multi_agent_env.py:29`` and the multi-agent episode collection in
``rllib/env/multi_agent_env_runner.py``).

API matches the reference's dict convention: ``reset() -> (obs_dict,
info_dict)``, ``step(action_dict) -> (obs, rewards, terminateds,
truncateds, infos)`` with a ``"__all__"`` key in terminateds/truncateds
signalling episode end.  Agents map to policies through
``policy_mapping_fn(agent_id)``; the runner groups each policy's
transitions and hands back per-policy PPO train batches (GAE computed
per agent trajectory at collection time).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

import ray_tpu


class MultiAgentEnv:
    """Base class: subclass and implement reset/step over agent dicts."""

    agents: List[str] = []

    def reset(self, *, seed: Optional[int] = None
              ) -> Tuple[Dict[str, Any], Dict[str, Any]]:
        raise NotImplementedError

    def step(self, action_dict: Dict[str, Any]):
        raise NotImplementedError

    @property
    def num_agents(self) -> int:
        return len(self.agents)


class MultiAgentCartPole(MultiAgentEnv):
    """N independent CartPoles, one per agent (the reference's standard
    multi-agent smoke env, ``rllib/examples/envs/classes/
    multi_agent.py`` MultiAgentCartPole)."""

    def __init__(self, num_agents: int = 2, seed: int = 0):
        import gymnasium as gym
        self.agents = [f"agent_{i}" for i in range(num_agents)]
        self._envs = {a: gym.make("CartPole-v1") for a in self.agents}
        self._seed = seed
        first = self._envs[self.agents[0]]
        self.observation_space = first.observation_space
        self.action_space = first.action_space

    def reset(self, *, seed: Optional[int] = None):
        obs, infos = {}, {}
        for i, a in enumerate(self.agents):
            o, info = self._envs[a].reset(
                seed=(seed or self._seed) + i)
            obs[a] = o
            infos[a] = info
        self._done = {a: False for a in self.agents}
        return obs, infos

    def step(self, action_dict: Dict[str, Any]):
        obs, rews, terms, truncs, infos = {}, {}, {}, {}, {}
        for a, act in action_dict.items():
            if self._done[a]:
                continue
            o, r, te, tr, info = self._envs[a].step(act)
            obs[a], rews[a], infos[a] = o, float(r), info
            terms[a], truncs[a] = te, tr
            if te or tr:
                self._done[a] = True
        terms["__all__"] = all(self._done.values())
        truncs["__all__"] = False
        return obs, rews, terms, truncs, infos


@ray_tpu.remote(num_cpus=1)
class MultiAgentEnvRunner:
    """Collect multi-agent rollouts; emit per-POLICY PPO batches.

    GAE runs here, per agent trajectory, so the learner receives flat
    (obs, actions, logp, advantages, value_targets) concatenations —
    the per-segment bookkeeping never crosses the actor boundary."""

    def __init__(self, env_factory_blob: bytes, modules_blob: bytes,
                 mapping_blob: bytes, rollout_length: int = 200,
                 gamma: float = 0.99, lam: float = 0.95, seed: int = 0):
        import cloudpickle
        self.env = cloudpickle.loads(env_factory_blob)()
        self.modules = cloudpickle.loads(modules_blob)  # policy -> module
        self.mapping = cloudpickle.loads(mapping_blob)
        self.rollout_length = rollout_length
        self.gamma = gamma
        self.lam = lam
        self.rng = np.random.default_rng(seed)
        self._key = None
        self._samplers = {}
        self.completed_returns: List[float] = []
        self._obs, _ = self.env.reset(seed=seed)
        self._ep_return = 0.0

    def _sampler(self, policy_id: str):
        import jax
        fn = self._samplers.get(policy_id)
        if fn is None:
            fn = jax.jit(self.modules[policy_id].sample_actions)
            self._samplers[policy_id] = fn
            if self._key is None:
                self._key = jax.random.PRNGKey(
                    int(self.rng.integers(2 ** 31)))
        return fn

    def sample(self, params_by_policy: Dict[str, Any]
               ) -> Dict[str, Dict[str, np.ndarray]]:
        import jax
        # per-agent open trajectory buffers
        traj = {a: {k: [] for k in ("obs", "actions", "logp", "values",
                                    "rewards", "terminateds")}
                for a in self.env.agents}
        closed: Dict[str, List[Dict[str, np.ndarray]]] = {}

        def close_agent(agent: str, bootstrap: float):
            t = traj[agent]
            if not t["obs"]:
                return
            batch = {k: np.asarray(v, np.float32) for k, v in t.items()}
            batch["obs"] = np.asarray(t["obs"], np.float32)
            batch["actions"] = np.asarray(t["actions"], np.int64)
            batch["bootstrap_value"] = np.float32(bootstrap)
            from ray_tpu.rllib.algorithms.ppo import _compute_gae
            closed.setdefault(self.mapping(agent), []).append(
                _compute_gae(batch, self.gamma, self.lam))
            for v in t.values():
                v.clear()

        for _ in range(self.rollout_length):
            actions: Dict[str, Any] = {}
            stats: Dict[str, Tuple[int, float, float]] = {}
            for agent, ob in self._obs.items():
                pid = self.mapping(agent)
                sampler = self._sampler(pid)
                self._key, sub = jax.random.split(self._key)
                a, logp, v = sampler(params_by_policy[pid],
                                     np.asarray(ob, np.float32)[None],
                                     sub)
                actions[agent] = int(a[0])
                stats[agent] = (int(a[0]), float(logp[0]), float(v[0]))
            nxt, rews, terms, truncs, _ = self.env.step(actions)
            for agent in list(actions):
                act, logp, val = stats[agent]
                t = traj[agent]
                t["obs"].append(np.asarray(self._obs[agent], np.float32))
                t["actions"].append(act)
                t["logp"].append(logp)
                t["values"].append(val)
                t["rewards"].append(rews.get(agent, 0.0))
                term = bool(terms.get(agent, False))
                t["terminateds"].append(float(term))
                self._ep_return += rews.get(agent, 0.0)
                if term or truncs.get(agent, False):
                    close_agent(agent, 0.0)
            if terms.get("__all__") or truncs.get("__all__"):
                self.completed_returns.append(self._ep_return)
                self._ep_return = 0.0
                self._obs, _ = self.env.reset(
                    seed=int(self.rng.integers(2 ** 31)))
            else:
                self._obs = nxt
        # close still-open trajectories with bootstrapped values
        for agent in self.env.agents:
            if traj[agent]["obs"]:
                pid = self.mapping(agent)
                sampler = self._sampler(pid)
                self._key, sub = jax.random.split(self._key)
                ob = self._obs.get(agent)
                boot = 0.0
                if ob is not None:
                    _, _, v = sampler(params_by_policy[pid],
                                      np.asarray(ob, np.float32)[None],
                                      sub)
                    boot = float(v[0])
                close_agent(agent, boot)
        return {pid: {k: np.concatenate([b[k] for b in batches])
                      if k != "bootstrap_value" else np.float32(0)
                      for k in batches[0]}
                for pid, batches in closed.items()}

    def get_metrics(self) -> Dict[str, Any]:
        recent = self.completed_returns[-100:]
        return {"episode_return_mean": (float(np.mean(recent))
                                        if recent else float("nan")),
                "episodes_total": len(self.completed_returns)}
