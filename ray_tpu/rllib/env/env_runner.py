"""EnvRunner — sampling actor.

Parity: reference ``rllib/env/single_agent_env_runner.py``: owns gym envs,
rolls out the current policy, returns batched trajectories (numpy host
arrays; the learner moves them to device).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

import ray_tpu


@ray_tpu.remote
class SingleAgentEnvRunner:
    def __init__(self, env_name: str, module_blob: bytes,
                 rollout_length: int = 256, seed: int = 0,
                 env_config: Optional[Dict[str, Any]] = None):
        import cloudpickle
        import gymnasium as gym
        self.env = gym.make(env_name, **(env_config or {}))
        self.module = cloudpickle.loads(module_blob)
        self.rollout_length = rollout_length
        self.rng = np.random.default_rng(seed)
        self.obs, _ = self.env.reset(seed=seed)
        self._episode_return = 0.0
        self._episode_len = 0
        self.completed_returns: List[float] = []
        self.completed_lengths: List[int] = []
        self._jit_sample = None
        self._key = None

    def _sampler(self):
        if self._jit_sample is None:
            import jax
            self._jit_sample = jax.jit(self.module.sample_actions)
            self._key = jax.random.PRNGKey(int(self.rng.integers(2**31)))
        return self._jit_sample

    def sample(self, params) -> Dict[str, np.ndarray]:
        """Roll out ``rollout_length`` steps; returns trajectory arrays."""
        import jax
        sampler = self._sampler()
        T = self.rollout_length
        obs_buf = np.zeros((T,) + np.shape(self.obs), np.float32)
        act_buf = np.zeros((T,), np.int64)
        logp_buf = np.zeros((T,), np.float32)
        val_buf = np.zeros((T,), np.float32)
        rew_buf = np.zeros((T,), np.float32)
        done_buf = np.zeros((T,), np.float32)
        for t in range(T):
            self._key, sub = jax.random.split(self._key)
            a, logp, v = sampler(params, self.obs[None, :], sub)
            a = int(a[0])
            obs_buf[t] = self.obs
            act_buf[t] = a
            logp_buf[t] = float(logp[0])
            val_buf[t] = float(v[0])
            nxt, rew, terminated, truncated, _ = self.env.step(a)
            rew_buf[t] = rew
            done = terminated or truncated
            done_buf[t] = float(terminated)
            self._episode_return += rew
            self._episode_len += 1
            if done:
                self.completed_returns.append(self._episode_return)
                self.completed_lengths.append(self._episode_len)
                self._episode_return = 0.0
                self._episode_len = 0
                nxt, _ = self.env.reset()
            self.obs = nxt
        # bootstrap value for the final state
        _, _, last_v = sampler(params, self.obs[None, :],
                               jax.random.PRNGKey(0))
        return {"obs": obs_buf, "actions": act_buf, "logp": logp_buf,
                "values": val_buf, "rewards": rew_buf,
                "terminateds": done_buf,
                "bootstrap_value": np.float32(last_v[0])}

    def sample_off_policy(self, params,
                          epsilon: float = 0.1) -> Dict[str, np.ndarray]:
        """Epsilon-greedy rollout returning (s, a, r, s', done)
        transitions — the replay-buffer food for value-based learners
        (DQN; reference single_agent_env_runner in off-policy mode)."""
        import jax
        if not hasattr(self, "_jit_greedy") or self._jit_greedy is None:
            import jax.numpy as jnp

            def greedy(params, obs):
                q, _ = self.module.forward(params, obs)
                return jnp.argmax(q, axis=-1)

            self._jit_greedy = jax.jit(greedy)
        T = self.rollout_length
        obs_buf = np.zeros((T,) + np.shape(self.obs), np.float32)
        next_buf = np.zeros_like(obs_buf)
        act_buf = np.zeros((T,), np.int64)
        rew_buf = np.zeros((T,), np.float32)
        done_buf = np.zeros((T,), np.float32)
        n_actions = self.env.action_space.n
        for t in range(T):
            if self.rng.random() < epsilon:
                a = int(self.rng.integers(n_actions))
            else:
                a = int(self._jit_greedy(params, self.obs[None, :])[0])
            obs_buf[t] = self.obs
            act_buf[t] = a
            nxt, rew, terminated, truncated, _ = self.env.step(a)
            rew_buf[t] = rew
            done_buf[t] = float(terminated)
            next_buf[t] = nxt
            self._episode_return += rew
            self._episode_len += 1
            if terminated or truncated:
                self.completed_returns.append(self._episode_return)
                self.completed_lengths.append(self._episode_len)
                self._episode_return = 0.0
                self._episode_len = 0
                nxt, _ = self.env.reset()
            self.obs = nxt
        return {"obs": obs_buf, "actions": act_buf, "rewards": rew_buf,
                "next_obs": next_buf, "terminateds": done_buf}

    def get_metrics(self) -> Dict[str, Any]:
        out = {
            "episode_return_mean": (float(np.mean(
                self.completed_returns[-100:]))
                if self.completed_returns else float("nan")),
            "episode_len_mean": (float(np.mean(
                self.completed_lengths[-100:]))
                if self.completed_lengths else float("nan")),
            "num_episodes": len(self.completed_returns),
        }
        return out

    def sample_continuous(self, params, warmup_random: bool = False
                          ) -> "Dict[str, np.ndarray]":
        """Stochastic continuous-action rollout (SAC exploration):
        actions sampled from the squashed-Gaussian policy (or the env's
        action space during warmup), transitions for the replay buffer."""
        import jax
        if getattr(self, "_jit_cont", None) is None:
            self._jit_cont = jax.jit(self.module.sample)
            self._key = jax.random.PRNGKey(
                int(self.rng.integers(2 ** 31)))
        T = self.rollout_length
        act_dim = int(np.prod(self.env.action_space.shape))
        obs_buf = np.zeros((T,) + np.shape(self.obs), np.float32)
        next_buf = np.zeros_like(obs_buf)
        act_buf = np.zeros((T, act_dim), np.float32)
        rew_buf = np.zeros((T,), np.float32)
        done_buf = np.zeros((T,), np.float32)
        for t in range(T):
            if warmup_random:
                a = self.env.action_space.sample().astype(np.float32)
            else:
                self._key, sub = jax.random.split(self._key)
                a = np.asarray(self._jit_cont(
                    params, self.obs[None, :], sub)[0][0], np.float32)
            obs_buf[t] = self.obs
            act_buf[t] = a.reshape(act_dim)
            nxt, rew, terminated, truncated, _ = self.env.step(
                a.reshape(self.env.action_space.shape))
            rew_buf[t] = rew
            done_buf[t] = float(terminated)
            next_buf[t] = nxt
            self._episode_return += rew
            self._episode_len += 1
            if terminated or truncated:
                self.completed_returns.append(self._episode_return)
                self.completed_lengths.append(self._episode_len)
                self._episode_return = 0.0
                self._episode_len = 0
                nxt, _ = self.env.reset()
            self.obs = np.asarray(nxt, np.float32)
        return {"obs": obs_buf, "actions": act_buf, "rewards": rew_buf,
                "next_obs": next_buf, "terminateds": done_buf}
