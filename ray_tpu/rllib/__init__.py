"""``ray_tpu.rllib`` — reinforcement learning (parity: ``ray.rllib``)."""

from ray_tpu.rllib.algorithms.impala import IMPALA, IMPALAConfig
from ray_tpu.rllib.algorithms.multi_agent_ppo import (MultiAgentPPO,
                                                      MultiAgentPPOConfig)
from ray_tpu.rllib.algorithms.ppo import PPO, PPOConfig
from ray_tpu.rllib.algorithms.sac import SAC, SACConfig
from ray_tpu.rllib.core.rl_module import (DiscreteMLPModule,
                                          MLPModuleConfig)
from ray_tpu.rllib.env.env_runner import SingleAgentEnvRunner
from ray_tpu.rllib.env.multi_agent_env import (MultiAgentCartPole,
                                               MultiAgentEnv,
                                               MultiAgentEnvRunner)

__all__ = ["PPO", "PPOConfig", "IMPALA", "IMPALAConfig",
           "DiscreteMLPModule", "MLPModuleConfig",
           "SingleAgentEnvRunner"]
