"""IMPALA — importance-weighted asynchronous actor-learner architecture.

Parity: reference ``rllib/algorithms/impala/`` (Espeholt et al. 2018):
env-runner actors sample *continuously* with whatever (stale) policy
params they were last handed; the learner consumes completed rollout
segments as they arrive and corrects for the policy lag with V-trace.
Decoupling sampling from learning is the point — no synchronous
sample-then-train barrier like PPO's.

TPU-first: the V-trace targets and the update are one jit-compiled
function (the time recursion is a ``lax.scan``); segments keep their
[B, T] time structure on device.  ``num_learners > 1`` scales out via
the DDP :class:`LearnerGroup` (host ring or the ``ici`` device world).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.rllib.core.rl_module import DiscreteMLPModule, MLPModuleConfig
from ray_tpu.rllib.env.env_runner import SingleAgentEnvRunner


@dataclass
class IMPALAConfig:
    env: str = "CartPole-v1"
    env_config: Dict[str, Any] = field(default_factory=dict)
    num_env_runners: int = 4
    rollout_length: int = 64
    # segments consumed per train() call (async: whichever finish first)
    segments_per_iteration: int = 4
    num_learners: int = 1
    learner_backend: str = "host"      # "host" ring | "ici" device world
    num_cpus_per_learner: float = 1.0
    num_tpus_per_learner: float = 0.0
    lr: float = 5e-4
    gamma: float = 0.99
    vtrace_rho_clip: float = 1.0
    vtrace_c_clip: float = 1.0
    vf_coeff: float = 0.5
    entropy_coeff: float = 0.01
    grad_clip: float = 40.0
    hidden: tuple = (64, 64)
    seed: int = 0

    def environment(self, env: str, env_config: Optional[Dict] = None):
        self.env = env
        if env_config:
            self.env_config = env_config
        return self

    def env_runners(self, num_env_runners: int,
                    rollout_length: Optional[int] = None):
        self.num_env_runners = num_env_runners
        if rollout_length:
            self.rollout_length = rollout_length
        return self

    def training(self, **kwargs):
        for k, v in kwargs.items():
            if not hasattr(self, k):
                raise ValueError(f"unknown training param {k!r}")
            setattr(self, k, v)
        return self

    def build(self) -> "IMPALA":
        return IMPALA(self)


def vtrace_targets(behavior_logp, target_logp, rewards, terminateds,
                   values, bootstrap_value, *, gamma: float,
                   rho_clip: float, c_clip: float):
    """V-trace corrected targets (all inputs [B, T]; bootstrap [B]).

    Returns (vs [B, T], pg_advantages [B, T]); both stop-gradiented by
    the caller.  The time recursion runs as a reversed ``lax.scan``.
    """
    import jax.numpy as jnp
    from jax import lax

    log_rho = target_logp - behavior_logp
    rho = jnp.minimum(jnp.exp(log_rho), rho_clip)
    c = jnp.minimum(jnp.exp(log_rho), c_clip)
    next_values = jnp.concatenate(
        [values[:, 1:], bootstrap_value[:, None]], axis=1)
    nonterminal = 1.0 - terminateds
    deltas = rho * (rewards + gamma * next_values * nonterminal - values)

    def step(acc, xs):
        delta_t, c_t, nt_t = xs
        acc = delta_t + gamma * c_t * nt_t * acc
        return acc, acc

    _, vs_minus_v = lax.scan(
        step, jnp.zeros(values.shape[0]),
        (deltas.T, c.T, nonterminal.T), reverse=True)
    vs = values + vs_minus_v.T
    vs_next = jnp.concatenate(
        [vs[:, 1:], bootstrap_value[:, None]], axis=1)
    pg_adv = rho * (rewards + gamma * vs_next * nonterminal - values)
    return vs, pg_adv


class IMPALALearner:
    """Jitted V-trace update (parity: impala_learner.py + vtrace)."""

    def __init__(self, module: DiscreteMLPModule, config: IMPALAConfig):
        import jax
        import jax.numpy as jnp
        import optax
        self.module = module
        self.config = config
        self.tx = optax.chain(
            optax.clip_by_global_norm(config.grad_clip),
            optax.rmsprop(config.lr, decay=0.99, eps=0.1))
        cfg = config

        def loss_fn(params, batch):
            B, T = batch["rewards"].shape
            obs = batch["obs"].reshape((B * T,) + batch["obs"].shape[2:])
            logits, values = module.forward(params, obs)
            logp_all = jax.nn.log_softmax(logits)
            target_logp = jnp.take_along_axis(
                logp_all, batch["actions"].reshape(-1)[:, None],
                -1)[:, 0].reshape(B, T)
            values = values.reshape(B, T)
            vs, pg_adv = vtrace_targets(
                batch["logp"], target_logp, batch["rewards"],
                batch["terminateds"], values, batch["bootstrap_value"],
                gamma=cfg.gamma, rho_clip=cfg.vtrace_rho_clip,
                c_clip=cfg.vtrace_c_clip)
            vs = jax.lax.stop_gradient(vs)
            pg_adv = jax.lax.stop_gradient(pg_adv)
            pi_loss = -jnp.mean(target_logp * pg_adv)
            vf_loss = 0.5 * jnp.mean((values - vs) ** 2)
            entropy = -jnp.mean(
                jnp.sum(jnp.exp(logp_all) * logp_all, -1))
            total = (pi_loss + cfg.vf_coeff * vf_loss
                     - cfg.entropy_coeff * entropy)
            return total, {"policy_loss": pi_loss, "vf_loss": vf_loss,
                           "entropy": entropy,
                           "mean_rho": jnp.mean(jnp.exp(
                               target_logp - batch["logp"]))}

        @jax.jit
        def update(params, opt_state, batch):
            (loss, metrics), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, batch)
            updates, opt_state = self.tx.update(grads, opt_state, params)
            import optax as _optax
            params = _optax.apply_updates(params, updates)
            metrics["total_loss"] = loss
            return params, opt_state, metrics

        @jax.jit
        def grad(params, batch):
            (loss, metrics), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, batch)
            metrics["total_loss"] = loss
            return grads, metrics

        @jax.jit
        def apply(params, opt_state, grads):
            updates, opt_state = self.tx.update(grads, opt_state, params)
            import optax as _optax
            return _optax.apply_updates(params, updates), opt_state

        self._update = update
        self._grad = grad
        self._apply = apply

    def init_state(self, key):
        params = self.module.init_params(key)
        return params, self.tx.init(params)

    def update(self, params, opt_state, train_batch: Dict[str, np.ndarray],
               allreduce: Optional[Callable] = None):
        """One V-trace SGD step over a stacked [B, T] segment batch."""
        import jax.numpy as jnp
        batch = {k: jnp.asarray(v) for k, v in train_batch.items()}
        if allreduce is None:
            params, opt_state, metrics = self._update(params, opt_state,
                                                      batch)
        else:
            grads, metrics = self._grad(params, batch)
            grads = allreduce(grads)
            params, opt_state = self._apply(params, opt_state, grads)
        return params, opt_state, {k: float(v)
                                   for k, v in metrics.items()}


def stack_segments(segments: List[Dict[str, np.ndarray]]
                   ) -> Dict[str, np.ndarray]:
    """[ {key: [T,...]} x B ]  ->  {key: [B, T, ...]} (+bootstrap [B])."""
    out = {}
    for key in segments[0]:
        if key == "bootstrap_value":
            out[key] = np.asarray([s[key] for s in segments], np.float32)
        else:
            out[key] = np.stack([s[key] for s in segments])
    return out


class IMPALA:
    """Async algorithm driver.

    Every env runner always has a sample in flight; ``train()`` drains
    whichever segments complete first, resubmits those runners
    immediately with the *current* params (so sampling never stops for
    learning), then takes one V-trace step on the collected batch.
    The behavior-vs-target policy lag this creates is exactly what
    V-trace corrects.
    """

    def __init__(self, config: IMPALAConfig):
        import cloudpickle
        import gymnasium as gym
        import jax
        self.config = config
        probe = gym.make(config.env, **config.env_config)
        obs_dim = int(np.prod(probe.observation_space.shape))
        num_actions = int(probe.action_space.n)
        probe.close()
        self.module = DiscreteMLPModule(MLPModuleConfig(
            obs_dim=obs_dim, num_actions=num_actions,
            hidden=tuple(config.hidden)))
        self.learner_group = None
        if config.num_learners > 1:
            from ray_tpu.rllib.core.learner_group import LearnerGroup
            self.learner_group = LearnerGroup(
                self.module, config, num_learners=config.num_learners,
                num_cpus_per_learner=config.num_cpus_per_learner,
                num_tpus_per_learner=config.num_tpus_per_learner,
                backend=config.learner_backend,
                learner_cls="ray_tpu.rllib.algorithms.impala."
                            "IMPALALearner")
            self.params = None
            self.learner = None
        else:
            self.learner = IMPALALearner(self.module, config)
            self.params, self.opt_state = self.learner.init_state(
                jax.random.PRNGKey(config.seed))
        blob = cloudpickle.dumps(self.module)
        self.env_runners = [
            SingleAgentEnvRunner.remote(
                config.env, blob, config.rollout_length,
                seed=config.seed + i, env_config=config.env_config)
            for i in range(config.num_env_runners)]
        self.iteration = 0
        self.timesteps_total = 0
        # async pump: one standing sample per runner
        self._inflight: Dict[bytes, Any] = {}   # ref bytes -> (idx, ref)
        params_ref = self._params_ref()
        for i in range(len(self.env_runners)):
            self._submit(i, params_ref)

    def _params_ref(self):
        if self.learner_group is not None:
            return self.learner_group.get_params_ref()
        import jax
        return ray_tpu.put(jax.tree.map(np.asarray, self.params))

    def _submit(self, runner_idx: int, params_ref) -> None:
        ref = self.env_runners[runner_idx].sample.remote(params_ref)
        self._inflight[ref.binary()] = (runner_idx, ref)

    def train(self) -> Dict[str, Any]:
        t0 = time.time()
        want = self.config.segments_per_iteration
        segments: List[Dict[str, np.ndarray]] = []
        params_ref = self._params_ref()
        while len(segments) < want:
            refs = [pair[1] for pair in self._inflight.values()]
            ready, _ = ray_tpu.wait(refs, num_returns=1, timeout=600)
            for ref in ready:
                idx, _ = self._inflight.pop(ref.binary())
                segments.append(ray_tpu.get(ref, timeout=600))
                # resubmit immediately with current (possibly stale)
                # params: sampling never waits for learning
                self._submit(idx, params_ref)
                if len(segments) >= want:
                    break
        train_batch = stack_segments(segments)
        if self.learner_group is not None:
            learner_metrics = self.learner_group.update(train_batch)
        else:
            self.params, self.opt_state, learner_metrics = \
                self.learner.update(self.params, self.opt_state,
                                    train_batch)
        runner_metrics = ray_tpu.get(
            [r.get_metrics.remote() for r in self.env_runners],
            timeout=120)
        returns = [m["episode_return_mean"] for m in runner_metrics
                   if not np.isnan(m["episode_return_mean"])]
        self.iteration += 1
        self.timesteps_total += int(np.prod(
            train_batch["rewards"].shape))
        return {
            "training_iteration": self.iteration,
            "timesteps_total": self.timesteps_total,
            "episode_return_mean": (float(np.mean(returns))
                                    if returns else float("nan")),
            "num_episodes": sum(m["num_episodes"]
                                for m in runner_metrics),
            "time_this_iter_s": time.time() - t0,
            **{f"learner/{k}": v for k, v in learner_metrics.items()},
        }

    def stop(self):
        for runner in self.env_runners:
            try:
                ray_tpu.kill(runner)
            except Exception:  # noqa: BLE001
                pass
        if self.learner_group is not None:
            self.learner_group.stop()
