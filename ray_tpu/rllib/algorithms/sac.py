"""SAC — soft actor-critic on JAX (continuous control).

Parity: reference ``rllib/algorithms/sac/`` (new stack): off-policy
actor-critic with twin clipped-double-Q critics, tanh-squashed Gaussian
policy, automatic entropy-temperature tuning, and polyak-averaged
target critics.  TPU-first: actor+critic+alpha updates fuse into ONE
jitted step over the sampled minibatch; replay stays in host numpy
(``dqn.ReplayBuffer`` shape, continuous actions).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Dict, Optional

import numpy as np

import ray_tpu
from ray_tpu.rllib.algorithms.dqn import ReplayBuffer
from ray_tpu.rllib.core.rl_module import (ContinuousModuleConfig,
                                          SquashedGaussianModule,
                                          TwinQModule)
from ray_tpu.rllib.env.env_runner import SingleAgentEnvRunner


@dataclass
class SACConfig:
    env: str = "Pendulum-v1"
    env_config: Dict[str, Any] = field(default_factory=dict)
    num_env_runners: int = 1
    rollout_length: int = 128
    actor_lr: float = 3e-4
    critic_lr: float = 3e-4
    alpha_lr: float = 3e-4
    gamma: float = 0.99
    tau: float = 0.005              # polyak target coefficient
    initial_alpha: float = 1.0
    target_entropy: Optional[float] = None   # default: -act_dim
    buffer_size: int = 100_000
    learn_start: int = 1_000
    train_batch_size: int = 256
    updates_per_iteration: int = 64
    hidden: tuple = (256, 256)
    seed: int = 0

    def environment(self, env: str, env_config: Optional[Dict] = None):
        self.env = env
        if env_config:
            self.env_config = env_config
        return self

    def env_runners(self, num_env_runners: int,
                    rollout_length: Optional[int] = None):
        self.num_env_runners = num_env_runners
        if rollout_length:
            self.rollout_length = rollout_length
        return self

    def training(self, **kwargs):
        for k, v in kwargs.items():
            if not hasattr(self, k):
                raise ValueError(f"unknown training param {k!r}")
            setattr(self, k, v)
        return self

    def build(self) -> "SAC":
        return SAC(self)


class SACLearner:
    """One jitted SAC update: critics + actor + alpha + polyak."""

    def __init__(self, actor: SquashedGaussianModule, critic: TwinQModule,
                 config: SACConfig):
        import jax
        import jax.numpy as jnp
        import optax

        self.actor = actor
        self.critic = critic
        self.config = config
        cfg = config
        act_dim = actor.config.act_dim
        target_entropy = (cfg.target_entropy
                          if cfg.target_entropy is not None
                          else -float(act_dim))
        self.tx_actor = optax.adam(cfg.actor_lr)
        self.tx_critic = optax.adam(cfg.critic_lr)
        self.tx_alpha = optax.adam(cfg.alpha_lr)

        def critic_loss(cp, ap, tcp, log_alpha, batch, key):
            next_a, next_logp = actor.sample(ap, batch["next_obs"], key)
            q1t, q2t = critic.forward(tcp, batch["next_obs"], next_a)
            alpha = jnp.exp(log_alpha)
            soft_q = jnp.minimum(q1t, q2t) - alpha * next_logp
            target = batch["rewards"] + cfg.gamma * \
                (1.0 - batch["terminateds"]) * soft_q
            target = jax.lax.stop_gradient(target)
            q1, q2 = critic.forward(cp, batch["obs"], batch["actions"])
            loss = jnp.mean((q1 - target) ** 2 + (q2 - target) ** 2)
            return loss, {"q1_mean": jnp.mean(q1),
                          "critic_loss": loss}

        def actor_loss(ap, cp, log_alpha, batch, key):
            a, logp = actor.sample(ap, batch["obs"], key)
            q1, q2 = critic.forward(cp, batch["obs"], a)
            alpha = jax.lax.stop_gradient(jnp.exp(log_alpha))
            loss = jnp.mean(alpha * logp - jnp.minimum(q1, q2))
            return loss, {"actor_loss": loss,
                          "entropy": -jnp.mean(logp),
                          "logp_mean": jnp.mean(logp)}

        def alpha_loss(log_alpha, logp_mean):
            return -log_alpha * jax.lax.stop_gradient(
                logp_mean + target_entropy)

        @jax.jit
        def update(state, batch, key):
            (ap, cp, tcp, log_alpha,
             opt_a, opt_c, opt_al) = state
            k1, k2 = jax.random.split(key)
            (closs, cmetrics), cgrads = jax.value_and_grad(
                critic_loss, has_aux=True)(cp, ap, tcp, log_alpha,
                                           batch, k1)
            cupd, opt_c = self.tx_critic.update(cgrads, opt_c, cp)
            cp = optax.apply_updates(cp, cupd)
            (aloss, ametrics), agrads = jax.value_and_grad(
                actor_loss, has_aux=True)(ap, cp, log_alpha, batch, k2)
            aupd, opt_a = self.tx_actor.update(agrads, opt_a, ap)
            ap = optax.apply_updates(ap, aupd)
            algrad = jax.grad(alpha_loss)(log_alpha,
                                          ametrics["logp_mean"])
            alupd, opt_al = self.tx_alpha.update(
                {"a": algrad}, opt_al, {"a": log_alpha})
            log_alpha = optax.apply_updates({"a": log_alpha}, alupd)["a"]
            tcp = jax.tree.map(
                lambda t, o: (1 - cfg.tau) * t + cfg.tau * o, tcp, cp)
            metrics = {**cmetrics, **ametrics,
                       "alpha": jnp.exp(log_alpha)}
            metrics.pop("logp_mean", None)
            return (ap, cp, tcp, log_alpha, opt_a, opt_c, opt_al), \
                metrics

        self._update = update

    def init_state(self, key):
        import jax
        import jax.numpy as jnp
        ka, kc = jax.random.split(key)
        ap = self.actor.init_params(ka)
        cp = self.critic.init_params(kc)
        log_alpha = jnp.asarray(
            np.log(self.config.initial_alpha), jnp.float32)
        return (ap, cp, cp, log_alpha,
                self.tx_actor.init(ap), self.tx_critic.init(cp),
                self.tx_alpha.init({"a": log_alpha}))


class SAC:
    """Algorithm driver (parity: ``SAC.train()``)."""

    def __init__(self, config: SACConfig):
        import cloudpickle
        import gymnasium as gym
        import jax
        self.config = config
        probe = gym.make(config.env, **config.env_config)
        obs_shape = probe.observation_space.shape
        space = probe.action_space
        probe.close()
        mcfg = ContinuousModuleConfig(
            obs_dim=int(np.prod(obs_shape)),
            act_dim=int(np.prod(space.shape)),
            act_low=tuple(np.asarray(space.low).ravel().tolist()),
            act_high=tuple(np.asarray(space.high).ravel().tolist()),
            hidden=tuple(config.hidden))
        self.actor = SquashedGaussianModule(mcfg)
        self.critic = TwinQModule(mcfg)
        self.learner = SACLearner(self.actor, self.critic, config)
        self.state = self.learner.init_state(
            jax.random.PRNGKey(config.seed))
        self._key = jax.random.PRNGKey(config.seed + 1)
        blob = cloudpickle.dumps(self.actor)
        self.env_runners = [
            SingleAgentEnvRunner.remote(
                config.env, blob, config.rollout_length,
                seed=config.seed + i, env_config=config.env_config)
            for i in range(config.num_env_runners)]
        self.buffer = ReplayBuffer(config.buffer_size, obs_shape,
                                   seed=config.seed)
        # continuous actions: retype the buffer's action storage
        self.buffer.actions = np.zeros(
            (config.buffer_size, mcfg.act_dim), np.float32)
        self.iteration = 0
        self.timesteps_total = 0
        self.updates_total = 0

    def train(self) -> Dict[str, Any]:
        import jax
        t0 = time.time()
        cfg = self.config
        actor_params = jax.tree.map(np.asarray, self.state[0])
        params_ref = ray_tpu.put(actor_params)
        warmup = self.timesteps_total < cfg.learn_start
        batches = ray_tpu.get(
            [r.sample_continuous.remote(params_ref, warmup)
             for r in self.env_runners], timeout=600)
        for b in batches:
            self.buffer.add_batch(b)
            self.timesteps_total += len(b["obs"])

        metrics: Dict[str, Any] = {}
        if len(self.buffer) >= cfg.learn_start:
            for _ in range(cfg.updates_per_iteration):
                mb = self.buffer.sample(cfg.train_batch_size)
                self._key, sub = jax.random.split(self._key)
                self.state, metrics = self.learner._update(
                    self.state, mb, sub)
                self.updates_total += 1
        runner_metrics = ray_tpu.get(
            [r.get_metrics.remote() for r in self.env_runners],
            timeout=120)
        returns = [m["episode_return_mean"] for m in runner_metrics
                   if not np.isnan(m["episode_return_mean"])]
        self.iteration += 1
        return {
            "training_iteration": self.iteration,
            "timesteps_total": self.timesteps_total,
            "updates_total": self.updates_total,
            "buffer_size": len(self.buffer),
            "episode_return_mean": (float(np.mean(returns))
                                    if returns else float("nan")),
            "time_this_iter_s": time.time() - t0,
            **{f"learner/{k}": float(v) for k, v in metrics.items()},
        }

    def stop(self):
        for runner in self.env_runners:
            try:
                ray_tpu.kill(runner)
            except Exception:  # noqa: BLE001
                pass
