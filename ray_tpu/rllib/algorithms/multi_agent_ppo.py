"""Multi-agent PPO (parity: the reference's multi-agent stack —
``config.multi_agent(policies=..., policy_mapping_fn=...)`` over
``rllib/core/rl_module/multi_rl_module.py``).

One PPOLearner per policy; runners return per-policy batches
(``MultiAgentEnvRunner``); each policy updates on its own agents'
experience.  Shared-policy setups (all agents -> one policy id) give
parameter sharing for free.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.rllib.algorithms.ppo import PPOConfig, PPOLearner
from ray_tpu.rllib.core.rl_module import DiscreteMLPModule, MLPModuleConfig
from ray_tpu.rllib.env.multi_agent_env import MultiAgentEnvRunner


@dataclass
class MultiAgentPPOConfig(PPOConfig):
    env_factory: Optional[Callable] = None     # () -> MultiAgentEnv
    policies: tuple = ("shared",)              # policy ids
    policy_mapping_fn: Optional[Callable] = None  # agent_id -> policy

    def multi_agent(self, policies, policy_mapping_fn):
        self.policies = tuple(policies)
        self.policy_mapping_fn = policy_mapping_fn
        return self

    def build(self) -> "MultiAgentPPO":
        return MultiAgentPPO(self)


class MultiAgentPPO:
    def __init__(self, config: MultiAgentPPOConfig):
        import cloudpickle
        import jax
        if config.env_factory is None:
            raise ValueError("MultiAgentPPOConfig.env_factory required")
        self.config = config
        probe = config.env_factory()
        obs_dim = int(np.prod(probe.observation_space.shape))
        num_actions = int(probe.action_space.n)
        mapping = config.policy_mapping_fn or (lambda agent: "shared")
        self.modules = {
            pid: DiscreteMLPModule(MLPModuleConfig(
                obs_dim=obs_dim, num_actions=num_actions,
                hidden=tuple(config.hidden)))
            for pid in config.policies}
        self.learners = {pid: PPOLearner(m, config)
                         for pid, m in self.modules.items()}
        keys = jax.random.split(jax.random.PRNGKey(config.seed),
                                len(self.modules))
        self.states = {pid: self.learners[pid].init_state(k)
                       for (pid, _), k in zip(self.modules.items(),
                                              keys)}
        self.env_runners = [
            MultiAgentEnvRunner.remote(
                cloudpickle.dumps(config.env_factory),
                cloudpickle.dumps(self.modules),
                cloudpickle.dumps(mapping),
                rollout_length=config.rollout_length,
                gamma=config.gamma, lam=config.lambda_,
                seed=config.seed + i)
            for i in range(config.num_env_runners)]
        self.iteration = 0
        self.timesteps_total = 0

    def train(self) -> Dict[str, Any]:
        import jax
        t0 = time.time()
        params_np = {pid: jax.tree.map(np.asarray, st[0])
                     for pid, st in self.states.items()}
        params_ref = ray_tpu.put(params_np)
        results = ray_tpu.get(
            [r.sample.remote(params_ref) for r in self.env_runners],
            timeout=600)
        merged: Dict[str, List] = {}
        for res in results:
            for pid, batch in res.items():
                merged.setdefault(pid, []).append(batch)
        metrics: Dict[str, Any] = {}
        for pid, batches in merged.items():
            train_batch = {
                k: np.concatenate([b[k] for b in batches])
                for k in batches[0] if k != "bootstrap_value"}
            self.timesteps_total += len(train_batch["obs"])
            params, opt_state = self.states[pid]
            params, opt_state, m = self.learners[pid].update(
                params, opt_state, train_batch)
            self.states[pid] = (params, opt_state)
            metrics.update({f"{pid}/{k}": v for k, v in m.items()})
        runner_metrics = ray_tpu.get(
            [r.get_metrics.remote() for r in self.env_runners],
            timeout=120)
        returns = [m["episode_return_mean"] for m in runner_metrics
                   if not np.isnan(m["episode_return_mean"])]
        self.iteration += 1
        return {
            "training_iteration": self.iteration,
            "timesteps_total": self.timesteps_total,
            "episode_return_mean": (float(np.mean(returns))
                                    if returns else float("nan")),
            "time_this_iter_s": time.time() - t0,
            **metrics,
        }

    def stop(self):
        for runner in self.env_runners:
            try:
                ray_tpu.kill(runner)
            except Exception:  # noqa: BLE001
                pass
