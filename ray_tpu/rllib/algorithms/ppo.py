"""PPO — proximal policy optimization on JAX.

Parity: reference ``rllib/algorithms/ppo/`` (new stack): Algorithm drives
env-runner actors (sampling) and a Learner (jitted clipped-surrogate SGD).
TPU-first: a single learner's update is one jit-compiled function (a
mesh's dp axis shards minibatches inside jit); ``num_learners>1`` scales
out as a DDP LearnerGroup (``rllib/core/learner_group.py``) whose
actors ring-allreduce gradients through the collective layer.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.rllib.core.rl_module import DiscreteMLPModule, MLPModuleConfig
from ray_tpu.rllib.env.env_runner import SingleAgentEnvRunner


@dataclass
class PPOConfig:
    """Builder-style config (parity: AlgorithmConfig/PPOConfig)."""
    env: str = "CartPole-v1"
    env_config: Dict[str, Any] = field(default_factory=dict)
    num_env_runners: int = 2
    rollout_length: int = 256
    num_learners: int = 1          # >1: DDP LearnerGroup fan-out
    learner_backend: str = "host"  # "host" ring | "ici" device world
    num_cpus_per_learner: float = 1.0
    num_tpus_per_learner: float = 0.0
    lr: float = 3e-4
    gamma: float = 0.99
    lambda_: float = 0.95
    clip_param: float = 0.2
    vf_coeff: float = 0.5
    entropy_coeff: float = 0.01
    num_sgd_epochs: int = 6
    minibatch_size: int = 128
    grad_clip: float = 0.5
    hidden: tuple = (64, 64)
    seed: int = 0

    # builder methods mirror the reference's fluent API
    def environment(self, env: str, env_config: Optional[Dict] = None):
        self.env = env
        if env_config:
            self.env_config = env_config
        return self

    def env_runners(self, num_env_runners: int,
                    rollout_length: Optional[int] = None):
        self.num_env_runners = num_env_runners
        if rollout_length:
            self.rollout_length = rollout_length
        return self

    def training(self, **kwargs):
        for k, v in kwargs.items():
            if not hasattr(self, k):
                raise ValueError(f"unknown training param {k!r}")
            setattr(self, k, v)
        return self

    def build(self) -> "PPO":
        return PPO(self)


def _compute_gae(batch: Dict[str, np.ndarray], gamma: float,
                 lam: float) -> Dict[str, np.ndarray]:
    rewards = batch["rewards"]
    values = batch["values"]
    terminateds = batch["terminateds"]
    T = len(rewards)
    adv = np.zeros(T, np.float32)
    last_gae = 0.0
    next_value = float(batch["bootstrap_value"])
    for t in reversed(range(T)):
        nonterminal = 1.0 - terminateds[t]
        delta = rewards[t] + gamma * next_value * nonterminal - values[t]
        last_gae = delta + gamma * lam * nonterminal * last_gae
        adv[t] = last_gae
        next_value = values[t]
    out = dict(batch)
    out["advantages"] = adv
    out["value_targets"] = adv + values
    return out


class PPOLearner:
    """Jitted PPO update (parity: rllib/core/learner + ppo_learner)."""

    def __init__(self, module: DiscreteMLPModule, config: PPOConfig):
        import jax
        import jax.numpy as jnp
        import optax
        self.module = module
        self.config = config
        self.tx = optax.chain(
            optax.clip_by_global_norm(config.grad_clip),
            optax.adam(config.lr))
        cfg = config

        def loss_fn(params, batch):
            logits, values = module.forward(params, batch["obs"])
            logp_all = jax.nn.log_softmax(logits)
            logp = jnp.take_along_axis(
                logp_all, batch["actions"][:, None], -1)[:, 0]
            ratio = jnp.exp(logp - batch["logp"])
            adv = batch["advantages"]
            adv = (adv - adv.mean()) / (adv.std() + 1e-8)
            unclipped = ratio * adv
            clipped = jnp.clip(ratio, 1 - cfg.clip_param,
                               1 + cfg.clip_param) * adv
            pi_loss = -jnp.mean(jnp.minimum(unclipped, clipped))
            vf_loss = jnp.mean((values - batch["value_targets"]) ** 2)
            entropy = -jnp.mean(
                jnp.sum(jnp.exp(logp_all) * logp_all, -1))
            total = (pi_loss + cfg.vf_coeff * vf_loss
                     - cfg.entropy_coeff * entropy)
            return total, {"policy_loss": pi_loss, "vf_loss": vf_loss,
                           "entropy": entropy,
                           "mean_ratio": ratio.mean()}

        @jax.jit
        def update(params, opt_state, batch):
            (loss, metrics), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, batch)
            updates, opt_state = self.tx.update(grads, opt_state, params)
            import optax as _optax
            params = _optax.apply_updates(params, updates)
            metrics["total_loss"] = loss
            return params, opt_state, metrics

        # split grad/apply pair for the DDP LearnerGroup path: gradients
        # leave jit, get allreduced across learner actors, come back
        @jax.jit
        def grad(params, batch):
            (loss, metrics), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, batch)
            metrics["total_loss"] = loss
            return grads, metrics

        @jax.jit
        def apply(params, opt_state, grads):
            updates, opt_state = self.tx.update(grads, opt_state, params)
            import optax as _optax
            return _optax.apply_updates(params, updates), opt_state

        self._update = update
        self._grad = grad
        self._apply = apply

    def init_state(self, key):
        params = self.module.init_params(key)
        return params, self.tx.init(params)

    def update(self, params, opt_state, train_batch: Dict[str, np.ndarray],
               allreduce: Optional[Callable] = None):
        """Minibatch SGD epochs.  With ``allreduce`` (LearnerGroup DDP),
        every step's gradients are averaged across learners before the
        optimizer applies them — all learners take identical steps."""
        import jax.numpy as jnp
        cfg = self.config
        n = len(train_batch["obs"])
        metrics = {}
        rng = np.random.default_rng(0)
        for _ in range(cfg.num_sgd_epochs):
            perm = rng.permutation(n)
            for start in range(0, n, cfg.minibatch_size):
                idx = perm[start:start + cfg.minibatch_size]
                mb = {k: jnp.asarray(v[idx]) for k, v in
                      train_batch.items() if k != "bootstrap_value"}
                if allreduce is None:
                    params, opt_state, metrics = self._update(
                        params, opt_state, mb)
                else:
                    grads, metrics = self._grad(params, mb)
                    grads = allreduce(grads)
                    params, opt_state = self._apply(params, opt_state,
                                                    grads)
        return params, opt_state, {k: float(v)
                                   for k, v in metrics.items()}


class PPO:
    """Algorithm driver (parity: ``Algorithm.train()`` loop)."""

    def __init__(self, config: PPOConfig):
        import cloudpickle
        import gymnasium as gym
        import jax
        self.config = config
        probe = gym.make(config.env, **config.env_config)
        obs_dim = int(np.prod(probe.observation_space.shape))
        num_actions = int(probe.action_space.n)
        probe.close()
        self.module = DiscreteMLPModule(MLPModuleConfig(
            obs_dim=obs_dim, num_actions=num_actions,
            hidden=tuple(config.hidden)))
        self.learner_group = None
        if config.num_learners > 1:
            from ray_tpu.rllib.core.learner_group import LearnerGroup
            self.learner_group = LearnerGroup(
                self.module, config, num_learners=config.num_learners,
                num_cpus_per_learner=config.num_cpus_per_learner,
                num_tpus_per_learner=config.num_tpus_per_learner,
                backend=config.learner_backend)
            self.params, self.opt_state = None, None
            self.learner = None
        else:
            self.learner = PPOLearner(self.module, config)
            self.params, self.opt_state = self.learner.init_state(
                jax.random.PRNGKey(config.seed))
        blob = cloudpickle.dumps(self.module)
        self.env_runners = [
            SingleAgentEnvRunner.remote(
                config.env, blob, config.rollout_length,
                seed=config.seed + i, env_config=config.env_config)
            for i in range(config.num_env_runners)]
        self.iteration = 0
        self.timesteps_total = 0

    def train(self) -> Dict[str, Any]:
        t0 = time.time()
        if self.learner_group is not None:
            # ref straight from the rank-0 learner into the env runners:
            # no driver round-trip or re-put of the full param tree
            params_np = self.learner_group.get_params_ref()
        else:
            params_np = ray_tpu.put(
                __import__("jax").tree.map(np.asarray, self.params))
        batches = ray_tpu.get(
            [runner.sample.remote(params_np)
             for runner in self.env_runners], timeout=600)
        processed = [
            _compute_gae(b, self.config.gamma, self.config.lambda_)
            for b in batches]
        train_batch = {
            k: np.concatenate([p[k] for p in processed])
            for k in processed[0] if k != "bootstrap_value"}
        if self.learner_group is not None:
            learner_metrics = self.learner_group.update(train_batch)
        else:
            self.params, self.opt_state, learner_metrics = \
                self.learner.update(self.params, self.opt_state,
                                    train_batch)
        runner_metrics = ray_tpu.get(
            [r.get_metrics.remote() for r in self.env_runners],
            timeout=120)
        returns = [m["episode_return_mean"] for m in runner_metrics
                   if not np.isnan(m["episode_return_mean"])]
        self.iteration += 1
        n = len(train_batch["obs"])
        if self.learner_group is not None:
            n -= n % self.learner_group.world  # trimmed rows never train
        self.timesteps_total += n
        return {
            "training_iteration": self.iteration,
            "timesteps_total": self.timesteps_total,
            "episode_return_mean": (float(np.mean(returns))
                                    if returns else float("nan")),
            "num_episodes": sum(m["num_episodes"]
                                for m in runner_metrics),
            "time_this_iter_s": time.time() - t0,
            **{f"learner/{k}": v for k, v in learner_metrics.items()},
        }

    def stop(self):
        for runner in self.env_runners:
            try:
                ray_tpu.kill(runner)
            except Exception:  # noqa: BLE001
                pass
        if self.learner_group is not None:
            self.learner_group.stop()

    # Tune integration: PPO as a function trainable
    @staticmethod
    def as_trainable(config_dict: Dict[str, Any],
                     stop_iters: int = 10) -> Callable:
        def trainable(tune_config):
            import ray_tpu.tune as tune
            merged = dict(config_dict)
            merged.update(tune_config)
            cfg = PPOConfig(**merged)
            algo = cfg.build()
            try:
                for _ in range(stop_iters):
                    tune.report(algo.train())
            finally:
                algo.stop()
        return trainable
