"""BC — behavior cloning from offline data.

Parity: reference ``rllib/algorithms/bc/`` (offline RL new stack): no
env interaction — the policy is supervised on logged (obs, action)
pairs read through ``ray_tpu.data`` (the reference reads offline
datasets through ray.data the same way).  Evaluation optionally rolls
the cloned policy in a live env.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Dict, Optional

import numpy as np

import ray_tpu
from ray_tpu.rllib.core.rl_module import DiscreteMLPModule, MLPModuleConfig


@dataclass
class BCConfig:
    env: str = "CartPole-v1"              # for eval rollouts + spaces
    env_config: Dict[str, Any] = field(default_factory=dict)
    lr: float = 1e-3
    train_batch_size: int = 256
    updates_per_iteration: int = 32
    hidden: tuple = (64, 64)
    seed: int = 0
    evaluation_num_episodes: int = 5

    def environment(self, env: str, env_config: Optional[Dict] = None):
        self.env = env
        if env_config:
            self.env_config = env_config
        return self

    def offline_data(self, dataset) -> "BCConfig":
        """``dataset``: ray_tpu.data.Dataset with 'obs' and 'actions'
        columns (reference: config.offline_data(input_=...))."""
        self._dataset = dataset
        return self

    def training(self, **kwargs):
        for k, v in kwargs.items():
            if not hasattr(self, k):
                raise ValueError(f"unknown training param {k!r}")
            setattr(self, k, v)
        return self

    def build(self) -> "BC":
        return BC(self, getattr(self, "_dataset", None))


class BC:
    """Offline supervised policy cloning + optional live evaluation."""

    def __init__(self, config: BCConfig, dataset):
        import gymnasium as gym
        import jax
        import jax.numpy as jnp
        import optax
        if dataset is None:
            raise ValueError("BCConfig.offline_data(dataset) is required")
        self.config = config
        probe = gym.make(config.env, **config.env_config)
        obs_dim = int(np.prod(probe.observation_space.shape))
        num_actions = int(probe.action_space.n)
        probe.close()
        self.module = DiscreteMLPModule(MLPModuleConfig(
            obs_dim=obs_dim, num_actions=num_actions,
            hidden=tuple(config.hidden)))
        self.tx = optax.adam(config.lr)
        self.params = self.module.init_params(
            jax.random.PRNGKey(config.seed))
        self.opt_state = self.tx.init(self.params)
        module = self.module

        def loss_fn(params, obs, actions):
            logits, _ = module.forward(params, obs)
            logp = jax.nn.log_softmax(logits)
            nll = -jnp.take_along_axis(logp, actions[:, None], -1)[:, 0]
            acc = jnp.mean(
                (jnp.argmax(logits, -1) == actions).astype(jnp.float32))
            return jnp.mean(nll), acc

        @jax.jit
        def update(params, opt_state, obs, actions):
            (loss, acc), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, obs, actions)
            updates, opt_state = self.tx.update(grads, opt_state, params)
            return (optax.apply_updates(params, updates), opt_state,
                    loss, acc)

        @jax.jit
        def act(params, obs):
            logits, _ = module.forward(params, obs)
            return jnp.argmax(logits, -1)

        self._update = update
        self._act = act
        # materialize the offline dataset once; epochs shuffle in-memory
        table = dataset.to_arrow()
        self._obs = np.stack([np.asarray(o, np.float32)
                              for o in table.column("obs").to_pylist()])
        self._actions = np.asarray(table.column("actions").to_pylist(),
                                   np.int64)
        self._rng = np.random.default_rng(config.seed)
        self.iteration = 0

    def train(self) -> Dict[str, Any]:
        t0 = time.time()
        cfg = self.config
        n = len(self._obs)
        loss = acc = 0.0
        for _ in range(cfg.updates_per_iteration):
            idx = self._rng.integers(0, n, size=min(
                cfg.train_batch_size, n))
            self.params, self.opt_state, loss, acc = self._update(
                self.params, self.opt_state, self._obs[idx],
                self._actions[idx])
        self.iteration += 1
        return {"training_iteration": self.iteration,
                "loss": float(loss), "action_accuracy": float(acc),
                "num_samples": n,
                "time_this_iter_s": time.time() - t0}

    def evaluate(self, num_episodes: Optional[int] = None
                 ) -> Dict[str, Any]:
        """Greedy rollouts of the cloned policy in the live env."""
        import gymnasium as gym
        episodes = num_episodes or self.config.evaluation_num_episodes
        act = self._act  # jitted once in __init__ (no per-call recompile)
        env = gym.make(self.config.env, **self.config.env_config)
        returns = []
        for ep in range(episodes):
            obs, _ = env.reset(seed=self.config.seed + ep)
            done, total = False, 0.0
            while not done:
                a = int(act(self.params, obs[None, :])[0])
                obs, rew, term, trunc, _ = env.step(a)
                total += rew
                done = term or trunc
            returns.append(total)
        env.close()
        return {"episode_return_mean": float(np.mean(returns)),
                "num_episodes": episodes}

    def stop(self):
        pass
