"""DQN — deep Q-learning on JAX (double DQN + target network).

Parity: reference ``rllib/algorithms/dqn/`` (new stack): env runners
collect epsilon-greedy transitions into a replay buffer; the learner
does jitted TD updates against a periodically-synced target network
(double-DQN action selection).  TPU-first: one jit step over the
sampled minibatch; the buffer stays in host numpy (HBM is for params
and batches, not replay history).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional

import numpy as np

import ray_tpu
from ray_tpu.rllib.core.rl_module import DiscreteMLPModule, MLPModuleConfig
from ray_tpu.rllib.env.env_runner import SingleAgentEnvRunner


@dataclass
class DQNConfig:
    env: str = "CartPole-v1"
    env_config: Dict[str, Any] = field(default_factory=dict)
    num_env_runners: int = 1
    rollout_length: int = 128
    lr: float = 1e-3
    gamma: float = 0.99
    buffer_size: int = 50_000
    learn_start: int = 500          # min transitions before updates
    train_batch_size: int = 64
    updates_per_iteration: int = 32
    target_update_freq: int = 200   # updates between target syncs
    double_q: bool = True
    epsilon_start: float = 1.0
    epsilon_end: float = 0.05
    epsilon_decay_steps: int = 5_000
    hidden: tuple = (64, 64)
    seed: int = 0

    def environment(self, env: str, env_config: Optional[Dict] = None):
        self.env = env
        if env_config:
            self.env_config = env_config
        return self

    def env_runners(self, num_env_runners: int,
                    rollout_length: Optional[int] = None):
        self.num_env_runners = num_env_runners
        if rollout_length:
            self.rollout_length = rollout_length
        return self

    def training(self, **kwargs):
        for k, v in kwargs.items():
            if not hasattr(self, k):
                raise ValueError(f"unknown training param {k!r}")
            setattr(self, k, v)
        return self

    def build(self) -> "DQN":
        return DQN(self)


class ReplayBuffer:
    """Uniform ring buffer over numpy transition arrays (reference:
    ``rllib/utils/replay_buffers/replay_buffer.py``)."""

    def __init__(self, capacity: int, obs_shape, seed: int = 0):
        self.capacity = capacity
        self.obs = np.zeros((capacity,) + tuple(obs_shape), np.float32)
        self.next_obs = np.zeros_like(self.obs)
        self.actions = np.zeros((capacity,), np.int64)
        self.rewards = np.zeros((capacity,), np.float32)
        self.terminateds = np.zeros((capacity,), np.float32)
        self._idx = 0
        self._size = 0
        self._rng = np.random.default_rng(seed)

    def add_batch(self, batch: Dict[str, np.ndarray]) -> None:
        n = len(batch["obs"])
        for i in range(n):
            j = self._idx
            self.obs[j] = batch["obs"][i]
            self.next_obs[j] = batch["next_obs"][i]
            self.actions[j] = batch["actions"][i]
            self.rewards[j] = batch["rewards"][i]
            self.terminateds[j] = batch["terminateds"][i]
            self._idx = (self._idx + 1) % self.capacity
            self._size = min(self._size + 1, self.capacity)

    def __len__(self) -> int:
        return self._size

    def sample(self, batch_size: int) -> Dict[str, np.ndarray]:
        idx = self._rng.integers(0, self._size, size=batch_size)
        return {"obs": self.obs[idx], "next_obs": self.next_obs[idx],
                "actions": self.actions[idx],
                "rewards": self.rewards[idx],
                "terminateds": self.terminateds[idx]}


class DQNLearner:
    """Jitted double-DQN TD update (reference dqn_learner shape)."""

    def __init__(self, module: DiscreteMLPModule, config: DQNConfig):
        import jax
        import jax.numpy as jnp
        import optax
        self.module = module
        self.config = config
        self.tx = optax.adam(config.lr)
        cfg = config

        def loss_fn(params, target_params, batch):
            q, _ = module.forward(params, batch["obs"])
            q_sa = jnp.take_along_axis(
                q, batch["actions"][:, None], -1)[:, 0]
            q_next_t, _ = module.forward(target_params,
                                         batch["next_obs"])
            if cfg.double_q:
                q_next_online, _ = module.forward(params,
                                                  batch["next_obs"])
                best = jnp.argmax(q_next_online, axis=-1)
                q_next = jnp.take_along_axis(
                    q_next_t, best[:, None], -1)[:, 0]
            else:
                q_next = jnp.max(q_next_t, axis=-1)
            target = batch["rewards"] + cfg.gamma * \
                (1.0 - batch["terminateds"]) * \
                jax.lax.stop_gradient(q_next)
            td = q_sa - target
            loss = jnp.mean(optax.huber_loss(q_sa, target))
            return loss, {"td_error_mean": jnp.mean(jnp.abs(td)),
                          "q_mean": jnp.mean(q_sa)}

        @jax.jit
        def update(params, target_params, opt_state, batch):
            (loss, metrics), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, target_params, batch)
            updates, opt_state = self.tx.update(grads, opt_state, params)
            import optax as _optax
            params = _optax.apply_updates(params, updates)
            metrics["loss"] = loss
            return params, opt_state, metrics

        self._update = update

    def init_state(self, key):
        params = self.module.init_params(key)
        return params, self.tx.init(params)


class DQN:
    """Algorithm driver (parity: ``DQN.train()``)."""

    def __init__(self, config: DQNConfig):
        import cloudpickle
        import gymnasium as gym
        import jax
        self.config = config
        probe = gym.make(config.env, **config.env_config)
        obs_shape = probe.observation_space.shape
        obs_dim = int(np.prod(obs_shape))
        num_actions = int(probe.action_space.n)
        probe.close()
        self.module = DiscreteMLPModule(MLPModuleConfig(
            obs_dim=obs_dim, num_actions=num_actions,
            hidden=tuple(config.hidden)))
        self.learner = DQNLearner(self.module, config)
        self.params, self.opt_state = self.learner.init_state(
            jax.random.PRNGKey(config.seed))
        self.target_params = self.params
        blob = cloudpickle.dumps(self.module)
        self.env_runners = [
            SingleAgentEnvRunner.remote(
                config.env, blob, config.rollout_length,
                seed=config.seed + i, env_config=config.env_config)
            for i in range(config.num_env_runners)]
        self.buffer = ReplayBuffer(config.buffer_size, obs_shape,
                                   seed=config.seed)
        self.iteration = 0
        self.timesteps_total = 0
        self.updates_total = 0

    def _epsilon(self) -> float:
        cfg = self.config
        frac = min(1.0, self.timesteps_total /
                   max(cfg.epsilon_decay_steps, 1))
        return cfg.epsilon_start + frac * (cfg.epsilon_end -
                                           cfg.epsilon_start)

    def train(self) -> Dict[str, Any]:
        import jax
        t0 = time.time()
        cfg = self.config
        eps = self._epsilon()
        params_np = ray_tpu.put(jax.tree.map(np.asarray, self.params))
        batches = ray_tpu.get(
            [r.sample_off_policy.remote(params_np, eps)
             for r in self.env_runners], timeout=600)
        for b in batches:
            self.buffer.add_batch(b)
            self.timesteps_total += len(b["obs"])

        metrics: Dict[str, Any] = {}
        if len(self.buffer) >= cfg.learn_start:
            for _ in range(cfg.updates_per_iteration):
                mb = self.buffer.sample(cfg.train_batch_size)
                self.params, self.opt_state, metrics = \
                    self.learner._update(self.params, self.target_params,
                                         self.opt_state, mb)
                self.updates_total += 1
                if self.updates_total % cfg.target_update_freq == 0:
                    self.target_params = self.params
        runner_metrics = ray_tpu.get(
            [r.get_metrics.remote() for r in self.env_runners],
            timeout=120)
        returns = [m["episode_return_mean"] for m in runner_metrics
                   if not np.isnan(m["episode_return_mean"])]
        self.iteration += 1
        return {
            "training_iteration": self.iteration,
            "timesteps_total": self.timesteps_total,
            "updates_total": self.updates_total,
            "epsilon": eps,
            "buffer_size": len(self.buffer),
            "episode_return_mean": (float(np.mean(returns))
                                    if returns else float("nan")),
            "time_this_iter_s": time.time() - t0,
            **{f"learner/{k}": float(v) for k, v in metrics.items()},
        }

    def stop(self):
        for runner in self.env_runners:
            try:
                ray_tpu.kill(runner)
            except Exception:  # noqa: BLE001
                pass
