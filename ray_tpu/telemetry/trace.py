"""Per-request distributed tracing + anomaly flight recorder (r24).

The per-subsystem aggregates (``telemetry/infer.py``,
``telemetry/fleet.py``) explain throughput but not *one* request: a
p99 TTFT outlier's queue wait, routing pick, tier fetches, handoff
legs and decode ticks are invisible as a causal timeline.  This module
is the cross-cutting layer that connects them:

- :class:`TraceContext` — ``(trace_id, parent_id, sampled)``, minted
  at ``FleetRouter``/``DisaggRouter`` submission (head-based sampling,
  ``RAY_TPU_TRACE_SAMPLE``) and propagated through every attempt: the
  routing pick, the engine's queue/prefix-walk/tier-fetch/prefill
  path, hedge races, cause-tagged failovers, and *across replicas* by
  riding the :class:`~ray_tpu.inference.kv_cache.KVHandoff` payload
  (``to_wire``/``from_wire``).
- :class:`FlightRecorder` — a bounded per-process ring buffer
  (``RAY_TPU_TRACE_RING`` spans) every span lands in.  Recording is a
  dict append under a lock; an unsampled request records nothing, so
  steady-state overhead stays under the r09-style 1% budget
  (``tests/test_trace.py`` asserts it by decomposition).
- :func:`anomaly` — the post-mortem trigger.  Deadline expiries,
  watchdog wedges, straggler demotions, failover-budget exhaustion
  and any :class:`~ray_tpu.util.chaos.InjectedFault` call it; when
  ``RAY_TPU_TRACE_DIR`` is set the whole ring dumps as a
  self-contained Perfetto chrome-trace JSON (merged with the
  ``util/tracing.py`` host spans), so the record of what the system
  was doing survives the incident.

Spans are flat records ``{name, trace_id, span_id, parent_id, start
(epoch seconds), dur, attributes}``; a request's span *tree* is
rebuilt from the parent links (the root ``request`` span is recorded
at mint time with ``dur=0`` so a mid-request dump is still rooted).
The host-sim fleet runs every replica in one process, so one global
recorder sees the whole story; in a multi-process deployment each
process dumps its own ring and the shared ``trace_id`` joins them.
"""

from __future__ import annotations

import collections
import contextlib
import dataclasses
import itertools
import json
import os
import sys
import threading
import time
import uuid
from typing import Any, Dict, List, Optional

# ----------------------------------------------------------------- config


@dataclasses.dataclass(frozen=True)
class TraceConfig:
    """Tracing knobs, resolved once from the environment.

    - ``RAY_TPU_TRACE_SAMPLE`` (default ``1``): head-based sampling
      probability in [0, 1] — the routers decide at mint time and the
      whole request inherits the verdict (deterministic: every
      ``1/rate``-th mint samples, so a fixed workload traces the same
      requests every run).  ``0`` disables span recording entirely;
      anomaly events still record.
    - ``RAY_TPU_TRACE_RING`` (default ``4096``): flight-recorder ring
      capacity in spans.  The ring is per-process and bounded — old
      spans fall off; ``dropped`` counts them.
    - ``RAY_TPU_TRACE_DIR`` (default unset): anomaly-dump directory.
      When set, every anomaly trigger writes the ring as a Perfetto
      chrome-trace JSON (``flight-<kind>-<n>.json``); unset means
      anomalies only record an event in the ring.
    """
    sample: float = 1.0
    ring: int = 4096
    dir: Optional[str] = None


_CONFIG: Optional[TraceConfig] = None


def trace_config(refresh: bool = False) -> TraceConfig:
    """The process-wide :class:`TraceConfig` (env read once, cached)."""
    global _CONFIG
    if _CONFIG is None or refresh:
        raw = os.environ.get("RAY_TPU_TRACE_SAMPLE", "1")
        try:
            sample = float(raw)
        except ValueError:
            print(f"RAY_TPU_TRACE_SAMPLE={raw!r} is not a number; "
                  "using 1", file=sys.stderr)
            sample = 1.0
        if not 0.0 <= sample <= 1.0:
            print(f"RAY_TPU_TRACE_SAMPLE={sample} outside [0, 1]; "
                  "clamping", file=sys.stderr)
            sample = min(max(sample, 0.0), 1.0)
        raw = os.environ.get("RAY_TPU_TRACE_RING", "4096")
        try:
            ring = int(raw)
        except ValueError:
            print(f"RAY_TPU_TRACE_RING={raw!r} is not an int; "
                  "using 4096", file=sys.stderr)
            ring = 4096
        if ring < 1:
            print(f"RAY_TPU_TRACE_RING={ring} < 1; using 4096",
                  file=sys.stderr)
            ring = 4096
        _CONFIG = TraceConfig(
            sample=sample, ring=ring,
            dir=os.environ.get("RAY_TPU_TRACE_DIR") or None)
    return _CONFIG


# ---------------------------------------------------------------- context
class TraceContext:
    """One request's identity on the wire: which trace every span
    joins (``trace_id``), which span new children hang off
    (``parent_id``), and whether this request records at all
    (``sampled`` — the head-based verdict, decided once at mint)."""

    __slots__ = ("trace_id", "parent_id", "sampled")

    def __init__(self, trace_id: str, parent_id: Optional[str] = None,
                 sampled: bool = True):
        self.trace_id = trace_id
        self.parent_id = parent_id
        self.sampled = sampled

    def child(self, parent_id: Optional[str]) -> "TraceContext":
        """Rebase: spans emitted under the returned context parent at
        ``parent_id`` (e.g. a routing attempt's span)."""
        return TraceContext(self.trace_id, parent_id, self.sampled)

    def to_wire(self) -> Dict[str, Any]:
        """Serializable form — rides the ``KVHandoff`` payload across
        replicas (and any other process boundary)."""
        return {"trace_id": self.trace_id, "parent_id": self.parent_id,
                "sampled": self.sampled}

    @classmethod
    def from_wire(cls, wire: Optional[Dict[str, Any]]
                  ) -> Optional["TraceContext"]:
        if not wire:
            return None
        return cls(wire["trace_id"], wire.get("parent_id"),
                   bool(wire.get("sampled", True)))

    def __repr__(self) -> str:
        return (f"TraceContext({self.trace_id!r}, "
                f"parent={self.parent_id!r}, sampled={self.sampled})")


_span_seq = itertools.count(1)
_mint_lock = threading.Lock()
_minted = 0
_sampled_count = 0


def new_span_id() -> str:
    return f"s{next(_span_seq):x}"


def mint(sampled: Optional[bool] = None) -> TraceContext:
    """Mint a fresh root context (router submission).  Head-based
    sampling: with rate ``r``, every ``1/r``-th mint samples —
    deterministic, so a fixed workload traces the same requests every
    run.  ``sampled`` forces the verdict (tests, anomaly re-traces)."""
    global _minted, _sampled_count
    if sampled is None:
        rate = trace_config().sample
        with _mint_lock:
            _minted += 1
            want = int(_minted * rate)
            sampled = want > _sampled_count
            if sampled:
                _sampled_count = want
    return TraceContext(uuid.uuid4().hex[:16], None, bool(sampled))


# --------------------------------------------------------------- recorder
class FlightRecorder:
    """Bounded per-process span ring.  Old spans fall off the back;
    an anomaly dump captures whatever the ring holds — the flight-
    recorder model: always on, bounded cost, read after the crash."""

    def __init__(self, capacity: int):
        self._ring: "collections.deque[Dict[str, Any]]" = \
            collections.deque(maxlen=capacity)
        self._lock = threading.Lock()
        self.capacity = capacity
        self.recorded = 0

    def record(self, rec: Dict[str, Any]) -> None:
        with self._lock:
            self._ring.append(rec)
            self.recorded += 1

    @property
    def dropped(self) -> int:
        with self._lock:
            return self.recorded - len(self._ring)

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def spans(self) -> List[Dict[str, Any]]:
        """Snapshot of the ring, oldest first."""
        with self._lock:
            return list(self._ring)

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()

    def chrome_events(self) -> List[Dict[str, Any]]:
        """Ring spans as Perfetto/chrome "X" complete events.  ``pid``
        groups by the span's replica (the cross-replica view), ``tid``
        by trace — one request reads as one lane."""
        out = []
        for rec in self.spans():
            attrs = rec.get("attributes") or {}
            tid = rec["trace_id"][:8] if rec.get("trace_id") else "global"
            out.append({
                "name": rec["name"], "cat": "trace", "ph": "X",
                "ts": rec["start"] * 1e6,
                # point events (roots, first_token, anomalies) get a
                # 1 µs floor: Perfetto renders them, and the cluster
                # timeline's every-event-has-extent invariant holds
                "dur": max(rec.get("dur", 0.0) * 1e6, 1.0),
                "pid": str(attrs.get("replica", "fleet")),
                "tid": tid,
                "args": {"trace_id": rec.get("trace_id"),
                         "span_id": rec.get("span_id"),
                         "parent_id": rec.get("parent_id"), **attrs},
            })
        return out


_RECORDER: Optional[FlightRecorder] = None


def recorder() -> FlightRecorder:
    """The process-wide ring (capacity from ``RAY_TPU_TRACE_RING``)."""
    global _RECORDER
    if _RECORDER is None:
        _RECORDER = FlightRecorder(trace_config().ring)
    return _RECORDER


def reset() -> None:
    """Fresh recorder + sampling counters under the *current* env
    (tests call ``trace_config(refresh=True)`` first when they flip
    knobs)."""
    global _RECORDER, _minted, _sampled_count
    _RECORDER = FlightRecorder(trace_config().ring)
    with _mint_lock:
        _minted = 0
        _sampled_count = 0


# ---------------------------------------------------------------- spans
def epoch_of(mono_ts: float) -> float:
    """Map a ``time.monotonic()`` stamp onto the epoch axis every
    recorded span uses (the tracing.py convention: epoch start,
    monotonic-derived duration)."""
    return time.time() - (time.monotonic() - mono_ts)


class SpanHandle:
    """Yielded by :func:`span`: the live span's id (for parenting
    children) and its attribute dict (mutable inside the block — e.g.
    the router adds the picked replica after the candidate loop)."""

    __slots__ = ("id", "attrs")

    def __init__(self, span_id: str, attrs: Dict[str, Any]):
        self.id = span_id
        self.attrs = attrs


@contextlib.contextmanager
def span(trace: Optional[TraceContext], name: str,
         parent_id: Optional[str] = None, **attrs):
    """Record a timed span under ``trace`` (no-op for None/unsampled
    contexts — the hot-path guard).  Parents at ``parent_id`` when
    given, else the context's own parent."""
    if trace is None or not trace.sampled:
        yield None
        return
    handle = SpanHandle(new_span_id(), attrs)
    start = time.time()
    m0 = time.monotonic()
    try:
        yield handle
    finally:
        recorder().record({
            "name": name, "trace_id": trace.trace_id,
            "span_id": handle.id,
            "parent_id": (parent_id if parent_id is not None
                          else trace.parent_id),
            "start": start, "dur": time.monotonic() - m0,
            "attributes": handle.attrs})


def record_span(name: str, trace: Optional[TraceContext], *,
                start: float, dur: float,
                span_id: Optional[str] = None,
                parent_id: Optional[str] = None,
                **attrs) -> Optional[str]:
    """Record a span with explicit times (``start`` on the epoch axis
    — use :func:`epoch_of` for monotonic stamps).  ``trace=None``
    records a *global* span (no trace id — e.g. the coalesced
    decode tick, which belongs to every active request at once).
    Returns the span id, or None when the context is unsampled."""
    if trace is not None and not trace.sampled:
        return None
    sid = span_id or new_span_id()
    recorder().record({
        "name": name,
        "trace_id": trace.trace_id if trace is not None else None,
        "span_id": sid,
        "parent_id": (parent_id if parent_id is not None
                      else (trace.parent_id if trace is not None
                            else None)),
        "start": start, "dur": dur, "attributes": attrs})
    return sid


def event(name: str, trace: Optional[TraceContext] = None,
          **attrs) -> Optional[str]:
    """Record an instant (zero-duration span) at now."""
    return record_span(name, trace, start=time.time(), dur=0.0, **attrs)


# -------------------------------------------------------------- anomalies
_anomaly_seq = itertools.count(1)


def anomaly(kind: str, trace: Optional[TraceContext] = None,
            **attrs) -> Optional[str]:
    """Record an anomaly event and — when ``RAY_TPU_TRACE_DIR`` is set
    — dump the flight recorder as a Perfetto JSON post-mortem.
    Anomalies record even for unsampled contexts (the trigger itself
    must never be invisible); returns the dump path or None.

    Triggers: ``deadline`` (``DeadlineExceededError``), ``wedge``
    (watchdog), ``demotion`` (straggler), ``failover_budget``
    (exhausted retries), ``injected_fault`` (any chaos-site
    :class:`~ray_tpu.util.chaos.InjectedFault`)."""
    recorder().record({
        "name": f"anomaly/{kind}",
        "trace_id": trace.trace_id if trace is not None else None,
        "span_id": new_span_id(),
        "parent_id": trace.parent_id if trace is not None else None,
        "start": time.time(), "dur": 0.0, "attributes": dict(attrs)})
    cfg = trace_config()
    if not cfg.dir:
        return None
    path = os.path.join(cfg.dir,
                        f"flight-{kind}-{next(_anomaly_seq):04d}.json")
    try:
        return dump(path, trigger=kind)
    except OSError as exc:  # a full/readonly disk must not kill serving
        print(f"flight-recorder dump to {path} failed: {exc}",
              file=sys.stderr)
        return None


def on_injected_fault(site: str, hit: int) -> Optional[str]:
    """The chaos seam: every armed :class:`InjectedFault` raise calls
    through here (see ``util/chaos.py:maybe_fail``)."""
    return anomaly("injected_fault", site=site, hit=hit)


def dump(path: str, trigger: Optional[str] = None) -> str:
    """Write the ring (merged with the ``util/tracing.py`` host spans)
    as a self-contained Perfetto chrome-trace JSON; returns ``path``."""
    events = recorder().chrome_events()
    try:  # host spans ride along so the dump stands alone in Perfetto
        from ray_tpu.telemetry.chrome_trace import _span_events
        from ray_tpu.util import tracing
        events.extend(_span_events(tracing.recorded_spans()))
    except Exception:       # noqa: BLE001 — a dump must always write
        pass
    events.sort(key=lambda e: e.get("ts", 0))
    rec = recorder()
    doc = {"traceEvents": events, "displayTimeUnit": "ms",
           "metadata": {"trigger": trigger, "recorded": rec.recorded,
                        "dropped": rec.dropped,
                        "ring_capacity": rec.capacity}}
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "w") as f:
        json.dump(doc, f)
    return path


def chrome_events() -> List[Dict[str, Any]]:
    """The ring as chrome events (the ``chrome_trace.trace_events`` /
    dashboard ``/api/timeline`` merge hook)."""
    if _RECORDER is None:       # never materialize a ring just to read it
        return []
    return _RECORDER.chrome_events()


# ---------------------------------------------------------- span algebra
def spans_for(trace_id: str) -> List[Dict[str, Any]]:
    """All ring spans of one trace, oldest first."""
    return [r for r in recorder().spans()
            if r.get("trace_id") == trace_id]


def span_tree(trace_id: str) -> Dict[Optional[str], List[Dict[str, Any]]]:
    """parent_id -> children for one trace (roots under ``None``)."""
    tree: Dict[Optional[str], List[Dict[str, Any]]] = {}
    for rec in spans_for(trace_id):
        tree.setdefault(rec.get("parent_id"), []).append(rec)
    return tree


def format_tree(trace_id: str) -> str:
    """Indented text rendering of one trace's span tree (the bench
    report's slowest-request view)."""
    tree = span_tree(trace_id)
    lines: List[str] = []

    def walk(parent: Optional[str], depth: int) -> None:
        for rec in sorted(tree.get(parent, ()),
                          key=lambda r: r["start"]):
            attrs = rec.get("attributes") or {}
            extras = " ".join(f"{k}={v}" for k, v in attrs.items()
                              if k not in ("trace_id",))
            lines.append(f"{'  ' * depth}{rec['name']} "
                         f"[{rec.get('dur', 0.0) * 1e3:.2f}ms]"
                         + (f" {extras}" if extras else ""))
            walk(rec["span_id"], depth + 1)

    walk(None, 0)
    return "\n".join(lines)
