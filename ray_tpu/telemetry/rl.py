"""RL-loop telemetry: rollout throughput, publish latency, staleness.

The third recorder family, beside :class:`~ray_tpu.telemetry.step.
StepTelemetry` (training) and :class:`~ray_tpu.telemetry.infer.
InferTelemetry` (serving): the RL loop records one entry per rollout
batch, per learner step and per weight publication, and the staleness
signal — ``param_version_lag``, how many publications behind the
trained-on trajectories were generated — rides a Prometheus gauge so
an operator can see actor/learner skew without reading logs.  Sinks
mirror r09: Prometheus through the control plane when a session is up
(``rl_rollout_tokens_per_sec`` / ``rl_learner_steps_per_sec`` /
``rl_param_version_lag`` gauges, ``rl_weight_publish_seconds``
histogram), and :meth:`summary` as the ``telemetry`` block of
``bench.py --rl`` JSON.

``RAY_TPU_TELEMETRY=0`` disables recording entirely.
"""

from __future__ import annotations

import statistics
import time
from typing import Any, Dict, List

from ray_tpu.telemetry.config import telemetry_config

_PUBLISH_BOUNDARIES = [0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005,
                       0.01, 0.025, 0.05, 0.1, 0.25, 1.0, 5.0]


class RLTelemetry:
    """Per-loop recorder for rollout/learner/publish records."""

    _MAX_RECORDS = 10_000
    _EMIT_INTERVAL_S = 0.5

    def __init__(self, *, label: str = "rl", config=None):
        tcfg = config or telemetry_config()
        self.enabled: bool = tcfg.enabled
        self.label = label
        self.rollouts: List[Dict[str, Any]] = []
        self.learner_steps: List[Dict[str, Any]] = []
        self.publishes: List[Dict[str, Any]] = []
        self.rollout_count = 0
        self.rollout_tokens = 0
        self.learner_step_count = 0
        self.publish_count = 0
        self.param_version = 0
        self.version_lags: List[int] = []
        self.drops: Dict[str, int] = {}
        self.backpressure = 0
        self.actor_restarts = 0
        self.learner_restarts = 0
        self._metrics = None
        self._metrics_dead = False
        self._metrics_last = 0.0

    # ---------------------------------------------------------- records
    def record_rollout(self, wall_s: float, *, tokens: int,
                       param_version: int) -> None:
        if not self.enabled:
            return
        self.rollout_count += 1
        self.rollout_tokens += tokens
        self.rollouts.append({"wall_s": wall_s, "tokens": tokens,
                              "param_version": param_version})
        del self.rollouts[:-self._MAX_RECORDS]
        self._emit_rates()

    def record_learner_step(self, wall_s: float, *,
                            version_lag: int) -> None:
        if not self.enabled:
            return
        self.learner_step_count += 1
        self.version_lags.append(int(version_lag))
        del self.version_lags[:-self._MAX_RECORDS]
        self.learner_steps.append({"wall_s": wall_s,
                                   "version_lag": int(version_lag)})
        del self.learner_steps[:-self._MAX_RECORDS]
        self._emit_lag(version_lag)

    def record_publish(self, wall_s: float, *, version: int) -> None:
        if not self.enabled:
            return
        self.publish_count += 1
        self.param_version = int(version)
        self.publishes.append({"wall_s": wall_s, "version": version})
        del self.publishes[:-self._MAX_RECORDS]
        self._emit_publish(wall_s)

    def record_backpressure(self) -> None:
        """A full-queue put rejected under the ``wait`` policy: the
        producer holds the batch and retries — NOT a drop (the batch
        is still trained eventually), so it gets its own counter."""
        if self.enabled:
            self.backpressure += 1

    def record_actor_restart(self) -> None:
        """A rollout actor died (engine fault, injected kill) and the
        supervisor replaced it — the fleet-health signal
        (``rl_actor_restarts_total``) for preemptible actor pools."""
        if not self.enabled:
            return
        self.actor_restarts += 1
        self._emit_restart()

    def record_learner_restart(self) -> None:
        """The learner was restored from its checkpoint mid-loop."""
        if self.enabled:
            self.learner_restarts += 1

    def record_queue_counters(self, *, drops_stale: int,
                              drops_overflow: int) -> None:
        """Final queue accounting (the loop stamps these at
        shutdown so the summary and the queue always agree)."""
        if self.enabled:
            self.drops["stale"] = int(drops_stale)
            self.drops["overflow"] = int(drops_overflow)

    # ---------------------------------------------------------- summary
    def summary(self) -> Dict[str, Any]:
        """The ``telemetry`` block for ``bench.py --rl`` JSON."""
        if not self.enabled:
            return {"enabled": False}
        out: Dict[str, Any] = {
            "enabled": True, "label": self.label,
            "rollouts": self.rollout_count,
            "rollout_tokens": self.rollout_tokens,
            "learner_steps": self.learner_step_count,
            "publishes": self.publish_count,
            "param_version": self.param_version,
            "drops": dict(self.drops),
            "backpressure_rejections": self.backpressure,
            "actor_restarts": self.actor_restarts,
            "learner_restarts": self.learner_restarts,
        }
        if self.rollouts:
            wall = sum(r["wall_s"] for r in self.rollouts)
            tok = sum(r["tokens"] for r in self.rollouts)
            if wall > 0:
                out["rollout_tokens_per_sec"] = tok / wall
            out["rollout_s"] = statistics.median(
                r["wall_s"] for r in self.rollouts)
        if self.learner_steps:
            # steady learner rate: drop the first step (carries the
            # compile on cold learners), the StepTelemetry policy
            steady = self.learner_steps[1:] or self.learner_steps
            wall = sum(r["wall_s"] for r in steady)
            if wall > 0:
                out["learner_steps_per_sec"] = len(steady) / wall
            out["learner_step_s"] = statistics.median(
                r["wall_s"] for r in steady)
        if self.version_lags:
            out["version_lag_mean"] = statistics.fmean(
                self.version_lags)
            out["version_lag_max"] = max(self.version_lags)
        if self.publishes:
            out["publish_s"] = statistics.median(
                r["wall_s"] for r in self.publishes)
            out["publish_max_s"] = max(r["wall_s"]
                                       for r in self.publishes)
        return out

    # ------------------------------------------------------- prometheus
    def _metric_objects(self):
        from ray_tpu._private.worker import is_initialized
        if not is_initialized():
            return None
        if self._metrics is None:
            from ray_tpu.util.metrics import Counter, Gauge, Histogram
            tags = ("label",)
            self._metrics = {
                "restarts": Counter(
                    "rl_actor_restarts_total",
                    "rollout actors restarted by the supervisor",
                    tag_keys=tags),
                "rollout_tok": Gauge("rl_rollout_tokens_per_sec",
                                     "actor rollout token throughput",
                                     tag_keys=tags),
                "learner_rate": Gauge("rl_learner_steps_per_sec",
                                      "learner update throughput",
                                      tag_keys=tags),
                "lag": Gauge("rl_param_version_lag",
                             "publications behind: version lag of the "
                             "last trained-on trajectory batch",
                             tag_keys=tags),
                "publish": Histogram(
                    "rl_weight_publish_seconds",
                    "weight snapshot publish latency",
                    boundaries=_PUBLISH_BOUNDARIES, tag_keys=tags),
            }
        return self._metrics

    def _emit_rates(self):
        if self._metrics_dead:
            return
        now = time.monotonic()
        if (self.rollout_count > 1
                and now - self._metrics_last < self._EMIT_INTERVAL_S):
            return
        self._metrics_last = now
        try:
            metrics = self._metric_objects()
            if metrics is None:
                return
            tags = {"label": self.label}
            last = self.rollouts[-1]
            if last["wall_s"] > 0:
                metrics["rollout_tok"].set(
                    last["tokens"] / last["wall_s"], tags=tags)
            steady = self.learner_steps[1:] or self.learner_steps
            wall = sum(r["wall_s"] for r in steady)
            if wall > 0:
                metrics["learner_rate"].set(len(steady) / wall,
                                            tags=tags)
        except Exception:  # noqa: BLE001 — never tax the loop
            self._metrics_dead = True

    def _emit_restart(self):
        if self._metrics_dead:
            return
        try:
            metrics = self._metric_objects()
            if metrics is not None:
                metrics["restarts"].inc(1.0,
                                        tags={"label": self.label})
        except Exception:  # noqa: BLE001 — never tax the loop
            self._metrics_dead = True

    def _emit_lag(self, lag: int):
        if self._metrics_dead:
            return
        try:
            metrics = self._metric_objects()
            if metrics is not None:
                metrics["lag"].set(float(lag),
                                   tags={"label": self.label})
        except Exception:  # noqa: BLE001 — never tax the loop
            self._metrics_dead = True

    def _emit_publish(self, wall_s: float):
        if self._metrics_dead:
            return
        try:
            metrics = self._metric_objects()
            if metrics is not None:
                metrics["publish"].observe(wall_s,
                                           tags={"label": self.label})
        except Exception:  # noqa: BLE001 — never tax the loop
            self._metrics_dead = True
