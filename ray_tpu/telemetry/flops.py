"""Analytic FLOPs + chip-peak accounting for the MFU figure.

The headline bench has always used the ``6·N`` params approximation;
the telemetry layer wants the *analytic* count from ``GPTConfig`` —
per-matmul, attention included, remat recompute charged — so the MFU
in a step record means "fraction of the MXU the schedule actually
earned" rather than "fraction of a rule of thumb".  The chip peak
table lives here too (it used to be private to ``bench.py``); both
consumers import it from this single home.
"""

from __future__ import annotations

from typing import Optional

# bf16 peak of the chip families we may land on (for the MFU figure)
CHIP_PEAK_TFLOPS = {
    "v4": 275.0,
    "v5 lite": 197.0, "v5e": 197.0,
    "v5p": 459.0,
    "v6 lite": 918.0, "v6e": 918.0,
}

# unknown device kinds (CPU host-sim included) fall back here so MFU
# stays defined everywhere; off-chip the figure is only a consistency
# check on the arithmetic, not a hardware claim
DEFAULT_PEAK_TFLOPS = 197.0


def chip_peak_tflops(device=None) -> float:
    """bf16 peak TFLOP/s of ``device`` (default: first visible device)."""
    if device is None:
        import jax
        device = jax.devices()[0]
    kind = getattr(device, "device_kind", "").lower()
    for key, peak in CHIP_PEAK_TFLOPS.items():
        if key in kind:
            return peak
    return DEFAULT_PEAK_TFLOPS


def gpt_fwd_flops_per_token(cfg, seq: int, *, causal: bool = True) -> float:
    """Matmul FLOPs per token of ONE forward pass of ``cfg`` at ``seq``.

    Counted per token of a length-``seq`` sequence (2 FLOPs per MAC):

    - qkv projections: ``3 · 2·d·H·hd``
    - attention score + value matmuls: ``2 · 2·seq·H·hd`` (each is an
      ``S×S×(H·hd)`` matmul per sequence → ``2·seq·H·hd`` per token),
      halved under a causal mask
    - output projection: ``2·H·hd·d``
    - FFN: ``2·d·f`` per matmul — 3 matmuls for swiglu (w1, w3, w2),
      2 for gelu; MoE charges the gate (``2·d·E``) plus ``top_k``
      experts' FFN
    - lm head: ``2·d·V``

    Embedding lookups are gathers (no MXU FLOPs) and norms/activations
    are vector-unit work — both excluded, matching how published MFU
    figures count.
    """
    d, H, hd = cfg.d_model, cfg.n_heads, cfg.head_dim
    f, L, V = cfg.ff_dim, cfg.n_layers, cfg.vocab_size
    qkv = 3 * 2 * d * H * hd
    attn = 2 * 2 * seq * H * hd
    if causal:
        attn /= 2
    out = 2 * H * hd * d
    ffn_matmuls = 3 if cfg.act == "swiglu" else 2
    ffn = ffn_matmuls * 2 * d * f
    if cfg.n_experts > 0:
        ffn = 2 * d * cfg.n_experts + cfg.moe_top_k * ffn
    layer = qkv + attn + out + ffn
    return L * layer + 2 * d * V


def gpt_train_flops_per_token(cfg, seq: int, *, causal: bool = True,
                              ce_recompute: Optional[bool] = None
                              ) -> float:
    """Matmul FLOPs per token of ONE training step of ``cfg`` at ``seq``.

    ``3×`` the forward (fwd + 2× backward), plus the recompute the
    configured schedule actually pays: ``cfg.remat`` re-runs every
    block's forward in the backward (+1× the layer stack), and a
    rematerializing CE recomputes the head matmul once (``+2·d·V``).
    ``ce_recompute`` says whether the CE path pays that recompute —
    True for ``ce_chunk >= 0`` remat AND for the flash-CE kernel
    (4 vocab matmuls even at ``ce_chunk=-1``); ``None`` infers from
    ``cfg.ce_chunk`` alone, which undercounts a flash-CE no-remat
    config — callers that know the dispatched CE mode (the telemetry
    recorder, bench) should pass it.
    """
    fwd = gpt_fwd_flops_per_token(cfg, seq, causal=causal)
    head = 2 * cfg.d_model * cfg.vocab_size
    total = 3 * fwd
    if cfg.remat:
        total += fwd - head          # one recompute of the layer stack
    if ce_recompute is None:
        ce_recompute = getattr(cfg, "ce_chunk", 0) >= 0
    if ce_recompute:
        total += head                # one recompute of the head matmul
    return total


def mfu(tokens_per_sec_per_device: float, flops_per_token: float,
        peak_tflops: Optional[float] = None) -> float:
    """Model FLOPs utilization: useful FLOP/s over the chip peak."""
    peak = peak_tflops or chip_peak_tflops()
    return tokens_per_sec_per_device * flops_per_token / (peak * 1e12)
