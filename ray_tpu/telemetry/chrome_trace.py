"""Chrome-trace / Perfetto exporter: one unified host+train timeline.

Merges three in-process sources into one ``traceEvents`` JSON that
loads in Perfetto / ``chrome://tracing``:

- the host-side span recorder (``ray_tpu.util.tracing`` fallback
  recorder — submit/task spans plus the named train-loop scopes the
  telemetry wrapper emits when tracing is enabled),
- every live :class:`~ray_tpu.telemetry.step.StepTelemetry` recorder's
  per-step records (step / dispatch / sync / compile complete-events),
- the r24 per-request flight recorder
  (:mod:`ray_tpu.telemetry.trace` — routing, handoff, prefill and
  decode spans, grouped by replica).

The dashboard ``/api/timeline`` appends the same events to the
task-event trace, so a browser pointed at the head node sees train
steps on the cluster timeline; ``export(path)`` writes the standalone
JSON object form (``{"traceEvents": [...]}``) the on-chip drivers
attach next to their xplane captures.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional


def _span_events(spans) -> List[Dict[str, Any]]:
    """util.tracing fallback-recorder spans -> Chrome complete events."""
    evs = []
    for s in spans:
        start = s.get("start")
        if start is None:
            continue
        # durations come from the monotonic clock when the recorder has
        # one (see util/tracing.py); "end" is epoch-placed either way
        dur = s.get("dur")
        if dur is None:
            end = s.get("end")
            if end is None:
                continue
            dur = max(end - start, 0.0)
        evs.append({
            "name": s.get("name", "?"), "cat": "host", "ph": "X",
            "ts": start * 1e6, "dur": dur * 1e6,
            "pid": "host", "tid": str(s.get("tid", "main")),
            "args": dict(s.get("attributes") or {}),
        })
    return evs


def trace_events(include_host: bool = True,
                 include_steps: bool = True,
                 include_requests: bool = True) -> List[Dict[str, Any]]:
    """Every exportable event currently held in this process."""
    evs: List[Dict[str, Any]] = []
    if include_host:
        from ray_tpu.util import tracing
        evs.extend(_span_events(tracing.recorded_spans()))
    if include_steps:
        from ray_tpu.telemetry.step import recorders
        for rec in recorders():
            evs.extend(rec.chrome_events())
    if include_requests:
        # r24 per-request spans: the flight-recorder ring joins the
        # same timeline, so /api/timeline shows serving requests next
        # to train steps for free
        from ray_tpu.telemetry import trace
        evs.extend(trace.chrome_events())
    evs.sort(key=lambda e: e.get("ts", 0))
    return evs


def export(path: Optional[str] = None, *,
           extra_events: Optional[List[Dict[str, Any]]] = None) -> str:
    """Perfetto JSON-object trace of everything recorded so far."""
    evs = trace_events()
    if extra_events:
        evs = sorted(evs + list(extra_events),
                     key=lambda e: e.get("ts", 0))
    out = json.dumps({"traceEvents": evs, "displayTimeUnit": "ms"})
    if path:
        with open(path, "w") as f:
            f.write(out)
    return out
