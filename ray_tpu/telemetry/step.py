"""Step-level training telemetry: the :class:`StepTelemetry` recorder.

Wraps a jitted train step (``build_gpt_train``/``build_gpt_train_pp``
``step_fn``) and emits one structured record per step:

- wall time with an explicit blocking ``jax.block_until_ready`` sync,
  split into dispatch (host returns) and sync (device drains),
- first-step compile time split from steady state — in AOT mode
  (``aot=True``) via an explicit ``lower().compile()`` whose compiled
  executable also yields the HBM footprint from ``memory_analysis()``,
- tokens/sec and an analytic-FLOPs MFU estimate
  (:mod:`ray_tpu.telemetry.flops`) against the chip peak,
- logical collective bytes/step per comm_mode
  (``ray_tpu.parallel.overlap.collective_bytes_per_step``).

Records flow to three sinks: the Chrome-trace exporter
(:mod:`ray_tpu.telemetry.chrome_trace`, merged into the dashboard
``/api/timeline``), Prometheus gauges/histograms through the
control-plane metrics (``train_step_seconds`` / ``train_mfu`` /
``train_collective_bytes`` on ``/metrics``), and the ``telemetry``
block in ``bench.py`` / ``ray_perf.py`` JSON.  ``RAY_TPU_TELEMETRY=0``
turns the whole wrapper into identity; ``RAY_TPU_PROFILE=<dir>``
additionally captures a ``jax.profiler`` xplane trace of the first
steady steps (see :mod:`ray_tpu.telemetry.config`).
"""

from __future__ import annotations

import statistics
import sys
import time
import weakref
from typing import Any, Dict, List, Optional

from ray_tpu.telemetry import flops as flops_mod
from ray_tpu.telemetry.config import telemetry_config

# live recorders, so the chrome-trace exporter / dashboard timeline can
# merge every in-process training loop without explicit plumbing
_RECORDERS: "weakref.WeakSet[StepTelemetry]" = weakref.WeakSet()


def recorders() -> List["StepTelemetry"]:
    return list(_RECORDERS)


def _memory_dict(compiled) -> Optional[Dict[str, int]]:
    """``memory_analysis()`` of an AOT-compiled step as plain ints."""
    try:
        ma = compiled.memory_analysis()
    except Exception:  # noqa: BLE001 — backend may not implement it
        return None
    if ma is None:
        return None
    out: Dict[str, int] = {}
    for field, key in (("argument_size_in_bytes", "argument_bytes"),
                       ("output_size_in_bytes", "output_bytes"),
                       ("temp_size_in_bytes", "temp_bytes"),
                       ("alias_size_in_bytes", "alias_bytes"),
                       ("generated_code_size_in_bytes",
                        "generated_code_bytes")):
        val = getattr(ma, field, None)
        if val is not None:
            out[key] = int(val)
    if not out:
        return None
    # arguments alias outputs for donated buffers; the liveness-ish
    # total charges each once
    out["total_bytes"] = (out.get("argument_bytes", 0)
                          + out.get("output_bytes", 0)
                          + out.get("temp_bytes", 0)
                          + out.get("generated_code_bytes", 0)
                          - out.get("alias_bytes", 0))
    return out


def _arg_signature(args):
    import jax
    return tuple(
        (getattr(leaf, "shape", None), str(getattr(leaf, "dtype", "")))
        for leaf in jax.tree.leaves(args))


def _find_tokens(args, kwargs):
    """The [B, S] token array of a step call, if one is recognizable."""
    for a in list(args) + list(kwargs.values()):
        if isinstance(a, dict) and "tokens" in a:
            tok = a["tokens"]
            if hasattr(tok, "shape") and len(tok.shape) == 2:
                return tok
    return None


class StepTelemetry:
    """Per-step telemetry recorder around one jitted train step.

    ``aot=True`` routes the first call through
    ``step_fn.lower(...).compile()`` — one compile total, an exact
    compile/steady split, and ``memory_analysis()`` HBM numbers; any
    failure on that path falls back loudly to the plain jit call.
    ``aot=False`` (the default the train-step builders use) never
    re-routes compilation: the first step's wall time simply includes
    the jit compile and is reported as ``first_step_s``.
    """

    _MAX_RECORDS = 10_000

    def __init__(self, cfg=None, mesh=None, *,
                 comm_mode: Optional[str] = None,
                 comm_quant: Optional[str] = None,
                 ce_mode: Optional[str] = None,
                 label: str = "train",
                 aot: bool = False,
                 chip_peak_tflops: Optional[float] = None,
                 config=None):
        tcfg = config or telemetry_config()
        self.enabled: bool = tcfg.enabled
        self.cfg = cfg
        self.mesh = mesh
        self.comm_mode = comm_mode
        self.comm_quant = comm_quant
        self.ce_mode = ce_mode
        self.label = label
        self.records: List[Dict[str, Any]] = []
        self.step_count = 0      # total steps seen (survives trimming)
        self.compile_s: Optional[float] = None
        self.first_step_s: Optional[float] = None
        self.memory: Optional[Dict[str, int]] = None
        self._aot = aot
        self._cfgobj = tcfg
        self._compiled = None
        self._signature = None
        self._compile_ts: Optional[float] = None
        self._tokens_per_step: Optional[int] = None
        self._seq: Optional[int] = None
        self._batch: Optional[int] = None
        self._peak = chip_peak_tflops
        self._fpt: Optional[float] = None   # cached; -1 = unavailable
        self._metrics = None          # lazily-created metric objects
        self._metrics_dead = False    # no cluster / emission failed
        self._metrics_last = 0.0      # last emission (monotonic)
        self._bytes_emitted = False
        self._profile_started = False
        self._profile_stopped = False
        if self.enabled:
            _RECORDERS.add(self)

    # ------------------------------------------------------------- wrap --

    def wrap(self, step_fn):
        """``step_fn -> step_fn`` (identity when telemetry is off)."""
        if not self.enabled:
            return step_fn
        import functools

        @functools.wraps(step_fn)
        def wrapped(*args, **kwargs):
            return self._call(step_fn, args, kwargs)

        wrapped.telemetry = self
        return wrapped

    def _call(self, step_fn, args, kwargs):
        import jax

        from ray_tpu.util import tracing
        i = self.step_count
        self.step_count += 1
        self._note_tokens(args, kwargs)
        self._profile(i, before=True)
        ts = time.time()
        t0 = time.monotonic()
        with tracing.span(f"{self.label}/step", step=i):
            with jax.profiler.StepTraceAnnotation(self.label, step_num=i):
                with tracing.span(f"{self.label}/dispatch", step=i):
                    out = self._dispatch(step_fn, args, kwargs, i, ts)
                t_disp = time.monotonic()
                with tracing.span(f"{self.label}/sync", step=i):
                    jax.block_until_ready(out)
        t_end = time.monotonic()
        self._profile(i, before=False)
        rec: Dict[str, Any] = {
            "step": i,
            "ts": ts,
            "wall_s": t_end - t0,
            "dispatch_s": t_disp - t0,
            "sync_s": t_end - t_disp,
        }
        if i == 0 and self.compile_s is not None:
            rec["compile_s"] = self.compile_s
        if i == 0:
            self.first_step_s = rec["wall_s"]
        if self._tokens_per_step:
            rec["tokens"] = self._tokens_per_step
            # step 0's wall includes the (jit or AOT) compile — a
            # throughput/MFU derived from it would be garbage, and step
            # 0 is the one record always emitted to Prometheus
            if i > 0:
                rec["tokens_per_sec"] = (self._tokens_per_step
                                         / rec["wall_s"])
                fpt = self.flops_per_token()
                if fpt is not None:
                    rec["mfu"] = flops_mod.mfu(
                        rec["tokens_per_sec"] / self.n_devices(), fpt,
                        self.chip_peak())
        loss = self._maybe_loss(out)
        if loss is not None:
            rec["loss"] = loss
        self.records.append(rec)
        if len(self.records) > self._MAX_RECORDS:
            # bounded like the control plane's task-event buffer: a
            # 100k-step run must not grow host memory (or the exported
            # timeline) without limit.  first_step_s/compile_s live as
            # attributes, so trimming the head loses nothing summary()
            # reports.
            del self.records[:len(self.records) - self._MAX_RECORDS]
        self._emit(rec)
        return out

    def _dispatch(self, step_fn, args, kwargs, i, ts):
        if not self._aot:
            return step_fn(*args, **kwargs)
        if i == 0:
            try:
                self._compile_ts = ts
                t0 = time.monotonic()
                compiled = step_fn.lower(*args, **kwargs).compile()
                self.compile_s = time.monotonic() - t0
                self.memory = _memory_dict(compiled)
                out = compiled(*args, **kwargs)
                self._compiled = compiled
                self._signature = _arg_signature((args, kwargs))
                return out
            except Exception as e:  # noqa: BLE001 — loud jit fallback
                print(f"telemetry: AOT compile path failed ({e!r}); "
                      "falling back to plain jit dispatch "
                      "(no compile/HBM split)", file=sys.stderr)
                self._aot = False
                self._compiled = None
                self.compile_s = None
                return step_fn(*args, **kwargs)
        if (self._compiled is not None
                and _arg_signature((args, kwargs)) == self._signature):
            try:
                return self._compiled(*args, **kwargs)
            except Exception as e:  # noqa: BLE001
                print(f"telemetry: compiled step call failed ({e!r}); "
                      "reverting to jit dispatch", file=sys.stderr)
                self._compiled = None
        return step_fn(*args, **kwargs)

    # ------------------------------------------------------- accounting --

    def _note_tokens(self, args, kwargs):
        if self._tokens_per_step is not None:
            return
        tok = _find_tokens(args, kwargs)
        if tok is not None:
            self._tokens_per_step = int(tok.shape[0]) * int(tok.shape[1])
            self._seq = int(tok.shape[1])
            self._batch = int(tok.shape[0])

    def _maybe_loss(self, out) -> Optional[float]:
        try:
            if (isinstance(out, tuple) and len(out) == 2
                    and isinstance(out[1], dict) and "loss" in out[1]):
                return float(out[1]["loss"])
        except Exception:  # noqa: BLE001 — loss stays optional
            pass
        return None

    def compiled_step(self):
        """The AOT-compiled executable (``aot=True`` after the first
        wrapped call), or None.  Benchmark loops that must stay free of
        the wrapper's per-step blocking sync call this directly — same
        executable, no recompile, no recording."""
        return self._compiled

    def n_devices(self) -> int:
        return getattr(self.mesh, "size", None) or 1

    def chip_peak(self) -> float:
        if self._peak is None:
            self._peak = flops_mod.chip_peak_tflops()
        return self._peak

    def _ce_recompute(self) -> Optional[bool]:
        """Whether the CE path recomputes the head matmul (4th vocab
        matmul): pinned mode wins; otherwise infer the dispatch —
        flash-CE pays it even at ``ce_chunk=-1``."""
        chunk_remat = getattr(self.cfg, "ce_chunk", 0) >= 0
        if self.ce_mode == "flash":
            return True
        if self.ce_mode in ("xla", "fused"):
            return chunk_remat
        if chunk_remat or self._seq is None or self._batch is None:
            return chunk_remat
        try:
            from ray_tpu.ops.flash_ce import uses_flash_ce
            return uses_flash_ce(self._batch * self._seq,
                                 self.cfg.d_model,
                                 self.cfg.vocab_size,
                                 n_devices=self.n_devices())
        except Exception:  # noqa: BLE001 — best-effort inference
            return chunk_remat

    def flops_per_token(self) -> Optional[float]:
        if self.cfg is None or self._seq is None:
            return None
        if self._fpt is None:     # constant once the batch shape is known
            try:
                self._fpt = flops_mod.gpt_train_flops_per_token(
                    self.cfg, self._seq,
                    ce_recompute=self._ce_recompute())
            except Exception:  # noqa: BLE001 — non-GPT cfg
                self._fpt = -1.0
        return None if self._fpt < 0 else self._fpt

    def collective_bytes(self) -> Optional[Dict[str, Any]]:
        if (self.cfg is None or self.mesh is None
                or self._seq is None):
            return None
        try:
            from ray_tpu.parallel import overlap as ovl
            return ovl.collective_bytes_per_step(
                self.cfg, self.mesh, batch=self._batch, seq=self._seq,
                comm_mode=self.comm_mode or "gspmd",
                quant=self.comm_quant or "none")
        except Exception:  # noqa: BLE001 — non-GPT cfg / odd mesh
            return None

    # ---------------------------------------------------------- summary --

    def summary(self) -> Dict[str, Any]:
        """The aggregate ``telemetry`` block for bench/perf JSON."""
        if not self.enabled:
            return {"enabled": False}
        out: Dict[str, Any] = {"enabled": True, "label": self.label,
                               "steps": self.step_count}
        if not self.records:
            return out
        out["compile_s"] = self.compile_s
        out["first_step_s"] = self.first_step_s
        # a single (compile-inclusive) step has no steady state to
        # report — mislabeling it would be off by orders of magnitude
        steady = [r for r in self.records if r["step"] > 0]
        if steady:
            wall = statistics.median(r["wall_s"] for r in steady)
            out.update({
                "steady_step_s": wall,
                "steady_dispatch_s": statistics.median(
                    r["dispatch_s"] for r in steady),
                "steady_sync_s": statistics.median(
                    r["sync_s"] for r in steady),
            })
            if self._tokens_per_step:
                tok_s = self._tokens_per_step / wall
                out["tokens_per_step"] = self._tokens_per_step
                out["tokens_per_sec"] = tok_s
                out["tokens_per_sec_per_device"] = \
                    tok_s / self.n_devices()
                fpt = self.flops_per_token()
                if fpt is not None:
                    out["flops_per_token"] = fpt
                    out["chip_peak_tflops"] = self.chip_peak()
                    out["mfu"] = flops_mod.mfu(
                        tok_s / self.n_devices(), fpt,
                        self.chip_peak())
        out["hbm"] = self.memory
        cb = self.collective_bytes()
        out["collective_bytes_per_step"] = cb
        if cb is not None:
            # flattened per-tier rows so perf JSON / dashboards can
            # plot the tier split without digging into the nested dict
            for tier in ("ici", "dcn"):
                t = cb.get(tier) or {}
                out[f"collective_bytes_{tier}"] = t.get("total", 0)
                out[f"collective_seconds_{tier}"] = t.get("seconds",
                                                          0.0)
            red = (cb.get("dcn") or {}).get("reduction_vs_flat")
            if red is not None:
                out["dcn_reduction_vs_flat"] = red
        if self.comm_mode is not None:
            out["comm_mode"] = self.comm_mode
        if self.comm_quant is not None:
            out["comm_quant"] = self.comm_quant
        return out

    # ------------------------------------------------------ chrome trace --

    def chrome_events(self) -> List[Dict[str, Any]]:
        """This recorder's steps as Chrome-trace complete events."""
        evs: List[Dict[str, Any]] = []
        pid, tid = "train", self.label
        if self.compile_s is not None and self._compile_ts is not None:
            evs.append({"name": f"{self.label}/compile", "cat": "train",
                        "ph": "X", "ts": self._compile_ts * 1e6,
                        "dur": self.compile_s * 1e6,
                        "pid": pid, "tid": tid, "args": {}})
        for r in self.records:
            args = {k: r[k] for k in ("loss", "tokens_per_sec", "mfu")
                    if k in r}
            args["sync_ms"] = r["sync_s"] * 1e3
            evs.append({"name": f"{self.label}/step {r['step']}",
                        "cat": "train_step", "ph": "X",
                        "ts": r["ts"] * 1e6, "dur": r["wall_s"] * 1e6,
                        "pid": pid, "tid": tid, "args": args})
            evs.append({"name": f"{self.label}/dispatch", "cat": "train",
                        "ph": "X", "ts": r["ts"] * 1e6,
                        "dur": r["dispatch_s"] * 1e6,
                        "pid": pid, "tid": f"{tid}/phases", "args": {}})
            evs.append({"name": f"{self.label}/sync", "cat": "train",
                        "ph": "X",
                        "ts": (r["ts"] + r["dispatch_s"]) * 1e6,
                        "dur": r["sync_s"] * 1e6,
                        "pid": pid, "tid": f"{tid}/phases", "args": {}})
        return evs

    # --------------------------------------------------------- profiler --

    def _profile(self, i: int, *, before: bool):
        pdir = self._cfgobj.profile_dir
        if not pdir:
            return
        first = self._cfgobj.profile_first
        last = first + self._cfgobj.profile_steps - 1
        try:
            import jax
            if (before and not self._profile_started and i >= first):
                jax.profiler.start_trace(pdir)
                self._profile_started = True
            elif (not before and self._profile_started
                    and not self._profile_stopped and i >= last):
                jax.profiler.stop_trace()
                self._profile_stopped = True
        except Exception as e:  # noqa: BLE001 — profiling is best-effort
            print(f"telemetry: xplane capture failed ({e!r})",
                  file=sys.stderr)
            self._profile_stopped = True
            self._profile_started = True

    def stop(self):
        """Finalize: stop a still-running xplane capture."""
        if self._profile_started and not self._profile_stopped:
            try:
                import jax
                jax.profiler.stop_trace()
            except Exception:  # noqa: BLE001
                pass
            self._profile_stopped = True

    # ------------------------------------------------------- prometheus --

    _STEP_BOUNDARIES = [0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                        0.25, 0.5, 1.0, 2.5, 5.0, 10.0]

    _EMIT_INTERVAL_S = 0.5

    def _emit(self, rec):
        """Per-step Prometheus gauges/histograms (control-plane metrics).

        Only when a ray_tpu session is up; the first failure disables
        emission for the rest of the run so a dead control plane cannot
        tax the step loop.  Emission is throttled to one batch per
        ``_EMIT_INTERVAL_S`` (step 0 always emits): the control plane is
        an RPC away, and a per-step RPC burst would tax fast steps for a
        scrape Prometheus only reads every few seconds anyway."""
        if self._metrics_dead:
            return
        now = time.monotonic()
        # steps 0 (compile + collective bytes) and 1 (first real
        # throughput/MFU) always emit; after that, the interval gates
        if rec["step"] > 1 and now - self._metrics_last \
                < self._EMIT_INTERVAL_S:
            return
        self._metrics_last = now
        try:
            from ray_tpu._private.worker import is_initialized
            if not is_initialized():
                return            # cluster may start later; retry then
            if self._metrics is None:
                from ray_tpu.util.metrics import Gauge, Histogram
                tags = ("label",)
                self._metrics = {
                    "step_s": Histogram(
                        "train_step_seconds",
                        "train step wall seconds (blocking sync)",
                        boundaries=self._STEP_BOUNDARIES,
                        tag_keys=tags),
                    "mfu": Gauge("train_mfu",
                                 "analytic-FLOPs model FLOPs utilization",
                                 tag_keys=tags),
                    "tok": Gauge("train_tokens_per_sec",
                                 "training throughput", tag_keys=tags),
                    "bytes": Gauge(
                        "train_collective_bytes",
                        "logical collective bytes/device/step",
                        tag_keys=tags),
                }
            tags = {"label": self.label}
            # step 0's wall includes the compile — keep the 30s-vs-50ms
            # outlier out of the step-seconds distribution, same policy
            # as the skipped step-0 throughput/MFU above
            if rec["step"] > 0:
                self._metrics["step_s"].observe(rec["wall_s"],
                                                tags=tags)
            if "mfu" in rec:
                self._metrics["mfu"].set(rec["mfu"], tags=tags)
            if "tokens_per_sec" in rec:
                self._metrics["tok"].set(rec["tokens_per_sec"],
                                         tags=tags)
            if not self._bytes_emitted:
                # once per run, on the first emission that actually
                # reaches the control plane (the cluster may have come
                # up after step 0)
                cb = self.collective_bytes()
                if cb is not None:
                    self._metrics["bytes"].set(cb["total"], tags=tags)
                self._bytes_emitted = True
        except Exception:  # noqa: BLE001 — never tax the step loop
            self._metrics_dead = True


def instrument(fns: Dict[str, Any], cfg=None, mesh=None, *,
               comm_mode: Optional[str] = None,
               comm_quant: Optional[str] = None,
               ce_mode: Optional[str] = None, label: str = "train",
               aot: bool = False,
               config=None) -> Dict[str, Any]:
    """Wrap the ``step_fn`` of a train-fns dict with a fresh recorder.

    Returns the same dict with ``step_fn`` wrapped and two extra keys:
    ``telemetry`` (the :class:`StepTelemetry`) and ``raw_step_fn`` (the
    unwrapped jitted step).  No-op (no extra keys) when telemetry is
    disabled."""
    rec = StepTelemetry(cfg, mesh, comm_mode=comm_mode,
                        comm_quant=comm_quant, ce_mode=ce_mode,
                        label=label, aot=aot, config=config)
    if not rec.enabled:
        return fns
    fns["raw_step_fn"] = fns["step_fn"]
    fns["step_fn"] = rec.wrap(fns["step_fn"])
    fns["telemetry"] = rec
    return fns
