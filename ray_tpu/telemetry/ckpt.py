"""Checkpoint telemetry: write latency, cadence, failure counts.

The fourth recorder family, beside :class:`~ray_tpu.telemetry.step.
StepTelemetry`, :class:`~ray_tpu.telemetry.infer.InferTelemetry` and
:class:`~ray_tpu.telemetry.rl.RLTelemetry`: the async train
checkpointer records one entry per snapshot write (wall seconds on the
*background* thread — the figure that says whether writes keep up with
the cadence, not whether they stall the step loop) plus the last
successfully persisted step.  Sinks mirror r09: Prometheus through the
control plane when a session is up (``train_checkpoint_seconds``
histogram, ``train_last_checkpoint_step`` gauge), and :meth:`summary`
as the ``checkpoint`` block of driver JSON.

``RAY_TPU_TELEMETRY=0`` disables recording entirely.
"""

from __future__ import annotations

import statistics
from typing import Any, Dict, List

from ray_tpu.telemetry.config import telemetry_config

_WRITE_BOUNDARIES = [0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                     0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0]


class CkptTelemetry:
    """Per-checkpointer recorder for snapshot-write records."""

    _MAX_RECORDS = 10_000

    def __init__(self, *, label: str = "train", config=None):
        tcfg = config or telemetry_config()
        self.enabled: bool = tcfg.enabled
        self.label = label
        self.writes: List[Dict[str, Any]] = []
        self.write_count = 0
        self.failed_count = 0
        self.last_step = -1
        self._metrics = None
        self._metrics_dead = False

    # ---------------------------------------------------------- records
    def record_write(self, wall_s: float, *, step: int) -> None:
        """One completed snapshot write (background thread)."""
        if not self.enabled:
            return
        self.write_count += 1
        self.last_step = int(step)
        self.writes.append({"wall_s": wall_s, "step": int(step)})
        del self.writes[:-self._MAX_RECORDS]
        self._emit(wall_s, step)

    def record_failure(self) -> None:
        """A snapshot write that raised (I/O error, injected fault):
        the trainer keeps going — a failed checkpoint must never kill
        the run it exists to protect — so failures get a counter the
        operator can alarm on instead."""
        if self.enabled:
            self.failed_count += 1

    # ---------------------------------------------------------- summary
    def summary(self) -> Dict[str, Any]:
        """The ``checkpoint`` block for driver JSON."""
        if not self.enabled:
            return {"enabled": False}
        out: Dict[str, Any] = {
            "enabled": True, "label": self.label,
            "checkpoints": self.write_count,
            "failed": self.failed_count,
            "last_checkpoint_step": self.last_step,
        }
        if self.writes:
            out["write_s"] = statistics.median(
                r["wall_s"] for r in self.writes)
            out["write_max_s"] = max(r["wall_s"] for r in self.writes)
        return out

    # ------------------------------------------------------- prometheus
    def _metric_objects(self):
        from ray_tpu._private.worker import is_initialized
        if not is_initialized():
            return None
        if self._metrics is None:
            from ray_tpu.util.metrics import Gauge, Histogram
            tags = ("label",)
            self._metrics = {
                "write": Histogram(
                    "train_checkpoint_seconds",
                    "async TrainState snapshot write wall seconds",
                    boundaries=_WRITE_BOUNDARIES, tag_keys=tags),
                "last_step": Gauge(
                    "train_last_checkpoint_step",
                    "last training step persisted to a checkpoint",
                    tag_keys=tags),
            }
        return self._metrics

    def _emit(self, wall_s: float, step: int):
        if self._metrics_dead:
            return
        try:
            metrics = self._metric_objects()
            if metrics is not None:
                tags = {"label": self.label}
                metrics["write"].observe(wall_s, tags=tags)
                metrics["last_step"].set(float(step), tags=tags)
        except Exception:  # noqa: BLE001 — never tax the train loop
            self._metrics_dead = True
