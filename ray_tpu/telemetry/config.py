"""Telemetry env knobs — the single home for training-telemetry config.

Follows the ``attention_config()`` / ``ce_config()`` / ``comm_config()``
precedent: one frozen dataclass resolved from the environment once,
``refresh=True`` for tests and A/B drivers that flip flags after import.
"""

from __future__ import annotations

import dataclasses
import os
import sys
from typing import Optional


@dataclasses.dataclass(frozen=True)
class TelemetryConfig:
    """Training-telemetry knobs, resolved once from the environment.

    - ``RAY_TPU_TELEMETRY`` (default ``1``): step-level telemetry on the
      train steps returned by ``build_gpt_train``/``build_gpt_train_pp``
      and the bench drivers — per-step wall/sync timing (with an
      explicit ``block_until_ready``), compile-vs-steady split,
      tokens/sec, analytic-FLOPs MFU, HBM footprint from
      ``memory_analysis()`` and logical collective bytes/step.  ``0``
      turns the whole layer into a no-op (the wrapped step IS the raw
      step); the overhead budget when on is <1% of steady-state step
      time, enforced by ``tests/test_telemetry.py``.
    - ``RAY_TPU_PROFILE`` (default unset): a directory; when set, the
      step recorder captures a ``jax.profiler`` xplane trace of steps
      1..3 (the steady window right after compile) into it — the
      on-chip A/B drivers (``scratch/r9_telemetry.py``) use this to get
      a device timeline without editing the loop under test.
    """
    enabled: bool = True
    profile_dir: Optional[str] = None
    # steps captured by the xplane trace when profile_dir is set:
    # [profile_first, profile_first + profile_steps)
    profile_first: int = 1
    profile_steps: int = 3


_CONFIG: Optional[TelemetryConfig] = None


def telemetry_config(refresh: bool = False) -> TelemetryConfig:
    """The process-wide :class:`TelemetryConfig` (env read once, cached)."""
    global _CONFIG
    if _CONFIG is None or refresh:
        raw = os.environ.get("RAY_TPU_TELEMETRY", "1")
        if raw not in ("0", "1"):
            print(f"RAY_TPU_TELEMETRY={raw!r} unknown; using '1'",
                  file=sys.stderr)
            raw = "1"
        _CONFIG = TelemetryConfig(
            enabled=(raw == "1"),
            profile_dir=os.environ.get("RAY_TPU_PROFILE") or None,
        )
    return _CONFIG
