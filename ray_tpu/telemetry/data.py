"""Input-pipeline telemetry: tok/s in, prefetch depth, trainer stalls.

The sixth recorder family, beside step/infer/rl/ckpt/fleet: the
streaming data plane records one entry per produced batch (packed
tokens + producer wall), one per consumer pop (how long the trainer
blocked on input — the figure that says whether the pipeline keeps up),
and counters for reader restarts and pack retries.  Sinks mirror r09:
Prometheus through the control plane when a session is up
(``data_input_tokens_per_sec`` gauge, ``data_prefetch_depth`` gauge,
``data_stall_seconds`` histogram, ``data_reader_restarts_total``
counter), and :meth:`summary` as the ``data`` block of driver JSON.

``RAY_TPU_TELEMETRY=0`` disables recording entirely.
"""

from __future__ import annotations

from typing import Any, Dict

from ray_tpu.telemetry.config import telemetry_config

_STALL_BOUNDARIES = [0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005,
                     0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
                     10.0, 30.0]


class DataTelemetry:
    """Per-loader recorder for the streaming input pipeline."""

    def __init__(self, *, label: str = "train", config=None):
        tcfg = config or telemetry_config()
        self.enabled: bool = tcfg.enabled
        self.label = label
        self.batches = 0
        self.input_tokens = 0
        self.producer_wall_s = 0.0
        self.stall_s_total = 0.0
        self.stall_s_max = 0.0
        self.stalls = 0
        self.reader_restarts = 0
        self.pack_retries = 0
        self.read_hedges = 0
        self.read_hedges_won = 0
        self._depth_sum = 0
        self._metrics = None
        self._metrics_dead = False

    # ---------------------------------------------------------- records
    def record_batch(self, packed_tokens: int, wall_s: float, *,
                     queue_depth: int = 0) -> None:
        """One batch produced (producer thread): non-pad tokens and
        the wall seconds since the previous batch left the packer."""
        if not self.enabled:
            return
        self.batches += 1
        self.input_tokens += int(packed_tokens)
        self.producer_wall_s += max(float(wall_s), 0.0)
        self._depth_sum += int(queue_depth)
        self._emit("batch", queue_depth=queue_depth)

    def record_stall(self, seconds: float) -> None:
        """One consumer pop: how long the trainer blocked on input
        (~0 when the prefetch queue keeps up)."""
        if not self.enabled:
            return
        seconds = max(float(seconds), 0.0)
        self.stalls += 1
        self.stall_s_total += seconds
        self.stall_s_max = max(self.stall_s_max, seconds)
        self._emit("stall", stall_s=seconds)

    def record_reader_restart(self) -> None:
        """A shard reader died (injected or real) and was restarted;
        the fetch was re-issued — counted, never silently absorbed."""
        if not self.enabled:
            return
        self.reader_restarts += 1
        self._emit("restart")

    def record_pack_retry(self) -> None:
        if self.enabled:
            self.pack_retries += 1

    def record_read_hedge(self, *, won: bool) -> None:
        """A shard read outlived its hedge budget and a standby read
        was raced against it (r19); ``won`` when the standby's
        response was the one used."""
        if not self.enabled:
            return
        self.read_hedges += 1
        if won:
            self.read_hedges_won += 1

    # ---------------------------------------------------------- summary
    def input_tok_s(self) -> float:
        return (self.input_tokens / self.producer_wall_s
                if self.producer_wall_s > 0 else 0.0)

    def summary(self) -> Dict[str, Any]:
        """The ``data`` block for driver JSON."""
        if not self.enabled:
            return {"enabled": False}
        out: Dict[str, Any] = {
            "enabled": True, "label": self.label,
            "batches": self.batches,
            "input_tokens": self.input_tokens,
            "input_tok_s": round(self.input_tok_s(), 1),
            "stall_s_total": round(self.stall_s_total, 6),
            "stall_s_max": round(self.stall_s_max, 6),
            "reader_restarts": self.reader_restarts,
            "pack_retries": self.pack_retries,
            "read_hedges": self.read_hedges,
            "read_hedges_won": self.read_hedges_won,
        }
        if self.batches:
            out["prefetch_depth_mean"] = round(
                self._depth_sum / self.batches, 3)
            out["packed_tokens_per_batch"] = round(
                self.input_tokens / self.batches, 1)
        return out

    # ------------------------------------------------------- prometheus
    def _metric_objects(self):
        from ray_tpu._private.worker import is_initialized
        if not is_initialized():
            return None
        if self._metrics is None:
            from ray_tpu.util.metrics import Counter, Gauge, Histogram
            tags = ("label",)
            self._metrics = {
                "tok_s": Gauge(
                    "data_input_tokens_per_sec",
                    "input-pipeline packed tokens produced per second",
                    tag_keys=tags),
                "depth": Gauge(
                    "data_prefetch_depth",
                    "prefetch-queue depth at the last produced batch",
                    tag_keys=tags),
                "stall": Histogram(
                    "data_stall_seconds",
                    "seconds the trainer blocked waiting for input",
                    boundaries=_STALL_BOUNDARIES, tag_keys=tags),
                "restarts": Counter(
                    "data_reader_restarts_total",
                    "shard-reader restarts (fetch re-issued)",
                    tag_keys=tags),
            }
        return self._metrics

    def _emit(self, kind: str, *, queue_depth: int = 0,
              stall_s: float = 0.0):
        if self._metrics_dead:
            return
        try:
            metrics = self._metric_objects()
            if metrics is None:
                return
            tags = {"label": self.label}
            if kind == "batch":
                metrics["tok_s"].set(self.input_tok_s(), tags=tags)
                metrics["depth"].set(float(queue_depth), tags=tags)
            elif kind == "stall":
                metrics["stall"].observe(stall_s, tags=tags)
            elif kind == "restart":
                metrics["restarts"].inc(1.0, tags=tags)
        except Exception:  # noqa: BLE001 — never tax the input path
            self._metrics_dead = True
