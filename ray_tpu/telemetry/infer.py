"""Inference telemetry: TTFT / per-token latency / decode throughput.

The serving-side sibling of :class:`ray_tpu.telemetry.step.StepTelemetry`
— the engine records one entry per prefill and per decode step (wall
time measured to the host-materialized sampled tokens, so it is the
honest blocking figure), plus per-request TTFT at first-token time.
Sinks mirror r09:

- the engine wraps each step in ``ray_tpu.util.tracing`` spans
  (``infer/prefill`` / ``infer/decode``), which the chrome-trace
  exporter already merges into the unified host timeline;
- Prometheus series through the control-plane metrics when a ray_tpu
  session is up (``infer_ttft_seconds`` / ``infer_decode_step_seconds``
  histograms, ``infer_decode_tokens_per_sec`` gauge), throttled and
  dead-on-first-failure exactly like the train recorder;
- :meth:`summary` is the ``telemetry`` block of ``bench.py --infer``
  and ``ray_perf`` JSON.

``RAY_TPU_TELEMETRY=0`` disables recording entirely (the engine checks
``enabled`` before touching the recorder).
"""

from __future__ import annotations

import statistics
import time
from typing import Any, Dict, List, Optional

from ray_tpu.telemetry.config import telemetry_config

_TTFT_BOUNDARIES = [0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                    1.0, 2.5, 5.0, 10.0, 30.0]
_STEP_BOUNDARIES = [0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005,
                    0.01, 0.025, 0.05, 0.1, 0.25, 1.0]


class InferTelemetry:
    """Per-engine recorder for prefill/decode/TTFT records."""

    _MAX_RECORDS = 10_000
    _EMIT_INTERVAL_S = 0.5

    def __init__(self, *, label: str = "infer", config=None):
        tcfg = config or telemetry_config()
        self.enabled: bool = tcfg.enabled
        self.label = label
        self.prefills: List[Dict[str, Any]] = []
        self.decodes: List[Dict[str, Any]] = []
        self.ttfts: List[float] = []
        self.prefill_count = 0
        self.decode_count = 0
        self.requests_done = 0
        self.decode_tokens = 0
        self.cache_info: Dict[str, Any] = {}
        self._metrics = None
        self._metrics_dead = False
        self._metrics_last = 0.0

    # ---------------------------------------------------------- records
    def record_prefill(self, wall_s: float, *, prompt_tokens: int,
                       bucket: int) -> None:
        if not self.enabled:
            return
        self.prefill_count += 1
        self.prefills.append({"wall_s": wall_s,
                              "prompt_tokens": prompt_tokens,
                              "bucket": bucket})
        del self.prefills[:-self._MAX_RECORDS]

    def record_decode(self, wall_s: float, *, active: int) -> None:
        if not self.enabled:
            return
        self.decode_count += 1
        self.decode_tokens += active
        self.decodes.append({"wall_s": wall_s, "active": active})
        del self.decodes[:-self._MAX_RECORDS]
        self._emit_decode(wall_s, active)

    def record_ttft(self, ttft_s: float) -> None:
        if not self.enabled:
            return
        self.ttfts.append(ttft_s)
        del self.ttfts[:-self._MAX_RECORDS]
        self._emit_ttft(ttft_s)

    def record_request_done(self) -> None:
        if self.enabled:
            self.requests_done += 1

    def record_cache_info(self, *, kv_dtype: str, cache_bytes: int,
                          kv_bytes_per_slot: int) -> None:
        """Static KV-cache geometry the engine reports once at
        construction: the storage dtype and the *true* per-slot
        footprint (codes + scale arrays for int8 caches) — the figures
        the ``bench.py --infer`` headline carries."""
        if self.enabled:
            self.cache_info = {"kv_dtype": kv_dtype,
                               "kv_cache_bytes": int(cache_bytes),
                               "kv_bytes_per_slot":
                                   int(kv_bytes_per_slot)}

    # ---------------------------------------------------------- summary
    def summary(self) -> Dict[str, Any]:
        """The ``telemetry`` block for ``bench.py --infer`` JSON."""
        if not self.enabled:
            return {"enabled": False}
        out: Dict[str, Any] = {
            "enabled": True, "label": self.label,
            "requests_done": self.requests_done,
            "prefills": self.prefill_count,
            "decode_steps": self.decode_count,
            "decode_tokens": self.decode_tokens,
            **self.cache_info,
        }
        if self.ttfts:
            out["ttft_s"] = statistics.median(self.ttfts)
            out["ttft_max_s"] = max(self.ttfts)
        if self.prefills:
            out["prefill_s"] = statistics.median(
                r["wall_s"] for r in self.prefills)
        if self.decodes:
            # steady decode: drop the first step (carries the compile
            # on cold engines), same policy as StepTelemetry step 0
            steady = self.decodes[1:] or self.decodes
            step_s = statistics.median(r["wall_s"] for r in steady)
            out["decode_step_s"] = step_s
            tok = sum(r["active"] for r in steady)
            wall = sum(r["wall_s"] for r in steady)
            if wall > 0:
                out["decode_tokens_per_sec"] = tok / wall
        return out

    # ------------------------------------------------------- prometheus
    def _metric_objects(self):
        from ray_tpu._private.worker import is_initialized
        if not is_initialized():
            return None
        if self._metrics is None:
            from ray_tpu.util.metrics import Gauge, Histogram
            tags = ("label",)
            self._metrics = {
                "ttft": Histogram(
                    "infer_ttft_seconds",
                    "time from request submit to first token",
                    boundaries=_TTFT_BOUNDARIES, tag_keys=tags),
                "step": Histogram(
                    "infer_decode_step_seconds",
                    "decode step wall seconds (to sampled tokens)",
                    boundaries=_STEP_BOUNDARIES, tag_keys=tags),
                "tok": Gauge("infer_decode_tokens_per_sec",
                             "decode throughput", tag_keys=tags),
            }
        return self._metrics

    def _emit_ttft(self, ttft_s: float):
        if self._metrics_dead:
            return
        try:
            metrics = self._metric_objects()
            if metrics is not None:
                metrics["ttft"].observe(ttft_s,
                                        tags={"label": self.label})
        except Exception:  # noqa: BLE001 — never tax the serve loop
            self._metrics_dead = True

    def _emit_decode(self, wall_s: float, active: int):
        if self._metrics_dead:
            return
        now = time.monotonic()
        if (self.decode_count > 1
                and now - self._metrics_last < self._EMIT_INTERVAL_S):
            return
        self._metrics_last = now
        try:
            metrics = self._metric_objects()
            if metrics is None:
                return
            tags = {"label": self.label}
            metrics["step"].observe(wall_s, tags=tags)
            if wall_s > 0:
                metrics["tok"].set(active / wall_s, tags=tags)
        except Exception:  # noqa: BLE001 — never tax the serve loop
            self._metrics_dead = True
