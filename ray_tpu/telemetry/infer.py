"""Inference telemetry: TTFT / per-token latency / decode throughput.

The serving-side sibling of :class:`ray_tpu.telemetry.step.StepTelemetry`
— the engine records one entry per prefill and per decode step (wall
time measured to the host-materialized sampled tokens, so it is the
honest blocking figure), plus per-request TTFT at first-token time.
Sinks mirror r09:

- the engine wraps each step in ``ray_tpu.util.tracing`` spans
  (``infer/prefill`` / ``infer/decode``), which the chrome-trace
  exporter already merges into the unified host timeline;
- Prometheus series through the control-plane metrics when a ray_tpu
  session is up (``infer_ttft_seconds`` / ``infer_decode_step_seconds``
  / ``infer_queue_wait_seconds`` histograms,
  ``infer_decode_tokens_per_sec`` / ``infer_queue_depth`` gauges),
  throttled and dead-on-first-failure exactly like the train recorder;
- :meth:`summary` is the ``telemetry`` block of ``bench.py --infer``
  and ``ray_perf`` JSON.

``RAY_TPU_TELEMETRY=0`` disables recording entirely (the engine checks
``enabled`` before touching the recorder).
"""

from __future__ import annotations

import statistics
import time
from typing import Any, Dict, List, Optional

from ray_tpu.telemetry.config import telemetry_config

_TTFT_BOUNDARIES = [0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                    1.0, 2.5, 5.0, 10.0, 30.0]
_STEP_BOUNDARIES = [0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005,
                    0.01, 0.025, 0.05, 0.1, 0.25, 1.0]


class InferTelemetry:
    """Per-engine recorder for prefill/decode/TTFT records."""

    _MAX_RECORDS = 10_000
    _MAX_EXEMPLARS = 64
    _EMIT_INTERVAL_S = 0.5

    def __init__(self, *, label: str = "infer", config=None):
        tcfg = config or telemetry_config()
        self.enabled: bool = tcfg.enabled
        self.label = label
        self.prefills: List[Dict[str, Any]] = []
        self.decodes: List[Dict[str, Any]] = []
        self.ttfts: List[float] = []
        # (ttft_s, trace_id) exemplars — the histogram-to-trace bridge
        self.ttft_exemplars: List[Any] = []
        # TTFT split by prefix-cache outcome: a hit request's first
        # token only pays the suffix prefill, so the two populations
        # have different distributions worth reporting separately
        self.ttfts_hit: List[float] = []
        self.ttfts_miss: List[float] = []
        self.queue_waits: List[float] = []
        self.prefill_count = 0
        self.decode_count = 0
        self.requests_done = 0
        self.decode_tokens = 0
        self.prompt_tokens = 0
        self.prefix_hit_tokens = 0
        self.deadline_exceeded: Dict[str, int] = {}
        # speculative decoding (r21): cumulative proposed/accepted
        # draft counts and verify-step count — the accept rate is the
        # one number that decides whether speculation pays
        self.spec_proposed = 0
        self.spec_accepted = 0
        self.spec_verify_steps = 0
        # tiered KV cache (r23): prefix hits by serving tier, plus the
        # demote (spill bytes) and promote (fetch latency) legs
        self.tier_hits: Dict[str, int] = {}
        self.kv_spill_bytes = 0
        self.kv_fetches = 0
        self.kv_fetch_seconds = 0.0
        self.kv_store_evictions = 0
        # multi-tenant LoRA (r25): per-replica adapter-cache outcomes
        # and load latency — the hit rate is what the router's
        # adapter-affinity scoring is supposed to move
        self.adapter_cache_hits = 0
        self.adapter_cache_misses = 0
        self.adapter_loads = 0
        self.adapter_load_seconds = 0.0
        self.cache_info: Dict[str, Any] = {}
        self._metrics = None
        self._metrics_dead = False
        self._metrics_last = 0.0
        self._queue_last = 0.0
        self._tier_last = 0.0

    # ---------------------------------------------------------- records
    def record_prefill(self, wall_s: float, *, prompt_tokens: int,
                       bucket: int, cached_tokens: int = 0) -> None:
        if not self.enabled:
            return
        self.prefill_count += 1
        self.prompt_tokens += prompt_tokens
        self.prefix_hit_tokens += cached_tokens
        self.prefills.append({"wall_s": wall_s,
                              "prompt_tokens": prompt_tokens,
                              "cached_tokens": cached_tokens,
                              "bucket": bucket})
        del self.prefills[:-self._MAX_RECORDS]

    def record_decode(self, wall_s: float, *, active: int) -> None:
        if not self.enabled:
            return
        self.decode_count += 1
        self.decode_tokens += active
        self.decodes.append({"wall_s": wall_s, "active": active})
        del self.decodes[:-self._MAX_RECORDS]
        self._emit_decode(wall_s, active)

    def record_verify(self, wall_s: float, *, proposed: int,
                      accepted: int, emitted: int) -> None:
        """One speculative verify step: ``proposed`` drafts scored,
        ``accepted`` of them kept, ``emitted`` real tokens delivered
        (accepted + the correction/bonus row, clipped by EOS/max_new).
        The step folds into the decode series — its wall time and
        emitted tokens are decode throughput, just > 1 token per
        dispatch — so ``decode_tokens_per_sec`` stays the honest
        engine-wide figure with speculation on."""
        if not self.enabled:
            return
        self.decode_count += 1
        self.decode_tokens += emitted
        self.spec_verify_steps += 1
        self.spec_proposed += proposed
        self.spec_accepted += accepted
        self.decodes.append({"wall_s": wall_s, "active": emitted})
        del self.decodes[:-self._MAX_RECORDS]
        self._emit_verify(wall_s, proposed, accepted, emitted)

    def record_ttft(self, ttft_s: float, *, prefix_hit: bool = False,
                    trace_id: Optional[str] = None) -> None:
        """``trace_id`` (when the request was trace-sampled) rides the
        Prometheus histogram as an exemplar — the jump from a p99
        bucket to the one request's flight-recorder span tree."""
        if not self.enabled:
            return
        self.ttfts.append(ttft_s)
        del self.ttfts[:-self._MAX_RECORDS]
        split = self.ttfts_hit if prefix_hit else self.ttfts_miss
        split.append(ttft_s)
        del split[:-self._MAX_RECORDS]
        if trace_id:
            self.ttft_exemplars.append((ttft_s, trace_id))
            del self.ttft_exemplars[:-self._MAX_EXEMPLARS]
        self._emit_ttft(ttft_s, trace_id)

    def record_queue(self, wait_s: float, *, depth: int) -> None:
        """Admission-time record: how long the request waited in the
        queue and how deep the queue stands behind it (the load-
        shedding signals: ``RAY_TPU_INFER_MAX_QUEUE`` caps the depth,
        these series say how close traffic runs to the cap)."""
        if not self.enabled:
            return
        self.queue_waits.append(wait_s)
        del self.queue_waits[:-self._MAX_RECORDS]
        self._emit_queue(wait_s, depth)

    def record_queue_depth(self, depth: int) -> None:
        """Submit-time gauge update: admissions stall exactly when the
        queue is backing up, so the depth gauge must also move on
        enqueue or it reads 0 through the whole overload.  Throttled
        like the decode emitter — high-QPS submits must not pay a
        metric emission each."""
        if not self.enabled or self._metrics_dead:
            return
        now = time.monotonic()
        if now - self._queue_last < self._EMIT_INTERVAL_S:
            return
        self._queue_last = now
        self._emit_queue(None, depth)

    def record_request_done(self) -> None:
        if self.enabled:
            self.requests_done += 1

    def record_deadline_exceeded(self, *, kind: str) -> None:
        """One request retired past its deadline (``kind`` = ``ttft``
        — never admitted in time — or ``total`` — expired mid-flight).
        Shed work is the load-limit signal, so it gets a Prometheus
        counter (``infer_deadline_exceeded_total``) operators can rate
        and alarm on."""
        if not self.enabled:
            return
        self.deadline_exceeded[kind] = \
            self.deadline_exceeded.get(kind, 0) + 1
        self._emit_deadline(kind)

    def record_prefix_hits(self, n_pages: int, *, tier: str) -> None:
        """``n_pages`` prefix pages served from ``tier`` (``hbm`` —
        resident refcount bump; ``dram`` — promoted from the host
        pool; ``store`` — fetched from the fleet-shared object store).
        The per-tier split is the whole point of the r23 hierarchy:
        a flat hit rate cannot say which tier is earning its bytes."""
        if not self.enabled:
            return
        self.tier_hits[tier] = self.tier_hits.get(tier, 0) + n_pages
        self._emit_prefix_hits(n_pages, tier)

    def record_kv_spill(self, nbytes: int) -> None:
        """One page demoted out of HBM (``nbytes`` in the spill
        encoding — int8 codes + scales by default, ~half the model-
        dtype figure)."""
        if not self.enabled:
            return
        self.kv_spill_bytes += nbytes
        self._emit_kv_spill(nbytes)

    def record_kv_fetch(self, wall_s: float, *, tier: str) -> None:
        """One page promoted back into HBM from a lower tier — the
        latency the admission paid instead of prefill FLOPs."""
        if not self.enabled:
            return
        self.kv_fetches += 1
        self.kv_fetch_seconds += wall_s
        self._emit_kv_fetch(wall_s, tier)

    def record_kv_store_evictions(self, n: int) -> None:
        """``n`` entries LRU-evicted from the capped fleet page store
        (``RAY_TPU_KV_STORE_CAP``) — the churn signal: a high rate says
        the cap is below the working set and re-admits are paying
        suffix prefills for pages the fleet once held."""
        if not self.enabled or n <= 0:
            return
        self.kv_store_evictions += n
        self._emit_store_evictions(n)

    def record_adapter_cache(self, *, hit: bool) -> None:
        """One adapter-resolution outcome: ``hit`` means the tenant's
        factors were already resident in the engine's bank (zero-cost
        resolution); a miss pays a store fetch + bank install before
        the request can admit."""
        if not self.enabled:
            return
        if hit:
            self.adapter_cache_hits += 1
        else:
            self.adapter_cache_misses += 1
        self._emit_adapter_cache(hit)

    def record_adapter_load(self, wall_s: float, *,
                            resident: int) -> None:
        """One adapter fetched from the store and installed into the
        bank (``wall_s`` = checkout + host ``.at[].set``), plus the
        resident-tenant count after the install (the gauge operators
        watch against ``RAY_TPU_ADAPTER_CACHE``)."""
        if not self.enabled:
            return
        self.adapter_loads += 1
        self.adapter_load_seconds += wall_s
        self._emit_adapter_load(wall_s, resident)

    def record_tier_occupancy(self, *, hbm: int, dram: int,
                              store: int) -> None:
        """Per-tick tier occupancy gauges (pages resident per tier),
        throttled like the decode emitter — the engine calls this every
        tick."""
        if not self.enabled or self._metrics_dead:
            return
        now = time.monotonic()
        if now - self._tier_last < self._EMIT_INTERVAL_S:
            return
        self._tier_last = now
        self._emit_tier_occupancy(hbm, dram, store)

    def record_cache_info(self, *, kv_dtype: str, cache_bytes: int,
                          kv_bytes_per_slot: int) -> None:
        """Static KV-cache geometry the engine reports once at
        construction: the storage dtype and the *true* per-slot
        footprint (codes + scale arrays for int8 caches) — the figures
        the ``bench.py --infer`` headline carries."""
        if self.enabled:
            self.cache_info = {"kv_dtype": kv_dtype,
                               "kv_cache_bytes": int(cache_bytes),
                               "kv_bytes_per_slot":
                                   int(kv_bytes_per_slot)}

    # ---------------------------------------------------------- summary
    def summary(self) -> Dict[str, Any]:
        """The ``telemetry`` block for ``bench.py --infer`` JSON."""
        if not self.enabled:
            return {"enabled": False}
        out: Dict[str, Any] = {
            "enabled": True, "label": self.label,
            "requests_done": self.requests_done,
            "prefills": self.prefill_count,
            "decode_steps": self.decode_count,
            "decode_tokens": self.decode_tokens,
            **self.cache_info,
        }
        out["prompt_tokens"] = self.prompt_tokens
        out["prefill_tokens_skipped"] = self.prefix_hit_tokens
        out["deadline_exceeded"] = dict(self.deadline_exceeded)
        if self.spec_verify_steps:
            out["spec"] = {
                "verify_steps": self.spec_verify_steps,
                "proposed": self.spec_proposed,
                "accepted": self.spec_accepted,
                "accept_rate": (self.spec_accepted
                                / self.spec_proposed
                                if self.spec_proposed else 0.0),
            }
        if self.prompt_tokens:
            out["prefix_hit_rate"] = (self.prefix_hit_tokens
                                      / self.prompt_tokens)
        if self.adapter_cache_hits or self.adapter_cache_misses:
            looked = self.adapter_cache_hits + self.adapter_cache_misses
            out["adapters"] = {
                "cache_hits": self.adapter_cache_hits,
                "cache_misses": self.adapter_cache_misses,
                "cache_hit_rate": self.adapter_cache_hits / looked,
                "loads": self.adapter_loads,
                "load_seconds": self.adapter_load_seconds,
            }
        if self.tier_hits or self.kv_fetches or self.kv_spill_bytes:
            out["tiers"] = {
                "hits": dict(self.tier_hits),
                "spill_bytes": self.kv_spill_bytes,
                "fetches": self.kv_fetches,
                "fetch_seconds": self.kv_fetch_seconds,
                "store_evictions": self.kv_store_evictions,
            }
        if self.ttfts:
            out["ttft_s"] = statistics.median(self.ttfts)
            out["ttft_mean_s"] = statistics.fmean(self.ttfts)
            out["ttft_max_s"] = max(self.ttfts)
        if self.ttft_exemplars:
            # the worst traced request — where tail diagnosis starts
            worst = max(self.ttft_exemplars, key=lambda e: e[0])
            out["ttft_worst_trace"] = {"ttft_s": worst[0],
                                       "trace_id": worst[1]}
        if self.ttfts_hit:
            out["ttft_prefix_hit_s"] = statistics.median(self.ttfts_hit)
        if self.ttfts_miss:
            out["ttft_prefix_miss_s"] = statistics.median(
                self.ttfts_miss)
        if self.queue_waits:
            out["queue_wait_s"] = statistics.median(self.queue_waits)
            out["queue_wait_max_s"] = max(self.queue_waits)
        if self.prefills:
            out["prefill_s"] = statistics.median(
                r["wall_s"] for r in self.prefills)
        if self.decodes:
            # steady decode: drop the first step (carries the compile
            # on cold engines), same policy as StepTelemetry step 0
            steady = self.decodes[1:] or self.decodes
            step_s = statistics.median(r["wall_s"] for r in steady)
            out["decode_step_s"] = step_s
            tok = sum(r["active"] for r in steady)
            wall = sum(r["wall_s"] for r in steady)
            if wall > 0:
                out["decode_tokens_per_sec"] = tok / wall
        return out

    # ------------------------------------------------------- prometheus
    def _metric_objects(self):
        from ray_tpu._private.worker import is_initialized
        if not is_initialized():
            return None
        if self._metrics is None:
            from ray_tpu.util.metrics import Counter, Gauge, Histogram
            tags = ("label",)
            self._metrics = {
                "ttft": Histogram(
                    "infer_ttft_seconds",
                    "time from request submit to first token",
                    boundaries=_TTFT_BOUNDARIES, tag_keys=tags),
                "step": Histogram(
                    "infer_decode_step_seconds",
                    "decode step wall seconds (to sampled tokens)",
                    boundaries=_STEP_BOUNDARIES, tag_keys=tags),
                "tok": Gauge("infer_decode_tokens_per_sec",
                             "decode throughput", tag_keys=tags),
                "queue_wait": Histogram(
                    "infer_queue_wait_seconds",
                    "time from request submit to slot admission",
                    boundaries=_TTFT_BOUNDARIES, tag_keys=tags),
                "queue_depth": Gauge(
                    "infer_queue_depth",
                    "requests waiting for a decode slot",
                    tag_keys=tags),
                "deadline": Counter(
                    "infer_deadline_exceeded_total",
                    "requests retired past their TTFT/total deadline",
                    tag_keys=("label", "kind")),
                "spec_proposed": Counter(
                    "infer_spec_proposed_total",
                    "speculative draft tokens proposed",
                    tag_keys=tags),
                "spec_accepted": Counter(
                    "infer_spec_accepted_total",
                    "speculative draft tokens accepted",
                    tag_keys=tags),
                "spec_rate": Gauge(
                    "infer_spec_accept_rate",
                    "cumulative speculative accept rate",
                    tag_keys=tags),
                # a gauge, not a histogram: draft counts are neither
                # seconds nor bytes, and the naming lint
                # (tests/test_metrics_naming.py) holds histograms to
                # those units — the accept *distribution* lives in
                # ``stats()["spec"]["k_hist"]``
                "spec_hist": Gauge(
                    "infer_spec_accepted_tokens",
                    "drafts accepted in the most recent verify step",
                    tag_keys=tags),
                "prefix_hits": Counter(
                    "infer_prefix_hits_total",
                    "prefix pages served, by tier",
                    tag_keys=("label", "tier")),
                "kv_spill": Counter(
                    "infer_kv_spill_bytes_total",
                    "KV page bytes demoted out of HBM",
                    tag_keys=tags),
                "kv_fetch": Histogram(
                    "infer_kv_fetch_seconds",
                    "KV page promote latency, by source tier",
                    boundaries=_STEP_BOUNDARIES,
                    tag_keys=("label", "tier")),
                "tier_pages": Gauge(
                    "infer_kv_tier_pages",
                    "prefix pages resident, by tier",
                    tag_keys=("label", "tier")),
                "store_evictions": Counter(
                    "infer_kv_store_evictions_total",
                    "entries LRU-evicted from the capped fleet "
                    "KV page store",
                    tag_keys=tags),
                "adapter_hits": Counter(
                    "serve_adapter_cache_hits_total",
                    "adapter resolutions served from the resident bank",
                    tag_keys=tags),
                "adapter_misses": Counter(
                    "serve_adapter_cache_misses_total",
                    "adapter resolutions that paid a store fetch",
                    tag_keys=tags),
                "adapter_load": Histogram(
                    "serve_adapter_load_seconds",
                    "adapter store-fetch + bank-install latency",
                    boundaries=_TTFT_BOUNDARIES, tag_keys=tags),
                "adapter_resident": Gauge(
                    "serve_adapter_resident",
                    "tenant adapters resident in the bank",
                    tag_keys=tags),
            }
        return self._metrics

    def _emit_ttft(self, ttft_s: float,
                   trace_id: Optional[str] = None):
        if self._metrics_dead:
            return
        try:
            metrics = self._metric_objects()
            if metrics is not None:
                metrics["ttft"].observe(
                    ttft_s, tags={"label": self.label},
                    exemplar=({"trace_id": trace_id}
                              if trace_id else None))
        except Exception:  # noqa: BLE001 — never tax the serve loop
            self._metrics_dead = True

    def _emit_queue(self, wait_s, depth: int):
        if self._metrics_dead:
            return
        try:
            metrics = self._metric_objects()
            if metrics is not None:
                tags = {"label": self.label}
                if wait_s is not None:
                    metrics["queue_wait"].observe(wait_s, tags=tags)
                metrics["queue_depth"].set(depth, tags=tags)
        except Exception:  # noqa: BLE001 — never tax the serve loop
            self._metrics_dead = True

    def _emit_deadline(self, kind: str):
        if self._metrics_dead:
            return
        try:
            metrics = self._metric_objects()
            if metrics is not None:
                metrics["deadline"].inc(
                    1.0, tags={"label": self.label, "kind": kind})
        except Exception:  # noqa: BLE001 — never tax the serve loop
            self._metrics_dead = True

    def _emit_verify(self, wall_s: float, proposed: int,
                     accepted: int, emitted: int):
        if self._metrics_dead:
            return
        try:
            metrics = self._metric_objects()
            if metrics is None:
                return
            tags = {"label": self.label}
            # counters are exact (never throttled — rates must add up);
            # the gauge/histograms ride the decode emitter's throttle
            metrics["spec_proposed"].inc(float(proposed), tags=tags)
            metrics["spec_accepted"].inc(float(accepted), tags=tags)
            now = time.monotonic()
            if (self.spec_verify_steps > 1
                    and now - self._metrics_last
                    < self._EMIT_INTERVAL_S):
                return
            self._metrics_last = now
            metrics["spec_hist"].set(float(accepted), tags=tags)
            if self.spec_proposed:
                metrics["spec_rate"].set(
                    self.spec_accepted / self.spec_proposed, tags=tags)
            metrics["step"].observe(wall_s, tags=tags)
            if wall_s > 0:
                metrics["tok"].set(emitted / wall_s, tags=tags)
        except Exception:  # noqa: BLE001 — never tax the serve loop
            self._metrics_dead = True

    def _emit_prefix_hits(self, n_pages: int, tier: str):
        if self._metrics_dead:
            return
        try:
            metrics = self._metric_objects()
            if metrics is not None:
                metrics["prefix_hits"].inc(
                    float(n_pages),
                    tags={"label": self.label, "tier": tier})
        except Exception:  # noqa: BLE001 — never tax the serve loop
            self._metrics_dead = True

    def _emit_kv_spill(self, nbytes: int):
        if self._metrics_dead:
            return
        try:
            metrics = self._metric_objects()
            if metrics is not None:
                metrics["kv_spill"].inc(float(nbytes),
                                        tags={"label": self.label})
        except Exception:  # noqa: BLE001 — never tax the serve loop
            self._metrics_dead = True

    def _emit_kv_fetch(self, wall_s: float, tier: str):
        if self._metrics_dead:
            return
        try:
            metrics = self._metric_objects()
            if metrics is not None:
                metrics["kv_fetch"].observe(
                    wall_s, tags={"label": self.label, "tier": tier})
        except Exception:  # noqa: BLE001 — never tax the serve loop
            self._metrics_dead = True

    def _emit_store_evictions(self, n: int):
        if self._metrics_dead:
            return
        try:
            metrics = self._metric_objects()
            if metrics is not None:
                metrics["store_evictions"].inc(
                    float(n), tags={"label": self.label})
        except Exception:  # noqa: BLE001 — never tax the serve loop
            self._metrics_dead = True

    def _emit_adapter_cache(self, hit: bool):
        if self._metrics_dead:
            return
        try:
            metrics = self._metric_objects()
            if metrics is not None:
                key = "adapter_hits" if hit else "adapter_misses"
                metrics[key].inc(1.0, tags={"label": self.label})
        except Exception:  # noqa: BLE001 — never tax the serve loop
            self._metrics_dead = True

    def _emit_adapter_load(self, wall_s: float, resident: int):
        if self._metrics_dead:
            return
        try:
            metrics = self._metric_objects()
            if metrics is not None:
                tags = {"label": self.label}
                metrics["adapter_load"].observe(wall_s, tags=tags)
                metrics["adapter_resident"].set(resident, tags=tags)
        except Exception:  # noqa: BLE001 — never tax the serve loop
            self._metrics_dead = True

    def _emit_tier_occupancy(self, hbm: int, dram: int, store: int):
        try:
            metrics = self._metric_objects()
            if metrics is None:
                return
            for tier, n in (("hbm", hbm), ("dram", dram),
                            ("store", store)):
                metrics["tier_pages"].set(
                    n, tags={"label": self.label, "tier": tier})
        except Exception:  # noqa: BLE001 — never tax the serve loop
            self._metrics_dead = True

    def _emit_decode(self, wall_s: float, active: int):
        if self._metrics_dead:
            return
        now = time.monotonic()
        if (self.decode_count > 1
                and now - self._metrics_last < self._EMIT_INTERVAL_S):
            return
        self._metrics_last = now
        try:
            metrics = self._metric_objects()
            if metrics is None:
                return
            tags = {"label": self.label}
            metrics["step"].observe(wall_s, tags=tags)
            if wall_s > 0:
                metrics["tok"].set(active / wall_s, tags=tags)
        except Exception:  # noqa: BLE001 — never tax the serve loop
            self._metrics_dead = True
