"""Fleet telemetry: router retries, replica restarts, affinity hit rate.

The fourth recorder family, beside train/infer/RL: the fleet router
and reconciler record every retry (split by cause — a dead replica, a
draining one, a full queue), every replica restart, per-replica queue
depth, and the prefix-affinity routing hit rate.  r19 adds the
gray-failure series: every hedge split by outcome (``issued`` /
``won`` / ``wasted``), every latency demotion, and the per-replica
EWMA latency score.  r20 adds the disaggregation series: every KV
handoff (bytes moved, wall seconds, pages, warm skips), per-pool
queue-depth gauges, and TTFT split by pool mode (``disagg`` vs
``colocated`` — the A/B the split exists for).  Sinks mirror r09:
Prometheus through the control plane when a session is up
(``serve_router_retries_total`` / ``serve_replica_restarts_total`` /
``serve_hedges_total`` / ``serve_replica_demotions_total`` /
``serve_handoff_bytes_total`` counters, ``serve_handoff_seconds`` /
``serve_ttft_seconds`` histograms, ``serve_replica_queue_depth`` /
``serve_replica_latency_score`` / ``serve_pool_queue_depth`` /
``serve_fleet_affinity_hit_rate`` gauges), and :meth:`summary` as the
``fleet`` block of ``bench.py --infer --replicas N`` JSON.

``RAY_TPU_TELEMETRY=0`` disables recording entirely.
"""

from __future__ import annotations

import statistics
import time
from typing import Any, Dict, List

from ray_tpu.telemetry.config import telemetry_config

_HANDOFF_BOUNDARIES = [0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005,
                       0.01, 0.025, 0.05, 0.1, 0.25, 1.0]
_TTFT_BOUNDARIES = [0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                    1.0, 2.5, 5.0, 10.0, 30.0]


class FleetTelemetry:
    """Per-fleet recorder for routing/reconciliation events."""

    _EMIT_INTERVAL_S = 0.5

    def __init__(self, *, label: str = "fleet", config=None):
        tcfg = config or telemetry_config()
        self.enabled: bool = tcfg.enabled
        self.label = label
        # cause -> count; causes: "dead" (replica death/wedge failover
        # or a failed routed submit), "draining", "queue_full"
        self.retries: Dict[str, int] = {}
        self.replica_restarts = 0
        self.affinity_routed = 0
        self.affinity_decisions = 0
        self.queue_depths: Dict[str, int] = {}
        # outcome -> count; outcomes: "issued" (hedge submitted),
        # "won" (the hedge delivered the stream), "wasted" (the
        # primary did — the hedge's work was thrown away)
        self.hedges: Dict[str, int] = {}
        # winner -> count for resolved hedge races ("primary" /
        # "hedge") — the r24 /metrics view of race outcomes
        self.hedge_winners: Dict[str, int] = {}
        # cause -> count for completed failovers ("dead" / "wedged" /
        # "handoff" / ...) — previously only visible per-stream
        self.failovers: Dict[str, int] = {}
        self.replica_demotions = 0
        self.latency_scores: Dict[str, float] = {}
        # r20 disaggregation series: handoff accounting + per-pool
        # depth gauges + TTFT populations split by pool mode
        self.handoffs = 0
        self.handoffs_skipped = 0
        self.handoff_bytes = 0
        self.handoff_pages = 0
        self.handoff_s: List[float] = []
        self.pool_depths: Dict[str, int] = {}
        self.ttfts_by_mode: Dict[str, List[float]] = {}
        self._metrics = None
        self._metrics_dead = False
        self._depth_last: Dict[str, float] = {}
        self._latency_last: Dict[str, float] = {}
        self._pool_last: Dict[str, float] = {}
        self._rate_last = 0.0

    # ---------------------------------------------------------- records
    def record_retry(self, cause: str) -> None:
        """One routed request re-routed or failed over (``cause`` in
        ``dead`` / ``draining`` / ``queue_full``) — the fleet's
        churn signal: a rising rate means replicas are dying,
        draining under scale-down, or shedding load."""
        if not self.enabled:
            return
        self.retries[cause] = self.retries.get(cause, 0) + 1
        self._emit_retry(cause)

    def record_restart(self) -> None:
        """The reconciler replaced a wedged/dead replica."""
        if not self.enabled:
            return
        self.replica_restarts += 1
        self._emit_restart()

    def record_hedge(self, outcome: str) -> None:
        """One hedge event: ``issued`` when the router races a second
        replica for an over-deadline first token, then exactly one of
        ``won`` (the hedge carried the stream) / ``wasted`` (the
        primary did) when the race resolves."""
        if outcome not in ("issued", "won", "wasted"):
            raise ValueError(f"unknown hedge outcome {outcome!r}; "
                             "expected issued/won/wasted")
        if not self.enabled:
            return
        self.hedges[outcome] = self.hedges.get(outcome, 0) + 1
        self._emit_hedge(outcome)

    def record_hedge_won(self, winner: str) -> None:
        """One resolved hedge race, by ``winner`` (``primary`` /
        ``hedge``) — ``serve_hedges_won_total`` makes the race outcome
        visible on ``/metrics`` instead of only as per-stream
        attributes."""
        if winner not in ("primary", "hedge"):
            raise ValueError(f"unknown hedge winner {winner!r}; "
                             "expected primary/hedge")
        if not self.enabled:
            return
        self.hedge_winners[winner] = \
            self.hedge_winners.get(winner, 0) + 1
        self._emit_hedge_won(winner)

    def record_failover(self, cause: str) -> None:
        """One in-flight stream failed over to another replica, by
        cause (``dead`` — replica death/wedge — or ``handoff`` — a
        faulted disagg transfer leg).  Distinct from
        ``record_retry``: retries count *submission* re-routes too;
        this counts only mid-stream recoveries."""
        if not self.enabled:
            return
        self.failovers[cause] = self.failovers.get(cause, 0) + 1
        self._emit_failover(cause)

    def record_demotion(self, replica_id: str) -> None:
        """The router demoted a replica for latency (its EWMA tick
        latency crossed slow_factor x the fleet median) — counted once
        per demotion episode, not per routing decision."""
        if not self.enabled:
            return
        self.replica_demotions += 1
        self._emit_demotion(replica_id)

    def record_latency_score(self, replica_id: str,
                             score: float) -> None:
        """Per-replica EWMA tick-latency gauge (throttled per replica
        — the router records every poll)."""
        if not self.enabled:
            return
        self.latency_scores[replica_id] = float(score)
        if self._metrics_dead:
            return
        now = time.monotonic()
        if now - self._latency_last.get(replica_id, 0.0) \
                < self._EMIT_INTERVAL_S:
            return
        self._latency_last[replica_id] = now
        self._emit_latency(replica_id, score)

    _MAX_RECORDS = 10_000

    def record_handoff(self, *, n_bytes: int, seconds: float,
                       pages: int, skipped: bool = False,
                       trace_id: str = None) -> None:
        """One prefill→decode KV handoff (r20): content bytes moved
        through the object store (0 for a warm, metadata-only handoff
        — counted in ``handoffs_skipped``), wall seconds export→import,
        and the page count behind the byte math.  ``trace_id`` rides
        the latency histogram as an exemplar (r24)."""
        if not self.enabled:
            return
        self.handoffs += 1
        if skipped:
            self.handoffs_skipped += 1
        self.handoff_bytes += int(n_bytes)
        self.handoff_pages += int(pages)
        if len(self.handoff_s) < self._MAX_RECORDS:
            self.handoff_s.append(float(seconds))
        self._emit_handoff(n_bytes, seconds, trace_id)

    def record_pool_depth(self, pool: str, depth: int) -> None:
        """Aggregate queue depth of one pool (``prefill`` /
        ``decode``) — the disagg scale signals: prefill backlog is
        admission pressure, decode backlog is slot occupancy
        (throttled per pool; the router records every poll)."""
        if not self.enabled:
            return
        self.pool_depths[pool] = int(depth)
        if self._metrics_dead:
            return
        now = time.monotonic()
        if now - self._pool_last.get(pool, 0.0) < self._EMIT_INTERVAL_S:
            return
        self._pool_last[pool] = now
        self._emit_pool_depth(pool, depth)

    def record_ttft(self, seconds: float, *, mode: str,
                    trace_id: str = None) -> None:
        """Per-request time-to-first-token, split by pool mode
        (``disagg`` when a dedicated prefill pool served it,
        ``colocated`` for the single-pool fleet) — the comparison the
        split exists for: prefill interference shows up exactly here
        and in the decode inter-token tail.  ``trace_id`` rides the
        histogram as an exemplar (r24): the jump from a p99 bucket to
        that one request's flight-recorder span tree."""
        if not self.enabled:
            return
        bucket = self.ttfts_by_mode.setdefault(mode, [])
        if len(bucket) < self._MAX_RECORDS:
            bucket.append(float(seconds))
        self._emit_ttft(seconds, mode, trace_id)

    def record_affinity(self, *, hit: bool) -> None:
        """One routing decision with affinity enabled: ``hit`` when a
        prefix-digest match picked the replica (the fleet-wide cache
        working), False when routing fell through to pow-2."""
        if not self.enabled:
            return
        self.affinity_decisions += 1
        if hit:
            self.affinity_routed += 1
        self._emit_affinity()

    def record_queue_depth(self, replica_id: str, depth: int) -> None:
        """Per-replica queue-depth gauge (throttled per replica —
        the router records every poll)."""
        if not self.enabled:
            return
        self.queue_depths[replica_id] = int(depth)
        if self._metrics_dead:
            return
        now = time.monotonic()
        if now - self._depth_last.get(replica_id, 0.0) \
                < self._EMIT_INTERVAL_S:
            return
        self._depth_last[replica_id] = now
        self._emit_depth(replica_id, depth)

    def forget_replica(self, replica_id: str) -> None:
        """Drop a stopped replica's gauge state."""
        self.queue_depths.pop(replica_id, None)
        self._depth_last.pop(replica_id, None)
        self.latency_scores.pop(replica_id, None)
        self._latency_last.pop(replica_id, None)

    # ---------------------------------------------------------- summary
    @property
    def affinity_hit_rate(self) -> float:
        if not self.affinity_decisions:
            return 0.0
        return self.affinity_routed / self.affinity_decisions

    def summary(self) -> Dict[str, Any]:
        """The ``fleet`` block for multi-replica bench JSON."""
        if not self.enabled:
            return {"enabled": False}

        def pct(xs, q):
            return xs[min(len(xs) - 1, int(q * len(xs)))]

        ttft_by_mode = {}
        for mode, xs in self.ttfts_by_mode.items():
            srt = sorted(xs)
            ttft_by_mode[mode] = {
                "count": len(srt),
                "mean_s": statistics.fmean(srt) if srt else 0.0,
                "p50_s": pct(srt, 0.50) if srt else 0.0,
                "p99_s": pct(srt, 0.99) if srt else 0.0,
            }
        return {
            "enabled": True, "label": self.label,
            "router_retries": dict(self.retries),
            "router_retries_total": sum(self.retries.values()),
            "replica_restarts": self.replica_restarts,
            "affinity_decisions": self.affinity_decisions,
            "affinity_routed": self.affinity_routed,
            "affinity_hit_rate": self.affinity_hit_rate,
            "replica_queue_depth": dict(self.queue_depths),
            "hedges": dict(self.hedges),
            "hedge_winners": dict(self.hedge_winners),
            "failovers": dict(self.failovers),
            "replica_demotions": self.replica_demotions,
            "replica_latency_score": dict(self.latency_scores),
            # r20 disaggregation block
            "handoffs": self.handoffs,
            "handoffs_skipped": self.handoffs_skipped,
            "handoff_bytes_total": self.handoff_bytes,
            "handoff_pages_total": self.handoff_pages,
            "handoff_s_mean": (statistics.fmean(self.handoff_s)
                               if self.handoff_s else 0.0),
            "handoff_s_max": (max(self.handoff_s)
                              if self.handoff_s else 0.0),
            "pool_queue_depth": dict(self.pool_depths),
            "ttft_s_by_mode": ttft_by_mode,
        }

    # ------------------------------------------------------- prometheus
    def _metric_objects(self):
        from ray_tpu._private.worker import is_initialized
        if not is_initialized():
            return None
        if self._metrics is None:
            from ray_tpu.util.metrics import Counter, Gauge, Histogram
            self._metrics = {
                "retries": Counter(
                    "serve_router_retries_total",
                    "routed requests re-routed or failed over, by "
                    "cause (dead / draining / queue_full)",
                    tag_keys=("label", "cause")),
                "restarts": Counter(
                    "serve_replica_restarts_total",
                    "replicas replaced by the fleet reconciler",
                    tag_keys=("label",)),
                "depth": Gauge(
                    "serve_replica_queue_depth",
                    "waiting + active requests on one replica",
                    tag_keys=("label", "replica")),
                "affinity": Gauge(
                    "serve_fleet_affinity_hit_rate",
                    "share of routing decisions won by a prefix-"
                    "affinity digest match",
                    tag_keys=("label",)),
                "hedges": Counter(
                    "serve_hedges_total",
                    "tail-latency hedges, by outcome (issued / won / "
                    "wasted)",
                    tag_keys=("label", "outcome")),
                "hedges_won": Counter(
                    "serve_hedges_won_total",
                    "resolved hedge races, by winner (primary / "
                    "hedge)",
                    tag_keys=("label", "winner")),
                "failovers": Counter(
                    "serve_failovers_total",
                    "mid-stream failovers to another replica, by "
                    "cause (dead / handoff)",
                    tag_keys=("label", "cause")),
                "demotions": Counter(
                    "serve_replica_demotions_total",
                    "replicas demoted from routing for EWMA tick "
                    "latency past slow_factor x the fleet median",
                    tag_keys=("label",)),
                "latency": Gauge(
                    "serve_replica_latency_score",
                    "EWMA engine-tick wall seconds for one replica "
                    "(the gray-failure health score)",
                    tag_keys=("label", "replica")),
                "handoff_bytes": Counter(
                    "serve_handoff_bytes_total",
                    "KV-page content bytes moved prefill->decode "
                    "through the object store (warm handoffs move 0)",
                    tag_keys=("label",)),
                "handoff_s": Histogram(
                    "serve_handoff_seconds",
                    "wall seconds per KV handoff, export through "
                    "decode-side admission",
                    boundaries=_HANDOFF_BOUNDARIES,
                    tag_keys=("label",)),
                "pool_depth": Gauge(
                    "serve_pool_queue_depth",
                    "aggregate waiting + active requests in one "
                    "disagg pool (prefill / decode)",
                    tag_keys=("label", "pool")),
                "ttft": Histogram(
                    "serve_ttft_seconds",
                    "per-request time-to-first-token, split by pool "
                    "mode (disagg / colocated)",
                    boundaries=_TTFT_BOUNDARIES,
                    tag_keys=("label", "mode")),
            }
        return self._metrics

    def _emit_hedge(self, outcome: str):
        if self._metrics_dead:
            return
        try:
            metrics = self._metric_objects()
            if metrics is not None:
                metrics["hedges"].inc(
                    1.0, tags={"label": self.label,
                               "outcome": outcome})
        except Exception:  # noqa: BLE001 — never tax the router
            self._metrics_dead = True

    def _emit_demotion(self, replica_id: str):
        if self._metrics_dead:
            return
        try:
            metrics = self._metric_objects()
            if metrics is not None:
                metrics["demotions"].inc(1.0,
                                         tags={"label": self.label})
        except Exception:  # noqa: BLE001 — never tax the router
            self._metrics_dead = True

    def _emit_latency(self, replica_id: str, score: float):
        try:
            metrics = self._metric_objects()
            if metrics is not None:
                metrics["latency"].set(
                    float(score),
                    tags={"label": self.label, "replica": replica_id})
        except Exception:  # noqa: BLE001 — never tax the router
            self._metrics_dead = True

    def _emit_hedge_won(self, winner: str):
        if self._metrics_dead:
            return
        try:
            metrics = self._metric_objects()
            if metrics is not None:
                metrics["hedges_won"].inc(
                    1.0, tags={"label": self.label, "winner": winner})
        except Exception:  # noqa: BLE001 — never tax the router
            self._metrics_dead = True

    def _emit_failover(self, cause: str):
        if self._metrics_dead:
            return
        try:
            metrics = self._metric_objects()
            if metrics is not None:
                metrics["failovers"].inc(
                    1.0, tags={"label": self.label, "cause": cause})
        except Exception:  # noqa: BLE001 — never tax the router
            self._metrics_dead = True

    def _emit_handoff(self, n_bytes: int, seconds: float,
                      trace_id: str = None):
        if self._metrics_dead:
            return
        try:
            metrics = self._metric_objects()
            if metrics is not None:
                metrics["handoff_bytes"].inc(
                    float(n_bytes), tags={"label": self.label})
                metrics["handoff_s"].observe(
                    float(seconds), tags={"label": self.label},
                    exemplar=({"trace_id": trace_id}
                              if trace_id else None))
        except Exception:  # noqa: BLE001 — never tax the router
            self._metrics_dead = True

    def _emit_pool_depth(self, pool: str, depth: int):
        try:
            metrics = self._metric_objects()
            if metrics is not None:
                metrics["pool_depth"].set(
                    float(depth),
                    tags={"label": self.label, "pool": pool})
        except Exception:  # noqa: BLE001 — never tax the router
            self._metrics_dead = True

    def _emit_ttft(self, seconds: float, mode: str,
                   trace_id: str = None):
        if self._metrics_dead:
            return
        try:
            metrics = self._metric_objects()
            if metrics is not None:
                metrics["ttft"].observe(
                    float(seconds),
                    tags={"label": self.label, "mode": mode},
                    exemplar=({"trace_id": trace_id}
                              if trace_id else None))
        except Exception:  # noqa: BLE001 — never tax the router
            self._metrics_dead = True

    def _emit_retry(self, cause: str):
        if self._metrics_dead:
            return
        try:
            metrics = self._metric_objects()
            if metrics is not None:
                metrics["retries"].inc(
                    1.0, tags={"label": self.label, "cause": cause})
        except Exception:  # noqa: BLE001 — never tax the router
            self._metrics_dead = True

    def _emit_restart(self):
        if self._metrics_dead:
            return
        try:
            metrics = self._metric_objects()
            if metrics is not None:
                metrics["restarts"].inc(1.0,
                                        tags={"label": self.label})
        except Exception:  # noqa: BLE001 — never tax the router
            self._metrics_dead = True

    def _emit_affinity(self):
        if self._metrics_dead:
            return
        now = time.monotonic()
        if (self.affinity_decisions > 1
                and now - self._rate_last < self._EMIT_INTERVAL_S):
            return
        self._rate_last = now
        try:
            metrics = self._metric_objects()
            if metrics is not None:
                metrics["affinity"].set(self.affinity_hit_rate,
                                        tags={"label": self.label})
        except Exception:  # noqa: BLE001 — never tax the router
            self._metrics_dead = True

    def _emit_depth(self, replica_id: str, depth: int):
        try:
            metrics = self._metric_objects()
            if metrics is not None:
                metrics["depth"].set(
                    float(depth),
                    tags={"label": self.label, "replica": replica_id})
        except Exception:  # noqa: BLE001 — never tax the router
            self._metrics_dead = True
