"""Elastic-training telemetry: mesh size, reshard latency, transitions.

The seventh recorder family, beside step/infer/rl/ckpt/fleet/data: the
elastic supervisor records one entry per topology transition (shrink or
expand — the reshard wall seconds cover host snapshot/restore +
``device_put`` onto the new mesh, the window in which no step runs)
plus the live device count.  Sinks mirror r09: Prometheus through the
control plane when a session is up (``train_mesh_devices`` gauge,
``train_reshard_seconds`` histogram, ``train_elastic_transitions_total``
counter split by kind), and :meth:`summary` as the ``elastic`` block of
driver JSON.

``RAY_TPU_TELEMETRY=0`` disables recording entirely.
"""

from __future__ import annotations

import statistics
from typing import Any, Dict, List

from ray_tpu.telemetry.config import telemetry_config

_RESHARD_BOUNDARIES = [0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                       0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0]


class ElasticTelemetry:
    """Per-loop recorder for elastic mesh transitions."""

    def __init__(self, *, label: str = "train", config=None):
        tcfg = config or telemetry_config()
        self.enabled: bool = tcfg.enabled
        self.label = label
        self.mesh_devices = 0
        self.transitions: Dict[str, int] = {}
        self.reshards: List[float] = []
        self.straggler_events = 0
        self._metrics = None
        self._metrics_dead = False

    # ---------------------------------------------------------- records
    def record_mesh(self, n_devices: int) -> None:
        """The current topology (call at loop start and after every
        transition — the gauge an operator watches during a shrink)."""
        if not self.enabled:
            return
        self.mesh_devices = int(n_devices)
        self._emit("mesh")

    def record_transition(self, kind: str, reshard_s: float, *,
                          n_devices: int) -> None:
        """One completed shrink/expand: the new device count and the
        reshard wall seconds (snapshot/restore + device_put — steps
        are stalled for exactly this long)."""
        if not self.enabled:
            return
        if kind not in ("shrink", "expand"):
            raise ValueError(f"unknown transition kind {kind!r}; "
                             "expected 'shrink' or 'expand'")
        self.transitions[kind] = self.transitions.get(kind, 0) + 1
        self.reshards.append(float(reshard_s))
        self.mesh_devices = int(n_devices)
        self._emit("transition", kind=kind, reshard_s=reshard_s)

    def record_straggler(self) -> None:
        """One sustained-straggle event from the straggler supervisor
        (the r19 gray-failure counter — fires whether or not the loop
        could shrink in response)."""
        if not self.enabled:
            return
        self.straggler_events += 1
        self._emit("straggler")

    # ---------------------------------------------------------- summary
    def summary(self) -> Dict[str, Any]:
        """The ``elastic`` block for driver JSON."""
        if not self.enabled:
            return {"enabled": False}
        out: Dict[str, Any] = {
            "enabled": True, "label": self.label,
            "mesh_devices": self.mesh_devices,
            "transitions": dict(self.transitions),
            "transitions_total": sum(self.transitions.values()),
            "straggler_events": self.straggler_events,
        }
        if self.reshards:
            out["reshard_s"] = statistics.median(self.reshards)
            out["reshard_max_s"] = max(self.reshards)
        return out

    # ------------------------------------------------------- prometheus
    def _metric_objects(self):
        from ray_tpu._private.worker import is_initialized
        if not is_initialized():
            return None
        if self._metrics is None:
            from ray_tpu.util.metrics import Counter, Gauge, Histogram
            tags = ("label",)
            self._metrics = {
                "devices": Gauge(
                    "train_mesh_devices",
                    "devices in the live training mesh",
                    tag_keys=tags),
                "reshard": Histogram(
                    "train_reshard_seconds",
                    "cross-mesh state reshard wall seconds",
                    boundaries=_RESHARD_BOUNDARIES, tag_keys=tags),
                "transitions": Counter(
                    "train_elastic_transitions_total",
                    "elastic mesh transitions, split by kind "
                    "(shrink/expand)",
                    tag_keys=tags + ("kind",)),
                "stragglers": Counter(
                    "train_straggler_events_total",
                    "sustained train-step straggles detected by the "
                    "straggler supervisor",
                    tag_keys=tags),
            }
        return self._metrics

    def _emit(self, what: str, *, kind: str = "",
              reshard_s: float = 0.0):
        if self._metrics_dead:
            return
        try:
            metrics = self._metric_objects()
            if metrics is None:
                return
            tags = {"label": self.label}
            metrics["devices"].set(float(self.mesh_devices), tags=tags)
            if what == "transition":
                metrics["reshard"].observe(reshard_s, tags=tags)
                metrics["transitions"].inc(
                    1.0, tags={**tags, "kind": kind})
            elif what == "straggler":
                metrics["stragglers"].inc(1.0, tags=tags)
        except Exception:  # noqa: BLE001 — never tax the train loop
            self._metrics_dead = True
