"""Step-level training telemetry.

The training path used to fly blind: ``bench.py`` hand-rolled
``perf_counter`` around whole steps and the tracing/metrics/dashboard
plumbing only ever saw Ray-parity tasks.  This package instruments the
train step itself:

- :class:`StepTelemetry` / :func:`instrument` wrap a jitted step and
  emit per-step records (wall/dispatch/sync with a blocking sync,
  compile-vs-steady split, tokens/sec, analytic-FLOPs MFU, HBM from
  ``memory_analysis()``, logical collective bytes/step),
- :mod:`~ray_tpu.telemetry.chrome_trace` exports a unified host+train
  Perfetto timeline (also merged into the dashboard ``/api/timeline``),
- per-step Prometheus series (``train_step_seconds``, ``train_mfu``,
  ``train_collective_bytes``) flow through the control-plane metrics
  to ``/metrics``,
- ``bench.py`` / ``ray_perf.py`` attach :meth:`StepTelemetry.summary`
  as the ``telemetry`` block of their JSON artifacts.

``RAY_TPU_TELEMETRY=0`` disables everything (identity wrapper);
``RAY_TPU_PROFILE=<dir>`` adds an xplane capture of the first steady
steps.  See :func:`telemetry_config`.
"""

from ray_tpu.telemetry import chrome_trace  # noqa: F401
from ray_tpu.telemetry.ckpt import CkptTelemetry  # noqa: F401
from ray_tpu.telemetry.data import DataTelemetry  # noqa: F401
from ray_tpu.telemetry.config import (TelemetryConfig,  # noqa: F401
                                      telemetry_config)
from ray_tpu.telemetry.elastic import ElasticTelemetry  # noqa: F401
from ray_tpu.telemetry.fleet import FleetTelemetry  # noqa: F401
from ray_tpu.telemetry.flops import (chip_peak_tflops,  # noqa: F401
                                     gpt_fwd_flops_per_token,
                                     gpt_train_flops_per_token, mfu)
from ray_tpu.telemetry.infer import InferTelemetry  # noqa: F401
from ray_tpu.telemetry.rl import RLTelemetry  # noqa: F401
from ray_tpu.telemetry.step import (StepTelemetry,  # noqa: F401
                                    instrument, recorders)

__all__ = [
    "TelemetryConfig", "telemetry_config",
    "StepTelemetry", "instrument", "recorders",
    "InferTelemetry",
    "RLTelemetry",
    "CkptTelemetry",
    "DataTelemetry",
    "ElasticTelemetry",
    "FleetTelemetry",
    "chrome_trace",
    "chip_peak_tflops", "gpt_fwd_flops_per_token",
    "gpt_train_flops_per_token", "mfu",
]
