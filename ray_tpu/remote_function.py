"""``@ray_tpu.remote`` functions.

Parity target: ``python/ray/remote_function.py`` — decorator builds a
RemoteFunction whose ``.remote()`` submits a task and returns ObjectRef(s);
``.options(...)`` overrides per-call.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional

from ray_tpu._private.task_spec import SchedulingStrategy, normalize_resources
from ray_tpu._private.worker import global_worker


def normalize_strategy(strategy) -> SchedulingStrategy:
    if strategy is None:
        return SchedulingStrategy()
    if isinstance(strategy, SchedulingStrategy):
        return strategy
    if isinstance(strategy, str):
        if strategy in ("DEFAULT", "default"):
            return SchedulingStrategy()
        if strategy in ("SPREAD", "spread"):
            return SchedulingStrategy(kind="spread")
        raise ValueError(f"unknown scheduling strategy {strategy!r}")
    # duck-typed strategy objects from ray_tpu.util.scheduling_strategies
    kind = type(strategy).__name__
    if kind == "NodeAffinitySchedulingStrategy":
        node_id = strategy.node_id
        if isinstance(node_id, str):
            node_id = bytes.fromhex(node_id)
        return SchedulingStrategy(kind="node_affinity", node_id=node_id,
                                  soft=strategy.soft)
    if kind == "PlacementGroupSchedulingStrategy":
        pg = strategy.placement_group
        return SchedulingStrategy(
            kind="placement_group", pg_id=pg.id.binary(),
            bundle_index=strategy.placement_group_bundle_index,
            capture_child_tasks=bool(
                strategy.placement_group_capture_child_tasks))
    raise TypeError(f"unsupported scheduling strategy: {strategy!r}")


def _apply_pg_resources(resources: Dict[str, float],
                        strategy: SchedulingStrategy) -> Dict[str, float]:
    """Rewrite resources to placement-group bundle resources.

    Mirrors the reference's formatted-resource trick: PG bundles publish
    ``pg_<id>_<index>_<name>`` custom resources; PG-scheduled tasks consume
    those instead of the raw node resources.
    """
    if strategy.kind != "placement_group":
        return resources
    pg_hex = strategy.pg_id.hex()
    out = {}
    for name, qty in resources.items():
        if qty <= 0:
            continue
        if strategy.bundle_index >= 0:
            out[f"pg_{pg_hex}_{strategy.bundle_index}_{name}"] = qty
        else:
            out[f"pg_{pg_hex}_{name}"] = qty
    return out


class RemoteFunction:
    def __init__(self, fn, **default_opts):
        self._function = fn
        self._default_opts = default_opts
        self._prepared = None   # submit_opts template (built once:
        #                         options are per-RemoteFunction static)
        functools.update_wrapper(self, fn)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Remote function '{getattr(self._function, '__name__', '?')}' "
            "cannot be called directly; use .remote().")

    def options(self, **opts) -> "RemoteFunction":
        merged = dict(self._default_opts)
        merged.update(opts)
        return RemoteFunction(self._function, **merged)

    def remote(self, *args, **kwargs):
        return self._remote(args, kwargs, self._default_opts)

    def _remote(self, args, kwargs, opts: Dict[str, Any]):
        worker = global_worker()
        # opts are fixed per RemoteFunction (options() returns a new
        # one), so the normalized submit template is built exactly once
        # — .remote() in a tight submission loop skips the dict churn
        submit_opts = self._prepared if opts is self._default_opts \
            else None
        if submit_opts is None:
            resources = normalize_resources(
                opts.get("num_cpus"), opts.get("num_gpus"),
                opts.get("num_tpus"), opts.get("resources"),
                opts.get("memory"), default_cpus=1.0)
            strategy = normalize_strategy(opts.get("scheduling_strategy"))
            resources = _apply_pg_resources(resources, strategy)
            submit_opts = {
                "num_returns": opts.get("num_returns", 1),
                "resources": resources,
                "scheduling_strategy": strategy,
                "name": opts.get("name"),
                "max_retries": opts.get("max_retries"),
                "retry_exceptions": opts.get("retry_exceptions", False),
                "runtime_env": opts.get("runtime_env"),
            }
            if submit_opts["max_retries"] is None:
                from ray_tpu._private.config import GLOBAL_CONFIG
                submit_opts["max_retries"] = \
                    GLOBAL_CONFIG.task_default_max_retries
            if opts is self._default_opts:
                self._prepared = submit_opts
        return worker.submit_task(self._function, args, kwargs, submit_opts)

    @property
    def func(self):
        return self._function

    def bind(self, *args, **kwargs):
        """DAG-building entrypoint (compiled DAGs / Serve graphs)."""
        from ray_tpu.dag import FunctionNode
        return FunctionNode(self, args, kwargs)
