"""Microbenchmark driver.

Parity: reference ``python/ray/_private/ray_perf.py`` — same metric names
so numbers are comparable line-for-line (`ray microbenchmark`).

``attention_perf`` (``python -m ray_tpu._private.ray_perf --attn``) is
the kernel-level entry: isolated flash-attention fwd+bwd throughput, so
kernel A/Bs (e.g. pack2 on/off) no longer need a full xplane trace.
``ce_perf`` (``--ce``) is the same for the loss head: isolated CE
fwd+bwd at the bench shape, flash-CE (streamed-logits Pallas kernel)
vs the no-remat XLA control.  ``collective_perf`` (``--collective``)
is the comm-schedule analogue: ring all-gather-matmul
(``parallel/overlap.py``) vs the barrier all-gather-then-matmul on a
tp ring.  ``decode_perf`` (``--decode``) is the serving-side entry:
cache-aware single-token decode attention, strip-mined Pallas kernel
vs the masked-einsum XLA fallback at the engine's gathered-context
shape.  ``train_step_perf`` (``--train``) runs the full train step
through the telemetry recorder and prints the ``telemetry`` JSON block
(compile split / MFU / HBM) in isolation.
"""

from __future__ import annotations

import sys
import time
from typing import Callable, Dict, List, Optional

import numpy as np

import ray_tpu


def timeit(name: str, fn: Callable, multiplier: int = 1,
           duration: float = 2.0) -> Dict[str, float]:
    # warmup
    fn()
    count = 0
    t0 = time.perf_counter()
    while time.perf_counter() - t0 < duration:
        fn()
        count += 1
    dt = time.perf_counter() - t0
    rate = count * multiplier / dt
    print(f"{name} per second {rate:.2f}")
    return {"name": name, "rate": rate}


def attention_perf(batch: int = 8, seq: int = 1024, heads: int = 12,
                   head_dim: int = 64, steps: int = 30,
                   causal: bool = True,
                   pack2: Optional[bool] = None,
                   rope: bool = True) -> Dict[str, float]:
    """Isolated flash-attention fwd+bwd microbenchmark.

    Times ``steps`` jitted grad evaluations of the flash kernel at the
    bench shape and reports tokens/s plus *effective* TFLOPs — real
    attention matmul FLOPs (2 fwd + 5 bwd score-shaped matmuls, halved
    under the causal mask) over wall-clock, the figure the MXU-width
    argument in ``docs/PERF.md`` is about.  ``pack2=None`` uses the
    process config; pass True/False for an A/B without env games.

    On CPU the kernels run in Pallas interpret mode — numbers are only
    meaningful on a real chip, but the entry stays runnable anywhere.
    """
    import jax
    import jax.numpy as jnp

    from ray_tpu.ops.attention import flash_attention

    on_tpu = jax.default_backend() == "tpu"
    dtype = jnp.bfloat16 if on_tpu else jnp.float32
    key = jax.random.PRNGKey(0)
    kq, kk, kv, kw = jax.random.split(key, 4)
    shape = (batch, seq, heads, head_dim)
    q = jax.random.normal(kq, shape, dtype)
    k = jax.random.normal(kk, shape, dtype)
    v = jax.random.normal(kv, shape, dtype)
    w = jax.random.normal(kw, shape, dtype)   # fixed cotangent
    positions = jnp.arange(seq) if rope else None

    def loss(q, k, v):
        o = flash_attention(q, k, v, causal=causal, positions=positions,
                            pack2=pack2)
        return jnp.sum(o.astype(jnp.float32) * w.astype(jnp.float32))

    grad_fn = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
    g = grad_fn(q, k, v)
    jax.block_until_ready(g)
    t0 = time.perf_counter()
    for _ in range(steps):
        g = grad_fn(q, k, v)
    jax.block_until_ready(g)
    dt = (time.perf_counter() - t0) / steps

    # 2 score-shaped matmuls fwd (s, o) + 5 bwd (s recompute, dp, dq,
    # dk, dv), each 2*B*H*S^2*D flops; causal halves the live blocks
    flops = 7 * 2 * batch * heads * seq * seq * head_dim
    if causal:
        flops /= 2
    tok_s = batch * seq / dt
    result = {
        "name": f"attention fwd+bwd pack2={pack2}",
        "ms_per_step": dt * 1e3,
        "tokens_per_sec": tok_s,
        "effective_tflops": flops / dt / 1e12,
    }
    print(f"{result['name']}: {result['ms_per_step']:.2f} ms  "
          f"{tok_s:,.0f} tok/s  "
          f"{result['effective_tflops']:.1f} eff TFLOPs")
    return result


def ce_perf(n_tokens: int = 24576, d_model: int = 768,
            vocab: int = 50304, steps: int = 20,
            mode: str = "flash") -> Dict[str, float]:
    """Isolated cross-entropy loss-head fwd+bwd microbenchmark.

    Times ``steps`` jitted grad evaluations of ``(sum_nll / n)`` w.r.t.
    (x, head) at the bench shape and reports ms plus *effective* MXU
    TFLOPs — each arm's real vocab-matmul count (flash: 4 = fwd +
    recompute + dX + dHead; no-remat: 3 = fwd + dX + dHead) over
    wall-clock.  This is the "is the Pallas matmul competitive with
    XLA's 150+ TFLOPs" number ``docs/PERF.md`` gates the flash-CE
    default on; note the no-remat arm *also* pays ~17 ms of HBM-rate
    reduce passes the FLOP figure does not credit, so compare
    ``ms_per_step``, not TFLOPs, for the end decision.

    ``mode``: "flash" (Pallas kernel, pinned via explicit call) or
    "noremat" (dense XLA formulation, logits resident between passes).
    On CPU the kernel runs in Pallas interpret mode — numbers are only
    meaningful on a real chip, but the entry stays runnable anywhere.
    """
    import jax
    import jax.numpy as jnp

    from ray_tpu.ops.flash_ce import _xla_ce_sum, flash_ce_sum

    on_tpu = jax.default_backend() == "tpu"
    dtype = jnp.bfloat16 if on_tpu else jnp.float32
    kx, kh, kt = jax.random.split(jax.random.PRNGKey(0), 3)
    x = jax.random.normal(kx, (n_tokens, d_model), dtype)
    head = (jax.random.normal(kh, (d_model, vocab), jnp.float32)
            * 0.02).astype(dtype)
    targets = jax.random.randint(kt, (n_tokens,), 0, vocab)

    # the control arm goes through the model's own CE glue
    # (gpt._chunked_ce pinned to mode="xla", chunk=-1), so the
    # microbench control is the literal no-remat path the dispatch
    # would run, not a lookalike that could drift
    if mode == "flash":
        def ce(x, head):
            return flash_ce_sum(x, head, targets)
    else:
        from ray_tpu.models.gpt import _chunked_ce

        def ce(x, head):
            return _chunked_ce(x, head, targets, chunk=-1, mode="xla")

    def loss(x, head):
        s, n = ce(x, head)
        return s / n

    grad_fn = jax.jit(jax.value_and_grad(loss, argnums=(0, 1)))
    out = grad_fn(x, head)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(steps):
        out = grad_fn(x, head)
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / steps

    matmuls = 4 if mode == "flash" else 3
    flops = matmuls * 2 * n_tokens * d_model * vocab
    result = {
        "name": f"ce fwd+bwd mode={mode}",
        "ms_per_step": dt * 1e3,
        "tokens_per_sec": n_tokens / dt,
        "effective_tflops": flops / dt / 1e12,
    }
    print(f"{result['name']}: {result['ms_per_step']:.2f} ms  "
          f"{result['tokens_per_sec']:,.0f} tok/s  "
          f"{result['effective_tflops']:.1f} eff TFLOPs "
          f"({matmuls} vocab matmuls)")
    return result


def fused_norm_perf(n_tokens: int = 24576, heads: int = 12,
                    head_dim: int = 64, d_model: int = 768,
                    steps: int = 30,
                    fused: bool = True) -> Dict[str, float]:
    """Isolated out-proj + residual + norm epilogue microbenchmark
    (``--fuse-norm``).

    Times ``steps`` jitted grad evaluations of the attention-block
    epilogue — out-proj matmul, residual add, pre-FFN rmsnorm — in the
    fused Pallas formulation (``ops/fused_norm.matmul_residual_norm``)
    vs the unfused XLA one, with cotangents flowing into *both*
    outputs (residual stream + normed hidden) like the real block.
    The A/B for the ~13 ms out-proj-fusion + ~10.7 ms
    [d]-reduction-dispatch headroom ``docs/PERF.md`` r13 tracks.  On
    CPU the kernel runs in Pallas interpret mode — numbers are only
    meaningful on a real chip, but the entry stays runnable anywhere.
    """
    import jax
    import jax.numpy as jnp

    from ray_tpu.ops.fused_norm import (matmul_residual_norm,
                                        xla_matmul_residual_norm)

    on_tpu = jax.default_backend() == "tpu"
    dtype = jnp.bfloat16 if on_tpu else jnp.float32
    K = heads * head_dim
    ka, kw, kr, ks, c1, c2 = jax.random.split(jax.random.PRNGKey(0), 6)
    a = jax.random.normal(ka, (n_tokens, K), dtype)
    w = jax.random.normal(kw, (K, d_model), dtype) * K ** -0.5
    resid = jax.random.normal(kr, (n_tokens, d_model), dtype)
    scale = jnp.ones((d_model,), dtype)
    wr = jax.random.normal(c1, (n_tokens, d_model), dtype)
    wy = jax.random.normal(c2, (n_tokens, d_model), dtype)
    op = matmul_residual_norm if fused else xla_matmul_residual_norm

    def loss(a, w, resid, scale):
        r, y = op(a, w, resid, scale)
        return (jnp.sum(r.astype(jnp.float32) * wr.astype(jnp.float32))
                + jnp.sum(y.astype(jnp.float32) * wy.astype(jnp.float32)))

    grad_fn = jax.jit(jax.grad(loss, argnums=(0, 1, 2, 3)))
    g = grad_fn(a, w, resid, scale)
    jax.block_until_ready(g)
    t0 = time.perf_counter()
    for _ in range(steps):
        g = grad_fn(a, w, resid, scale)
    jax.block_until_ready(g)
    dt = (time.perf_counter() - t0) / steps

    # 1 fwd matmul + 2 bwd (da, dw); the norm itself is VPU/HBM work
    flops = 3 * 2 * n_tokens * K * d_model
    result = {
        "name": f"out-proj+norm epilogue fused={fused}",
        "ms_per_step": dt * 1e3,
        "tokens_per_sec": n_tokens / dt,
        "effective_tflops": flops / dt / 1e12,
    }
    print(f"{result['name']}: {result['ms_per_step']:.2f} ms  "
          f"{result['tokens_per_sec']:,.0f} tok/s  "
          f"{result['effective_tflops']:.1f} eff TFLOPs")
    return result


def decode_perf(batch: int = 8, ctx: int = 1024, heads: int = 12,
                head_dim: int = 64, steps: int = 50,
                impl: str = "auto") -> Dict[str, float]:
    """Isolated decode-attention microbenchmark (``--decode``).

    Times ``steps`` jitted evaluations of the cache-aware single-token
    attention (``ops/attention.py:decode_attention``) at a padded
    context of ``ctx`` with mixed valid lengths — the per-layer
    attention cost of one engine decode tick.  ``impl`` A/Bs the
    strip-mined Pallas kernel against the masked-einsum XLA fallback
    without env games.  On CPU the kernel runs in Pallas interpret
    mode — numbers are only meaningful on a real chip, but the entry
    stays runnable anywhere.
    """
    import jax
    import jax.numpy as jnp

    from ray_tpu.ops.attention import decode_attention

    on_tpu = jax.default_backend() == "tpu"
    dtype = jnp.bfloat16 if on_tpu else jnp.float32
    kq, kk, kv = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(kq, (batch, heads, head_dim), dtype)
    k = jax.random.normal(kk, (batch, ctx, heads, head_dim), dtype)
    v = jax.random.normal(kv, (batch, ctx, heads, head_dim), dtype)
    lengths = jnp.arange(1, batch + 1) * (ctx // batch)

    fn = jax.jit(lambda q, k, v: decode_attention(q, k, v, lengths,
                                                  impl=impl))
    out = fn(q, k, v)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(steps):
        out = fn(q, k, v)
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / steps

    # 2 context-shaped matmuls (scores, p@V), each 2*B*H*ctx*D flops
    # on the padded context (masking does not skip compute)
    flops = 2 * 2 * batch * heads * ctx * head_dim
    result = {
        "name": f"decode attention impl={impl}",
        "us_per_step": dt * 1e6,
        "tokens_per_sec": batch / dt,
        "effective_gflops": flops / dt / 1e9,
    }
    print(f"{result['name']}: {result['us_per_step']:.1f} us  "
          f"{result['tokens_per_sec']:,.0f} tok/s  "
          f"{result['effective_gflops']:.1f} eff GFLOPs")
    return result


def train_step_perf(steps: int = 8, batch: Optional[int] = None,
                    seq: Optional[int] = None) -> Dict[str, float]:
    """Instrumented GPT train-step microbench: one telemetry block.

    Runs ``steps`` steps of the single-device GPT train step through a
    :class:`ray_tpu.telemetry.StepTelemetry` recorder in AOT mode and
    prints the ``telemetry`` summary as one JSON line — compile split,
    blocking-sync steady step time, tokens/s, analytic-FLOPs MFU and
    the ``memory_analysis()`` HBM footprint, the same block
    ``bench.py`` attaches to its headline JSON.  On CPU the shapes
    shrink to a smoke configuration (numbers exercise the recorder,
    not the hardware).
    """
    import json

    import jax
    import jax.numpy as jnp

    from ray_tpu.models import training
    from ray_tpu.models.gpt import GPTConfig
    from ray_tpu.parallel.mesh import make_mesh
    from ray_tpu.telemetry import StepTelemetry

    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        cfg = GPTConfig.gpt2(vocab_size=50304, max_seq=1024,
                             dtype=jnp.bfloat16, remat=False,
                             unroll_layers=True, ce_chunk=-1)
        batch, seq = batch or 24, seq or 1024
    else:
        cfg = GPTConfig(vocab_size=2048, d_model=128, n_layers=2,
                        n_heads=4, max_seq=256, dtype=jnp.float32)
        batch, seq = batch or 4, seq or 128
    mesh = make_mesh(dp=1, devices=jax.devices()[:1])
    fns = training.build_gpt_train(cfg, mesh, telemetry=False)
    tel = StepTelemetry(cfg, mesh, comm_mode=fns["comm_mode"],
                        label="ray_perf", aot=True)
    step = tel.wrap(fns["step_fn"])
    state = fns["init_fn"](jax.random.PRNGKey(0))
    data = training.synthetic_lm_batch(jax.random.PRNGKey(1), batch,
                                       seq, cfg.vocab_size)
    for _ in range(steps):
        state, _ = step(state, data)
    tel.stop()
    summary = tel.summary()
    summary["metric"] = "train_step_telemetry"
    print(json.dumps(summary))
    return summary


def collective_perf(tokens: int = 4096, d_model: int = 512,
                    d_out: int = 2048, steps: int = 20,
                    n_devices: Optional[int] = None) -> List[Dict[str,
                                                                  float]]:
    """Isolated TP-collective microbenchmark: ring all-gather-matmul
    (``parallel/overlap.py``) vs the barrier schedule (all_gather, then
    matmul) on a tp ring over the visible devices.

    This is the kernel-level view of the r08 overlap bet: the ring
    version pays the same ICI bytes but hides each hop behind one
    matmul chunk, so the delta here bounds what the full-step schedule
    can recover.  On CPU the ring runs but measures nothing real —
    numbers are only meaningful on a chip (the entry stays runnable
    anywhere, same policy as ``--attn``/``--ce``).
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ray_tpu.parallel.compat import shard_map
    from ray_tpu.parallel.mesh import make_mesh
    from ray_tpu.parallel.overlap import ring_allgather_matmul

    n = n_devices or len(jax.devices())
    mesh = make_mesh(tp=n, devices=jax.devices()[:n])
    on_tpu = jax.default_backend() == "tpu"
    dtype = jnp.bfloat16 if on_tpu else jnp.float32
    kx, kw = jax.random.split(jax.random.PRNGKey(0))
    x = jax.device_put(
        jax.random.normal(kx, (tokens, d_model), dtype),
        NamedSharding(mesh, P("tp", None)))
    w = jax.device_put(
        jax.random.normal(kw, (d_model, d_out), dtype) * 0.02,
        NamedSharding(mesh, P(None, "tp")))

    def ring(xs, ws):
        return ring_allgather_matmul(xs, ws, "tp" if n > 1 else None)

    def barrier(xs, ws):
        full = (jax.lax.all_gather(xs, "tp", axis=0, tiled=True)
                if n > 1 else xs)
        return jnp.einsum("tk,km->tm", full, ws)

    results = []
    for name, body in (("ring all-gather-matmul", ring),
                       ("all-gather then matmul", barrier)):
        fn = jax.jit(shard_map(body, mesh=mesh,
                               in_specs=(P("tp", None), P(None, "tp")),
                               out_specs=P(None, "tp")))
        out = fn(x, w)
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        for _ in range(steps):
            out = fn(x, w)
        jax.block_until_ready(out)
        dt = (time.perf_counter() - t0) / steps
        flops = 2 * tokens * d_model * (d_out // max(n, 1)) * n
        gathered = tokens * d_model * x.dtype.itemsize * (n - 1) / max(n, 1)
        r = {"name": name, "ms_per_step": dt * 1e3,
             "effective_tflops": flops / dt / 1e12,
             "gathered_bytes_per_device": gathered}
        print(f"{r['name']}: {r['ms_per_step']:.3f} ms  "
              f"{r['effective_tflops']:.2f} eff TFLOPs  "
              f"({gathered/2**20:.2f} MiB gathered/device)")
        results.append(r)
    return results


def main(duration: float = 2.0) -> List[Dict[str, float]]:
    results = []
    value = np.zeros(16 * 1024, dtype=np.uint8)  # small object
    big = np.zeros(100 * 1024 * 1024, dtype=np.uint8)  # 100MB

    # --- object store ---
    ref = ray_tpu.put(value)
    results.append(timeit(
        "single client get calls (shm store)",
        lambda: ray_tpu.get(ref), duration=duration))
    results.append(timeit(
        "single client put calls (shm store)",
        lambda: ray_tpu.put(value), duration=duration))

    def put_gb():
        ray_tpu.get(ray_tpu.put(big))
    results.append(timeit("single client put gigabytes",
                          put_gb, multiplier=big.nbytes // 2**30 or 1,
                          duration=duration))

    # --- tasks ---
    @ray_tpu.remote
    def tiny(x):
        return x

    results.append(timeit(
        "single client tasks sync",
        lambda: ray_tpu.get(tiny.remote(0)), duration=duration))

    def batch_tasks():
        ray_tpu.get([tiny.remote(i) for i in range(100)])
    results.append(timeit("single client tasks and get batch",
                          batch_tasks, multiplier=100,
                          duration=duration))

    # --- wait ---
    refs_1k = [ray_tpu.put(i) for i in range(1000)]
    results.append(timeit(
        "single client wait 1k refs",
        lambda: ray_tpu.wait(refs_1k, num_returns=1000, timeout=10),
        duration=duration))

    # --- actors ---
    @ray_tpu.remote
    class Echo:
        def ping(self, x=None):
            return x

    actor = Echo.remote()
    ray_tpu.get(actor.ping.remote())
    results.append(timeit(
        "1:1 actor calls sync",
        lambda: ray_tpu.get(actor.ping.remote()), duration=duration))

    def async_batch():
        ray_tpu.get([actor.ping.remote(i) for i in range(100)])
    results.append(timeit("1:1 actor calls async", async_batch,
                          multiplier=100, duration=duration))

    actors = [Echo.remote() for _ in range(4)]
    for a in actors:
        ray_tpu.get(a.ping.remote())

    def nn_batch():
        ray_tpu.get([a.ping.remote(i) for a in actors
                     for i in range(25)])
    results.append(timeit("n:n actor calls async", nn_batch,
                          multiplier=100, duration=duration))

    # --- compiled DAG (mutable channels) vs chained actor tasks ---
    from ray_tpu.dag import InputNode

    @ray_tpu.remote
    class Stage:
        def step(self, x):
            return x + 1

    s1, s2, s3 = Stage.remote(), Stage.remote(), Stage.remote()
    ray_tpu.get([s.step.remote(0) for s in (s1, s2, s3)])

    def chained():
        ray_tpu.get(s3.step.remote(s2.step.remote(s1.step.remote(0))))
    results.append(timeit("3-stage actor pipeline calls (tasks)",
                          chained, duration=duration))

    a, b, c = Stage.bind(), Stage.bind(), Stage.bind()
    with InputNode() as inp:
        dag = c.step.bind(b.step.bind(a.step.bind(inp)))
    compiled = dag.experimental_compile()
    compiled.execute(0).get()
    state = {"futs": []}

    def channel_call():
        state["futs"].append(compiled.execute(0))
        if len(state["futs"]) >= 3:
            state["futs"].pop(0).get()
    results.append(timeit(
        "3-stage actor pipeline calls (compiled dag channels)",
        channel_call, duration=duration))
    for f in state["futs"]:
        f.get()
    compiled.teardown()
    return results


if __name__ == "__main__":
    if "--attn" in sys.argv:
        # kernel A/B: packed vs single-head schedule, no cluster needed
        attention_perf(pack2=True)
        attention_perf(pack2=False)
    elif "--ce" in sys.argv:
        # loss-head A/B: streamed-logits Pallas CE vs no-remat XLA
        ce_perf(mode="flash")
        ce_perf(mode="noremat")
    elif "--fuse-norm" in sys.argv:
        # norm-epilogue A/B: fused Pallas out-proj+residual+norm vs XLA
        fused_norm_perf(fused=True)
        fused_norm_perf(fused=False)
    elif "--collective" in sys.argv:
        # TP-schedule A/B: ring all-gather-matmul vs barrier gather
        collective_perf()
    elif "--decode" in sys.argv:
        # cache-aware decode attention A/B: Pallas kernel vs XLA mask
        decode_perf(impl="pallas")
        decode_perf(impl="xla")
    elif "--train" in sys.argv:
        # instrumented train step: the bench telemetry block in isolation
        train_step_perf()
    else:
        ray_tpu.init()
        try:
            main()
        finally:
            ray_tpu.shutdown()
