"""Env-gated debug tracing into the session log directory.

Replaces the ad-hoc fixed-path ``/tmp/*.log`` scaffolding: predictable
/tmp filenames are a symlink hazard on shared hosts, and traces belong
with the session's other logs.  Enable with ``RAY_TPU_DEBUG_TRACE=1``
(or the legacy per-subsystem vars); lines land in
``<session_dir>/logs/debug_trace_<pid>.log`` via the logging module, or
a secure tempfile when no session dir is known.
"""

from __future__ import annotations

import logging
import os
import time
from typing import Optional

_logger: Optional[logging.Logger] = None


def enabled(var: str = "RAY_TPU_DEBUG_TRACE") -> bool:
    return (os.environ.get(var) == "1"
            or os.environ.get("RAY_TPU_DEBUG_TRACE") == "1")


def _get_logger() -> logging.Logger:
    global _logger
    if _logger is None:
        logger = logging.getLogger("ray_tpu.debug_trace")
        logger.propagate = False
        logger.setLevel(logging.DEBUG)
        session = os.environ.get("RAY_TPU_SESSION_DIR")
        if session:
            log_dir = os.path.join(session, "logs")
            os.makedirs(log_dir, exist_ok=True)
            path = os.path.join(log_dir,
                                f"debug_trace_{os.getpid()}.log")
        else:
            import tempfile
            fd, path = tempfile.mkstemp(prefix="ray_tpu_trace_",
                                        suffix=".log")
            os.close(fd)
        logger.addHandler(logging.FileHandler(path))
        _logger = logger
    return _logger


def trace(tag: str, *parts, var: str = "RAY_TPU_DEBUG_TRACE",
          stack: int = 0) -> None:
    """One trace line (and optionally a short stack) if enabled."""
    if not enabled(var):
        return
    msg = (f"{time.monotonic():.3f} {os.getpid()} {tag} "
           + " ".join(str(p) for p in parts))
    if stack:
        import traceback
        msg += "\n" + "".join(traceback.format_stack(limit=stack))
    _get_logger().debug(msg)
