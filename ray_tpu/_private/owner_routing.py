"""Shared owner-routing for refcount traffic.

One implementation of the route-by-owner rule used by the ref tracker,
the node manager's dependency pins, and the core worker's caller-side
pre-pins: deltas for an object go to its OWNER node manager
(``update_owned_refs``); ownerless objects fall back to the control
plane (``update_refs``).  Failures are swallowed — a dead owner's
objects are freed by the owner-death path, so the lost delta is moot.
"""

from __future__ import annotations

from typing import Callable, Dict, Iterable, Optional


def bucket_by_owner(deltas: Dict[bytes, int],
                    owner_of: Callable[[bytes], Optional[str]]
                    ) -> Dict[Optional[str], Dict[bytes, int]]:
    out: Dict[Optional[str], Dict[bytes, int]] = {}
    for oid, d in deltas.items():
        out.setdefault(owner_of(oid), {})[oid] = d
    return out


def route_updates(cp, peer: Callable[[str], object], holder: bytes,
                  by_owner: Dict[Optional[str], Dict[bytes, int]],
                  holder_node: bytes = b"",
                  local_addr: str = "", local=None) -> None:
    """Send each owner bucket to its counter.  ``local_addr``/``local``
    short-circuit the bucket addressed to the caller itself (a node
    manager routing pins to objects it owns)."""
    for addr, deltas in by_owner.items():
        try:
            if addr is None:
                cp.update_refs(holder, deltas, holder_node)
            elif local is not None and addr == local_addr:
                local(holder, deltas, holder_node)
            else:
                peer(addr).call("update_owned_refs", holder, deltas,
                                holder_node)
        except Exception:  # noqa: BLE001 - dead owner: freed by
            pass           # the owner-death path anyway


def route_purge(cp, peer: Callable[[str], object], holder: bytes,
                addrs: Iterable[Optional[str]],
                local_addr: str = "", local=None) -> None:
    for addr in set(addrs):
        try:
            if addr is None:
                cp.purge_holder(holder)
            elif local is not None and addr == local_addr:
                local(holder)
            else:
                peer(addr).call("purge_owned_holder", holder)
        except Exception:  # noqa: BLE001
            pass
