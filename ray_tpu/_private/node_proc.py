"""Extra node-manager process (multi-node simulation on one host).

Started by :meth:`ray_tpu._private.node.HeadNode.add_node`; runs one
NodeManager with its own worker pool and its OWN shm store root against
the shared control plane.  Objects created on other nodes arrive via the
chunked pull protocol (``NodeManager.fetch_object_chunk``), mirroring the
reference's node-to-node object manager
(``src/ray/object_manager/object_manager.cc`` Push/Pull).
"""

from __future__ import annotations

import json
import os
import signal
import threading

from ray_tpu._private import protocol
from ray_tpu._private.node_manager import NodeManager
from ray_tpu._private.object_store import ShmStore


def build_env(*, session_dir: str, cp_addr: str, node_id: bytes,
              shm_root: str, spill_dir: str, resources: dict,
              use_tcp: bool, node_ip: str = "127.0.0.1") -> dict:
    """The node_proc env contract, in ONE place (used by
    HeadNode.add_node and the ``ray-tpu start --address`` CLI)."""
    return {
        "RAY_TPU_SESSION_DIR": session_dir,
        "RAY_TPU_CP_SOCK": cp_addr,
        "RAY_TPU_USE_TCP": "1" if use_tcp else "0",
        "RAY_TPU_NODE_ID": node_id.hex(),
        "RAY_TPU_SHM_ROOT": shm_root,
        "RAY_TPU_SPILL_DIR": spill_dir,
        "RAY_TPU_NODE_RESOURCES": json.dumps(resources),
        "RAY_TPU_NODE_IP": node_ip,
    }


def main():
    session_dir = os.environ["RAY_TPU_SESSION_DIR"]
    cp_sock = os.environ["RAY_TPU_CP_SOCK"]
    node_id = bytes.fromhex(os.environ["RAY_TPU_NODE_ID"])
    resources = json.loads(os.environ["RAY_TPU_NODE_RESOURCES"])
    cp = protocol.RpcClient(cp_sock)
    store = ShmStore(os.environ["RAY_TPU_SHM_ROOT"],
                     spill_dir=os.environ.get("RAY_TPU_SPILL_DIR") or None)
    nm = NodeManager(node_id=node_id, session_dir=session_dir,
                     control_plane=cp, cp_sock_path=cp_sock,
                     shm_store=store, resources=resources,
                     node_ip=os.environ.get("RAY_TPU_NODE_IP",
                                            "127.0.0.1"))
    stop = threading.Event()

    def _term(signum, frame):
        stop.set()

    signal.signal(signal.SIGTERM, _term)
    signal.signal(signal.SIGINT, _term)
    stop.wait()
    nm.stop()
    store.destroy()


if __name__ == "__main__":
    main()
