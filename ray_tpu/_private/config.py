"""Runtime configuration flag registry.

TPU-native equivalent of the reference's ``RAY_CONFIG(type, name, default)``
macro registry (reference: ``src/ray/common/ray_config_def.h``).  Flags are
declared once here, may be overridden by ``RAY_TPU_<NAME>`` environment
variables, and by a ``_system_config`` dict passed to ``ray_tpu.init``.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass
from typing import Any, Callable, Dict


@dataclass
class _Flag:
    name: str
    default: Any
    type: Callable
    doc: str


class ConfigRegistry:
    def __init__(self):
        self._flags: Dict[str, _Flag] = {}
        self._overrides: Dict[str, Any] = {}

    def define(self, name: str, default: Any, doc: str = "") -> None:
        ftype = type(default)
        if ftype is bool:
            def conv(v):
                if isinstance(v, str):
                    return v.lower() in ("1", "true", "yes", "on")
                return bool(v)
        else:
            conv = ftype
        self._flags[name] = _Flag(name, default, conv, doc)

    def get(self, name: str) -> Any:
        flag = self._flags[name]
        if name in self._overrides:
            return self._overrides[name]
        env = os.environ.get(f"RAY_TPU_{name.upper()}")
        if env is not None:
            return flag.type(env)
        return flag.default

    def set(self, name: str, value: Any) -> None:
        flag = self._flags[name]
        self._overrides[name] = flag.type(value)

    def apply_system_config(self, system_config: Dict[str, Any]) -> None:
        for k, v in (system_config or {}).items():
            if k not in self._flags:
                raise ValueError(f"Unknown system config flag: {k}")
            self.set(k, v)

    def reset(self) -> None:
        self._overrides.clear()

    def to_json(self) -> str:
        return json.dumps({k: self.get(k) for k in self._flags})

    def items(self):
        return {k: self.get(k) for k in self._flags}.items()

    def __getattr__(self, name: str) -> Any:
        try:
            return self.get(name)
        except KeyError:
            raise AttributeError(name) from None


GLOBAL_CONFIG = ConfigRegistry()
_d = GLOBAL_CONFIG.define

# --- core object store -----------------------------------------------------
_d("inline_object_max_bytes", 100 * 1024,
   "Objects at or below this size live in the control-plane memory store "
   "instead of the node shared-memory store.")
_d("shm_store_capacity_bytes", 0,
   "Capacity of the node shm object store. 0 = 30% of system memory.")
_d("shm_eviction_headroom", 0.1,
   "Fraction of capacity freed beyond demand when evicting.")
_d("object_spill_dir", "",
   "Directory for spilling evicted primary objects. '' = <session>/spill.")
_d("object_store_mmap_threshold_bytes", 1024 * 1024,
   "Reads at or above this size return zero-copy views into shm.")
_d("object_samehost_fastpath", 1,
   "Same-host node-to-node transfers copy the sealed shm file "
   "kernel-side instead of pulling RPC chunks (0 disables, e.g. to "
   "exercise the broadcast chain in tests).")
_d("object_transfer_chunk_bytes", 5 * 1024 * 1024,
   "Chunk size for node-to-node object pulls (reference: 5MiB chunks, "
   "common/ray_config_def.h object_manager_default_chunk_size).")
_d("object_gc_grace_s", 2.0,
   "Seconds an unreferenced object survives before the control plane "
   "frees it (covers the submit->deserialize ref handoff window).")
_d("object_gc_period_s", 1.0, "Control-plane GC sweep period.")

# --- scheduler -------------------------------------------------------------
_d("worker_pool_min_workers", 0, "Prestarted workers per node.")
_d("forksrv_warm_delay_s", 3.0,
   "Seconds after node-manager boot before the fork template warms "
   "(0 = immediately); deferred so N simultaneous node adds don't "
   "starve registration heartbeats on small hosts.")
_d("worker_max_concurrent_starts", 16,
   "Worker processes allowed to be starting (forked, not yet "
   "registered) at once.  Startup cost is the child's imports, which "
   "run in parallel across processes; this bounds the fork burst.")

# --- memory monitor (reference: common/memory_monitor.h,
# raylet/worker_killing_policy.cc) --------------------------------------
_d("memory_monitor_refresh_ms", 250,
   "Node memory sampling period; 0 disables OOM killing.")
_d("memory_usage_threshold", 0.95,
   "Node memory usage fraction above which the OOM policy kills a "
   "worker (newest retriable task first).")
_d("memory_monitor_limit_bytes", 0,
   "If >0, usage = sum(worker RSS)/limit instead of /proc/meminfo — "
   "lets tests (and containers without cgroup visibility) bound the "
   "worker pool explicitly.")
_d("worker_lease_timeout_s", 30.0, "Timeout for leasing a worker.")
_d("scheduler_spread_threshold", 0.5,
   "Hybrid policy: pack nodes below this utilization, then spread.")
_d("scheduler_top_k_fraction", 0.2,
   "Hybrid policy: random pick among best k = max(1, frac*nodes).")
_d("max_pending_tasks_per_node", 1_000_000, "Backpressure bound.")
_d("max_tasks_in_flight_per_worker", 1,
   "Pipelined task pushes per leased worker.")

# --- fault tolerance -------------------------------------------------------
_d("task_default_max_retries", 3, "Default retries for normal tasks.")
_d("actor_default_max_restarts", 0, "Default actor restarts.")
_d("health_check_period_s", 1.0, "Control-plane liveness probe period.")
_d("health_check_timeout_s", 10.0, "Misses before a node is declared dead.")
_d("lineage_max_bytes", 64 * 1024 * 1024,
   "Budget for retained lineage specs per worker.")
_d("cp_persistence", False,
   "Journal durable control-plane tables to <session>/cp_journal.bin so "
   "a restarted head (init(session_name=<old>)) restores cluster "
   "metadata and surviving nodes reconnect (reference: GCS Redis "
   "persistence, redis_store_client.cc).")
_d("cp_journal_sync", False,
   "fsync the control-plane journal on every record (durable against "
   "host crash, slower).")
_d("cp_journal_compact_records", 100_000,
   "Snapshot-compact the journal once this many records accumulate.")

# --- observability ---------------------------------------------------------
_d("log_to_driver", True,
   "Stream worker stdout/stderr lines to the driver console via the "
   "control-plane pubsub (reference: _private/log_monitor.py).")

# --- networking ------------------------------------------------------------
_d("use_tcp", False,
   "Bind control plane and node managers on TCP instead of unix sockets "
   "so RPCs can cross hosts (reference: rpc/grpc_server.cc binds TCP).")
_d("node_ip", "127.0.0.1", "Advertised IP for this node's TCP services.")
_d("rpc_connect_timeout_s", 10.0, "Socket connect timeout.")
_d("rpc_frame_max_bytes", 512 * 1024 * 1024, "Max RPC frame size.")
_d("pubsub_poll_timeout_s", 60.0, "Long-poll timeout for subscribers.")

# --- logging / events ------------------------------------------------------
_d("event_stats", True, "Record per-handler event-loop stats.")
_d("task_events_max_buffer", 65536, "Ring buffer size for task events.")

# --- TPU layer -------------------------------------------------------------
_d("tpu_chips_per_host", 0, "Override detected chip count. 0 = autodetect.")
_d("mesh_default_axes", "dp,fsdp,tp",
   "Default logical mesh axis order for SPMD groups.")
_d("collective_chunk_bytes", 4 * 1024 * 1024,
   "Chunk size for host-side (CPU backend) collective pipelining.")
