"""Zero-copy object serialization.

TPU-native equivalent of the reference's serialization layer
(``python/ray/_private/serialization.py`` + ``includes/serialization.pxi``):
cloudpickle for arbitrary Python with pickle protocol-5 out-of-band buffers
so large numpy / jax host arrays are written and read without copies.

Wire layout of a sealed object::

    [8s magic "RTPUOBJ1"][u32 nbuf][u64 meta_len]
    [nbuf x (u64 offset, u64 length)]        # offsets from start of payload
    [meta bytes (cloudpickle)]
    [64-byte-aligned buffer 0][... buffer 1] ...

Readers reconstruct the object with ``pickle.loads(meta, buffers=views)``
where each view is a slice of one mmap — numpy arrays come back as views
over shared memory (copied only if the caller mutates them; we expose them
read-only like the reference does for plasma-backed arrays).
"""

from __future__ import annotations

import pickle
from typing import Any, List, Sequence, Tuple

import cloudpickle

MAGIC = b"RTPUOBJ1"
_ALIGN = 64
_HEADER = len(MAGIC) + 4 + 8  # magic, nbuf, meta_len


def _align(n: int) -> int:
    return (n + _ALIGN - 1) & ~(_ALIGN - 1)


class SerializedObject:
    """A serialized object: metadata bytes + out-of-band buffers."""

    __slots__ = ("meta", "buffers", "total_bytes")

    def __init__(self, meta: bytes, buffers: List[memoryview]):
        self.meta = meta
        self.buffers = buffers
        offset = _align(_HEADER + 16 * len(buffers) + len(meta))
        for b in buffers:
            offset = _align(offset + b.nbytes)
        self.total_bytes = offset

    def write_into(self, dst: memoryview) -> int:
        """Write the framed object into ``dst``; returns bytes written."""
        nbuf = len(self.buffers)
        header_end = _HEADER + 16 * nbuf
        dst[:len(MAGIC)] = MAGIC
        dst[len(MAGIC):len(MAGIC) + 4] = nbuf.to_bytes(4, "little")
        dst[len(MAGIC) + 4:_HEADER] = len(self.meta).to_bytes(8, "little")
        offset = _align(header_end + len(self.meta))
        index = []
        for b in self.buffers:
            index.append((offset, b.nbytes))
            offset = _align(offset + b.nbytes)
        pos = _HEADER
        for off, length in index:
            dst[pos:pos + 8] = off.to_bytes(8, "little")
            dst[pos + 8:pos + 16] = length.to_bytes(8, "little")
            pos += 16
        dst[header_end:header_end + len(self.meta)] = self.meta
        for (off, length), b in zip(index, self.buffers):
            dst[off:off + length] = b
        return offset

    def to_bytes(self) -> bytes:
        out = bytearray(self.total_bytes)
        self.write_into(memoryview(out))
        return bytes(out)


def serialize(value: Any) -> SerializedObject:
    buffers: List[pickle.PickleBuffer] = []
    meta = cloudpickle.dumps(value, protocol=5, buffer_callback=buffers.append)
    views = []
    for pb in buffers:
        view = pb.raw()
        if not view.contiguous:
            view = memoryview(pb.raw().tobytes())
        views.append(view)
    return SerializedObject(meta, views)


def parse_frame(payload: memoryview) -> Tuple[memoryview, List[memoryview]]:
    """Split a framed payload into (meta, buffer views). Zero-copy."""
    if bytes(payload[:len(MAGIC)]) != MAGIC:
        raise ValueError("corrupt object: bad magic")
    nbuf = int.from_bytes(payload[len(MAGIC):len(MAGIC) + 4], "little")
    meta_len = int.from_bytes(payload[len(MAGIC) + 4:_HEADER], "little")
    header_end = _HEADER + 16 * nbuf
    views = []
    pos = _HEADER
    for _ in range(nbuf):
        off = int.from_bytes(payload[pos:pos + 8], "little")
        length = int.from_bytes(payload[pos + 8:pos + 16], "little")
        views.append(payload[off:off + length])
        pos += 16
    meta = payload[header_end:header_end + meta_len]
    return meta, views


def deserialize_frame(payload: memoryview) -> Any:
    meta, views = parse_frame(payload)
    return pickle.loads(bytes(meta), buffers=views)


def deserialize(meta: bytes, buffers: Sequence[memoryview]) -> Any:
    return pickle.loads(meta, buffers=list(buffers))


def dumps(value: Any) -> bytes:
    """One-shot serialize to a contiguous frame (for small objects / RPC)."""
    return serialize(value).to_bytes()


def loads(data: bytes) -> Any:
    return deserialize_frame(memoryview(data))
