"""Node manager — per-node scheduler daemon (raylet-equivalent).

TPU-native analogue of the reference raylet (``src/ray/raylet/``):
worker pool (forks language workers), task queueing + dispatch, dependency
management, actor hosting, resource accounting, and spillback to other
nodes.  One NodeManager runs in the head process (serving the driver
in-process) and one per extra node process; they all talk to the same
control plane.

Scheduling follows the reference's hybrid policy shape
(``raylet/scheduling/policy/hybrid_scheduling_policy.cc``): prefer the
local node while utilization is below ``scheduler_spread_threshold``, then
spread by lowest utilization; explicit strategies (spread / node-affinity /
placement-group) override.
"""

from __future__ import annotations

import logging
import os
import signal
import socket
import subprocess
import sys
import threading
import time
import traceback
from collections import defaultdict, deque
from typing import Any, Dict, List, Optional

from ray_tpu._private import protocol, serialization
from ray_tpu._private.config import GLOBAL_CONFIG
from ray_tpu._private.ids import NodeID, WorkerID
from ray_tpu._private.task_spec import (TaskSpec, acquire, fits, release)
from ray_tpu.exceptions import (ActorDiedError, WorkerCrashedError,
                                format_remote_traceback)

logger = logging.getLogger(__name__)

_EXIT_SENTINEL = {"type": "exit"}

_CONN_ERRORS = (protocol.ConnectionClosed, ConnectionResetError,
                ConnectionRefusedError, BrokenPipeError, OSError,
                EOFError)


class _ResilientCP:
    """Control-plane client that rides out a head restart.

    Wraps the remote RpcClient: a connection failure blocks and retries
    (bounded) instead of raising, so in-flight bookkeeping — task result
    commits, actor state updates — lands once the restarted head rebinds
    its socket (reference flow: raylet reconnect on NotifyGCSRestart,
    ``node_manager.proto:352``).  Only used for the out-of-process client;
    the head's in-process ControlPlane needs none of this.
    """

    def __init__(self, client, retry_window_s: float = 30.0):
        self._client = client
        self._window = retry_window_s

    def __getattr__(self, name: str):
        target = getattr(self._client, name)

        def call(*args, **kwargs):
            deadline = time.time() + self._window
            while True:
                try:
                    return target(*args, **kwargs)
                except _CONN_ERRORS:
                    if time.time() > deadline:
                        raise
                    time.sleep(0.5)

        call.__name__ = name
        return call


class _ForkedProc:
    """Popen-shaped handle for a worker forked by the forkserver.

    The child is the *template's* child, not ours (and the template
    auto-reaps), so liveness can't use waitpid — and a bare pid check
    is unsafe once the kernel recycles the pid.  Identity is the
    (pid, /proc start_time) pair recorded at fork: poll() reports dead
    and kill()/terminate() become no-ops the moment the pid belongs to
    a different process."""

    def __init__(self, pid: int, start_time: Optional[int] = None):
        self.pid = pid
        self._start_time = start_time

    def _alive(self) -> bool:
        from ray_tpu._private.worker_forkserver import proc_start_time
        if self._start_time is None:
            # the fork reply carried no start_time: the child died and
            # was reaped before it could be stat'ed.  Treat as dead —
            # a bare pid match here could be a recycled pid, and
            # signalling it would hit an unrelated process.
            return False
        now = proc_start_time(self.pid)
        return now is not None and now == self._start_time

    def poll(self) -> Optional[int]:
        return None if self._alive() else 0

    def terminate(self) -> None:
        if not self._alive():
            return
        try:
            os.kill(self.pid, signal.SIGTERM)
        except ProcessLookupError:
            pass

    def kill(self) -> None:
        if not self._alive():
            return
        try:
            os.kill(self.pid, signal.SIGKILL)
        except ProcessLookupError:
            pass

    def wait(self, timeout: Optional[float] = None) -> int:
        deadline = None if timeout is None else time.time() + timeout
        while self.poll() is None:
            if deadline is not None and time.time() > deadline:
                raise subprocess.TimeoutExpired(f"pid:{self.pid}",
                                                timeout or 0)
            time.sleep(0.01)
        return 0


class _Worker:
    """NM-side view of one worker process."""

    def __init__(self, worker_id: bytes, proc: Optional[subprocess.Popen],
                 tpu: bool = False):
        self.worker_id = worker_id
        self.proc = proc
        self.tpu = tpu
        self.sock: Optional[socket.socket] = None
        self.send_lock = threading.Lock()
        self.state = "starting"  # starting | idle | busy | actor | dead
        self.current_task: Optional[TaskSpec] = None
        self.actor_id: Optional[bytes] = None
        self.blocked = False
        self.inflight_actor_tasks: Dict[bytes, TaskSpec] = {}
        self.task_started_at = 0.0
        self.oom_killed: Optional[float] = None  # usage at OOM kill

    def send(self, msg: Any) -> bool:
        if self.sock is None:
            return False
        try:
            with self.send_lock:
                protocol.send_msg(self.sock, msg)
            return True
        except (BrokenPipeError, ConnectionResetError, OSError):
            return False


class _ActorState:
    def __init__(self, creation_spec: TaskSpec):
        self.creation_spec = creation_spec
        self.worker: Optional[_Worker] = None
        self.state = "PENDING"
        self.queued: deque = deque()  # actor TaskSpecs awaiting a live worker
        self.restarts_used = 0
        self.resources = dict(creation_spec.resources)


class _PendingQueues:
    """Ready-to-schedule tasks bucketed by scheduling shape.

    The dispatch loop previously drained and re-queued one flat deque
    each wake: with N queued tasks and bounded worker capacity that is
    O(N) scanned per dispatched task — O(N^2) to drain a 100k backlog.
    A task that cannot dispatch blocks only tasks of its own *shape*
    (same resources + strategy target), so dispatch walks each shape's
    head and stops that shape at the first failure: one wake is
    O(shapes + dispatched).  Reference analogue: per-SchedulingClass
    deques in ``raylet/local_task_manager.h``.
    """

    def __init__(self):
        self._queues: Dict[Any, deque] = {}
        self._count = 0

    @staticmethod
    def shape_key(spec: TaskSpec) -> Any:
        strat = spec.scheduling_strategy
        return (tuple(sorted(spec.resources.items())), strat.kind,
                getattr(strat, "node_id", None),
                getattr(strat, "pg_id", None))

    def append(self, spec: TaskSpec) -> None:
        key = self.shape_key(spec)
        q = self._queues.get(key)
        if q is None:
            q = self._queues[key] = deque()
        q.append(spec)
        self._count += 1

    def push_front(self, key: Any, spec: TaskSpec) -> None:
        q = self._queues.get(key)
        if q is None:
            q = self._queues[key] = deque()
        q.appendleft(spec)
        self._count += 1

    def pop_front(self, key: Any) -> Optional[TaskSpec]:
        q = self._queues.get(key)
        if not q:
            if q is not None:
                del self._queues[key]   # prune drained shapes
            return None
        self._count -= 1
        spec = q.popleft()
        if not q:
            del self._queues[key]
        return spec

    def shapes(self) -> List[Any]:
        return [k for k, q in self._queues.items() if q]

    def shape_counts(self) -> Dict[Any, int]:
        """Pending count per resource shape — O(#shapes), for the
        heartbeat demand vector (key[0] is the sorted resources tuple)."""
        out: Dict[Any, int] = {}
        for key, q in self._queues.items():
            if q:
                out[key[0]] = out.get(key[0], 0) + len(q)
        return out

    def remove(self, task_id: bytes) -> Optional[TaskSpec]:
        for q in self._queues.values():
            for i, spec in enumerate(q):
                if spec.task_id == task_id:
                    del q[i]
                    self._count -= 1
                    return spec
        return None

    def __len__(self) -> int:
        return self._count

    def __iter__(self):
        for q in self._queues.values():
            yield from q


class NodeManager:
    def __init__(self, node_id: bytes, session_dir: str, control_plane,
                 cp_sock_path: str, shm_store, resources: Dict[str, float],
                 node_ip: str = "127.0.0.1", labels: Optional[Dict] = None):
        self.node_id = node_id
        self.session_dir = session_dir
        if isinstance(control_plane, protocol.RpcClient):
            control_plane = _ResilientCP(control_plane)
        self.cp = control_plane  # ControlPlane, or _ResilientCP(RpcClient)
        self.cp_sock_path = cp_sock_path
        self.store = shm_store
        if getattr(shm_store, "on_evict", None) is None:
            # dropped secondary copies must leave the broadcast chain,
            # or later joiners chain off a node that has nothing
            shm_store.on_evict = self._on_store_evict
        self.resources_total = dict(resources)
        self.resources_available = dict(resources)
        self.node_ip = node_ip
        self.labels = labels or {}
        self._res_lock = threading.RLock()

        if GLOBAL_CONFIG.use_tcp:
            self.sock_path = f"tcp://{node_ip}:0"
        else:
            self.sock_path = os.path.join(
                session_dir, "sockets", f"nm_{node_id.hex()[:12]}.sock")
        self._server = protocol.RpcServer(self.sock_path, self,
                                          name=f"nm-{node_id.hex()[:6]}")
        self.sock_path = self._server.address  # resolve ephemeral TCP port

        self._workers: Dict[bytes, _Worker] = {}
        self._idle: deque = deque()
        # pre-warmed worker forkserver (lazy; CPU workers only)
        self._forksrv_proc: Optional[subprocess.Popen] = None
        self._forksrv_sock: Optional[socket.socket] = None
        self._forksrv_failed = False
        self._forksrv_lock = threading.RLock()
        self._starting = 0
        self._actors: Dict[bytes, _ActorState] = {}
        self._pending = _PendingQueues()         # ready-to-schedule specs
        self._waiting: Dict[bytes, TaskSpec] = {}  # task_id -> waiting on deps
        # dependency resolution (one resolver thread, not one per task):
        # dep object id -> task ids blocked on it, task id -> unready deps
        self._dep_map: Dict[bytes, set] = {}
        self._task_unready: Dict[bytes, set] = {}
        self._dep_kick = threading.Event()
        self._dep_blocked = False
        self._retries_left: Dict[bytes, int] = {}
        # CP-side effects that outlasted _ResilientCP's retry window
        # (head outage): retried from the heartbeat loop so a caller's
        # get() can't hang forever on a result that was never committed
        self._deferred_cp: List[Any] = []
        self._lock = threading.RLock()
        self._wake = threading.Event()
        self._stopped = threading.Event()
        # TPU chip assignment bookkeeping
        self._free_chips: List[int] = list(
            range(int(resources.get("TPU", 0))))
        self._worker_chips: Dict[bytes, List[int]] = {}
        # remote node manager clients (for spillback / actor routing)
        self._peers: Dict[bytes, protocol.RpcClient] = {}
        # ---- owned-object reference counts (decentralized ownership,
        # reference: core_worker/reference_count.cc).  This NM owns the
        # lifetime of every object created by its workers/driver; ref
        # holders anywhere in the cluster flush +1/-1 deltas HERE (the
        # CP is out of the per-ref hot path) and _owner_sweep frees
        # owned objects unreferenced past the grace period.
        self._owner_lock = threading.Lock()
        self._owner_by_holder: Dict[bytes, Dict[bytes, int]] = (
            defaultdict(lambda: defaultdict(int)))
        self._owner_totals: Dict[bytes, int] = {}
        self._owner_zero_since: Dict[bytes, float] = {}
        # holder -> {node -> {oid: count}}: per-NODE contributions, so a
        # whole-node death subtracts exactly what that node's processes
        # flushed (its own NM can't send the purge) without touching the
        # same holder's pins from surviving nodes — e.g. the caller-side
        # pre-pin and the hosting NM's pin share the task:<id> holder
        # but live on different nodes.
        self._owner_holder_contrib: Dict[
            bytes, Dict[bytes, Dict[bytes, int]]] = {}
        self._owner_peers: Dict[str, protocol.RpcClient] = {}
        self._last_owner_sweep = time.time()

        self.cp.register_node(node_id, {
            "ip": node_ip,
            "sock_path": self.sock_path,
            "resources_total": self.resources_total,
            "resources_available": self.resources_available,
            "labels": self.labels,
            "session_dir": session_dir,
        })

        self._dispatch_thread = threading.Thread(
            target=self._dispatch_loop, name="nm-dispatch", daemon=True)
        self._dispatch_thread.start()
        self._dep_thread = threading.Thread(
            target=self._dep_resolver_loop, name="nm-depresolve",
            daemon=True)
        self._dep_thread.start()
        if GLOBAL_CONFIG.memory_monitor_refresh_ms > 0:
            threading.Thread(target=self._memory_monitor_loop,
                             name="nm-memmon", daemon=True).start()
        self._hb_thread = threading.Thread(
            target=self._heartbeat_loop, name="nm-heartbeat", daemon=True)
        self._hb_thread.start()
        # Warm the fork template shortly after boot (without waiting):
        # its import cost overlaps cluster setup instead of the first
        # spawn burst.  Deferred a beat — N nodes added together each
        # booting a template AT registration starves the very
        # heartbeats that prove the nodes alive on small hosts.
        def _warm():
            try:
                if self._stopped.is_set():
                    return  # NM shut down before the warm fired
                with self._forksrv_lock:
                    if self._forksrv_sock is None \
                            and not self._forksrv_failed:
                        self._launch_forkserver_proc()
            except Exception:  # noqa: BLE001 — cold spawn still works
                pass
        self._forksrv_warm_timer = threading.Timer(
            GLOBAL_CONFIG.forksrv_warm_delay_s, _warm)
        self._forksrv_warm_timer.daemon = True
        self._forksrv_warm_timer.start()
        for _ in range(GLOBAL_CONFIG.worker_pool_min_workers):
            self._spawn_worker()

    # ------------------------------------------------------------------
    # Public RPC surface (called by drivers/workers via RpcClient, or
    # in-process by the driver).
    # ------------------------------------------------------------------
    def _pin_dependencies(self, spec: TaskSpec) -> None:
        """Keep arg objects alive while the task is queued/running.

        The pin is a refcount held under a per-task holder id, purged when
        the task reaches a terminal state (reference: the submitting
        worker's reference_count.cc holds deps until the task completes).
        Pins route to each dependency's OWNER node manager
        (``spec.ref_owners``); ownerless deps pin at the control plane.
        """
        deps = spec.dependencies()
        if not deps:
            return
        from ray_tpu._private import owner_routing
        owner_routing.route_updates(
            self.cp, self._owner_peer, b"task:" + spec.task_id,
            owner_routing.bucket_by_owner({d: 1 for d in deps},
                                          spec.ref_owners.get),
            holder_node=self.node_id,
            local_addr=self.sock_path, local=self.update_owned_refs)

    def _unpin_dependencies(self, spec: TaskSpec) -> None:
        deps = spec.dependencies()
        if not deps:
            return
        from ray_tpu._private import owner_routing
        owner_routing.route_purge(
            self.cp, self._owner_peer, b"task:" + spec.task_id,
            {spec.ref_owners.get(d) for d in deps},
            local_addr=self.sock_path, local=self.purge_owned_holder)

    # ------------------------------------------------------------------
    # Owned-object refcounting (this NM = owner).  RPC surface used by
    # ref trackers, pinning NMs, and caller-side pre-pins cluster-wide.
    # ------------------------------------------------------------------
    def _owner_peer(self, addr: str) -> protocol.RpcClient:
        client = self._owner_peers.get(addr)
        if client is None:
            client = protocol.RpcClient(addr)
            self._owner_peers[addr] = client
        return client

    def update_owned_refs(self, holder_id: bytes,
                          deltas: Dict[bytes, int],
                          holder_node: bytes = b"") -> None:
        now = time.time()
        with self._owner_lock:
            if holder_node:
                contrib = self._owner_holder_contrib.setdefault(
                    holder_id, {}).setdefault(holder_node, {})
            held = self._owner_by_holder[holder_id]
            for oid, d in deltas.items():
                oid = bytes(oid)
                if holder_node:
                    c = contrib.get(oid, 0) + d
                    if c:
                        contrib[oid] = c
                    else:
                        contrib.pop(oid, None)
                held[oid] += d
                if held[oid] == 0:
                    held.pop(oid)
                total = self._owner_totals.get(oid, 0) + d
                if total > 0:
                    self._owner_totals[oid] = total
                    self._owner_zero_since.pop(oid, None)
                else:
                    # net<=0: born-and-dropped within one flush window,
                    # or a drop against untracked state — either way the
                    # object is now unreferenced
                    self._owner_totals.pop(oid, None)
                    self._owner_zero_since.setdefault(oid, now)
            if not held:
                self._owner_by_holder.pop(holder_id, None)

    def purge_owned_holder(self, holder_id: bytes) -> None:
        """Drop every count a (finished task / dead process) holder
        contributed to objects owned here."""
        with self._owner_lock:
            held = self._owner_by_holder.pop(holder_id, None)
            self._owner_holder_contrib.pop(holder_id, None)
        if held:
            self.update_owned_refs(b"_purge",
                                   {o: -d for o, d in held.items()})
            with self._owner_lock:
                self._owner_by_holder.pop(b"_purge", None)

    def purge_owned_node_holders(self, node_id: bytes) -> None:
        """A whole node died: subtract exactly the contributions flushed
        here by processes on that node (their NM died with them; the
        head broadcasts this from its node-death handler).  Holders with
        pins from surviving nodes keep those pins."""
        with self._owner_lock:
            victims = []
            for h, nodes in list(self._owner_holder_contrib.items()):
                contrib = nodes.pop(node_id, None)
                if contrib:
                    # clamp to what the holder still actually holds: a
                    # stale/negative contribution must not resurrect an
                    # emptied holder (the defaultdict would recreate it
                    # with residual counts nothing will ever purge)
                    held = (self._owner_by_holder.get(h) or {})
                    deltas = {}
                    for oid, d in contrib.items():
                        take = min(d, held.get(oid, 0))
                        if take > 0:
                            deltas[oid] = -take
                    if deltas:
                        victims.append((h, deltas))
                if not nodes:
                    self._owner_holder_contrib.pop(h, None)
        for h, deltas in victims:
            self.update_owned_refs(h, deltas)

    def debug_state(self) -> Dict[str, Any]:
        """Introspection snapshot for ``ray-tpu stack``-style debugging:
        queue depths, worker states, per-actor queue lengths."""
        with self._lock:
            return {
                "pending": len(self._pending),
                "waiting": len(self._waiting),
                "workers": {w.worker_id.hex()[:12]:
                            {"state": w.state,
                             "task": (w.current_task.name
                                      if w.current_task else None),
                             "inflight_actor_tasks":
                             len(w.inflight_actor_tasks)}
                            for w in self._workers.values()},
                "actors": {aid.hex()[:12]:
                           {"state": st.state,
                            "queued": len(st.queued),
                            "worker": (st.worker.worker_id.hex()[:12]
                                       if st.worker else None)}
                           for aid, st in self._actors.items()},
            }

    def signal_stack_dump(self) -> List[int]:
        """``ray stack`` equivalent (reference: py-spy-based
        ``python/ray/scripts/scripts.py stack``): SIGUSR1 every live
        worker — their registered faulthandler writes all-thread python
        tracebacks to their log files — and dump this NM process's own
        threads to stderr.  Returns the signalled pids."""
        import faulthandler
        import signal as _signal
        pids: List[int] = []
        with self._lock:
            workers = list(self._workers.values())
        for w in workers:
            if w.proc is not None and w.state != "dead":
                try:
                    os.kill(w.proc.pid, _signal.SIGUSR1)
                    pids.append(w.proc.pid)
                except (ProcessLookupError, PermissionError):
                    pass
        faulthandler.dump_traceback(all_threads=True)
        return pids

    def owned_refs_summary(self) -> Dict[str, int]:
        with self._owner_lock:
            return {"tracked_objects": len(self._owner_totals),
                    "holders": len(self._owner_by_holder),
                    "zero_pending": len(self._owner_zero_since)}

    def _owner_sweep(self) -> None:
        """Free owned objects unreferenced past the grace period: drop
        their directory entries at the CP in one batch, then fan the shm
        deletion out to every node (the owner drives GC; the CP is
        touched once per object lifetime, not per ref event)."""
        grace = GLOBAL_CONFIG.object_gc_grace_s
        now = time.time()
        cutoff = now - grace
        with self._owner_lock:
            victims = [o for o, t0 in self._owner_zero_since.items()
                       if t0 < cutoff]
        if not victims:
            return
        res = self.cp.free_owned(victims)
        freed = res["freed"]
        with self._owner_lock:
            for o in freed:
                self._owner_zero_since.pop(o, None)
                self._owner_totals.pop(o, None)
            # ids never committed: keep briefly (commit may be in
            # flight), forget zero-marks that stayed uncommitted long
            # past the grace
            for o in res["pending"]:
                if self._owner_zero_since.get(o, now) < cutoff - 60.0:
                    self._owner_zero_since.pop(o, None)
        if not freed:
            return
        self.delete_objects(freed)
        for info in self.cp.list_nodes():
            if (info.get("state") != "ALIVE"
                    or info["node_id"] == self.node_id):
                continue
            try:
                self._owner_peer(info["sock_path"]).call(
                    "delete_objects", freed)
            except (OSError, ConnectionError):
                pass

    def submit_task(self, spec: TaskSpec) -> None:
        self._pin_dependencies(spec)
        self.cp.add_lineage(spec.task_id, spec)
        with self._lock:
            self._retries_left.setdefault(spec.task_id, spec.max_retries)
            self._pending.append(spec)
        self.cp.add_task_event({"task_id": spec.task_id.hex(),
                                "name": spec.name, "state": "PENDING",
                                "node": self.node_id.hex()})
        self._wake.set()

    def submit_actor_creation(self, spec: TaskSpec) -> None:
        assert spec.actor_creation and spec.actor_id
        self._pin_dependencies(spec)
        with self._lock:
            self._actors[spec.actor_id] = _ActorState(spec)
            self._pending.append(spec)
        self._wake.set()

    def _satrace(self, *parts) -> None:
        from ray_tpu._private.debug_trace import trace
        trace("submit_actor_task", *parts, var="RAY_TPU_DEBUG_FREE")

    def submit_actor_task(self, spec: TaskSpec) -> None:
        """Queue a method call on an actor hosted by this node."""
        self._pin_dependencies(spec)
        with self._lock:
            astate = self._actors.get(spec.actor_id)
            if astate is None or astate.state == "DEAD":
                self._satrace("DROP dead", spec.name, spec.task_id.hex()[:20])
                self._fail_task(spec, ActorDiedError(
                    spec.actor_id.hex() if spec.actor_id else "",
                    "actor not found or dead"))
                return
            # dedup (best-effort, matching the reference's at-least-once
            # retry semantics): drop a resend whose twin is queued,
            # in flight, or already committed a result
            if any(t.task_id == spec.task_id for t in astate.queued) or (
                    astate.worker is not None and spec.task_id in
                    astate.worker.inflight_actor_tasks):
                self._satrace("DROP dup-queued", spec.name,
                              spec.task_id.hex()[:20])
                return
            ret_ids = spec.return_object_ids()
            if ret_ids:
                try:
                    if self.cp.get_location(ret_ids[0]) is not None:
                        self._satrace("DROP committed", spec.name,
                                      spec.task_id.hex()[:20])
                        return  # the retried copy already finished
                except Exception:  # noqa: BLE001
                    pass
            self._satrace("QUEUE", spec.name, spec.task_id.hex()[:20],
                          "astate", astate.state,
                          "worker", bool(astate.worker))
            astate.queued.append(spec)
            self._flush_actor_queue_locked(astate)
        self._wake.set()

    def kill_actor(self, actor_id: bytes, no_restart: bool = True) -> bool:
        with self._lock:
            astate = self._actors.get(actor_id)
            if astate is None:
                return False
            if no_restart:
                astate.restarts_used = astate.creation_spec.max_restarts + 1
            worker = astate.worker
        if worker is not None and worker.proc is not None:
            worker.proc.terminate()
        elif worker is not None:
            # in-process actor (driver-hosted) — not supported; mark dead
            self._on_actor_worker_death(astate, "killed")
        return True

    def cancel_task(self, task_id: bytes) -> bool:
        from ray_tpu.exceptions import TaskCancelledError
        with self._lock:
            spec = self._pending.remove(task_id)
            if spec is None:
                spec = self._waiting.pop(task_id, None)
                if spec is not None:
                    # drop its dependency bookkeeping
                    for d in self._task_unready.pop(task_id, ()):
                        tids = self._dep_map.get(d)
                        if tids is not None:
                            tids.discard(task_id)
                            if not tids:
                                del self._dep_map[d]
        if spec is not None:
            self._fail_task(spec, TaskCancelledError(task_id.hex()))
            return True
        return False

    def node_stats(self) -> Dict[str, Any]:
        with self._lock, self._res_lock:
            return {
                "node_id": self.node_id.hex(),
                "resources_total": dict(self.resources_total),
                "resources_available": dict(self.resources_available),
                "num_workers": len(self._workers),
                "num_idle": len(self._idle),
                "num_pending": len(self._pending),
                "num_waiting": len(self._waiting),
                "num_actors": len(self._actors),
                "store": self.store.stats(),
            }

    def reserve_bundle(self, pg_id: bytes, bundle_index: int,
                       resources: Dict[str, float]) -> bool:
        """Placement-group 2PC 'prepare+commit' collapsed to one step.

        Mirrors the effect of the reference's
        ``PrepareBundleResources``/``CommitBundleResources``
        (``protobuf/node_manager.proto``): on success the node exposes
        bundle-indexed custom resources that PG-scheduled tasks consume.
        """
        wildcard = f"pg_{pg_id.hex()}"
        indexed = f"pg_{pg_id.hex()}_{bundle_index}"
        with self._res_lock:
            if not fits(self.resources_available, resources):
                return False
            acquire(self.resources_available, resources)
            for name, qty in resources.items():
                self.resources_total[f"{indexed}_{name}"] = qty
                self.resources_available[f"{indexed}_{name}"] = qty
                self.resources_total[f"{wildcard}_{name}"] = (
                    self.resources_total.get(f"{wildcard}_{name}", 0) + qty)
                self.resources_available[f"{wildcard}_{name}"] = (
                    self.resources_available.get(f"{wildcard}_{name}", 0)
                    + qty)
        self._wake.set()
        return True

    def return_bundle(self, pg_id: bytes, bundle_index: int,
                      resources: Dict[str, float]) -> None:
        wildcard = f"pg_{pg_id.hex()}"
        indexed = f"pg_{pg_id.hex()}_{bundle_index}"
        with self._res_lock:
            for name, qty in resources.items():
                self.resources_total.pop(f"{indexed}_{name}", None)
                self.resources_available.pop(f"{indexed}_{name}", None)
                wkey = f"{wildcard}_{name}"
                if wkey in self.resources_total:
                    self.resources_total[wkey] -= qty
                    self.resources_available[wkey] = (
                        self.resources_available.get(wkey, 0) - qty)
                    if self.resources_total[wkey] <= 0:
                        self.resources_total.pop(wkey, None)
                        self.resources_available.pop(wkey, None)
            release(self.resources_available, resources)
        self._wake.set()

    def shutdown_node(self) -> None:
        self.stop()

    # ------------------------------------------------------------------
    # Object pull protocol (node-to-node transfer; reference:
    # object_manager.cc Push/Pull + chunk_object_reader.cc)
    # ------------------------------------------------------------------
    def fetch_object_meta(self, object_id: bytes) -> Optional[Dict[str, Any]]:
        view = self.store.get_view(object_id)
        if view is None:
            return None
        meta = {"size": len(view), "ip": self.node_ip}
        # same-host fastpath: a co-hosted puller kernel-copies the
        # sealed file instead of pulling RPC chunks
        path = self.store.sealed_path(object_id)
        if path:
            meta["path"] = path
        return meta

    def push_object_chunk(self, object_id: bytes, total: int,
                          offset: int, data: bytes) -> bool:
        """Receive one chunk of an object pushed by a cross-host client
        driver (its local store isn't reachable from the cluster, so the
        primary copy lands here; reference: object_manager Push RPCs)."""
        return self.store.write_push_chunk(object_id, total, offset,
                                           data)

    def fetch_object_chunk(self, object_id: bytes, offset: int,
                           length: int) -> Optional[bytes]:
        return self.store.read_chunk(object_id, offset, length)

    def fetch_partial_chunk(self, object_id: bytes, offset: int,
                            length: int):
        """Broadcast-chain read: serve from a sealed copy OR the prefix
        an in-progress pull on this node has already written (None =
        not there yet; the downstream puller polls).  ``{"gone": True}``
        = no copy and no pull in flight here — the puller should stop
        polling and re-chain instead of waiting out its stall budget."""
        data = self.store.read_partial_chunk(object_id, offset, length)
        if data is None and not self.store.has_any_copy(object_id):
            return {"gone": True}
        return data

    # ------------------------------------------------------------------
    # Log access (``ray logs`` parity + dashboard log pane; reference:
    # dashboard/modules/log/log_agent.py serves per-node worker logs)
    # ------------------------------------------------------------------
    def list_logs(self) -> List[Dict[str, Any]]:
        log_dir = os.path.join(self.session_dir, "logs")
        out: List[Dict[str, Any]] = []
        try:
            for name in sorted(os.listdir(log_dir)):
                path = os.path.join(log_dir, name)
                if os.path.isfile(path):
                    out.append({"name": name,
                                "size": os.path.getsize(path),
                                "mtime": os.path.getmtime(path)})
        except OSError:
            pass
        return out

    def tail_log(self, name: str,
                 nbytes: int = 65536) -> Optional[bytes]:
        """Last ``nbytes`` of a session log, or None when this node
        doesn't have that file (callers probe several nodes)."""
        if os.sep in name or name.startswith("."):
            raise ValueError(f"bad log name {name!r}")
        path = os.path.join(self.session_dir, "logs", name)
        try:
            size = os.path.getsize(path)
            with open(path, "rb") as f:
                f.seek(max(0, size - nbytes))
                return f.read(nbytes)
        except OSError:
            return None

    def delete_objects(self, object_ids: List[bytes]) -> int:
        """GC fan-out target: drop local shm copies of freed objects."""
        n = 0
        for oid in object_ids:
            if self.store.delete(oid):
                self._on_store_evict(oid)
                n += 1
        return n

    def _on_store_evict(self, object_id: bytes) -> None:
        """A local copy was dropped: leave the object's broadcast chain
        so downstream pullers aren't pointed at an empty parent."""
        try:
            self.cp.leave_broadcast(object_id, self.node_id)
        except Exception:  # noqa: BLE001 — bookkeeping best-effort
            pass

    # ------------------------------------------------------------------
    # Worker channel (hijacked connection)
    # ------------------------------------------------------------------
    def stream_worker(self, conn: socket.socket, worker_id: bytes) -> None:
        """A worker process registered its task channel."""
        with self._lock:
            worker = self._workers.get(worker_id)
            if worker is None:
                worker = _Worker(worker_id, None)
                self._workers[worker_id] = worker
            worker.sock = conn
            worker.state = "idle"
            self._starting = max(0, self._starting - 1)
            self._idle.append(worker)
        self._wake.set()
        self._worker_reader(worker)

    _CONN_ERRORS = _CONN_ERRORS

    def _worker_reader(self, worker: _Worker) -> None:
        """Two distinct failure domains: the worker socket (worker died —
        run death handling) and control-plane calls made while handling a
        message (head outage — _ResilientCP block-retries through the
        restart; this branch is the backstop for an outage longer than
        its window)."""
        while True:
            try:
                msg = protocol.recv_msg(worker.sock)
            except self._CONN_ERRORS:
                break
            try:
                self._handle_worker_msg(worker, msg)
            except self._CONN_ERRORS:
                logger.error(
                    "control plane unreachable handling %s from worker "
                    "%s; message dropped", msg.get("type"),
                    worker.worker_id.hex()[:12])
        try:
            self._on_worker_death(worker)
        except self._CONN_ERRORS:
            logger.error("control plane unreachable reporting death of "
                         "worker %s", worker.worker_id.hex()[:12])

    def _handle_worker_msg(self, worker: _Worker, msg: Dict[str, Any]):
        kind = msg.get("type")
        if kind == "done":
            task_id = msg["task_id"]
            with self._lock:
                if worker.actor_id is not None:
                    done_actor_spec = worker.inflight_actor_tasks.pop(
                        task_id, None)
                    spec = None
                else:
                    done_actor_spec = None
                    spec = worker.current_task
                    worker.current_task = None
            if done_actor_spec is not None:
                self._unpin_dependencies(done_actor_spec)
            if spec is not None:
                self._release_task_resources(spec, worker)
                retrying = False
                if msg.get("error") and msg.get("error_payload") is not None:
                    # Application exception with retry_exceptions=True: the
                    # worker deferred the error commit so we can resubmit.
                    with self._lock:
                        left = self._retries_left.get(spec.task_id, 0)
                        if left > 0:
                            self._retries_left[spec.task_id] = left - 1
                            self._pending.append(spec)
                            retrying = True
                    if not retrying:
                        def commit_error(spec=spec,
                                         payload=msg["error_payload"]):
                            for oid in spec.return_object_ids():
                                self.cp.put_inline(
                                    oid, payload, is_error=True,
                                    owner_addr=spec.owner_addr)
                            self._fail_generator_stream(spec, payload)
                        self._cp_effect_or_defer(commit_error)
                with self._lock:
                    if not retrying:
                        self._retries_left.pop(spec.task_id, None)
                    if worker.state == "busy":
                        worker.state = "idle"
                        self._idle.append(worker)
                if not retrying:
                    self._unpin_dependencies(spec)
            self.cp.add_task_event({
                "task_id": task_id.hex(), "state": "FINISHED"
                if not msg.get("error") else "FAILED",
                "node": self.node_id.hex()})
            self._wake.set()
        elif kind == "actor_ready":
            with self._lock:
                astate = self._actors.get(msg["actor_id"])
                if astate is not None:
                    astate.state = "ALIVE"
                    astate.worker = worker
                    worker.actor_id = msg["actor_id"]
                    worker.state = "actor"
                    self._flush_actor_queue_locked(astate)
            def publish_alive(actor_id=msg["actor_id"], pid=msg.get("pid")):
                self.cp.update_actor(actor_id, state="ALIVE",
                                     node_id=self.node_id,
                                     nm_sock=self.sock_path, pid=pid)
            self._cp_effect_or_defer(publish_alive)
            self._wake.set()
        elif kind == "actor_init_failed":
            with self._lock:
                astate = self._actors.get(msg["actor_id"])
                spec = worker.current_task
                worker.current_task = None
                worker.actor_id = None
            if spec is not None:
                self._release_task_resources(spec, worker)
            with self._lock:
                # recycle the worker: the failed __init__ left no state
                worker.state = "idle"
                self._idle.append(worker)
            if astate is not None:
                # Creation raised: do not restart, error is in the object.
                astate.restarts_used = astate.creation_spec.max_restarts + 1
                self._on_actor_worker_death(astate, "init failed",
                                            from_msg=True, worker=worker)
            self._wake.set()
        elif kind == "blocked":
            # Worker blocked in get(): release its CPU so the node can run
            # other tasks (reference: CPU borrowing while blocked).
            with self._lock:
                if not worker.blocked and worker.current_task:
                    worker.blocked = True
                    cpus = worker.current_task.resources.get("CPU", 0)
                    if cpus:
                        with self._res_lock:
                            release(self.resources_available, {"CPU": cpus})
            self._wake.set()
        elif kind == "unblocked":
            with self._lock:
                if worker.blocked and worker.current_task:
                    worker.blocked = False
                    cpus = worker.current_task.resources.get("CPU", 0)
                    if cpus:
                        with self._res_lock:
                            acquire(self.resources_available, {"CPU": cpus})
        elif kind == "exit":
            with self._lock:
                worker.state = "dead"

    # ------------------------------------------------------------------
    # Dispatch
    # ------------------------------------------------------------------
    def _dispatch_loop(self):
        while not self._stopped.is_set():
            self._wake.wait(timeout=0.2)
            self._wake.clear()
            try:
                self._dispatch_once()
            except Exception:  # noqa: BLE001
                traceback.print_exc()

    def _dispatch_once(self):
        with self._lock:
            shape_keys = self._pending.shapes()
        for key in shape_keys:
            while not self._stopped.is_set():
                with self._lock:
                    spec = self._pending.pop_front(key)
                if spec is None:
                    break
                deps = spec.dependencies()
                if deps:
                    locs = self.cp.get_locations(deps)
                    unready = [d for d in deps if locs.get(d) is None]
                    if unready:
                        self._register_dep_wait(spec, unready)
                        continue
                if not self._try_dispatch(spec):
                    with self._lock:
                        # head-of-shape blocks only its own shape
                        self._pending.push_front(key, spec)
                    break

    def _register_dep_wait(self, spec: TaskSpec, deps: List[bytes]):
        with self._lock:
            self._waiting[spec.task_id] = spec
            pend = self._task_unready.setdefault(spec.task_id, set())
            for d in deps:
                pend.add(d)
                self._dep_map.setdefault(d, set()).add(spec.task_id)
            blocked = self._dep_blocked
        self._dep_kick.set()
        if blocked:
            # interrupt the resolver's standing server-side wait so the
            # new ids join the waited set
            try:
                self.cp.kick_waiters(self.node_id)
            except Exception:  # noqa: BLE001
                pass

    def _dep_resolver_loop(self):
        """One thread resolves all tasks' dependencies.

        Replaces the thread-per-waiting-task design (10k queued tasks
        meant 10k ``nm-depwait`` threads): a single standing
        ``cp.wait_any`` over the union of unready deps, interrupted via
        ``kick_waiters`` when registration adds new ids.  Reference
        analogue: ``raylet/dependency_manager.cc``.
        """
        while not self._stopped.is_set():
            # snapshot + blocked flag under ONE lock acquisition: a task
            # registering after the snapshot then sees blocked=True and
            # sends a kick; the CP keeps kicks sticky so one that lands
            # before wait_any registers its waiter is consumed on entry
            # instead of lost (30s stall otherwise).
            with self._lock:
                deps = list(self._dep_map)
                if deps:
                    self._dep_blocked = True
            if not deps:
                self._dep_kick.wait(timeout=1.0)
                self._dep_kick.clear()
                continue
            try:
                ready = self.cp.wait_any(deps, 1, 30.0, kick=self.node_id)
            except Exception:  # noqa: BLE001
                if self._stopped.is_set():
                    return
                time.sleep(0.5)
                continue
            finally:
                with self._lock:
                    self._dep_blocked = False
            self._dep_kick.clear()
            if ready:
                self._resolve_deps(ready)

    def _resolve_deps(self, ready: List[bytes]):
        moved = False
        with self._lock:
            for d in ready:
                for tid in self._dep_map.pop(d, ()):
                    pend = self._task_unready.get(tid)
                    if pend is None:
                        continue
                    pend.discard(d)
                    if not pend:
                        del self._task_unready[tid]
                        spec = self._waiting.pop(tid, None)
                        if spec is not None:
                            self._pending.append(spec)
                            moved = True
        if moved:
            self._wake.set()

    def _pick_node(self, spec: TaskSpec) -> Optional[Dict[str, Any]]:
        """Choose a target node; None => run locally.

        Raises :class:`InfeasibleTaskError` for tasks no node can ever
        satisfy (the reference surfaces infeasible-task warnings instead
        of silently requeueing forever) and for hard affinity to a dead
        node.
        """
        from ray_tpu.exceptions import InfeasibleTaskError
        strategy = spec.scheduling_strategy
        nodes = [n for n in self.cp.list_nodes() if n["state"] == "ALIVE"]
        if strategy.kind == "node_affinity":
            if strategy.node_id == self.node_id:
                return None
            for n in nodes:
                if n["node_id"] == strategy.node_id:
                    return n
            if strategy.soft:
                return None
            raise InfeasibleTaskError(
                f"task {spec.name!r} has hard affinity to node "
                f"{strategy.node_id.hex()[:12]}, which is not alive")
        if strategy.kind == "spread":
            # Least-loaded first (queue depth from heartbeats, locally from
            # live state), round-robin only to break ties between bursts
            # (reference: spread_scheduling_policy.cc sorts by load).
            candidates = sorted(
                (n for n in nodes
                 if fits(n.get("resources_total", {}), spec.resources)
                 or n["node_id"] == self.node_id),
                key=lambda n: n["node_id"])
            if not candidates:
                return None

            def _queue_depth(n):
                if n["node_id"] == self.node_id:
                    with self._lock:
                        return len(self._pending) + len(self._waiting)
                return n.get("load", {}).get("num_pending", 0)

            depths = [_queue_depth(n) for n in candidates]
            least = min(depths)
            tied = [n for n, d in zip(candidates, depths) if d == least]
            self._spread_rr = getattr(self, "_spread_rr", -1) + 1
            best = tied[self._spread_rr % len(tied)]
            return None if best["node_id"] == self.node_id else best
        # default hybrid: local first if it can ever fit and is under
        # the spread threshold; else best remote fit.
        with self._res_lock:
            local_fits_now = fits(self.resources_available, spec.resources)
            local_fits_ever = fits(self.resources_total, spec.resources)
            total_cpu = self.resources_total.get("CPU", 0) or 1
            local_util = 1.0 - (self.resources_available.get("CPU", 0)
                                / total_cpu)
        if local_fits_now:
            return None
        if (local_fits_ever
                and local_util < GLOBAL_CONFIG.scheduler_spread_threshold):
            return None
        for n in nodes:
            if n["node_id"] == self.node_id:
                continue
            if fits(n.get("resources_available", {}), spec.resources):
                return n
        if local_fits_ever:
            return None
        if not any(fits(n.get("resources_total", {}), spec.resources)
                   for n in nodes):
            # an active autoscaler may be able to PROVISION a fitting
            # node type: keep the task queued (its shape rides the
            # heartbeat demand vector) instead of failing it — the
            # reference keeps infeasible tasks pending with warnings
            if self._provisionable(spec.resources):
                return None
            raise InfeasibleTaskError(
                f"task {spec.name!r} requests {spec.resources}, which no "
                f"node in the cluster can ever satisfy")
        return None  # a node could fit it later; keep requeueing

    def _provisionable(self, resources: Dict[str, float]) -> bool:
        """True if an autoscaler has registered a node type whose shape
        could satisfy these resources.  The registry blob is TTL-cached:
        this runs on every dispatch retry of an infeasible-shaped task,
        and an identical CP read ~5x/s per shape adds up."""
        now = time.time()
        cached = getattr(self, "_node_types_cache", None)
        if cached is None or now - cached[0] > 5.0:
            types = None
            try:
                blob = self.cp.kv_get(b"node_types",
                                      namespace="_autoscaler")
                if blob:
                    import json
                    types = json.loads(blob)
            except Exception:  # noqa: BLE001
                types = None
            cached = (now, types)
            self._node_types_cache = cached
        types = cached[1]
        if not types:
            return False
        return any(fits(shape, resources) for shape in types.values())

    def _try_dispatch(self, spec: TaskSpec) -> bool:
        from ray_tpu.exceptions import InfeasibleTaskError
        try:
            target = self._pick_node(spec)
        except InfeasibleTaskError as e:
            if spec.actor_creation and spec.actor_id:
                self.cp.update_actor(spec.actor_id, state="DEAD",
                                     death_reason=str(e))
            self._fail_task(spec, e)
            return True  # terminally handled; do not requeue
        if target is not None:
            try:
                peer = self._peer_client(target)
                if spec.actor_creation:
                    peer.call("submit_actor_creation", spec)
                else:
                    peer.call("submit_task", spec)
                return True
            except (OSError, ConnectionError):
                pass  # fall through to local
        with self._res_lock:
            if not fits(self.resources_available, spec.resources):
                return False
            acquire(self.resources_available, spec.resources)
        need_tpu = spec.resources.get("TPU", 0) > 0
        worker = self._take_idle_worker(need_tpu)
        if worker is None:
            with self._res_lock:
                release(self.resources_available, spec.resources)
            # spawn toward the whole same-shape backlog, not one worker
            # per dispatch wake (this spec + everything queued behind it)
            with self._lock:
                backlog = 1 + len(self._pending._queues.get(
                    _PendingQueues.shape_key(spec), ()))
            self._maybe_spawn_worker(need_tpu, count=backlog)
            return False
        try:
            chips = self._assign_chips(spec, worker)
        except RuntimeError as e:
            print(f"[node_manager] {e}; requeueing task", file=sys.stderr)
            with self._lock:
                worker.state = "idle"
                self._idle.append(worker)
            with self._res_lock:
                release(self.resources_available, spec.resources)
            return False
        with self._lock:
            worker.current_task = spec
            worker.task_started_at = time.time()
            worker.state = "busy" if not spec.actor_creation else "actor"
        ok = worker.send({"type": "task", "spec": spec, "chips": chips})
        if not ok:
            self._on_worker_death(worker)
            return False
        self.cp.add_task_event({"task_id": spec.task_id.hex(),
                                "name": spec.name, "state": "RUNNING",
                                "node": self.node_id.hex(),
                                "worker": worker.worker_id.hex()})
        return True

    def _flush_actor_queue_locked(self, astate: _ActorState):
        if astate.state != "ALIVE" or astate.worker is None:
            return
        while astate.queued:
            spec = astate.queued.popleft()
            astate.worker.inflight_actor_tasks[spec.task_id] = spec
            if not astate.worker.send({"type": "task", "spec": spec,
                                       "chips": None}):
                astate.queued.appendleft(spec)
                astate.worker.inflight_actor_tasks.pop(spec.task_id, None)
                break

    # ------------------------------------------------------------------
    # Worker pool
    # ------------------------------------------------------------------
    def _take_idle_worker(self, need_tpu: bool = False) -> Optional[_Worker]:
        with self._lock:
            for i, w in enumerate(self._idle):
                if (w.state == "idle" and w.sock is not None
                        and w.tpu == need_tpu):
                    del self._idle[i]
                    return w
            # clean out dead entries
            self._idle = deque(w for w in self._idle
                               if w.state == "idle" and w.sock is not None)
            return None

    def _maybe_spawn_worker(self, tpu: bool = False, count: int = 1):
        """Spawn up to ``count`` workers toward the pending backlog.

        Worker startup cost is dominated by the child's imports, which
        parallelize across processes — so an actor-creation burst (128
        actors = 128 workers) spawns in batches instead of one per
        dispatch wake (the round-4 probe measured 2 actors/s precisely
        because of that serialization).  ``_starting`` still bounds the
        in-flight forks so a tight dispatch loop cannot fork-bomb.
        """
        spawn = 0
        with self._lock:
            max_concurrent_starts = GLOBAL_CONFIG.worker_max_concurrent_starts
            max_workers = int(self.resources_total.get("CPU", 1)) + 64
            while (spawn < count
                   and self._starting + spawn < max_concurrent_starts
                   and (len(self._workers) + self._starting + spawn
                        < max_workers)):
                spawn += 1
            self._starting += spawn
        for _ in range(spawn):
            self._spawn_worker(tpu)

    def _worker_env(self, worker_id: bytes, tpu: bool) -> Dict[str, str]:
        env = dict(os.environ)
        if not tpu:
            # CPU workers skip the TPU runtime entirely: drop any site hook
            # that pre-imports jax/claims chips, and pin jax (if a task
            # imports it) to the host platform.  This makes worker startup
            # ~10x faster and keeps the node's TPU chips free for workers
            # that actually request the TPU resource.
            parts = [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
                     if p and "axon" not in p]
            repo_root = os.path.dirname(os.path.dirname(
                os.path.dirname(os.path.abspath(__file__))))
            if repo_root not in parts:
                parts.append(repo_root)
            env["PYTHONPATH"] = os.pathsep.join(parts)
            env["JAX_PLATFORMS"] = "cpu"
        env.update({
            "RAY_TPU_SESSION_DIR": self.session_dir,
            "RAY_TPU_CP_SOCK": self.cp_sock_path,
            "RAY_TPU_NM_SOCK": self.sock_path,
            "RAY_TPU_WORKER_ID": worker_id.hex(),
            "RAY_TPU_NODE_ID": self.node_id.hex(),
            "RAY_TPU_SHM_ROOT": self.store.root,
            "RAY_TPU_SPILL_DIR": self.store.spill_dir or "",
            "RAY_TPU_LOG_TO_DRIVER":
                "1" if GLOBAL_CONFIG.log_to_driver else "0",
        })
        return env

    def _forksrv_sock_path(self) -> str:
        return os.path.join(
            self.session_dir, "sockets",
            f"forksrv_{self.node_id.hex()[:12]}.sock")

    def _launch_forkserver_proc(self) -> None:
        """Start the template process WITHOUT waiting for it.

        Called at NM boot so the template's import cost overlaps with
        cluster setup instead of landing inside the first actor/task
        spawn burst (on a 1-core host, N nodes lazily booting N
        templates serializes ~N x seconds into the creation window)."""
        sock_path = self._forksrv_sock_path()
        if self._forksrv_proc is not None and \
                self._forksrv_proc.poll() is None:
            return
        env = self._worker_env(b"\0" * 16, tpu=False)
        env["RAY_TPU_FORKSRV_SOCK"] = sock_path
        os.makedirs(os.path.dirname(sock_path), exist_ok=True)
        log_dir = os.path.join(self.session_dir, "logs")
        os.makedirs(log_dir, exist_ok=True)
        out = open(os.path.join(log_dir, "forkserver.log"), "ab")
        self._forksrv_proc = subprocess.Popen(
            [sys.executable, "-m",
             "ray_tpu._private.worker_forkserver"],
            env=env, stdout=out, stderr=subprocess.STDOUT)
        out.close()

    def _ensure_forkserver(self) -> Optional[protocol.RpcClient]:
        """Start (once) and connect to the pre-warmed worker forkserver.

        Returns the connected socket wrapper, or None if the template
        is unavailable (caller falls back to cold spawn)."""
        with self._forksrv_lock:
            if self._forksrv_sock is not None:
                return self._forksrv_sock
            if self._forksrv_failed:
                return None
            sock_path = self._forksrv_sock_path()
            if self._forksrv_proc is None or \
                    self._forksrv_proc.poll() is not None:
                self._launch_forkserver_proc()
            deadline = time.time() + 30.0
            while time.time() < deadline:
                try:
                    s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
                    s.connect(sock_path)
                    self._forksrv_sock = s
                    return s
                except (FileNotFoundError, ConnectionRefusedError, OSError):
                    if self._forksrv_proc.poll() is not None:
                        break
                    time.sleep(0.05)
            self._forksrv_failed = True
            return None

    def _fork_worker(self, worker_id: bytes, env: Dict[str, str],
                     log_path: str) -> "Optional[tuple]":
        """Ask the forkserver for a worker; returns (pid, start_time)
        or None (caller falls back to cold spawn)."""
        from ray_tpu._private import worker_forkserver as fsrv
        sock = self._ensure_forkserver()
        if sock is None:
            return None
        # only ship the vars the child must override; the template
        # already inherited the rest of the NM environment
        child_env = {k: v for k, v in env.items()
                     if k.startswith("RAY_TPU_") or k == "JAX_PLATFORMS"}
        with self._forksrv_lock:
            try:
                fsrv._send_obj(sock, {"env": child_env,
                                      "log_path": log_path})
                reply = fsrv._recv_obj(sock)
                return reply["pid"], reply.get("start_time")
            except (EOFError, OSError, ConnectionResetError):
                try:
                    sock.close()
                except OSError:
                    pass
                self._forksrv_sock = None
                self._forksrv_failed = True
                return None

    def _spawn_worker(self, tpu: bool = False):
        worker_id = WorkerID.from_random().binary()
        env = self._worker_env(worker_id, tpu)
        log_dir = os.path.join(self.session_dir, "logs")
        os.makedirs(log_dir, exist_ok=True)
        log_path = os.path.join(
            log_dir, f"worker-{worker_id.hex()[:12]}.log")
        proc = None
        if not tpu:
            forked = self._fork_worker(worker_id, env, log_path)
            if forked is not None:
                proc = _ForkedProc(*forked)
        if proc is None:
            out = open(log_path, "ab")
            proc = subprocess.Popen(
                [sys.executable, "-m", "ray_tpu._private.worker_proc"],
                env=env, stdout=out, stderr=subprocess.STDOUT,
                start_new_session=False)
            out.close()
        with self._lock:
            # a forked worker can register its stream before we get here;
            # attach the proc handle to the existing entry in that case
            worker = self._workers.get(worker_id)
            if worker is None:
                worker = _Worker(worker_id, proc, tpu=tpu)
                self._workers[worker_id] = worker
            else:
                worker.proc = proc
                worker.tpu = tpu

    def _assign_chips(self, spec: TaskSpec,
                      worker: _Worker) -> Optional[List[int]]:
        n = int(spec.resources.get("TPU", 0))
        if n <= 0:
            return None
        with self._res_lock:
            if len(self._free_chips) < n:
                # TPU resource accounting said the task fits, so the chip
                # list must agree; a skew here would silently hand the task
                # fewer chips than it asked for.
                raise RuntimeError(
                    f"chip accounting skew: task {spec.name!r} needs {n} "
                    f"chips but only {len(self._free_chips)} are free")
            chips = self._free_chips[:n]
            del self._free_chips[:n]
        self._worker_chips[worker.worker_id] = chips
        return chips

    def _release_task_resources(self, spec: TaskSpec, worker: _Worker):
        with self._res_lock:
            res = dict(spec.resources)
            if worker.blocked:
                res.pop("CPU", None)
                worker.blocked = False
            release(self.resources_available, res)
            chips = self._worker_chips.pop(worker.worker_id, None)
            if chips:
                self._free_chips.extend(chips)

    # ------------------------------------------------------------------
    # Failure handling
    # ------------------------------------------------------------------
    def _on_worker_death(self, worker: _Worker):
        with self._lock:
            if worker.state == "dead":
                return
            prev_state = worker.state
            worker.state = "dead"
            self._workers.pop(worker.worker_id, None)
            spec = worker.current_task
            worker.current_task = None
            actor_id = worker.actor_id
        try:
            # drop the dead process's refcount contributions wholesale —
            # at the CP (ownerless refs) and at every owner NM the dead
            # worker may have flushed deltas to
            self.cp.purge_holder(worker.worker_id)
            self.purge_owned_holder(worker.worker_id)
            for info in self.cp.list_nodes():
                if (info.get("state") != "ALIVE"
                        or info["node_id"] == self.node_id):
                    continue
                try:
                    self._owner_peer(info["sock_path"]).call(
                        "purge_owned_holder", worker.worker_id)
                except (OSError, ConnectionError):
                    pass
        except Exception:  # noqa: BLE001
            pass
        if prev_state == "starting":
            with self._lock:
                self._starting = max(0, self._starting - 1)
        if spec is not None:
            self._release_task_resources(spec, worker)
            if actor_id is None and not spec.actor_creation:
                reason = ""
                if worker.oom_killed is not None:
                    reason = ("killed by the memory monitor: node "
                              f"memory usage {worker.oom_killed:.0%} "
                              "exceeded "
                              f"{GLOBAL_CONFIG.memory_usage_threshold:.0%}")
                self._maybe_retry(spec, reason)
        if actor_id is not None or (spec is not None and spec.actor_creation):
            aid = actor_id or spec.actor_id
            with self._lock:
                astate = self._actors.get(aid)
            if astate is not None:
                self._on_actor_worker_death(astate, "worker died",
                                            worker=worker)
        self._wake.set()

    def _maybe_retry(self, spec: TaskSpec, reason: str = ""):
        with self._lock:
            left = self._retries_left.get(spec.task_id, 0)
            if left > 0:
                self._retries_left[spec.task_id] = left - 1
                self._pending.append(spec)
                retried = True
            else:
                retried = False
        if retried:
            self.cp.add_task_event({"task_id": spec.task_id.hex(),
                                    "state": "RETRY",
                                    "node": self.node_id.hex()})
            self._wake.set()
        else:
            self._fail_task(spec, WorkerCrashedError(
                f"worker died while running task {spec.name}"
                + (f" ({reason})" if reason else "")))

    def _on_actor_worker_death(self, astate: _ActorState, reason: str,
                               from_msg: bool = False,
                               worker: Optional[_Worker] = None):
        spec = astate.creation_spec
        # Fail in-flight calls on the dead worker; they are not retried
        # (at-most-once actor semantics unless max_task_retries).
        dead_worker = worker or astate.worker
        inflight = []
        if dead_worker is not None:
            with self._lock:
                inflight = list(dead_worker.inflight_actor_tasks.values())
                dead_worker.inflight_actor_tasks.clear()
        can_restart = (spec.max_restarts == -1
                       or astate.restarts_used < spec.max_restarts)
        # reversed + appendleft keeps the original submission order at
        # the front of the queue (forward appendleft would reverse it)
        for t in reversed(inflight):
            if t.max_task_retries != 0 and can_restart:
                with self._lock:
                    astate.queued.appendleft(t)
            else:
                self._fail_task(t, ActorDiedError(
                    spec.actor_id.hex(), reason))
        with self._lock:
            astate.worker = None
            if can_restart:
                astate.state = "RESTARTING"
                astate.restarts_used += 1
                if spec.actor_creation:
                    self._pending.append(spec)
            else:
                astate.state = "DEAD"
                queued = list(astate.queued)
                astate.queued.clear()
        if can_restart:
            self.cp.update_actor(spec.actor_id, state="RESTARTING",
                                 num_restarts=astate.restarts_used)
            self._wake.set()
        else:
            if not from_msg:
                # creation object may still be pending a consumer: mark error
                self._fail_task(spec, ActorDiedError(spec.actor_id.hex(),
                                                     reason))
            for t in queued:
                self._fail_task(t, ActorDiedError(spec.actor_id.hex(),
                                                  reason))
            self.cp.update_actor(spec.actor_id, state="DEAD",
                                 death_reason=reason)

    def _fail_task(self, spec: TaskSpec, error: BaseException):
        """Commit error objects for every return so getters unblock."""
        from ray_tpu.exceptions import TaskError
        self._unpin_dependencies(spec)
        err = TaskError(error, format_remote_traceback(error),
                        spec.task_id.hex())
        data = serialization.dumps(err)
        for oid in spec.return_object_ids():
            if self.cp.get_location(oid) is None:
                self.cp.put_inline(oid, data, is_error=True,
                                   owner_addr=spec.owner_addr)
        self._fail_generator_stream(spec, data)
        self.cp.add_task_event({"task_id": spec.task_id.hex(),
                                "state": "FAILED",
                                "node": self.node_id.hex()})

    def _fail_generator_stream(self, spec: TaskSpec, error_data: bytes):
        """Terminate a dead generator stream so consumers unblock.

        Commits the error as the next stream item and seals the stream with
        a length marker (items live at return indices 1.., marker at
        GEN_LEN_INDEX — see CoreWorker generator protocol).
        """
        if not spec.is_generator:
            return
        from ray_tpu._private.ids import ObjectID, TaskID
        from ray_tpu._private.worker import GEN_LEN_INDEX
        tid = TaskID(spec.task_id)
        len_oid = ObjectID(
            spec.task_id + GEN_LEN_INDEX.to_bytes(4, "big")).binary()
        if self.cp.get_location(len_oid) is not None:
            return  # stream completed normally
        index = 0
        while self.cp.get_location(
                ObjectID.for_task_return(tid, index + 1).binary()) is not None:
            index += 1
        self.cp.put_inline(
            ObjectID.for_task_return(tid, index + 1).binary(),
            error_data, is_error=True)
        self.cp.put_inline(len_oid, serialization.dumps(index + 1))

    # ------------------------------------------------------------------
    def _peer_client(self, node_info: Dict[str, Any]) -> protocol.RpcClient:
        nid = node_info["node_id"]
        if isinstance(nid, str):
            nid = bytes.fromhex(nid)
        client = self._peers.get(nid)
        if client is None:
            client = protocol.RpcClient(node_info["sock_path"])
            self._peers[nid] = client
        return client

    # ------------------------------------------------------------------
    # Memory monitor + OOM worker-killing policy (reference:
    # common/memory_monitor.h node sampling thread +
    # raylet/worker_killing_policy.cc "newest retriable task first")
    # ------------------------------------------------------------------
    @staticmethod
    def _worker_rss(pid: int) -> int:
        try:
            with open(f"/proc/{pid}/statm") as f:
                pages = int(f.read().split()[1])
            return pages * os.sysconf("SC_PAGE_SIZE")
        except (OSError, ValueError, IndexError):
            return 0

    def _memory_usage(self) -> float:
        limit = GLOBAL_CONFIG.memory_monitor_limit_bytes
        if limit > 0:
            with self._lock:
                pids = [w.proc.pid for w in self._workers.values()
                        if w.proc is not None and w.state != "dead"]
            return sum(self._worker_rss(p) for p in pids) / limit
        try:
            total = avail = None
            with open("/proc/meminfo") as f:
                for line in f:
                    if line.startswith("MemTotal:"):
                        total = int(line.split()[1])
                    elif line.startswith("MemAvailable:"):
                        avail = int(line.split()[1])
            if total and avail is not None:
                return 1.0 - avail / total
        except OSError:
            pass
        return 0.0

    def _pick_oom_victim(self) -> Optional[_Worker]:
        """Newest retriable task first; actors are never chosen (their
        in-flight calls are not idempotent by default)."""
        with self._lock:
            cands = [w for w in self._workers.values()
                     if w.state == "busy" and w.current_task is not None
                     and w.proc is not None
                     and not w.current_task.actor_creation]
            if not cands:
                return None

            def key(w):
                retriable = self._retries_left.get(
                    w.current_task.task_id, 0) > 0
                return (retriable, getattr(w, "task_started_at", 0.0))

            return max(cands, key=key)

    def _memory_monitor_loop(self):
        period = GLOBAL_CONFIG.memory_monitor_refresh_ms / 1000.0
        threshold = GLOBAL_CONFIG.memory_usage_threshold
        while not self._stopped.wait(period):
            try:
                usage = self._memory_usage()
                if usage < threshold:
                    continue
                victim = self._pick_oom_victim()
                if victim is None:
                    continue
                spec = victim.current_task
                logger.warning(
                    "memory usage %.0f%% over threshold %.0f%%: OOM "
                    "policy killing worker %s (task %s)", usage * 100,
                    threshold * 100, victim.worker_id.hex()[:12],
                    spec.name if spec else "?")
                victim.oom_killed = usage
                if spec is not None:
                    self._cp_effect_or_defer(
                        lambda s=spec: self.cp.add_task_event(
                            {"task_id": s.task_id.hex(),
                             "state": "OOM_KILL",
                             "node": self.node_id.hex()}))
                victim.proc.kill()
                # let the worker-reader thread run the death handling
                # before re-sampling (the RSS drop takes a beat)
                time.sleep(period)
            except Exception:  # noqa: BLE001 — keep the monitor alive
                traceback.print_exc()

    def _cp_effect_or_defer(self, fn) -> None:
        """Run a control-plane side effect now; on an outage longer than
        _ResilientCP's window, queue it for heartbeat-loop retry instead
        of dropping it (a dropped result commit hangs the caller's get)."""
        try:
            fn()
        except self._CONN_ERRORS:
            logger.warning("control plane unreachable; deferring %s",
                           getattr(fn, "__name__", "cp effect"))
            with self._lock:
                self._deferred_cp.append(fn)

    def _drain_deferred_cp(self) -> None:
        with self._lock:
            if not self._deferred_cp:
                return
            pending, self._deferred_cp = self._deferred_cp, []
        survivors = []
        for fn in pending:
            try:
                fn()
            except self._CONN_ERRORS:
                survivors.append(fn)
            except Exception:  # noqa: BLE001 — effect itself is broken
                logger.exception("deferred control-plane effect failed")
        if survivors:
            with self._lock:
                self._deferred_cp = survivors + self._deferred_cp

    def _heartbeat_loop(self):
        period = GLOBAL_CONFIG.health_check_period_s
        while not self._stopped.wait(period):
            try:
                with self._res_lock:
                    avail = dict(self.resources_available)
                with self._lock:
                    # per-shape demand so the autoscaler can launch
                    # nodes that actually FIT the queue (reference:
                    # resource_demand_scheduler.py demand vector).
                    # _PendingQueues already buckets by shape, so this
                    # is O(#shapes), not O(backlog); dep-waiting tasks
                    # are folded in too (their resources are demand the
                    # moment the deps land)
                    shapes = dict(self._pending.shape_counts())
                    for spec in self._waiting.values():
                        key = tuple(sorted(spec.resources.items()))
                        shapes[key] = shapes.get(key, 0) + 1
                    load = {
                        "num_pending": len(self._pending)
                        + len(self._waiting),
                        "pending_shapes": [
                            {"resources": dict(k), "count": c}
                            for k, c in sorted(
                                shapes.items(), key=lambda kv: -kv[1]
                            )[:8]],
                    }
                self.cp.heartbeat_node(self.node_id, avail, load)
            except Exception:  # noqa: BLE001
                pass
            self._drain_deferred_cp()
            if (time.time() - self._last_owner_sweep
                    >= GLOBAL_CONFIG.object_gc_period_s):
                self._last_owner_sweep = time.time()
                try:
                    self._owner_sweep()
                except Exception:  # noqa: BLE001
                    pass

    def stop(self):
        if self._stopped.is_set():
            return
        self._stopped.set()
        timer = getattr(self, "_forksrv_warm_timer", None)
        if timer is not None:
            timer.cancel()
        self._wake.set()
        with self._lock:
            workers = list(self._workers.values())
        for w in workers:
            w.send(_EXIT_SENTINEL)
        deadline = time.time() + 2.0
        for w in workers:
            if w.proc is not None:
                try:
                    w.proc.wait(timeout=max(0.05, deadline - time.time()))
                except subprocess.TimeoutExpired:
                    w.proc.terminate()
                    try:
                        w.proc.wait(timeout=1.0)
                    except subprocess.TimeoutExpired:
                        w.proc.kill()
        with self._forksrv_lock:
            if self._forksrv_sock is not None:
                try:
                    self._forksrv_sock.close()
                except OSError:
                    pass
                self._forksrv_sock = None
            if self._forksrv_proc is not None:
                self._forksrv_proc.terminate()
                try:
                    self._forksrv_proc.wait(timeout=1.0)
                except subprocess.TimeoutExpired:
                    self._forksrv_proc.kill()
                self._forksrv_proc = None
        self._server.shutdown()
        # the caller destroys the shm store right after stop() returns
        # (node.py / node_proc.py): join the loops that touch it so an
        # in-flight owner sweep can't call into a detached native arena
        cur = threading.current_thread()
        for t in (self._hb_thread, self._dispatch_thread,
                  self._dep_thread):
            if t is not cur and t.is_alive():
                t.join(timeout=5.0)
