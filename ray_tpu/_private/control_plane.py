"""Control plane — the cluster metadata authority.

TPU-native equivalent of the reference's GCS server
(``src/ray/gcs/gcs_server/gcs_server.cc``): internal KV, node table +
health, actor directory (incl. named actors), object directory + inline
memory store, placement-group table, pubsub, and task events.  One instance
lives in the head process and is served both in-process (the driver calls
methods directly) and over a unix socket (workers and extra node managers
use :class:`ray_tpu._private.protocol.RpcClient`).

Design departures from the reference, on purpose:
- storage is in-memory python structures guarded by one lock per table —
  persistence/failover (Redis-equivalent) is a later-round concern;
- object *data* for small objects lives here (the reference keeps small
  objects in the owner's in-process store; centralizing them gives every
  process cheap access on one host, and the shm store handles the rest).
"""

from __future__ import annotations

import os
import threading
import time
from collections import OrderedDict, defaultdict
from typing import Any, Dict, List, Optional, Tuple

ACTOR_STATES = ("PENDING", "ALIVE", "RESTARTING", "DEAD")


_WILDCARD = object()   # fired marker for notify_all


class _Waiter:
    __slots__ = ("event", "fired", "lock")

    def __init__(self):
        self.event = threading.Event()
        self.fired = set()
        self.lock = threading.Lock()

    def take_fired(self) -> set:
        # clear-then-swap under the lock: a notify that lands after the
        # swap re-sets the event, so the next wait wakes immediately
        # (clearing after the swap could strand a fired key behind a
        # cleared event for a full poll interval)
        with self.lock:
            self.event.clear()
            fired, self.fired = self.fired, set()
        return fired


class _Waiters:
    """Per-key waiter registry.

    The previous design was one condition variable per table:
    every object commit woke every blocked ``get()``/``wait()`` in the
    process and each re-ran its full predicate — an O(waiters x events)
    wakeup storm at 10k+ queued tasks.  Here waiters register the exact
    keys they care about (object ids, actor ids, channels); an event
    wakes only the waiters registered on its key and tells them *which*
    keys fired, so e.g. a 10k-ref ``wait`` re-checks only fired ids
    (reference analogue: per-object waiter lists in
    ``raylet/wait_manager.cc``).
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._by_key: Dict[Any, set] = {}

    def register(self, keys) -> _Waiter:
        w = _Waiter()
        with self._lock:
            for k in keys:
                self._by_key.setdefault(k, set()).add(w)
        return w

    def unregister(self, keys, w: _Waiter) -> None:
        with self._lock:
            for k in keys:
                s = self._by_key.get(k)
                if s is not None:
                    s.discard(w)
                    if not s:
                        del self._by_key[k]

    def notify(self, keys) -> None:
        hit = []
        with self._lock:
            for k in keys:
                s = self._by_key.get(k)
                if s:
                    for w in s:
                        hit.append((w, k))
        for w, k in hit:
            with w.lock:
                w.fired.add(k)
            w.event.set()

    def notify_all(self) -> None:
        with self._lock:
            waiters = {w for s in self._by_key.values() for w in s}
        for w in waiters:
            with w.lock:
                w.fired.add(_WILDCARD)
            w.event.set()

    def wait_for(self, predicate, timeout: Optional[float], keys):
        """Re-evaluate ``predicate`` when any of ``keys`` fires."""
        deadline = None if timeout is None else time.monotonic() + timeout
        w = self.register(keys)
        try:
            while True:
                value = predicate()
                if value is not None:
                    return value
                remaining = None
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return None
                w.event.wait(remaining if remaining is not None else 1.0)
                w.take_fired()
        finally:
            self.unregister(keys, w)



def _free_trace(kind, oids, cp=None):
    from ray_tpu._private.debug_trace import enabled, trace
    if not enabled("RAY_TPU_DEBUG_FREE"):
        return
    trace(f"free cp={id(cp)}", kind, [o.hex() for o in oids],
          var="RAY_TPU_DEBUG_FREE", stack=6)


class ControlPlane:
    def __init__(self, journal=None):
        self._lock = threading.RLock()
        # persistence: append-only journal of durable mutations
        # (``persistence.py``; reference: redis_store_client.cc).  Set
        # after construction via attach_journal() when restoring.
        self._journal = journal
        self._replaying = False
        # internal KV (function table, runtime metadata, user internal_kv)
        self._kv: Dict[Tuple[str, bytes], bytes] = {}
        # object directory: id -> location dict
        #   {"where": "inline"} | {"where": "shm", "size": int}
        #   plus "error": bool when the stored value is a wrapped TaskError
        self._objects: Dict[bytes, Dict[str, Any]] = {}
        self._inline_data: Dict[bytes, bytes] = {}
        self._object_waiters = _Waiters()
        # pending resolver kicks that arrived with no wait registered
        # (consumed by the next wait_any(kick=key); see kick_waiters)
        self._sticky_kicks: set = set()
        # broadcast chains: object -> ordered list of puller nodes
        self._bcast_chains: Dict[bytes, List[bytes]] = {}
        # actors
        self._actors: Dict[bytes, Dict[str, Any]] = {}
        self._named_actors: Dict[Tuple[str, str], bytes] = {}
        self._actor_waiters = _Waiters()
        # nodes
        self._nodes: Dict[bytes, Dict[str, Any]] = {}
        # placement groups
        self._placement_groups: Dict[bytes, Dict[str, Any]] = {}
        self._pg_waiters = _Waiters()
        # pubsub: channel -> (seq, messages ring)
        self._channels: Dict[str, List[Tuple[int, Any]]] = defaultdict(list)
        self._channel_seq: Dict[str, int] = defaultdict(int)
        self._pub_waiters = _Waiters()
        # reference counting FALLBACK for ownerless refs (generator
        # items, internal ids): per-holder counts + aggregate; an object
        # is freeable once its aggregate sits at zero past the grace
        # period.  Owner-governed objects (commit carries owner_addr)
        # are counted and freed by their owner node manager — the CP
        # keeps only the directory entry (reference split:
        # core_worker/reference_count.cc owns counts,
        # ownership_based_object_directory.cc serves locations).
        self._refs_by_holder: Dict[bytes, Dict[bytes, int]] = defaultdict(
            lambda: defaultdict(int))
        self._ref_totals: Dict[bytes, int] = defaultdict(int)
        self._zero_since: Dict[bytes, float] = {}
        # holder -> node hosting it, so a whole-node death can purge
        # every holder that died with it (their NM can't)
        self._holder_node: Dict[bytes, bytes] = {}
        # objects whose owner node died, freed after a grace; bounded
        # ring so late get()s raise OwnerDiedError instead of hanging
        self._owner_died_tombstones: "OrderedDict[bytes, bool]" = (
            OrderedDict())
        # lineage: task_id -> TaskSpec for re-execution of lost objects
        # (reference: task_manager.cc lineage + object_recovery_manager)
        self._lineage: Dict[bytes, Any] = {}
        self._lineage_cap = 20000
        # task events ring buffer
        self._task_events: List[Dict[str, Any]] = []
        self._task_events_cap = 65536
        # errors pushed to drivers
        # int-valued until a float increment arrives (user metrics)
        self._counters: Dict[str, float] = defaultdict(int)
        self.start_time = time.time()

    # ----------------------------------------------------- persistence ----
    def _j(self, op: str, *args) -> None:
        if self._journal is not None and not self._replaying:
            self._journal.append(op, args)

    def attach_journal(self, journal) -> None:
        self._journal = journal

    def dump_state(self) -> Dict[str, Any]:
        """Durable tables only (snapshot compaction payload)."""
        with self._lock:
            return {
                "kv": dict(self._kv),
                "objects": {k: dict(v) for k, v in self._objects.items()},
                "inline_data": dict(self._inline_data),
                "actors": {k: dict(v) for k, v in self._actors.items()},
                "named_actors": dict(self._named_actors),
                "nodes": {k: dict(v) for k, v in self._nodes.items()},
                "placement_groups": {
                    k: dict(v) for k, v in self._placement_groups.items()},
            }

    def load_state(self, state: Dict[str, Any]) -> None:
        with self._lock:
            self._kv = dict(state.get("kv", {}))
            self._objects = {k: dict(v) for k, v in
                             state.get("objects", {}).items()}
            self._inline_data = dict(state.get("inline_data", {}))
            self._actors = {k: dict(v) for k, v in
                            state.get("actors", {}).items()}
            self._named_actors = dict(state.get("named_actors", {}))
            self._nodes = {k: dict(v) for k, v in
                           state.get("nodes", {}).items()}
            self._placement_groups = {
                k: dict(v) for k, v in
                state.get("placement_groups", {}).items()}

    def post_restore(self) -> None:
        """Fixups after replay: give restored nodes one fresh heartbeat
        window to reconnect (survivors re-heartbeat within 1s over the
        rebound socket; the death watcher reaps the rest).

        The *previous head's* node entry is marked DEAD immediately: the
        restarted head re-registers its own fresh entry, and leaving two
        ALIVE nodes advertising ``node:__internal_head__`` lets
        ``init(address='auto')`` attach to the dead one."""
        now = time.time()
        with self._lock:
            for info in self._nodes.values():
                if info.get("state") != "ALIVE":
                    continue
                if "node:__internal_head__" in (
                        info.get("resources_total") or {}):
                    info["state"] = "DEAD"
                    info["death_reason"] = "head restarted"
                else:
                    info["last_heartbeat"] = now
        self._object_waiters.notify_all()
        self._actor_waiters.notify_all()
        self._pg_waiters.notify_all()

    def compact_journal(self) -> bool:
        """Snapshot-compact now. Holds the CP lock across dump+swap so a
        mutation can't append to the old file after the snapshot was
        taken (that record would vanish in the swap)."""
        j = self._journal
        if j is None:
            return False
        with self._lock:
            j.compact(self.dump_state())
        return True

    def maybe_compact(self, threshold: int = 100_000) -> bool:
        j = self._journal
        if j is None or j._records_since_snapshot < threshold:
            return False
        return self.compact_journal()

    # ------------------------------------------------------------- KV ----
    def kv_put(self, key: bytes, value: bytes, overwrite: bool = True,
               namespace: str = "default") -> bool:
        with self._lock:
            k = (namespace, bytes(key))
            if not overwrite and k in self._kv:
                return False
            self._kv[k] = bytes(value)
            self._j("kv_put", bytes(key), bytes(value), overwrite, namespace)
            return True

    def kv_get(self, key: bytes, namespace: str = "default") -> Optional[bytes]:
        with self._lock:
            return self._kv.get((namespace, bytes(key)))

    def kv_del(self, key: bytes, namespace: str = "default") -> bool:
        with self._lock:
            hit = self._kv.pop((namespace, bytes(key)), None) is not None
            if hit:
                self._j("kv_del", bytes(key), namespace)
            return hit

    def kv_exists(self, key: bytes, namespace: str = "default") -> bool:
        with self._lock:
            return (namespace, bytes(key)) in self._kv

    def kv_keys(self, prefix: bytes = b"",
                namespace: str = "default") -> List[bytes]:
        with self._lock:
            return [k for (ns, k) in self._kv
                    if ns == namespace and k.startswith(prefix)]

    # --------------------------------------------------------- objects ----
    def put_inline(self, object_id: bytes, data: bytes,
                   is_error: bool = False, owner: bytes = b"",
                   owner_addr: str = "") -> None:
        _free_trace(f"put_inline err={is_error}", [object_id], self)
        with self._lock:
            self._inline_data[object_id] = data
            self._objects[object_id] = {
                "where": "inline", "size": len(data), "error": is_error,
                "owner": owner, "owner_addr": owner_addr,
                "commit_time": time.time(),
            }
            self._j("put_inline", object_id, data, is_error, owner,
                    owner_addr)
        self._object_waiters.notify([object_id])

    def commit_shm(self, object_id: bytes, size: int,
                   node_id: bytes = b"", is_error: bool = False,
                   owner: bytes = b"", owner_addr: str = "") -> None:
        with self._lock:
            self._objects[object_id] = {
                "where": "shm", "size": size, "node": node_id,
                "error": is_error, "owner": owner,
                "owner_addr": owner_addr,
                "commit_time": time.time(),
            }
            self._j("commit_shm", object_id, size, node_id, is_error,
                    owner, owner_addr)
        self._object_waiters.notify([object_id])

    def get_location(self, object_id: bytes) -> Optional[Dict[str, Any]]:
        with self._lock:
            loc = self._objects.get(object_id)
            if loc is None and object_id in self._owner_died_tombstones:
                return {"where": "tombstone", "owner_died": True}
            return dict(loc) if loc else None

    def get_inline(self, object_id: bytes) -> Optional[bytes]:
        with self._lock:
            return self._inline_data.get(object_id)

    def wait_object(self, object_id: bytes,
                    timeout: Optional[float]) -> Optional[Dict[str, Any]]:
        """Block until the object is committed; returns its location."""
        out = self._object_waiters.wait_for(
            lambda: self.get_location(object_id), timeout, [object_id])
        if out is None:
            from ray_tpu._private.debug_trace import enabled, trace
            if enabled("RAY_TPU_DEBUG_FREE"):
                with self._lock:
                    present = object_id in self._objects
                    n = len(self._objects)
                trace("wait_object TIMEOUT", f"oid={object_id.hex()}",
                      f"present={present} cp_id={id(self)} n={n}",
                      var="RAY_TPU_DEBUG_FREE")
        return out

    def wait_fetch(self, object_id: bytes, timeout: Optional[float]
                   ) -> Optional[Dict[str, Any]]:
        """wait_object + inline payload in ONE round trip — the
        small-object get() hot path (task/actor-call results) pays a
        single RPC instead of wait + location + fetch."""
        loc = self.wait_object(object_id, timeout)
        if loc is None:
            return None
        data = None
        if loc.get("where") == "inline":
            with self._lock:
                data = self._inline_data.get(bytes(object_id))
        return {"loc": loc, "data": data}

    def get_locations(self, object_ids: List[bytes]
                      ) -> Dict[bytes, Optional[Dict[str, Any]]]:
        """Bulk location lookup (one RPC for a whole dependency set)."""
        def loc(o: bytes):
            if o in self._objects:
                return dict(self._objects[o])
            if o in self._owner_died_tombstones:
                return {"where": "tombstone", "owner_died": True}
            return None

        with self._lock:
            return {bytes(o): loc(bytes(o)) for o in object_ids}

    # ---------------------------------------------------- broadcast -----
    def join_broadcast(self, object_id: bytes,
                       node_id: bytes) -> Optional[Dict[str, Any]]:
        """Register ``node_id`` as a puller of ``object_id`` and return
        the node it should chain from (None = pull from the primary).

        Chain-push broadcast (reference: ``object_manager/
        push_manager.cc`` / the 1-GiB-to-many envelope): instead of N
        pullers hammering the one source, each puller chains off the
        previous one, re-serving chunks as they land — aggregate
        bandwidth scales with the number of links, and the source
        serves exactly one stream."""
        object_id, node_id = bytes(object_id), bytes(node_id)
        with self._lock:
            chain = self._bcast_chains.setdefault(object_id, [])
            parent = None
            for n in reversed(chain):
                if n == node_id:
                    continue
                info = self._nodes.get(n)
                if info is not None and info.get("state") == "ALIVE":
                    parent = {"node_id": n,
                              "sock_path": info["sock_path"]}
                    break
            if node_id not in chain:
                chain.append(node_id)
            return parent

    def leave_broadcast(self, object_id: bytes, node_id: bytes) -> None:
        """Drop a failed puller so later joiners don't chain off it."""
        with self._lock:
            chain = self._bcast_chains.get(bytes(object_id))
            if chain is not None:
                try:
                    chain.remove(bytes(node_id))
                except ValueError:
                    pass

    def kick_waiters(self, key: bytes) -> None:
        """Wake a ``wait_any(..., kick=key)`` blocked on stale ids.

        Node managers use this to interrupt their dependency-resolver's
        standing wait when newly submitted tasks add ids to the set.
        The kick is *sticky*: if no wait is registered when it lands, the
        next ``wait_any(kick=key)`` consumes it on entry and returns
        immediately, so a kick can never be lost to the race between the
        caller's RPC and the resolver's waiter registration."""
        key = bytes(key)
        with self._lock:
            self._sticky_kicks.add(key)
        self._object_waiters.notify([("__kick__", key)])

    def wait_any(self, object_ids: List[bytes], num_returns: int,
                 timeout: Optional[float],
                 kick: Optional[bytes] = None) -> List[bytes]:
        """Return ids of committed objects once >= num_returns are ready.

        Incremental: the id set is scanned once, then only ids whose
        commit actually fired are checked — a 10k-ref wait does O(ids +
        commits) work instead of O(ids x wakeups).  With ``kick``, a
        ``kick_waiters(kick)`` call returns the currently ready subset
        early (possibly short of ``num_returns``).
        """
        ids = [bytes(o) for o in object_ids]
        kick_key = ("__kick__", bytes(kick)) if kick is not None else None
        keys = list(ids) + ([kick_key] if kick_key else [])
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        w = self._object_waiters.register(keys)
        try:
            kicked = False
            with self._lock:
                # tombstoned (owner-died, already freed) ids count as
                # ready: the subsequent get() raises OwnerDiedError
                # instead of the wait hanging forever
                done = [o for o in ids if o in self._objects
                        or o in self._owner_died_tombstones]
                if kick is not None and bytes(kick) in self._sticky_kicks:
                    self._sticky_kicks.discard(bytes(kick))
                    kicked = True
            remaining = set(ids) - set(done)
            while not kicked and len(done) < num_returns and remaining:
                wait_t = 1.0
                if deadline is not None:
                    wait_t = deadline - time.monotonic()
                    if wait_t <= 0:
                        break
                w.event.wait(min(wait_t, 5.0))
                fired = w.take_fired()
                if not fired and deadline is None:
                    continue
                if _WILDCARD in fired:
                    check = list(remaining)
                else:
                    check = [o for o in fired if o in remaining]
                if check:
                    with self._lock:
                        newly = [o for o in check if o in self._objects
                                 or o in self._owner_died_tombstones]
                    done.extend(newly)
                    remaining.difference_update(newly)
                if kick_key is not None and kick_key in fired:
                    with self._lock:
                        self._sticky_kicks.discard(bytes(kick))
                    break
            return done
        finally:
            self._object_waiters.unregister(keys, w)

    def free_objects(self, object_ids: List[bytes]) -> int:
        freed = 0
        with self._lock:
            for o in object_ids:
                o = bytes(o)
                if o in self._objects:
                    self._objects.pop(o, None)
                    self._inline_data.pop(o, None)
                    self._bcast_chains.pop(o, None)
                    freed += 1
            if freed:
                self._j("free_objects", [bytes(o) for o in object_ids])
        if freed:
            _free_trace("free_objects", [bytes(o) for o in object_ids])
        return freed

    def free_owned(self, object_ids: List[bytes]) -> Dict[str, List[bytes]]:
        """Drop directory entries for objects freed by their OWNER node
        manager (the owner holds the refcounts; the CP is only the
        directory).  Ids not committed yet are returned as ``pending``
        so the owner keeps them on its zero list."""
        freed: List[bytes] = []
        pending: List[bytes] = []
        with self._lock:
            for o in object_ids:
                o = bytes(o)
                if o in self._objects:
                    self._objects.pop(o, None)
                    self._inline_data.pop(o, None)
                    freed.append(o)
                else:
                    pending.append(o)
            if freed:
                self._j("free_objects", freed)
        if freed:
            _free_trace("free_owned", freed)
        return {"freed": freed, "pending": pending}

    # ------------------------------------------------ refcounting / GC ----
    def update_refs(self, holder_id: bytes, deltas: Dict[bytes, int],
                    holder_node: bytes = b"") -> None:
        now = time.time()
        with self._lock:
            if holder_node:
                self._holder_node[holder_id] = holder_node
            held = self._refs_by_holder[holder_id]
            for oid, d in deltas.items():
                oid = bytes(oid)
                held[oid] += d
                if held[oid] == 0:
                    held.pop(oid)
                total = self._ref_totals[oid] + d
                if total > 0:
                    self._ref_totals[oid] = total
                    self._zero_since.pop(oid, None)
                else:
                    # total <= 0: d == 0 (ref born and dropped within one
                    # flush window) or a negative delta against untracked
                    # state (e.g. a survivor dropping a ref the restored
                    # head never saw) — either way the object is now
                    # unreferenced
                    self._ref_totals.pop(oid, None)
                    self._zero_since.setdefault(oid, now)
            if not held:
                self._refs_by_holder.pop(holder_id, None)

    def purge_holder(self, holder_id: bytes) -> None:
        """Drop every count contributed by a dead holder (worker/pin)."""
        with self._lock:
            held = self._refs_by_holder.pop(holder_id, None)
            self._holder_node.pop(holder_id, None)
        if held:
            # re-apply as negative deltas under a synthetic holder so the
            # totals/zero bookkeeping stays in one code path
            self.update_refs(b"_purge", {o: -d for o, d in held.items()})
            with self._lock:
                self._refs_by_holder.pop(b"_purge", None)

    def purge_node_holders(self, node_id: bytes) -> None:
        """Drop the contributions of every holder (worker/driver) that
        lived on a dead node — its NM died with it and can never send
        the per-worker purge itself."""
        with self._lock:
            victims = [h for h, n in self._holder_node.items()
                       if n == node_id]
        for h in victims:
            self.purge_holder(h)

    def gc_sweep(self, grace_s: float = 2.0) -> List[bytes]:
        """Free committed objects unreferenced for longer than the grace.

        Only objects that were tracked at least once are eligible — bare
        commits without any ObjectRef holder (e.g. generator items not yet
        iterated) are left alone.  Returns the freed ids so the caller can
        fan out shm deletions to node stores.
        """
        cutoff = time.time() - grace_s
        with self._lock:
            victims = []
            for oid, t0 in self._zero_since.items():
                if t0 >= cutoff:
                    continue
                info = self._objects.get(oid)
                if info is None:
                    continue
                # owner-governed objects are freed by their owner NM,
                # not here — a stray CP-side zero mark (e.g. a transient
                # bare ref) must not free an object with live owner-side
                # refs.  Owner death turns governance back to the CP.
                if info.get("owner_addr") and not info.get("owner_died"):
                    continue
                victims.append(oid)
            for oid in victims:
                info = self._objects.pop(oid, None)
                self._inline_data.pop(oid, None)
                self._zero_since.pop(oid, None)
                if info is not None and info.get("owner_died"):
                    self._owner_died_tombstones[oid] = True
                    while len(self._owner_died_tombstones) > 10000:
                        self._owner_died_tombstones.popitem(last=False)
            if victims:
                self._j("free_objects", victims)
                _free_trace("gc_sweep", victims)
            # forget zero-marks for ids that were never committed
            stale = [oid for oid, t0 in self._zero_since.items()
                     if t0 < cutoff - 60.0]
            for oid in stale:
                self._zero_since.pop(oid, None)
        return victims

    def refs_summary(self) -> Dict[str, int]:
        with self._lock:
            return {"tracked_objects": len(self._ref_totals),
                    "holders": len(self._refs_by_holder),
                    "zero_pending": len(self._zero_since)}

    # --------------------------------------------------------- lineage ----
    def add_lineage(self, task_id: bytes, spec: Any) -> None:
        with self._lock:
            self._lineage[bytes(task_id)] = spec
            while len(self._lineage) > self._lineage_cap:
                self._lineage.pop(next(iter(self._lineage)))

    def get_lineage(self, task_id: bytes) -> Optional[Any]:
        with self._lock:
            return self._lineage.get(bytes(task_id))

    def objects_summary(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "count": len(self._objects),
                "inline_bytes": sum(len(v) for v in self._inline_data.values()),
            }

    def list_objects(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [dict(loc, object_id=oid.hex())
                    for oid, loc in self._objects.items()]

    # ---------------------------------------------------------- actors ----
    def register_actor(self, actor_id: bytes, info: Dict[str, Any]) -> None:
        with self._lock:
            name = info.get("name")
            ns = info.get("namespace", "default")
            if name:
                existing = self._named_actors.get((ns, name))
                if existing is not None:
                    state = self._actors.get(existing, {}).get("state")
                    if state not in (None, "DEAD"):
                        raise ValueError(
                            f"Actor name '{name}' already taken in "
                            f"namespace '{ns}'")
                self._named_actors[(ns, name)] = actor_id
            info = dict(info)
            info.setdefault("state", "PENDING")
            info.setdefault("num_restarts", 0)
            info["actor_id"] = actor_id
            self._actors[actor_id] = info
            self._j("register_actor", actor_id, info)
        self._actor_waiters.notify([actor_id])

    def update_actor(self, actor_id: bytes, **updates) -> None:
        with self._lock:
            info = self._actors.get(actor_id)
            if info is None:
                return
            info.update(updates)
            if updates.get("state") == "DEAD" and info.get("name"):
                self._named_actors.pop(
                    (info.get("namespace", "default"), info["name"]), None)
            self._j("update_actor", actor_id, updates)
        self._actor_waiters.notify([actor_id])
        self.publish(f"actor:{actor_id.hex()}", updates)

    def get_actor_info(self, actor_id: bytes) -> Optional[Dict[str, Any]]:
        with self._lock:
            info = self._actors.get(actor_id)
            return dict(info) if info else None

    def wait_actor_state(self, actor_id: bytes, states: Tuple[str, ...],
                         timeout: Optional[float]) -> Optional[Dict[str, Any]]:
        def check():
            info = self.get_actor_info(actor_id)
            if info and info.get("state") in states:
                return info
            return None
        return self._actor_waiters.wait_for(check, timeout, [actor_id])

    def resolve_named_actor(self, name: str,
                            namespace: str = "default") -> Optional[bytes]:
        with self._lock:
            return self._named_actors.get((namespace, name))

    def list_actors(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [dict(v) for v in self._actors.values()]

    def list_named_actors(self, all_namespaces: bool = False,
                          namespace: str = "default") -> List[Any]:
        with self._lock:
            if all_namespaces:
                return [{"namespace": ns, "name": n}
                        for (ns, n) in self._named_actors]
            return [n for (ns, n) in self._named_actors if ns == namespace]

    # ----------------------------------------------------------- nodes ----
    def register_node(self, node_id: bytes, info: Dict[str, Any]) -> None:
        with self._lock:
            info = dict(info)
            info["node_id"] = node_id
            info.setdefault("state", "ALIVE")
            info["last_heartbeat"] = time.time()
            self._nodes[node_id] = info
            self._j("register_node", node_id, info)
        self.publish("nodes", {"event": "register", "node_id": node_id.hex()})

    def heartbeat_node(self, node_id: bytes,
                       resources_available: Optional[Dict] = None,
                       load: Optional[Dict] = None) -> None:
        with self._lock:
            info = self._nodes.get(node_id)
            if info is None:
                return
            info["last_heartbeat"] = time.time()
            if resources_available is not None:
                info["resources_available"] = resources_available
            if load is not None:
                info["load"] = load

    def mark_node_dead(self, node_id: bytes, reason: str = "") -> None:
        with self._lock:
            info = self._nodes.get(node_id)
            if info is None:
                return
            info["state"] = "DEAD"
            info["death_reason"] = reason
            self._j("mark_node_dead", node_id, reason)
            # Objects OWNED by the dead node lose their refcounter:
            # mark them owner_died (get() raises OwnerDiedError or
            # recovers via lineage) and hand lifetime back to the CP
            # sweep, which frees them after the grace and leaves a
            # tombstone (reference: owner fate-sharing,
            # core_worker/reference_count.cc OwnerDied).
            dead_addr = info.get("sock_path")
            if dead_addr:
                now = time.time()
                for oid, entry in self._objects.items():
                    if entry.get("owner_addr") == dead_addr:
                        entry["owner_died"] = True
                        self._zero_since.setdefault(oid, now)
        self.publish("nodes", {"event": "dead", "node_id": node_id.hex()})

    def list_nodes(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [dict(v) for v in self._nodes.values()]

    def get_node(self, node_id: bytes) -> Optional[Dict[str, Any]]:
        with self._lock:
            info = self._nodes.get(node_id)
            return dict(info) if info else None

    # ------------------------------------------------- placement groups ----
    def register_placement_group(self, pg_id: bytes,
                                 info: Dict[str, Any]) -> None:
        with self._lock:
            info = dict(info)
            info["pg_id"] = pg_id
            info.setdefault("state", "PENDING")
            self._placement_groups[pg_id] = info
            self._j("register_placement_group", pg_id, info)
        self._pg_waiters.notify([pg_id])

    def update_placement_group(self, pg_id: bytes, **updates) -> None:
        with self._lock:
            info = self._placement_groups.get(pg_id)
            if info is None:
                return
            info.update(updates)
            self._j("update_placement_group", pg_id, updates)
        self._pg_waiters.notify([pg_id])

    def get_placement_group(self, pg_id: bytes) -> Optional[Dict[str, Any]]:
        with self._lock:
            info = self._placement_groups.get(pg_id)
            return dict(info) if info else None

    def wait_placement_group(self, pg_id: bytes,
                             timeout: Optional[float]) -> Optional[Dict]:
        def check():
            info = self.get_placement_group(pg_id)
            if info and info.get("state") in ("CREATED", "REMOVED"):
                return info
            return None
        return self._pg_waiters.wait_for(check, timeout, [pg_id])

    def list_placement_groups(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [dict(v) for v in self._placement_groups.values()]

    # ---------------------------------------------------------- pubsub ----
    def publish(self, channel: str, message: Any) -> int:
        with self._lock:
            self._channel_seq[channel] += 1
            seq = self._channel_seq[channel]
            ring = self._channels[channel]
            ring.append((seq, message))
            if len(ring) > 4096:
                del ring[: len(ring) - 4096]
        self._pub_waiters.notify([channel])
        return seq

    def poll(self, channel: str, cursor: int,
             timeout: Optional[float]) -> Tuple[int, List[Any]]:
        """Long-poll messages with seq > cursor."""
        def fetch():
            with self._lock:
                msgs = [(s, m) for (s, m) in self._channels.get(channel, [])
                        if s > cursor]
            if msgs:
                return msgs
            return None
        msgs = self._pub_waiters.wait_for(fetch, timeout, [channel])
        if not msgs:
            return cursor, []
        new_cursor = max(s for s, _ in msgs)
        return new_cursor, [m for _, m in msgs]

    # ------------------------------------------------------ task events ----
    def add_task_event(self, event: Dict[str, Any]) -> None:
        with self._lock:
            event = dict(event)
            event.setdefault("time", time.time())
            self._task_events.append(event)
            if len(self._task_events) > self._task_events_cap:
                del self._task_events[: len(self._task_events)
                                      - self._task_events_cap]

    def list_task_events(self, limit: int = 10000) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._task_events[-limit:])

    def tasks_last_state(self) -> List[Dict[str, Any]]:
        """Latest event per task id (node-death recovery scans this)."""
        with self._lock:
            last: Dict[str, Dict[str, Any]] = {}
            for ev in self._task_events:
                tid = ev.get("task_id")
                if tid:
                    last[tid] = ev
            return list(last.values())

    # -------------------------------------------------------- counters ----
    def incr(self, name: str, amount: float = 1) -> float:
        """Accumulate a counter; float amounts accumulate exactly
        (user metrics count fractional quantities, e.g. seconds)."""
        with self._lock:
            self._counters[name] += amount
            return self._counters[name]

    def counters(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._counters)

    def ping(self) -> float:
        return time.time()
