"""Control-plane persistence: append-only journal + snapshot compaction.

TPU-native equivalent of the reference's GCS Redis persistence
(``src/ray/gcs/store_client/redis_store_client.cc``,
``gcs_init_data.cc`` rehydration): every durable control-plane mutation
is appended to a length-prefixed pickle log in the session directory; a
restarted head replays the log, rebinds the same sockets, and surviving
node managers / workers reconnect on their next call (the RPC client
reconnects per call — the ``NotifyGCSRestart`` flow of
``node_manager.proto:352`` falls out of the transport).

High-frequency ephemeral state (heartbeats, pubsub rings, task events,
refcounts) is deliberately NOT journaled — it regenerates within one
heartbeat period.

Format: ``[u32 length][pickle((op, args))]`` records.  A record whose op
is ``__snapshot__`` carries a full state dict and resets replay state
(compaction rewrites the log as one snapshot).  A truncated tail (crash
mid-write) is ignored.
"""

from __future__ import annotations

import os
import pickle
import struct
import threading
from typing import Any, Iterator, List, Tuple

_LEN = struct.Struct("<I")

SNAPSHOT_OP = "__snapshot__"


class Journal:
    """Append-only op log with atomic snapshot compaction."""

    def __init__(self, path: str, sync: bool = False):
        self.path = path
        self.sync = sync
        self._lock = threading.Lock()
        os.makedirs(os.path.dirname(path), exist_ok=True)
        # A crash mid-write leaves a torn tail record.  Replay stops at
        # it — so if we blindly append after it, everything appended now
        # sits *behind* the tear and silently vanishes from every future
        # replay.  Truncate to the last valid record boundary first.
        valid = self.scan_valid(path)
        if valid is not None and valid < os.path.getsize(path):
            with open(path, "r+b") as f:
                f.truncate(valid)
        self._f = open(path, "ab")
        self._records_since_snapshot = 0

    def append(self, op: str, args: Tuple[Any, ...]) -> None:
        payload = pickle.dumps((op, args), protocol=5)
        with self._lock:
            self._f.write(_LEN.pack(len(payload)) + payload)
            self._f.flush()
            if self.sync:
                os.fsync(self._f.fileno())
            self._records_since_snapshot += 1

    @staticmethod
    def scan_valid(path: str) -> "int | None":
        """Byte offset of the end of the last well-formed record."""
        if not os.path.exists(path):
            return None
        valid = 0
        with open(path, "rb") as f:
            while True:
                head = f.read(_LEN.size)
                if len(head) < _LEN.size:
                    return valid
                (length,) = _LEN.unpack(head)
                payload = f.read(length)
                if len(payload) < length:
                    return valid
                try:
                    pickle.loads(payload)
                except Exception:  # noqa: BLE001 — corrupt record ends log
                    return valid
                valid += _LEN.size + length

    @staticmethod
    def replay(path: str) -> Iterator[Tuple[str, Tuple[Any, ...]]]:
        """Yield records; stop silently at a truncated tail."""
        if not os.path.exists(path):
            return
        with open(path, "rb") as f:
            while True:
                head = f.read(_LEN.size)
                if len(head) < _LEN.size:
                    return
                (length,) = _LEN.unpack(head)
                payload = f.read(length)
                if len(payload) < length:
                    return
                try:
                    yield pickle.loads(payload)
                except Exception:  # noqa: BLE001 — corrupt record ends log
                    return

    def compact(self, state: Any) -> None:
        """Atomically replace the log with one snapshot record."""
        payload = pickle.dumps((SNAPSHOT_OP, (state,)), protocol=5)
        tmp = self.path + ".tmp"
        with self._lock:
            with open(tmp, "wb") as f:
                f.write(_LEN.pack(len(payload)) + payload)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.path)
            self._f.close()
            self._f = open(self.path, "ab")
            self._records_since_snapshot = 0

    def close(self) -> None:
        with self._lock:
            try:
                self._f.close()
            except OSError:
                pass


def restore_control_plane(cp, path: str) -> int:
    """Replay a journal into a fresh ControlPlane. Returns record count."""
    n = 0
    cp._replaying = True
    try:
        for op, args in Journal.replay(path):
            n += 1
            if op == SNAPSHOT_OP:
                cp.load_state(args[0])
                continue
            method = getattr(cp, op, None)
            if method is None:
                continue
            if op == "update_actor":
                actor_id, updates = args
                method(actor_id, **updates)
            elif op == "update_placement_group":
                pg_id, updates = args
                method(pg_id, **updates)
            else:
                method(*args)
    finally:
        cp._replaying = False
    cp.post_restore()
    return n
