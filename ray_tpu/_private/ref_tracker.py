"""Process-local reference tracking for ObjectRefs.

TPU-native counterpart of the owner-side reference counter in the
reference core worker (``src/ray/core_worker/reference_count.cc``,
1.6k LoC).  Design difference, on purpose: ownership bookkeeping is
centralized in the control plane (which already holds the object
directory), so each process only aggregates +1/-1 deltas from
``ObjectRef.__init__``/``__del__`` and flushes them in batches.  The
control plane frees objects whose aggregate count sits at zero past a
grace period (``control_plane.gc_sweep``); the grace covers the handoff
window where a ref is serialized into a task spec before the node
manager's dependency pin lands.

Per-process deltas are keyed by this process's holder id so the control
plane can drop a crashed process's contributions wholesale
(``purge_holder``) instead of leaking positive counts forever.
"""

from __future__ import annotations

import atexit
import os
import threading
from collections import defaultdict
from typing import Dict, Optional


class RefTracker:
    def __init__(self, holder_id: bytes, control_plane,
                 flush_interval: float = 0.2):
        self.holder_id = holder_id
        self.cp = control_plane
        self._lock = threading.Lock()
        self._deltas: Dict[bytes, int] = defaultdict(int)
        self._dirty = threading.Event()
        self._stopped = threading.Event()
        self._thread = threading.Thread(target=self._flush_loop,
                                        name="ref-flush", daemon=True)
        self._thread.start()
        self._flush_interval = flush_interval
        atexit.register(self.flush)

    def add(self, object_id: bytes, delta: int) -> None:
        with self._lock:
            self._deltas[object_id] += delta
        self._dirty.set()

    def flush(self) -> None:
        with self._lock:
            if not self._deltas:
                return
            # Zero-net entries are KEPT: a ref created and dropped within
            # one flush window nets to 0, but the control plane must still
            # learn the object was tracked and is now unreferenced
            # (otherwise it never becomes eligible for GC).
            batch = dict(self._deltas)
            self._deltas.clear()
        try:
            self.cp.update_refs(self.holder_id, batch)
        except Exception:  # noqa: BLE001 - cp may be shutting down
            pass

    def _flush_loop(self) -> None:
        while not self._stopped.is_set():
            self._dirty.wait(timeout=5.0)
            self._dirty.clear()
            if self._stopped.wait(self._flush_interval):
                break
            self.flush()

    def stop(self) -> None:
        self._stopped.set()
        self._dirty.set()
        self.flush()


_tracker: Optional[RefTracker] = None
_tracker_lock = threading.Lock()


def install_tracker(holder_id: bytes, control_plane) -> RefTracker:
    global _tracker
    with _tracker_lock:
        if _tracker is not None:
            _tracker.stop()
        _tracker = RefTracker(holder_id, control_plane)
        return _tracker


def uninstall_tracker() -> None:
    global _tracker
    with _tracker_lock:
        if _tracker is not None:
            _tracker.stop()
            _tracker = None


def track_ref(object_id: bytes) -> bool:
    """+1 for a newly constructed ObjectRef. Returns whether counted."""
    t = _tracker
    if t is None:
        return False
    t.add(object_id, +1)
    return True


def untrack_ref(object_id: bytes) -> None:
    t = _tracker
    if t is not None:
        t.add(object_id, -1)
