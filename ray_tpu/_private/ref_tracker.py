"""Process-local reference tracking for ObjectRefs.

TPU-native counterpart of the owner-side reference counter in the
reference core worker (``src/ray/core_worker/reference_count.cc``,
1.6k LoC).  Ownership is decentralized at NODE granularity: the object's
owner is the node manager of the process that created the ref (put /
task submission), its address rides every pickled ref, and each process
aggregates +1/-1 deltas from ``ObjectRef.__init__``/``__del__`` and
flushes them in batches DIRECTLY to the owner node manager — the
control plane is out of the per-ref hot path and keeps only the object
directory.  The owner frees objects whose aggregate count sits at zero
past a grace period (``node_manager.NodeManager._owner_sweep``); refs
with no owner address (internal ids, e.g. generator items) fall back to
the control plane's centralized counter (``control_plane.gc_sweep``),
which also covers pre-ownership sessions.

Per-process deltas are keyed by this process's holder id so an owner
can drop a crashed process's contributions wholesale (``purge_holder``
/ ``purge_owned_holder``) instead of leaking positive counts forever.
"""

from __future__ import annotations

import atexit
import threading
from collections import defaultdict
from typing import Dict, Optional


class RefTracker:
    def __init__(self, holder_id: bytes, control_plane,
                 node_id: bytes = b"", flush_interval: float = 0.2):
        self.holder_id = holder_id
        self.cp = control_plane
        self.node_id = node_id
        self._lock = threading.Lock()
        self._deltas: Dict[bytes, int] = defaultdict(int)
        # object id -> owner NM address (first binding wins so +1/-1 for
        # one object always route to the same counter); None = CP
        self._owner_of: Dict[bytes, Optional[str]] = {}
        # cumulative live count per object in THIS process: lets us
        # forget the owner binding once the last local ref is flushed
        self._live: Dict[bytes, int] = defaultdict(int)
        self._owner_clients: Dict[str, object] = {}
        self._dirty = threading.Event()
        self._stopped = threading.Event()
        self._thread = threading.Thread(target=self._flush_loop,
                                        name="ref-flush", daemon=True)
        self._thread.start()
        self._flush_interval = flush_interval
        atexit.register(self.flush)

    def add(self, object_id: bytes, delta: int,
            owner: Optional[str] = None) -> None:
        with self._lock:
            self._deltas[object_id] += delta
            self._owner_of.setdefault(object_id, owner)
            self._live[object_id] += delta
            if self._live[object_id] == 0:
                self._live.pop(object_id)
        self._dirty.set()

    def _owner_client(self, addr: str):
        client = self._owner_clients.get(addr)
        if client is None:
            from ray_tpu._private.protocol import RpcClient
            client = RpcClient(addr)
            self._owner_clients[addr] = client
        return client

    def flush(self) -> None:
        with self._lock:
            if not self._deltas:
                return
            # Zero-net entries are KEPT: a ref created and dropped within
            # one flush window nets to 0, but the counter must still
            # learn the object was tracked and is now unreferenced
            # (otherwise it never becomes eligible for GC).
            batch = dict(self._deltas)
            self._deltas.clear()
            owners = {oid: self._owner_of.get(oid) for oid in batch}
            # forget bindings whose last local ref is in this batch
            for oid in batch:
                if oid not in self._live:
                    self._owner_of.pop(oid, None)
        from ray_tpu._private import owner_routing
        owner_routing.route_updates(
            self.cp, self._owner_client, self.holder_id,
            owner_routing.bucket_by_owner(batch, owners.get),
            holder_node=self.node_id)

    def _flush_loop(self) -> None:
        while not self._stopped.is_set():
            self._dirty.wait(timeout=5.0)
            self._dirty.clear()
            if self._stopped.wait(self._flush_interval):
                break
            self.flush()

    def stop(self) -> None:
        self._stopped.set()
        self._dirty.set()
        self.flush()
        # clean detach: release every count this process still holds —
        # at the CP and at every owner NM it ever flushed to (nothing
        # else purges a cleanly-exiting driver's holder id)
        from ray_tpu._private import owner_routing
        owner_routing.route_purge(
            self.cp, self._owner_client, self.holder_id,
            list(self._owner_clients.keys()) + [None])
        for client in self._owner_clients.values():
            try:
                client.close()
            except Exception:  # noqa: BLE001
                pass


_tracker: Optional[RefTracker] = None
_tracker_lock = threading.Lock()


def install_tracker(holder_id: bytes, control_plane,
                    node_id: bytes = b"") -> RefTracker:
    global _tracker
    with _tracker_lock:
        if _tracker is not None:
            _tracker.stop()
        _tracker = RefTracker(holder_id, control_plane, node_id)
        return _tracker


def uninstall_tracker() -> None:
    global _tracker
    with _tracker_lock:
        if _tracker is not None:
            _tracker.stop()
            _tracker = None


def track_ref(object_id: bytes, owner: Optional[str] = None) -> bool:
    """+1 for a newly constructed ObjectRef. Returns whether counted."""
    t = _tracker
    if t is None:
        return False
    t.add(object_id, +1, owner)
    return True


def untrack_ref(object_id: bytes) -> None:
    t = _tracker
    if t is not None:
        t.add(object_id, -1)


def rebind_ref(object_id: bytes, owner: Optional[str]) -> None:
    """Re-route future deltas for an object to a NEW owner (ownership
    adoption after owner-death recovery)."""
    t = _tracker
    if t is not None:
        with t._lock:
            t._owner_of[object_id] = owner
