"""CoreWorker — the in-process runtime of every driver and worker.

TPU-native analogue of the reference core worker
(``src/ray/core_worker/core_worker.cc`` + ``python/ray/_private/worker.py``):
object put/get/wait, task + actor-task submission, the function table, and
generator streaming.  The driver holds in-process handles to the control
plane and local node manager; worker processes hold socket clients — the
logic is identical either way.
"""

from __future__ import annotations

import contextvars
import hashlib
import os
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import cloudpickle

from ray_tpu._private import serialization
from ray_tpu._private.config import GLOBAL_CONFIG
from ray_tpu._private.ids import ActorID, JobID, ObjectID, TaskID, WorkerID
from ray_tpu._private.task_spec import Arg, SchedulingStrategy, TaskSpec
from ray_tpu.exceptions import (ActorDiedError, GetTimeoutError, TaskError)
from ray_tpu.object_ref import ObjectRef, ObjectRefGenerator

# index of the generator end-of-stream marker object
GEN_LEN_INDEX = 2**32 - 2


class CoreWorker:
    def __init__(self, mode: str, job_id: JobID, worker_id: WorkerID,
                 node_id: bytes, control_plane, node_manager, shm_store,
                 session_dir: str, namespace: str = "default",
                 nm_notify=None, nm_addr: str = ""):
        assert mode in ("driver", "worker")
        self.mode = mode
        self.job_id = job_id
        self.worker_id = worker_id
        self.node_id = node_id
        # RPC address of this worker's node manager: the OWNER of every
        # object this worker creates (node-granularity ownership;
        # reference: reference_count.cc owner = creating worker)
        self.nm_addr = nm_addr
        # Node advertised as the location of this worker's shm commits.
        # Differs from node_id only for cross-host attached drivers,
        # whose puts are mirrored to the head node's store.
        self.commit_node_id = node_id
        self.cp = control_plane
        self.nm = node_manager
        self.store = shm_store
        if shm_store is not None \
                and getattr(shm_store, "on_evict", None) is None:
            # a dropped secondary copy must leave the broadcast chain
            # (same wiring as the NM's store instance; whichever process
            # evicts tells the CP)
            def _left(oid, _self=self):
                try:
                    _self.cp.leave_broadcast(oid, _self.node_id)
                except Exception:  # noqa: BLE001 — best-effort
                    pass
            shm_store.on_evict = _left
        self.session_dir = session_dir
        self.namespace = namespace
        self._nm_notify = nm_notify  # callable(msg) to notify NM blocked state
        self._fn_cache: Dict[bytes, Any] = {}
        self._fn_keys: Dict[int, bytes] = {}  # id(fn) -> registered key
        self._actor_nm_cache: Dict[bytes, Any] = {}
        self._actor_direct_cache: Dict[bytes, Any] = {}
        # direct_addr whose dial failed: calls stay on the NM relay
        # (which preserves per-caller order) until the actor publishes
        # a *different* addr — mixing paths could reorder calls
        self._actor_direct_failed: Dict[bytes, str] = {}
        # direct-channel result push-back: return oid -> pending entry
        # {event, payload, error, actor}.  Filled by per-actor reader
        # threads; get() consumes entries instead of 3 CP round trips.
        # Pure latency cache: results also commit at the CP, so a lost
        # push (conn death wakes the entry with payload=None) just
        # means the normal location/wait/fetch flow.
        self._direct_pending: Dict[bytes, Dict[str, Any]] = {}
        self._direct_pending_lock = threading.Lock()
        # actors whose result-stream reader thread is alive: pending
        # entries are only registered while the reader is — an entry
        # nobody will ever fill must not exist, or a get() with no
        # timeout would park on it forever
        self._direct_readers_ok: set = set()
        # actor liveness cache: (state, num_restarts) per actor.  The
        # submit hot path was paying TWO get_actor_info round trips per
        # call (route + inflight bookkeeping); stale entries are safe —
        # a failed direct dial or the inflight watcher invalidates, and
        # the at-least-once + dedup machinery absorbs a spurious resend.
        self._actor_state_cache: Dict[bytes, Tuple[str, int]] = {}
        self._seq_lock = threading.Lock()
        self._actor_seq: Dict[bytes, int] = {}
        # Client-side buffering for calls to not-yet-ALIVE actors
        # (reference: caller-side buffer in direct_actor_task_submitter).
        self._actor_buffers: Dict[bytes, List] = {}
        self._actor_buffer_lock = threading.Lock()
        self._gen_len_cache: Dict[bytes, int] = {}
        self._nm_peers: Dict[str, Any] = {}
        self.num_remote_pulls = 0
        # Caller-side in-flight actor calls (reference:
        # direct_actor_task_submitter pending queue): watched so calls
        # in flight when an actor's host dies are failed or resent
        # instead of hanging forever.
        self._inflight_actor: Dict[bytes, Dict[bytes, Tuple]] = {}
        self._inflight_lock = threading.Lock()
        self._watcher_started = False
        self.current_actor = None
        self.current_actor_id: Optional[bytes] = None
        # Per-execution-context task id (contextvar: safe under threaded
        # actor pools and async actor event loops alike).
        self._task_id_var: "contextvars.ContextVar[Optional[bytes]]" = (
            contextvars.ContextVar(f"task_id_{worker_id.hex()[:8]}",
                                   default=None))

    @property
    def current_task_id(self) -> Optional[bytes]:
        return self._task_id_var.get()

    @current_task_id.setter
    def current_task_id(self, value: Optional[bytes]) -> None:
        self._task_id_var.set(value)

    # ------------------------------------------------------------------
    # Function / class table
    # ------------------------------------------------------------------
    def register_function(self, fn, prefix: bytes = b"fn:") -> bytes:
        cached = self._fn_keys.get(id(fn))
        if cached is not None:
            return cached
        blob = cloudpickle.dumps(fn)
        key = prefix + hashlib.sha1(blob).digest()
        self.cp.kv_put(key, blob, overwrite=False, namespace="_functions")
        self._fn_keys[id(fn)] = key
        self._fn_cache[key] = fn
        return key

    def load_function(self, key: bytes):
        fn = self._fn_cache.get(key)
        if fn is None:
            blob = self.cp.kv_get(key, namespace="_functions")
            if blob is None:
                raise RuntimeError(f"function {key!r} not found in table")
            fn = cloudpickle.loads(blob)
            self._fn_cache[key] = fn
        return fn

    # ------------------------------------------------------------------
    # Objects
    # ------------------------------------------------------------------
    def put(self, value: Any) -> ObjectRef:
        oid = ObjectID.from_random().binary()
        self.put_object(oid, value, owner_addr=self.nm_addr)
        return ObjectRef(oid, self.nm_addr or None)

    def put_object(self, oid: bytes, value: Any,
                   is_error: bool = False,
                   owner_addr: Optional[str] = None) -> Optional[bytes]:
        """Commit a value under ``oid``.  ``owner_addr`` is the node
        manager owning the object's lifetime (the caller's NM for task
        returns, ours for puts); empty/None commits a CP-governed object
        (centralized refcounting fallback).  Returns the serialized
        payload when it committed inline (the direct-channel result
        push reuses it), else None."""
        sobj = serialization.serialize(value)
        owner = self.worker_id.binary()
        if sobj.total_bytes <= GLOBAL_CONFIG.inline_object_max_bytes:
            data = sobj.to_bytes()
            self.cp.put_inline(oid, data, is_error=is_error,
                               owner=owner, owner_addr=owner_addr or "")
            return data
        self.store.put_serialized(oid, sobj)
        self.cp.commit_shm(oid, sobj.total_bytes,
                           node_id=self.commit_node_id,
                           is_error=is_error, owner=owner,
                           owner_addr=owner_addr or "")
        return None

    def _fetch_committed(self, oid: bytes, loc: Dict[str, Any],
                         preloaded: Optional[bytes] = None) -> Any:
        if loc["where"] == "inline":
            data = preloaded if preloaded is not None \
                else self.cp.get_inline(oid)
            if data is None:
                raise KeyError(f"inline object {oid.hex()} vanished")
            value = serialization.deserialize_frame(memoryview(data))
        else:
            value = self.store.get_object(oid)
            if value is None and self._pull_remote(oid, loc):
                value = self.store.get_object(oid)
            if value is None:
                raise KeyError(f"shm object {oid.hex()} missing from store")
        return value

    # ------------------------------------------------------------------
    # Node-to-node object transfer (pull side).  Reference:
    # object_manager/pull_manager.cc — here the *consumer* worker pulls
    # chunks from the node manager of the node holding the primary copy
    # and seals a local secondary copy.
    # ------------------------------------------------------------------
    def _pull_remote(self, oid: bytes, loc: Dict[str, Any]) -> bool:
        src_node = loc.get("node")
        if not src_node or src_node == self.node_id:
            return False
        info = self.cp.get_node(src_node)
        if info is None or info.get("state") != "ALIVE":
            return False
        peer = self._nm_peer(info["sock_path"])
        try:
            meta = peer.call("fetch_object_meta", oid)
            if meta is None:
                return False
            size = meta["size"]
            # Same-host fastpath: co-hosted nodes share tmpfs, so a
            # sealed source file copies kernel-side — one memcpy, no
            # RPC chunking (multi-node-per-host deployments; the sim
            # fixtures are exactly this shape).
            path = meta.get("path")
            if path and GLOBAL_CONFIG.object_samehost_fastpath \
                    and self._same_host(meta.get("ip")) \
                    and os.path.exists(path) \
                    and self.store.put_file_copy(oid, path, size):
                self.num_remote_pulls += 1
                return True
            if self._pull_chained(oid, size, peer):
                self.num_remote_pulls += 1
                return True
            return False
        except (OSError, IOError, ConnectionError):
            return False

    def _pull_chained(self, oid: bytes, size: int, primary_peer) -> bool:
        """Chain-push broadcast pull (reference: push_manager.cc role).

        Join the object's broadcast chain at the CP; pull chunks from
        the assigned parent — which may still be mid-pull itself, in
        which case its node re-serves the prefix it already has
        (``fetch_partial_chunk``) and we poll forward.  On a dead or
        stalled parent, leave the chain and restart against the
        primary, so a mid-broadcast node death costs one retry, not the
        broadcast."""
        chunk_bytes = GLOBAL_CONFIG.object_transfer_chunk_bytes
        parent_peer, parent_node = primary_peer, None
        try:
            parent = self.cp.join_broadcast(oid, self.node_id)
            if parent is not None:
                parent_node = parent["node_id"]
                parent_peer = self._nm_peer(parent["sock_path"])
        except Exception:  # noqa: BLE001 — no chain: primary direct
            pass

        def chunks_from(peer, partial: bool):
            off = 0
            stall_deadline = time.monotonic() + 20.0
            # a parent that reports "gone" has no copy and no pull in
            # flight — give it a short grace (it may be between its
            # join and its first written chunk), then re-chain
            gone_deadline = time.monotonic() + 3.0
            while off < size:
                n = min(chunk_bytes, size - off)
                method = ("fetch_partial_chunk" if partial
                          else "fetch_object_chunk")
                data = peer.call(method, oid, off, n)
                if isinstance(data, dict):         # {"gone": True}
                    if off > 0 or time.monotonic() > gone_deadline:
                        raise IOError(
                            f"parent lost {oid.hex()} at {off}")
                    time.sleep(0.05)
                    continue
                if data is None:
                    if not partial:
                        raise IOError(f"object {oid.hex()} gone at src")
                    if time.monotonic() > stall_deadline:
                        raise IOError(f"parent stalled at {off}")
                    time.sleep(0.02)
                    continue
                if len(data) != n:
                    raise IOError(
                        f"short chunk pulling {oid.hex()} "
                        f"({len(data)}/{n})")
                stall_deadline = time.monotonic() + 20.0
                yield data
                off += n

        if parent_node is not None:
            try:
                self.store.put_stream(
                    oid, size, chunks_from(parent_peer, partial=True))
                return True
            except (OSError, IOError, ConnectionError):
                # parent died/stalled mid-chain: drop it and fall back
                try:
                    self.cp.leave_broadcast(oid, parent_node)
                except Exception:  # noqa: BLE001
                    pass
        try:
            self.store.put_stream(
                oid, size, chunks_from(primary_peer, partial=False))
            return True
        except (OSError, IOError, ConnectionError):
            try:
                self.cp.leave_broadcast(oid, self.node_id)
            except Exception:  # noqa: BLE001
                pass
            return False

    def _same_host(self, src_ip: Optional[str]) -> bool:
        """Whether the source node's sealed file is on THIS host's
        tmpfs.  UDS sessions are single-host by construction; TCP
        sessions compare the source ip against our own NM's — a path
        that merely *exists* locally could be a different host's
        bind-mounted store."""
        from ray_tpu._private.protocol import is_tcp_address, \
            parse_tcp_address
        if not self.nm_addr or not is_tcp_address(self.nm_addr):
            return True
        if not src_ip:
            return False
        try:
            local_ip, _ = parse_tcp_address(self.nm_addr)
        except Exception:  # noqa: BLE001
            return False
        return src_ip == local_ip

    def _nm_peer(self, sock_path: str):
        from ray_tpu._private.protocol import RpcClient
        client = self._nm_peers.get(sock_path)
        if client is None:
            client = RpcClient(sock_path)
            self._nm_peers[sock_path] = client
        return client

    # ------------------------------------------------------------------
    # Lineage reconstruction.  Reference:
    # core_worker/object_recovery_manager.cc + TaskManager::ResubmitTask —
    # a lost object (evicted shm copy, dead holder node) is recomputed by
    # re-executing the deterministic task that created it; return ids are
    # derived from the task id, so the re-execution commits the same ids.
    # ------------------------------------------------------------------
    def _recover_object(self, oid: bytes, attempts: int = 3,
                        adopt: bool = False) -> Dict[str, Any]:
        from ray_tpu.exceptions import ObjectLostError
        task_id = oid[: TaskID.SIZE]
        for _ in range(attempts):
            spec = self.cp.get_lineage(task_id)
            if spec is None:
                raise ObjectLostError(
                    oid.hex(), "no lineage to reconstruct (ray.put "
                    "objects and actor-task returns are not "
                    "reconstructible)")
            if adopt:
                # owner-death recovery: recommitting under the dead
                # owner address would leak the recomputed copy, so this
                # worker's NM adopts ownership and we register OUR ref
                # there (rebinding the local tracker so the eventual -1
                # routes the same way).  Other borrowers still pointing
                # at the dead owner can re-trigger recovery — at-least-
                # once, never a leak.
                spec.owner_addr = self.nm_addr
            # invalidate the stale location so waiters block on the
            # re-execution's commit instead of re-reading the dead copy
            self.cp.free_objects([oid])
            if hasattr(self.nm, "call"):
                self.nm.call("submit_task", spec)
            else:
                self.nm.submit_task(spec)
            loc = self.cp.wait_object(oid, 300.0)
            if loc is not None:
                if adopt and self.nm_addr:
                    try:
                        self._nm_peer(self.nm_addr).call(
                            "update_owned_refs", self.worker_id.binary(),
                            {oid: 1}, self.node_id)
                        from ray_tpu._private.ref_tracker import rebind_ref
                        rebind_ref(oid, self.nm_addr)
                    except Exception:  # noqa: BLE001 - best effort
                        pass
                return loc
        raise ObjectLostError(oid.hex(), "reconstruction failed")

    def _handle_owner_died(self, oid: bytes) -> Dict[str, Any]:
        """The node owning ``oid``'s refcount died.  Task returns are
        recomputed through lineage (and adopted by this worker's owner);
        ``put`` objects fate-share with their owner (reference:
        OwnerDiedError semantics in ``python/ray/exceptions.py``)."""
        from ray_tpu.exceptions import ObjectLostError, OwnerDiedError
        try:
            return self._recover_object(oid, adopt=True)
        except OwnerDiedError:
            raise
        except ObjectLostError:
            raise OwnerDiedError(
                oid.hex(), "the node owning this object died and it "
                "has no lineage to reconstruct") from None

    def get(self, refs: Union[ObjectRef, Sequence[ObjectRef]],
            timeout: Optional[float] = None) -> Any:
        single = isinstance(refs, ObjectRef)
        ref_list = [refs] if single else list(refs)
        for r in ref_list:
            if not isinstance(r, ObjectRef):
                raise TypeError(
                    f"get() expects ObjectRef(s), got {type(r).__name__}")
        ids = [r.binary() for r in ref_list]
        # ONE deadline across the direct-push wait and the CP flow: a
        # fallback after a consumed wait must not restart the budget
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        # direct-channel fast path: results pushed by the actor worker
        # resolve with zero control-plane round trips
        direct_vals: Dict[bytes, Any] = {}
        direct_errs: Dict[bytes, bool] = {}
        pending = []
        with self._direct_pending_lock:
            for o in ids:
                e = self._direct_pending.get(o)
                if e is not None:
                    pending.append((o, e))
        if pending:
            self._notify_blocked(True)
            try:
                for o, e in pending:
                    t = (None if deadline is None
                         else max(0.0, deadline - time.monotonic()))
                    if e["event"].wait(t):
                        with self._direct_pending_lock:
                            self._direct_pending.pop(o, None)
                        if e["payload"] is not None:
                            direct_vals[o] = serialization.loads(
                                e["payload"])
                            direct_errs[o] = e["error"]
                    # payload None (big result / conn died) or timeout:
                    # the CP flow below handles it
            finally:
                self._notify_blocked(False)
        rest = [o for o in ids if o not in direct_vals]
        # one bulk location RPC; blocked waits use the combined
        # wait+fetch so a small result costs one round trip total
        locs = self.cp.get_locations(rest) if rest else {}
        preloaded: Dict[bytes, bytes] = {}
        unready = [o for o in rest if locs.get(o) is None]
        if unready:
            self._notify_blocked(True)
            try:
                for o in unready:
                    t = (None if deadline is None
                         else max(0.0, deadline - time.monotonic()))
                    out = self.cp.wait_fetch(o, t)
                    if out is None:
                        raise GetTimeoutError(
                            f"get() timed out waiting for {o.hex()}")
                    locs[o] = out["loc"]
                    if out.get("data") is not None:
                        preloaded[o] = out["data"]
            finally:
                self._notify_blocked(False)
        values = []
        for o in ids:
            if o in direct_vals:
                value = direct_vals[o]
                if direct_errs.get(o):
                    if isinstance(value, TaskError):
                        raise value.as_instanceof_cause()
                    if isinstance(value, BaseException):
                        raise value
                values.append(value)
                continue
            loc = locs.get(o)
            if loc is None:
                raise GetTimeoutError(f"object {o.hex()} not available")
            if loc.get("owner_died"):
                loc = self._handle_owner_died(o)
            try:
                value = self._fetch_committed(o, loc,
                                              preloaded=preloaded.get(o))
            except KeyError:
                loc = self._recover_object(o)
                value = self._fetch_committed(o, loc)
            if loc.get("error"):
                if isinstance(value, TaskError):
                    raise value.as_instanceof_cause()
                if isinstance(value, BaseException):
                    raise value
            values.append(value)
        return values[0] if single else values

    def wait(self, refs: Sequence[ObjectRef], num_returns: int = 1,
             timeout: Optional[float] = None,
             fetch_local: bool = True) -> Tuple[List[ObjectRef],
                                                List[ObjectRef]]:
        if isinstance(refs, ObjectRef):
            raise TypeError("wait() expects a list of ObjectRefs")
        ids = [r.binary() for r in refs]
        if num_returns > len(ids):
            raise ValueError("num_returns exceeds number of refs")
        self._notify_blocked(True)
        try:
            ready_ids = set(self.cp.wait_any(ids, num_returns, timeout))
        finally:
            self._notify_blocked(False)
        ready, not_ready = [], []
        for r in refs:
            # Ray contract: len(ready) <= num_returns; surplus completed
            # refs stay in not_ready and are returned by the next wait().
            if r.binary() in ready_ids and len(ready) < num_returns:
                ready.append(r)
            else:
                not_ready.append(r)
        return ready, not_ready

    def free(self, refs: Sequence[ObjectRef]) -> int:
        ids = [r.binary() for r in refs]
        for o in ids:
            self.store.delete(o)
        return self.cp.free_objects(ids)

    def _notify_blocked(self, blocked: bool):
        if self.mode == "worker" and self._nm_notify is not None:
            try:
                self._nm_notify({"type": "blocked" if blocked
                                 else "unblocked"})
            except Exception:  # noqa: BLE001
                pass

    # ------------------------------------------------------------------
    # Generator streaming
    # ------------------------------------------------------------------
    def _gen_len_oid(self, task_id: bytes) -> bytes:
        return ObjectID(task_id + GEN_LEN_INDEX.to_bytes(4, "big")).binary()

    def commit_generator_item(self, task_id: bytes, index: int, value: Any,
                              is_error: bool = False) -> bytes:
        # Streamed items live at return indices 1.. (index 0 is the task's
        # nominal return, which carries the item count).
        oid = ObjectID.for_task_return(TaskID(task_id), index + 1).binary()
        self.put_object(oid, value, is_error=is_error)
        return oid

    def commit_generator_done(self, task_id: bytes, length: int) -> None:
        self.put_object(self._gen_len_oid(task_id), length)

    def peek_generator_length(self, task_id: bytes) -> Optional[int]:
        cached = self._gen_len_cache.get(task_id)
        if cached is not None:
            return cached
        oid = self._gen_len_oid(task_id)
        loc = self.cp.get_location(oid)
        if loc is None:
            return None
        length = self._fetch_committed(oid, loc)
        self._gen_len_cache[task_id] = length
        return length

    def wait_generator_length(self, task_id: bytes) -> Optional[int]:
        return self.peek_generator_length(task_id)

    def wait_ready_or_len(self, oid: bytes, task_id: bytes):
        len_oid = self._gen_len_oid(task_id)
        while True:
            ready = self.cp.wait_any([oid, len_oid], 1, 30.0)
            if ready:
                return

    # ------------------------------------------------------------------
    # Task submission
    # ------------------------------------------------------------------
    def _serialize_args(self, args: Sequence[Any],
                        kwargs: Dict[str, Any]) -> Tuple[List[Arg],
                                                         Dict[str, Arg],
                                                         Dict[bytes, str]]:
        ref_owners: Dict[bytes, str] = {}

        def one(value: Any) -> Arg:
            if isinstance(value, ObjectRef):
                if value.owner_addr():
                    ref_owners[value.binary()] = value.owner_addr()
                return Arg(object_id=value.binary())
            if isinstance(value, ObjectRefGenerator):
                raise TypeError(
                    "Pass generator refs individually, not the generator")
            sobj = serialization.serialize(value)
            if sobj.total_bytes <= GLOBAL_CONFIG.inline_object_max_bytes:
                return Arg(inline=sobj.to_bytes())
            oid = ObjectID.from_random().binary()
            self.store.put_serialized(oid, sobj)
            self.cp.commit_shm(oid, sobj.total_bytes,
                               node_id=self.commit_node_id,
                               owner=self.worker_id.binary(),
                               owner_addr=self.nm_addr)
            if self.nm_addr:
                ref_owners[oid] = self.nm_addr
            return Arg(object_id=oid)

        ser_args = [one(a) for a in args]
        ser_kwargs = {k: one(v) for k, v in kwargs.items()}
        return ser_args, ser_kwargs, ref_owners

    def submit_task(self, fn, args: Sequence[Any], kwargs: Dict[str, Any],
                    opts: Dict[str, Any]) -> Union[ObjectRef,
                                                   List[ObjectRef],
                                                   ObjectRefGenerator]:
        fn_key = self.register_function(fn)
        num_returns = opts.get("num_returns", 1)
        streaming = num_returns in ("streaming", "dynamic")
        task_id = TaskID.for_normal_task(self.job_id)
        ser_args, ser_kwargs, ref_owners = self._serialize_args(
            args, kwargs)
        spec = TaskSpec(
            task_id=task_id.binary(), job_id=self.job_id.binary(),
            name=opts.get("name") or getattr(fn, "__qualname__", "task"),
            function_key=fn_key, args=ser_args, kwargs=ser_kwargs,
            num_returns=1 if streaming else num_returns,
            resources=dict(opts["resources"]),
            max_retries=opts.get(
                "max_retries", GLOBAL_CONFIG.task_default_max_retries),
            retry_exceptions=bool(opts.get("retry_exceptions", False)),
            scheduling_strategy=opts.get(
                "scheduling_strategy") or SchedulingStrategy(),
            is_generator=streaming,
            owner_id=self.worker_id.binary(),
            owner_addr=self.nm_addr, ref_owners=ref_owners,
            runtime_env=opts.get("runtime_env") or {},
            parent_task_id=self.current_task_id,
        )
        from ray_tpu.util.tracing import submit_span
        with submit_span(spec.name):
            self.nm.submit_task(spec)
        if streaming:
            return ObjectRefGenerator(task_id.binary())
        refs = [ObjectRef(o, self.nm_addr or None)
                for o in spec.return_object_ids()]
        return refs[0] if num_returns == 1 else refs

    # ------------------------------------------------------------------
    # Actors
    # ------------------------------------------------------------------
    def create_actor(self, cls, args: Sequence[Any], kwargs: Dict[str, Any],
                     opts: Dict[str, Any]) -> bytes:
        cls_key = self.register_function(cls, prefix=b"cls:")
        actor_id = ActorID.of(self.job_id)
        task_id = TaskID.for_actor_creation(actor_id)
        ser_args, ser_kwargs, ref_owners = self._serialize_args(
            args, kwargs)
        name = opts.get("name")
        spec = TaskSpec(
            task_id=task_id.binary(), job_id=self.job_id.binary(),
            name=f"{getattr(cls, '__name__', 'Actor')}.__init__",
            function_key=cls_key, args=ser_args, kwargs=ser_kwargs,
            num_returns=1, resources=opts["resources"],
            scheduling_strategy=opts.get(
                "scheduling_strategy") or SchedulingStrategy(),
            actor_id=actor_id.binary(), actor_creation=True,
            max_restarts=opts.get("max_restarts", 0),
            max_task_retries=opts.get("max_task_retries", 0),
            max_concurrency=opts.get("max_concurrency", 1),
            owner_id=self.worker_id.binary(),
            owner_addr=self.nm_addr, ref_owners=ref_owners,
            runtime_env=opts.get("runtime_env") or {},
        )
        self.cp.register_actor(actor_id.binary(), {
            "name": name, "namespace": opts.get("namespace", self.namespace),
            "class_name": getattr(cls, "__name__", "Actor"),
            "state": "PENDING",
            "max_restarts": opts.get("max_restarts", 0),
            "max_task_retries": opts.get("max_task_retries", 0),
            "method_num_returns": opts.get("method_num_returns") or {},
            "lifetime": opts.get("lifetime"),
            "resources": opts["resources"],
            # kept so the head can reschedule the actor on another node
            # when its host dies (gcs_actor_manager restart path)
            "creation_spec": spec,
        })
        self.nm.submit_actor_creation(spec)
        return actor_id.binary()

    def _actor_nm(self, actor_id: bytes, wait: bool = True):
        """Client to the node manager hosting the actor."""
        info = self.cp.get_actor_info(actor_id)
        if info is None:
            raise ActorDiedError(actor_id.hex(), "unknown actor")
        state = info.get("state")
        if state in ("PENDING", "RESTARTING") and wait:
            self._notify_blocked(True)
            try:
                info = self.cp.wait_actor_state(
                    actor_id, ("ALIVE", "DEAD"), timeout=300.0)
            finally:
                self._notify_blocked(False)
            if info is None:
                raise ActorDiedError(actor_id.hex(),
                                     "timed out waiting for actor start")
        if info.get("state") == "DEAD":
            raise ActorDiedError(actor_id.hex(),
                                 info.get("death_reason", "actor is dead"))
        nm_sock = info.get("nm_sock")
        if nm_sock is None:
            raise ActorDiedError(actor_id.hex(), "actor has no address")
        if self.nm is not None and getattr(self.nm, "sock_path", None) == \
                nm_sock:
            return self.nm
        client = self._actor_nm_cache.get(actor_id)
        if client is None or getattr(client, "sock_path", None) != nm_sock:
            from ray_tpu._private.protocol import RpcClient
            client = RpcClient(nm_sock)
            client.sock_path = nm_sock
            self._actor_nm_cache[actor_id] = client
        return client

    def submit_actor_task(self, actor_id: bytes, method_name: str,
                          args: Sequence[Any], kwargs: Dict[str, Any],
                          opts: Dict[str, Any]) -> Union[ObjectRef,
                                                         List[ObjectRef],
                                                         ObjectRefGenerator]:
        num_returns = opts.get("num_returns", 1)
        streaming = num_returns in ("streaming", "dynamic")
        task_id = TaskID.for_actor_task(ActorID(actor_id))
        ser_args, ser_kwargs, ref_owners = self._serialize_args(
            args, kwargs)
        with self._seq_lock:
            seq = self._actor_seq.get(actor_id, 0)
            self._actor_seq[actor_id] = seq + 1
        spec = TaskSpec(
            task_id=task_id.binary(), job_id=self.job_id.binary(),
            name=f"actor.{method_name}",
            function_key=b"", args=ser_args, kwargs=ser_kwargs,
            num_returns=1 if streaming else num_returns,
            resources={}, actor_id=actor_id, actor_method=method_name,
            seq_no=seq, is_generator=streaming,
            max_task_retries=opts.get("max_task_retries", 0),
            owner_id=self.worker_id.binary(),
            owner_addr=self.nm_addr, ref_owners=ref_owners,
        )
        # Pin arg objects from the moment of submission.  A call made
        # while the actor is still PENDING sits in the caller-side
        # buffer where the node manager's pin (submit_actor_task)
        # doesn't exist yet — if the caller drops its ObjectRefs in that
        # window, GC frees the args and the task hangs resolving them.
        # purge clears the whole "task:" holder at completion, so the
        # node manager re-pinning the same holder is harmless.  Pins
        # route to each dep's owner, like the ref tracker's deltas.
        deps = spec.dependencies()
        if deps:
            self._update_pins(b"task:" + spec.task_id,
                              {d: 1 for d in deps}, spec.ref_owners)
        self._route_or_buffer(spec, streaming)
        if streaming:
            return ObjectRefGenerator(task_id.binary())
        refs = [ObjectRef(o, self.nm_addr or None)
                for o in spec.return_object_ids()]
        return refs[0] if num_returns == 1 else refs

    def _update_pins(self, holder: bytes, deltas: Dict[bytes, int],
                     ref_owners: Dict[bytes, str]) -> None:
        """Apply pin refcount deltas at each object's owner (CP for
        ownerless objects)."""
        from ray_tpu._private import owner_routing
        owner_routing.route_updates(
            self.cp, self._nm_peer, holder,
            owner_routing.bucket_by_owner(deltas, ref_owners.get),
            holder_node=self.node_id)

    def _route_now(self, spec: TaskSpec, streaming: bool = False,
                   restarts_seen: Optional[int] = None) -> None:
        # Direct caller->callee transport (reference:
        # transport/direct_actor_task_submitter.cc): dial the actor
        # worker's own socket, skipping the hosting NM's relay +
        # queue + task-event machinery on the per-call hot path.
        # Streaming calls and misses fall back to the NM relay (which
        # also owns restart-time requeueing).
        if not streaming:
            direct = self._actor_direct(spec.actor_id)
            if direct is not None:
                rets = spec.return_object_ids()
                oid = rets[0] if spec.num_returns == 1 and rets else None
                if oid is not None:
                    with self._direct_pending_lock:
                        if spec.actor_id not in self._direct_readers_ok:
                            oid = None  # no reader: CP flow only
                        elif oid in self._direct_pending:
                            pass  # resend: keep the (maybe-filled) entry
                        else:
                            # bounded: refs the caller never get()s must
                            # not pin payloads forever.  Wake evictees —
                            # a get() already parked on one falls back
                            # to the CP flow instead of stranding.
                            while len(self._direct_pending) >= 4096:
                                old = self._direct_pending.pop(
                                    next(iter(self._direct_pending)))
                                old["event"].set()
                            self._direct_pending[oid] = {
                                "event": threading.Event(),
                                "payload": None, "error": False,
                                "actor": spec.actor_id}
                try:
                    direct.call("call_actor", spec)
                    self._record_inflight(spec, streaming,
                                          restarts_seen)
                    return
                except Exception:  # noqa: BLE001 — stale addr: relay
                    if oid is not None:
                        with self._direct_pending_lock:
                            self._direct_pending.pop(oid, None)
                    self._actor_direct_cache.pop(spec.actor_id, None)
                    self._actor_state_cache.pop(spec.actor_id, None)
                    self._actor_direct_failed[spec.actor_id] = (
                        direct.sock_path)
        nm = self._actor_nm(spec.actor_id, wait=False)
        if nm is self.nm and self.mode == "driver":
            nm.submit_actor_task(spec)
        elif hasattr(nm, "call"):
            nm.call("submit_actor_task", spec)
        else:
            nm.submit_actor_task(spec)
        self._record_inflight(spec, streaming, restarts_seen)

    def _actor_direct(self, actor_id: bytes):
        """Cached client to the actor's direct-call socket (None when
        the actor hasn't published one / is mid-restart).  "No direct
        addr" is cached with a TTL: without it every call to such an
        actor pays a control-plane round trip on the hot path."""
        client = self._actor_direct_cache.get(actor_id)
        if client is not None:
            if isinstance(client, float):       # negative entry
                if time.monotonic() < client:
                    return None
                self._actor_direct_cache.pop(actor_id, None)
            else:
                return client
        info = self.cp.get_actor_info(actor_id)
        if not info or info.get("state") != "ALIVE":
            return None
        addr = info.get("direct_addr")
        if not addr:
            self._actor_direct_cache[actor_id] = time.monotonic() + 10.0
            return None
        if self._actor_direct_failed.get(actor_id) == addr:
            return None  # relay-pinned until the actor re-publishes
        self._actor_direct_failed.pop(actor_id, None)
        from ray_tpu._private.protocol import RpcClient
        client = RpcClient(addr, connect_timeout=2.0)
        self._start_direct_result_reader(actor_id, client)
        self._actor_direct_cache[actor_id] = client
        return client

    def _start_direct_result_reader(self, actor_id: bytes,
                                    client) -> None:
        """Open the per-caller result stream on the actor's direct
        server and drain pushed results into ``_direct_pending``."""
        from ray_tpu._private import protocol as _proto
        try:
            sock = client.hijack("stream_results",
                                 self.worker_id.binary())
        except Exception:  # noqa: BLE001 — push-back is optional
            return
        with self._direct_pending_lock:
            self._direct_readers_ok.add(actor_id)

        def reader():
            try:
                while True:
                    msg = _proto.recv_msg(sock)
                    entry = None
                    with self._direct_pending_lock:
                        entry = self._direct_pending.get(msg.get("oid"))
                    if entry is not None:
                        entry["payload"] = msg.get("payload")
                        entry["error"] = bool(msg.get("error"))
                        entry["event"].set()
            except Exception:  # noqa: BLE001 — conn died
                pass
            finally:
                try:
                    sock.close()
                except OSError:
                    pass
                # drop the liveness mark FIRST (no new registrations),
                # then wake every waiter still parked on this actor:
                # they fall back to the CP flow
                with self._direct_pending_lock:
                    self._direct_readers_ok.discard(actor_id)
                    stale = [e for e in self._direct_pending.values()
                             if e["actor"] == actor_id]
                for e in stale:
                    e["event"].set()

        threading.Thread(target=reader, daemon=True,
                         name=f"direct-res-{actor_id.hex()[:6]}").start()

    # ------------------------------------------------------------------
    # In-flight actor call tracking.  If the hosting node dies, the node
    # manager that knew about the call dies with it — the caller is the
    # only party able to fail or resend.  A 1s watcher prunes committed
    # calls and reacts to actor DEAD / restart transitions.
    # ------------------------------------------------------------------
    def _record_inflight(self, spec: TaskSpec, streaming: bool,
                         restarts_seen: Optional[int] = None) -> None:
        if not streaming and not spec.return_object_ids():
            return  # num_returns=0: nothing to watch for
        if restarts_seen is None:
            cached = self._actor_state_cache.get(spec.actor_id)
            if cached is not None:
                restarts_seen = cached[1]
            else:
                info = self.cp.get_actor_info(spec.actor_id) or {}
                restarts_seen = info.get("num_restarts", 0)
                if info:
                    self._actor_state_cache[spec.actor_id] = (
                        info.get("state", "?"), restarts_seen)
        with self._inflight_lock:
            self._inflight_actor.setdefault(spec.actor_id, {})[
                spec.task_id] = (spec, streaming, restarts_seen)
            if not self._watcher_started:
                self._watcher_started = True
                threading.Thread(target=self._inflight_watch_loop,
                                 daemon=True,
                                 name="actor-inflight-watch").start()

    def _call_committed(self, spec: TaskSpec, streaming: bool) -> bool:
        if streaming:
            oid = self._gen_len_oid(spec.task_id)
        else:
            ids = spec.return_object_ids()
            if not ids:
                return True
            oid = ids[0]
        return self.cp.get_location(oid) is not None

    def _inflight_watch_loop(self) -> None:
        while True:
            time.sleep(1.0)
            try:
                self._inflight_watch_once()
            except Exception:  # noqa: BLE001 - transient cp error; keep
                continue       # watching (a dead watcher would strand
                               # every future in-flight call)

    def _inflight_watch_once(self) -> None:
        with self._inflight_lock:
            snapshot = {aid: dict(tasks) for aid, tasks
                        in self._inflight_actor.items()}
        for actor_id, tasks in snapshot.items():
            done = [tid for tid, (spec, streaming, _) in tasks.items()
                    if self._call_committed(spec, streaming)]
            for tid in done:
                tasks.pop(tid)
            with self._inflight_lock:
                for tid in done:
                    self._inflight_actor.get(actor_id, {}).pop(tid, None)
                if not self._inflight_actor.get(actor_id):
                    self._inflight_actor.pop(actor_id, None)
            if not tasks:
                continue
            info = self.cp.get_actor_info(actor_id)
            state = (info or {}).get("state")
            # keep the submit-path cache honest while calls are watched
            if info is None:
                self._actor_state_cache.pop(actor_id, None)
            else:
                self._actor_state_cache[actor_id] = (
                    state, info.get("num_restarts", 0))
            if info is None or state == "DEAD":
                for tid, (spec, streaming, _) in tasks.items():
                    if not self._call_committed(spec, streaming):
                        self._fail_actor_call(
                            spec, streaming, ActorDiedError(
                                actor_id.hex(),
                                (info or {}).get("death_reason",
                                                 "actor is dead")))
                with self._inflight_lock:
                    # pop only what we actually failed: a call recorded
                    # after the snapshot must stay tracked
                    actor_tasks = self._inflight_actor.get(actor_id, {})
                    for tid in tasks:
                        actor_tasks.pop(tid, None)
                    if not actor_tasks:
                        self._inflight_actor.pop(actor_id, None)
            elif state == "ALIVE":
                restarts = info.get("num_restarts", 0)
                for tid, (spec, streaming, seen) in tasks.items():
                    if restarts <= seen:
                        continue  # same incarnation; still running
                    if self._call_committed(spec, streaming):
                        continue
                    if spec.max_task_retries != 0:
                        try:
                            self._route_now(spec, streaming)
                        except ActorDiedError as e:
                            self._fail_actor_call(spec, streaming, e)
                        except (OSError, ConnectionError):
                            continue  # retry next tick
                    else:
                        self._fail_actor_call(
                            spec, streaming, ActorDiedError(
                                actor_id.hex(),
                                "actor restarted; in-flight call "
                                "lost (set max_task_retries to "
                                "resend)"))
                        with self._inflight_lock:
                            self._inflight_actor.get(
                                actor_id, {}).pop(tid, None)

    def _fail_actor_call(self, spec: TaskSpec, streaming: bool,
                         error: BaseException) -> None:
        err = TaskError(error, "", spec.task_id.hex())
        data = serialization.dumps(err)
        for oid in spec.return_object_ids():
            self.cp.put_inline(oid, data, is_error=True)
        if streaming:
            self.commit_generator_done(spec.task_id, 1)
            self.commit_generator_item(spec.task_id, 0, err, is_error=True)
        deps = spec.dependencies()
        if deps:
            # release the submit-time dependency pin at each dep's owner
            from ray_tpu._private import owner_routing
            owner_routing.route_purge(
                self.cp, self._nm_peer, b"task:" + spec.task_id,
                {spec.ref_owners.get(d) for d in deps})

    def _abtrace(self, *parts) -> None:
        from ray_tpu._private.debug_trace import trace
        trace("actor_buffer", *parts, var="RAY_TPU_DEBUG_ACTOR_BUFFER")

    def _route_or_buffer(self, spec: TaskSpec, streaming: bool) -> None:
        """Route to the actor's node manager, or buffer until it's ALIVE.

        Buffered calls preserve per-caller order: a single flusher thread
        per actor drains the buffer FIFO once the actor starts.
        """
        actor_id = spec.actor_id
        cached = self._actor_state_cache.get(actor_id)
        if cached is not None and cached[0] == "ALIVE":
            # hot path: no control-plane round trip.  {} (not None) so
            # the dead-branch below can't mistake the cache hit for
            # "actor unknown"
            info: Optional[Dict[str, Any]] = {}
            state = "ALIVE"
        else:
            info = self.cp.get_actor_info(actor_id)
            state = info.get("state") if info else None
            if info:
                self._actor_state_cache[actor_id] = (
                    state, info.get("num_restarts", 0))
        self._abtrace("route_or_buffer", spec.name,
                      actor_id.hex()[:8], "state", state)
        with self._actor_buffer_lock:
            buffer = self._actor_buffers.get(actor_id)
            if state == "ALIVE" and buffer is None:
                pass  # fall through to direct route below
            elif state == "DEAD" or info is None:
                self._fail_actor_call(spec, streaming, ActorDiedError(
                    actor_id.hex() if actor_id else "",
                    (info or {}).get("death_reason", "actor is dead")))
                return
            else:
                if buffer is None:
                    buffer = []
                    self._actor_buffers[actor_id] = buffer
                    threading.Thread(
                        target=self._flush_actor_buffer,
                        args=(actor_id,), daemon=True,
                        name="actor-buffer-flush").start()
                buffer.append((spec, streaming))
                return
        try:
            self._route_now(spec, streaming)
            self._abtrace("routed_direct", spec.name)
        except ActorDiedError as e:
            self._abtrace("fail_direct", spec.name, str(e)[:60])
            self._fail_actor_call(spec, streaming, e)
        except (OSError, ConnectionError):
            self._actor_state_cache.pop(actor_id, None)
            # The actor's node manager is unreachable (its node just
            # died); buffer the call — the health loop will transition
            # the actor to RESTARTING (new address) or DEAD shortly.
            with self._actor_buffer_lock:
                buffer = self._actor_buffers.get(actor_id)
                if buffer is None:
                    buffer = []
                    self._actor_buffers[actor_id] = buffer
                    threading.Thread(
                        target=self._flush_actor_buffer,
                        args=(actor_id,), daemon=True,
                        name="actor-buffer-flush").start()
                buffer.append((spec, streaming))

    def _flush_actor_buffer(self, actor_id: bytes) -> None:
        deadline = time.monotonic() + 600.0
        info = self.cp.wait_actor_state(actor_id, ("ALIVE", "DEAD"),
                                        timeout=600.0)
        self._abtrace("flusher_woke", actor_id.hex()[:8],
                      (info or {}).get("state"))
        while True:
            with self._actor_buffer_lock:
                buffered = self._actor_buffers.get(actor_id, [])
                if not buffered:
                    self._actor_buffers.pop(actor_id, None)
                    return
                batch = list(buffered)
                buffered.clear()
            retry = []
            for spec, streaming in batch:
                if info is None or info.get("state") != "ALIVE":
                    self._fail_actor_call(
                        spec, streaming, ActorDiedError(
                            actor_id.hex(),
                            "actor failed to start" if info is None
                            else info.get("death_reason",
                                          "actor is dead")))
                else:
                    try:
                        self._route_now(spec, streaming)
                        self._abtrace("flushed", spec.name)
                    except ActorDiedError as e:
                        self._abtrace("fail_flush", spec.name,
                                      str(e)[:60])
                        self._fail_actor_call(spec, streaming, e)
                    except (OSError, ConnectionError):
                        retry.append((spec, streaming))
            if retry:
                if time.monotonic() > deadline:
                    for spec, streaming in retry:
                        self._fail_actor_call(
                            spec, streaming, ActorDiedError(
                                actor_id.hex(),
                                "actor unreachable past deadline"))
                    continue
                # stale ALIVE info pointing at a dead node: wait for the
                # health loop to move the actor, then try again
                with self._actor_buffer_lock:
                    self._actor_buffers.setdefault(actor_id,
                                                   []).extend(retry)
                time.sleep(0.5)
                info = self.cp.wait_actor_state(
                    actor_id, ("ALIVE", "DEAD"),
                    timeout=max(0.0, deadline - time.monotonic()))

    def kill_actor(self, actor_id: bytes, no_restart: bool = True):
        self._actor_state_cache.pop(actor_id, None)
        self._actor_direct_cache.pop(actor_id, None)
        try:
            nm = self._actor_nm(actor_id, wait=True)
        except ActorDiedError:
            return
        if hasattr(nm, "call"):
            nm.call("kill_actor", actor_id, no_restart)
        else:
            nm.kill_actor(actor_id, no_restart)

    def cancel_task(self, ref: ObjectRef):
        if hasattr(self.nm, "call"):
            return self.nm.call("cancel_task", ref.task_id())
        return self.nm.cancel_task(ref.task_id())


# ----------------------------------------------------------------------
# Global worker management
# ----------------------------------------------------------------------
_global_worker: Optional[CoreWorker] = None
_global_node = None
_init_lock = threading.RLock()


def global_worker() -> CoreWorker:
    if _global_worker is None:
        raise RuntimeError(
            "ray_tpu has not been initialized; call ray_tpu.init() first")
    return _global_worker


def set_global_worker(worker: Optional[CoreWorker], node=None):
    global _global_worker, _global_node
    _global_worker = worker
    _global_node = node


def global_node():
    return _global_node


def is_initialized() -> bool:
    return _global_worker is not None
