"""Head-node / session bootstrap.

TPU-native analogue of ``python/ray/_private/node.py`` + ``services.py``:
creates the session directory, starts the control plane and the head node
manager (in-process rather than as separate daemons — one host needs no
process boundary; extra nodes run :mod:`ray_tpu._private.node_proc`).
"""

from __future__ import annotations

import atexit
import getpass
import json
import os
import shutil
import subprocess
import sys
import tempfile
import threading
import time
from typing import Any, Dict, Optional, Tuple

from ray_tpu._private import protocol
from ray_tpu._private.config import GLOBAL_CONFIG
from ray_tpu._private.control_plane import ControlPlane
from ray_tpu._private.ids import JobID, NodeID, WorkerID
from ray_tpu._private.node_manager import NodeManager
from ray_tpu._private.object_store import ShmStore
from ray_tpu._private.worker import CoreWorker


def _default_tmp_root() -> str:
    return os.path.join(tempfile.gettempdir(),
                        f"ray_tpu_{getpass.getuser()}")


def _shm_root(session_name: str) -> str:
    base = "/dev/shm" if os.path.isdir("/dev/shm") else tempfile.gettempdir()
    return os.path.join(base, f"ray_tpu_{session_name}")


def _gc_stale_sessions(keep: Optional[str] = None) -> None:
    """Remove session/shm dirs whose head process is gone.

    Session names embed the head pid (``session_<ts>_<pid>``); a dead pid
    means a crashed driver left state behind (reference equivalent: session
    dir cleanup in ``ray start``).  ``keep`` preserves a named session —
    the head-restart path re-enters a dead head's session dir to replay
    its control-plane journal.
    """
    import glob
    import re
    for path in (glob.glob(os.path.join(_default_tmp_root(), "session_*"))
                 + glob.glob(_shm_root("session_*"))
                 # cross-host client stores: client_<session>_<clientpid>
                 + glob.glob(os.path.join(_default_tmp_root(), "client_*"))):
        if keep and path.endswith(keep):
            continue
        m = re.search(r"_(\d+)$", path)
        if not m:
            continue
        pid = int(m.group(1))
        try:
            os.kill(pid, 0)
        except ProcessLookupError:
            shutil.rmtree(path, ignore_errors=True)
        except PermissionError:
            pass


def default_resources(num_cpus: Optional[float],
                      num_tpus: Optional[float],
                      resources: Optional[Dict[str, float]]) -> Dict[str,
                                                                     float]:
    from ray_tpu.accelerators.tpu import (TPUAcceleratorManager,
                                          detect_num_tpus)
    out: Dict[str, float] = {}
    out["CPU"] = float(num_cpus) if num_cpus is not None else float(
        os.cpu_count() or 1)
    tpus = float(num_tpus) if num_tpus is not None else float(
        detect_num_tpus())
    if tpus:
        out["TPU"] = tpus
        head_res = TPUAcceleratorManager.get_pod_head_resource_name()
        if head_res:
            out[head_res] = 1.0
        out.update(TPUAcceleratorManager.get_pod_slice_resources())
    out.update({k: float(v) for k, v in (resources or {}).items()})
    out.setdefault("node:__internal_head__", 1.0)
    return out


def _session_candidates(tmp_root: Optional[str] = None):
    """(cp_address, session_dir) candidates, newest session first."""
    import glob
    root = tmp_root or _default_tmp_root()

    def mtime(path):
        try:
            return os.path.getmtime(path)
        except OSError:  # deleted between glob and stat
            return 0.0

    out = []
    for session in sorted(glob.glob(os.path.join(root, "session_*")),
                          key=mtime, reverse=True):
        addr_file = os.path.join(session, "cp_address")
        try:
            if os.path.exists(addr_file):
                with open(addr_file) as f:
                    out.append((f.read().strip(), session))
                continue
        except OSError:
            continue
        sock = os.path.join(session, "sockets", "cp.sock")
        if os.path.exists(sock):
            out.append((sock, session))
    return out


def find_session_cp_address(tmp_root: Optional[str] = None
                            ) -> Optional[Tuple[str, str]]:
    """Newest session's (cp_address, session_dir) on this host (may be
    stale — AttachedNode probes candidates with ping)."""
    candidates = _session_candidates(tmp_root)
    return candidates[0] if candidates else None


class _ClientStore(ShmStore):
    """Store for a CROSS-HOST attached driver.

    The session's shm arena isn't path-attachable from another machine,
    so this driver keeps a *private* local store (reads: the existing
    chunked pull protocol fetches remote objects into it) and mirrors
    every put to the head node manager chunk-by-chunk — the primary copy
    must live where cluster workers can pull it (reference shape:
    ``python/ray/util/client/server/proxier.py`` routing object I/O
    through a server-side worker).
    """

    def __init__(self, root: str, head_nm_client, **kwargs):
        super().__init__(root, **kwargs)
        self._head_nm = head_nm_client
        self._push_chunk = GLOBAL_CONFIG.object_transfer_chunk_bytes

    def put_serialized(self, object_id: bytes, obj) -> int:
        size = super().put_serialized(object_id, obj)
        view = self.get_view(object_id)
        if view is None:
            raise RuntimeError(
                f"object {object_id.hex()} vanished from the client store "
                "before it could be pushed to the cluster")
        total = len(view)
        if total == 0:
            self._head_nm.call("push_object_chunk", object_id, 0, 0, b"")
            return size
        off = 0
        while off < total:
            n = min(self._push_chunk, total - off)
            # slice per chunk: one chunk-sized copy live at a time
            self._head_nm.call("push_object_chunk", object_id,
                               total, off, bytes(view[off:off + n]))
            off += n
        del view
        # drop the mmap this get_view cached: a mapped object is skipped
        # by eviction, and a put-mostly client would otherwise pin every
        # pushed object in its private store forever
        self.release_mapping(object_id)
        return size


class AttachedNode:
    """A second driver connected to an EXISTING cluster.

    The client-mode the reference reaches with ``ray.init(address=...)``
    (``python/ray/_private/worker.py`` connect-to-existing): this
    process gets its own CoreWorker/job but rides the running session's
    control plane and head node manager.  On the same host the shm
    store is attached by path; from another host (detected by the
    session directory not existing locally, or forced with
    ``RAY_TPU_REMOTE_ATTACH=1``) object I/O routes through the head
    node manager over TCP: puts push chunks up, gets ride the standard
    pull protocol into a private local store.

    ``shutdown()`` detaches — it never tears the session down.
    """

    def __init__(self, address: str = "auto",
                 namespace: str = "default"):
        if address == "auto":
            # probe newest-first: a cleanly-shut-down session leaves its
            # dir (and cp_address file) behind, so ping until live
            cp_addr = session_dir = None
            for cand_addr, cand_dir in _session_candidates():
                try:
                    protocol.RpcClient(cand_addr,
                                       connect_timeout=2.0).ping()
                    cp_addr, session_dir = cand_addr, cand_dir
                    break
                except Exception:  # noqa: BLE001 — dead session
                    continue
            if cp_addr is None:
                raise ConnectionError(
                    "address='auto': no live ray_tpu session on this "
                    "host")
        elif os.path.isdir(address):  # a session directory
            with open(os.path.join(address, "cp_address")) as f:
                cp_addr = f.read().strip()
            session_dir = address
        else:  # explicit cp address (tcp:// or socket path)
            cp_addr = address
            session_dir = None
        self.cp_sock_path = cp_addr
        cp = protocol.RpcClient(cp_addr)
        cp.ping()  # fail fast on a dead session
        # the head node hosts the shared store + default scheduler
        head = None
        for info in cp.list_nodes():
            if info.get("state") != "ALIVE":
                continue
            if "node:__internal_head__" in (
                    info.get("resources_total") or {}):
                head = info
                break
        if head is None:
            raise ConnectionError("no ALIVE head node in session")
        self.session_dir = session_dir or head["session_dir"]
        self.session_name = os.path.basename(self.session_dir)
        self.node_id = head["node_id"]
        nm = protocol.RpcClient(head["sock_path"])
        remote_host = (os.environ.get("RAY_TPU_REMOTE_ATTACH") == "1"
                       or not os.path.isdir(self.session_dir))
        self._client_root = None
        if remote_host:
            # cross-host: private local store + push/pull through the
            # head NM (requires a tcp:// session).  The client gets its
            # OWN node id: pulls of head-resident objects must not be
            # skipped as "local" (worker._pull_remote compares node ids).
            self.node_id = NodeID.from_random().binary()
            # reap private stores left by drivers that died without a
            # clean shutdown — on a client-only host no HeadNode ever
            # runs this GC for us
            _gc_stale_sessions()
            client_root = os.path.join(
                _default_tmp_root(),
                f"client_{self.session_name}_{os.getpid()}")
            self._client_root = client_root
            store = _ClientStore(
                client_root, nm,
                spill_dir=GLOBAL_CONFIG.object_spill_dir
                or os.path.join(client_root, "spill"))
        else:
            # same host: attach the session's shm root by path —
            # per-object files + multi-process-safe arena.  spill_dir
            # mirrors the head's default so spilled objects stay
            # readable here.
            store = ShmStore(_shm_root(self.session_name),
                             spill_dir=GLOBAL_CONFIG.object_spill_dir
                             or os.path.join(self.session_dir, "spill"))
        self.store = store
        self.control_plane = cp
        self.job_id = JobID.from_random()
        self.worker = CoreWorker(
            mode="driver", job_id=self.job_id,
            worker_id=WorkerID.from_random(), node_id=self.node_id,
            control_plane=cp, node_manager=nm, shm_store=store,
            session_dir=self.session_dir, namespace=namespace,
            nm_addr=head["sock_path"])
        if remote_host:
            # puts are mirrored to the head's store: advertise THAT as
            # the committed location so cluster workers pull from it
            self.worker.commit_node_id = head["node_id"]
        from ray_tpu._private.ref_tracker import install_tracker
        install_tracker(self.worker.worker_id.binary(), cp,
                        node_id=self.node_id)
        self.log_monitor = None
        if GLOBAL_CONFIG.log_to_driver:
            from ray_tpu._private.log_streaming import DriverLogMonitor
            self.log_monitor = DriverLogMonitor(cp)
            self.log_monitor.start()
        self._stopped = False

    def shutdown(self):
        if self._stopped:
            return
        self._stopped = True
        from ray_tpu._private.ref_tracker import uninstall_tracker
        uninstall_tracker()
        try:
            # release every ref this driver still holds — nothing else
            # purges an attached driver's holder id (a crashed attach
            # leaks its pins until session end; bounded, but clean
            # detach should not)
            self.control_plane.purge_holder(self.worker.worker_id.binary())
        except Exception:  # noqa: BLE001 — session may be gone
            pass
        if self.log_monitor is not None:
            self.log_monitor.stop()
        if self._client_root:
            shutil.rmtree(self._client_root, ignore_errors=True)


class HeadNode:
    """Everything a single-host cluster needs, hosted in the driver."""

    def __init__(self, num_cpus: Optional[float] = None,
                 num_tpus: Optional[float] = None,
                 resources: Optional[Dict[str, float]] = None,
                 namespace: str = "default",
                 system_config: Optional[Dict[str, Any]] = None,
                 session_name: Optional[str] = None):
        GLOBAL_CONFIG.apply_system_config(system_config or {})
        _gc_stale_sessions(keep=session_name)
        self.session_name = session_name or (
            f"session_{time.strftime('%Y%m%d_%H%M%S')}_{os.getpid()}")
        self.session_dir = os.path.join(_default_tmp_root(),
                                        self.session_name)
        os.makedirs(os.path.join(self.session_dir, "sockets"), exist_ok=True)
        os.makedirs(os.path.join(self.session_dir, "logs"), exist_ok=True)
        self.shm_root = _shm_root(self.session_name)
        self.spill_dir = (GLOBAL_CONFIG.object_spill_dir
                          or os.path.join(self.session_dir, "spill"))

        self.control_plane = ControlPlane()
        self.cp_journal = None
        if GLOBAL_CONFIG.cp_persistence:
            from ray_tpu._private.persistence import (Journal,
                                                      restore_control_plane)
            journal_path = os.path.join(self.session_dir, "cp_journal.bin")
            restored = 0
            if os.path.exists(journal_path):
                restored = restore_control_plane(self.control_plane,
                                                 journal_path)
            self.cp_journal = Journal(journal_path,
                                      sync=GLOBAL_CONFIG.cp_journal_sync)
            self.control_plane.attach_journal(self.cp_journal)
            if restored:
                # compact on every restart so a crash loop can't grow the
                # journal (replays re-append on top of the old log)
                self.control_plane.compact_journal()
        if GLOBAL_CONFIG.use_tcp:
            self.cp_sock_path = f"tcp://{GLOBAL_CONFIG.node_ip}:0"
        else:
            self.cp_sock_path = os.path.join(self.session_dir, "sockets",
                                             "cp.sock")
        self.cp_server = protocol.RpcServer(self.cp_sock_path,
                                            self.control_plane, name="cp")
        self.cp_sock_path = self.cp_server.address
        with open(os.path.join(self.session_dir, "cp_address"), "w") as f:
            f.write(self.cp_sock_path)
        self.store = ShmStore(self.shm_root, spill_dir=self.spill_dir)
        self.node_id = NodeID.from_random().binary()
        self.resources = default_resources(num_cpus, num_tpus, resources)
        self.node_manager = NodeManager(
            node_id=self.node_id, session_dir=self.session_dir,
            control_plane=self.control_plane,
            cp_sock_path=self.cp_sock_path, shm_store=self.store,
            resources=self.resources)
        self.job_id = JobID.from_random()
        self.worker = CoreWorker(
            mode="driver", job_id=self.job_id,
            worker_id=WorkerID.from_random(), node_id=self.node_id,
            control_plane=self.control_plane,
            node_manager=self.node_manager, shm_store=self.store,
            session_dir=self.session_dir, namespace=namespace,
            nm_addr=self.node_manager.sock_path)
        from ray_tpu._private.ref_tracker import install_tracker
        install_tracker(self.worker.worker_id.binary(),
                        self.control_plane, node_id=self.node_id)
        self._extra_nodes: list = []
        self._stopped = False
        self._health_thread = threading.Thread(
            target=self._health_loop, daemon=True, name="head-health")
        self._health_thread.start()
        self._gc_thread = threading.Thread(
            target=self._gc_loop, daemon=True, name="head-object-gc")
        self._gc_thread.start()
        self.log_monitor = None
        if GLOBAL_CONFIG.log_to_driver:
            from ray_tpu._private.log_streaming import DriverLogMonitor
            self.log_monitor = DriverLogMonitor(self.control_plane)
            self.log_monitor.start()
        atexit.register(self.shutdown)

    # ------------------------------------------------------------------
    def add_node(self, num_cpus: float = 1.0, num_tpus: float = 0.0,
                 resources: Optional[Dict[str, float]] = None,
                 env: Optional[Dict[str, str]] = None) -> bytes:
        """Spawn an extra node-manager process (multi-node simulation).

        Parity: reference ``python/ray/cluster_utils.py`` ``Cluster.add_node``
        (real raylet processes on one machine).
        """
        node_id = NodeID.from_random().binary()
        res = {"CPU": float(num_cpus)}
        if num_tpus:
            res["TPU"] = float(num_tpus)
        res.update(resources or {})
        proc_env = dict(os.environ)
        proc_env.update(env or {})
        from ray_tpu._private.node_proc import build_env
        # Every node owns a DISTINCT shm root: objects move between
        # nodes only via the chunked pull protocol (node_manager
        # fetch_object_chunk), never via a shared filesystem.  This is
        # what makes the single-host simulation faithful to multi-host
        # (reference: per-node plasma + object_manager Push/Pull).
        proc_env.update(build_env(
            session_dir=self.session_dir, cp_addr=self.cp_sock_path,
            node_id=node_id,
            shm_root=f"{self.shm_root}_node_{node_id.hex()[:12]}",
            spill_dir=os.path.join(self.spill_dir,
                                   f"node_{node_id.hex()[:12]}"),
            resources=res, use_tcp=GLOBAL_CONFIG.use_tcp,
            node_ip=GLOBAL_CONFIG.node_ip))
        log = open(os.path.join(self.session_dir, "logs",
                                f"node-{node_id.hex()[:12]}.log"), "ab")
        proc = subprocess.Popen(
            [sys.executable, "-m", "ray_tpu._private.node_proc"],
            env=proc_env, stdout=log, stderr=subprocess.STDOUT)
        log.close()
        self._extra_nodes.append((node_id, proc))
        deadline = time.time() + 30
        while time.time() < deadline:
            info = self.control_plane.get_node(node_id)
            if info is not None:
                return node_id
            if proc.poll() is not None:
                raise RuntimeError(
                    f"node process exited with {proc.returncode}")
            time.sleep(0.05)
        raise TimeoutError("extra node failed to register")

    def remove_node(self, node_id: bytes) -> None:
        for nid, proc in self._extra_nodes:
            if nid == node_id:
                proc.terminate()
                try:
                    proc.wait(timeout=5)
                except subprocess.TimeoutExpired:
                    proc.kill()
                self.control_plane.mark_node_dead(node_id, "removed")
                self._on_node_dead(node_id)
                return
        raise KeyError(node_id.hex())

    # ------------------------------------------------------------------
    def _health_loop(self):
        timeout = GLOBAL_CONFIG.health_check_timeout_s
        period = GLOBAL_CONFIG.health_check_period_s
        while not self._stopped:
            time.sleep(period)
            if self._stopped:
                return
            now = time.time()
            for info in self.control_plane.list_nodes():
                if info["state"] != "ALIVE":
                    continue
                if info["node_id"] == self.node_id:
                    continue
                if now - info.get("last_heartbeat", now) > timeout:
                    self.control_plane.mark_node_dead(
                        info["node_id"], "missed heartbeats")
                    try:
                        self._on_node_dead(info["node_id"])
                    except Exception:  # noqa: BLE001
                        import traceback
                        traceback.print_exc()

    def _on_node_dead(self, node_id: bytes):
        """Recover cluster state owned by a dead node.

        Reference behavior: ``gcs_actor_manager.cc`` (restart or kill the
        node's actors), ``gcs_placement_group_manager`` (reschedule
        bundles), and owner-side task retry.  Here the head drives all
        three from control-plane state.
        """
        cp = self.control_plane
        dead_hex = node_id.hex()
        # 0. refcounts: the dead node's workers flushed counts to the CP
        # and to owner NMs cluster-wide; their own NM died before it
        # could purge them, so the head broadcasts the purge
        cp.purge_node_holders(node_id)
        self.node_manager.purge_owned_node_holders(node_id)
        for info in cp.list_nodes():
            if (info.get("state") != "ALIVE"
                    or info["node_id"] == self.node_id):
                continue
            try:
                protocol.RpcClient(info["sock_path"]).call(
                    "purge_owned_node_holders", node_id)
            except (OSError, ConnectionError):
                pass
        # 1. actors hosted on the dead node: restart elsewhere or kill
        for info in cp.list_actors():
            if info.get("node_id") != node_id:
                continue
            if info.get("state") not in ("ALIVE", "PENDING", "RESTARTING"):
                continue
            aid = info["actor_id"]
            spec = info.get("creation_spec")
            max_restarts = info.get("max_restarts", 0)
            used = info.get("num_restarts", 0)
            if spec is not None and (max_restarts == -1
                                     or used < max_restarts):
                cp.update_actor(aid, state="RESTARTING",
                                num_restarts=used + 1, nm_sock=None,
                                node_id=None)
                self.node_manager.submit_actor_creation(spec)
            else:
                cp.update_actor(
                    aid, state="DEAD",
                    death_reason=f"node {dead_hex[:12]} died")
        # 2. normal tasks that were queued/running there: re-execute from
        # lineage (their callers still wait on the return objects)
        for ev in cp.tasks_last_state():
            if ev.get("node") != dead_hex:
                continue
            if ev.get("state") not in ("PENDING", "RUNNING", "RETRY"):
                continue
            spec = cp.get_lineage(bytes.fromhex(ev["task_id"]))
            if spec is not None and not spec.actor_creation \
                    and spec.actor_id is None:
                self.node_manager.submit_task(spec)
        # 3. placement groups with bundles on the dead node: release the
        # surviving reservations and re-reserve the whole group
        from ray_tpu.util import placement_group as pg_mod
        nodes_by_hex = {n["node_id"].hex(): n for n in cp.list_nodes()}
        for pg in cp.list_placement_groups():
            bundle_nodes = pg.get("bundle_nodes") or []
            if dead_hex not in bundle_nodes or pg.get("state") in (
                    "REMOVED", "FAILED"):
                continue
            for index, (bundle, nid_hex) in enumerate(
                    zip(pg.get("bundles", []), bundle_nodes)):
                node = nodes_by_hex.get(nid_hex)
                if node is None or node["state"] != "ALIVE":
                    continue
                try:
                    pg_mod._call(
                        pg_mod._nm_client_for(self.worker, node),
                        "return_bundle", pg["pg_id"], index, bundle)
                except (OSError, ConnectionError):
                    pass
            cp.update_placement_group(pg["pg_id"], state="RESCHEDULING",
                                      bundle_nodes=[])
            threading.Thread(
                target=pg_mod._reserve_loop,
                args=(pg["pg_id"], pg.get("bundles", []),
                      pg.get("strategy", "PACK")),
                daemon=True, name="pg-reschedule").start()

    def _gc_loop(self):
        """Periodic object GC: free unreferenced objects + fan out shm
        deletions to every node's store (reference: owner-driven
        free + plasma deletion)."""
        period = GLOBAL_CONFIG.object_gc_period_s
        grace = GLOBAL_CONFIG.object_gc_grace_s
        while not self._stopped:
            time.sleep(period)
            if self._stopped:
                return
            try:
                freed = self.control_plane.gc_sweep(grace)
                self.control_plane.maybe_compact(
                    GLOBAL_CONFIG.cp_journal_compact_records)
            except Exception:  # noqa: BLE001
                continue
            if not freed:
                continue
            self.node_manager.delete_objects(freed)
            for info in self.control_plane.list_nodes():
                if (info["state"] != "ALIVE"
                        or info["node_id"] == self.node_id):
                    continue
                try:
                    protocol.RpcClient(info["sock_path"]).call(
                        "delete_objects", freed)
                except (OSError, ConnectionError):
                    pass

    def shutdown(self):
        if self._stopped:
            return
        self._stopped = True
        from ray_tpu._private.ref_tracker import uninstall_tracker
        uninstall_tracker()
        for nid, proc in self._extra_nodes:
            proc.terminate()
        for nid, proc in self._extra_nodes:
            try:
                proc.wait(timeout=3)
            except subprocess.TimeoutExpired:
                proc.kill()
        self.node_manager.stop()
        if self.log_monitor is not None:
            self.log_monitor.stop()
        self.cp_server.shutdown()
        if self.cp_journal is not None:
            self.cp_journal.close()
        self.store.destroy()
        shutil.rmtree(self.spill_dir, ignore_errors=True)
        # extra-node stores (SIGKILLed nodes never ran their own cleanup)
        import glob
        for path in glob.glob(f"{self.shm_root}_node_*"):
            shutil.rmtree(path, ignore_errors=True)
