"""ctypes binding for the native arena store (src/shmstore/shmstore.cc).

Builds the .so on first use if the toolchain is available (the build is a
single translation unit, sub-second); callers fall back to the pure-python
file store when unavailable.
"""

from __future__ import annotations

import ctypes
import mmap as _mmap
import os
import subprocess
import threading
from typing import Optional

_LIB_LOCK = threading.Lock()
_LIB: Optional[ctypes.CDLL] = None
_BUILD_FAILED = False


def _native_dir() -> str:
    return os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "_native")


def _lib_path() -> str:
    return os.path.join(_native_dir(), "libshmstore.so")


def _src_dir() -> str:
    return os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), "src", "shmstore")


def _ensure_built() -> Optional[str]:
    global _BUILD_FAILED
    path = _lib_path()
    src = os.path.join(_src_dir(), "shmstore.cc")
    if os.path.exists(path) and os.path.exists(src) and \
            os.path.getmtime(path) >= os.path.getmtime(src):
        return path
    if _BUILD_FAILED or not os.path.exists(src):
        return path if os.path.exists(path) else None
    os.makedirs(_native_dir(), exist_ok=True)
    try:
        subprocess.run(
            ["g++", "-O2", "-fPIC", "-std=c++17", "-shared", "-o",
             path + ".tmp", src, "-lpthread"],
            check=True, capture_output=True, timeout=120)
        os.replace(path + ".tmp", path)
        return path
    except (subprocess.SubprocessError, OSError):
        _BUILD_FAILED = True
        return path if os.path.exists(path) else None


def get_lib() -> Optional[ctypes.CDLL]:
    global _LIB
    with _LIB_LOCK:
        if _LIB is not None:
            return _LIB
        path = _ensure_built()
        if path is None:
            return None
        try:
            lib = ctypes.CDLL(path)
        except OSError:
            return None
        lib.shmstore_create.restype = ctypes.c_void_p
        lib.shmstore_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64,
                                        ctypes.c_uint32]
        lib.shmstore_attach.restype = ctypes.c_void_p
        lib.shmstore_attach.argtypes = [ctypes.c_char_p]
        lib.shmstore_create_object.restype = ctypes.c_int64
        lib.shmstore_create_object.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64]
        lib.shmstore_seal.restype = ctypes.c_int
        lib.shmstore_seal.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.shmstore_get.restype = ctypes.c_int64
        lib.shmstore_get.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                     ctypes.POINTER(ctypes.c_uint64),
                                     ctypes.c_int]
        lib.shmstore_get_copy.restype = ctypes.c_int64
        lib.shmstore_get_copy.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                          ctypes.c_char_p, ctypes.c_uint64]
        lib.shmstore_evict.restype = ctypes.c_int
        lib.shmstore_evict.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.shmstore_release.restype = ctypes.c_int
        lib.shmstore_release.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.shmstore_delete.restype = ctypes.c_int
        lib.shmstore_delete.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.shmstore_contains.restype = ctypes.c_int
        lib.shmstore_contains.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.shmstore_stats.argtypes = [ctypes.c_void_p,
                                       ctypes.POINTER(ctypes.c_uint64 * 6)]
        lib.shmstore_base.restype = ctypes.c_void_p
        lib.shmstore_base.argtypes = [ctypes.c_void_p]
        lib.shmstore_detach.argtypes = [ctypes.c_void_p]
        _LIB = lib
        return _LIB


class NativeArena:
    """One mmap'd arena; create on the head, attach everywhere else."""

    def __init__(self, path: str, capacity: int = 0,
                 max_entries: int = 65536, create: bool = False):
        lib = get_lib()
        if lib is None:
            raise RuntimeError("native shmstore unavailable")
        self.lib = lib
        self.path = path
        if create:
            self.handle = lib.shmstore_create(path.encode(), capacity,
                                              max_entries)
            if not self.handle:
                # lost a create race: attach instead (the C side retries
                # until the winner's release-store publishes the magic)
                self.handle = lib.shmstore_attach(path.encode())
        else:
            # the creator may not have created the file yet; retry briefly
            import time as _time
            self.handle = None
            for _ in range(100):
                self.handle = lib.shmstore_attach(path.encode())
                if self.handle:
                    break
                _time.sleep(0.01)
        if not self.handle:
            raise RuntimeError(f"cannot open arena at {path}")
        base = lib.shmstore_base(self.handle)
        size = os.path.getsize(path)
        # one python memoryview over the whole arena for zero-copy reads
        self._view = (ctypes.c_ubyte * size).from_address(base)
        self.mem = memoryview(self._view).cast("B")

    def put(self, object_id: bytes, payload_writer, size: int) -> bool:
        """payload_writer(memoryview) fills the reserved slice."""
        off = self.lib.shmstore_create_object(self.handle, object_id, size)
        if off < 0:
            return False
        payload_writer(self.mem[off:off + size])
        self.lib.shmstore_seal(self.handle, object_id)
        return True

    def put_bytes(self, object_id: bytes, data: bytes) -> bool:
        return self.put(object_id, lambda m: m.__setitem__(
            slice(None), data), len(data))

    def get(self, object_id: bytes) -> Optional[memoryview]:
        """Copy the object out under the store mutex.

        Deliberately NOT zero-copy: a borrowed view into the arena can
        outlive the entry (delete + reallocate corrupts it from under the
        reader — round-1 advisory).  Arena objects are small (see
        ``ShmStore.ARENA_MAX_OBJECT``), so the locked memcpy is cheap;
        large objects take the file-mmap path, which IS zero-copy and
        unlink-safe.
        """
        while True:
            size = self.lib.shmstore_get_copy(self.handle, object_id,
                                              None, 0)
            if size < 0:
                return None
            buf = ctypes.create_string_buffer(size)
            rc = self.lib.shmstore_get_copy(self.handle, object_id, buf,
                                            size)
            if rc == -2:
                continue  # recreated bigger between the two calls; retry
            if rc < 0:
                return None
            return memoryview(buf)[:rc].toreadonly()

    def contains(self, object_id: bytes) -> bool:
        return bool(self.lib.shmstore_contains(self.handle, object_id))

    def delete(self, object_id: bytes) -> bool:
        return self.lib.shmstore_delete(self.handle, object_id) == 0

    def stats(self) -> dict:
        out = (ctypes.c_uint64 * 6)()
        self.lib.shmstore_stats(self.handle, ctypes.byref(out))
        return {"used_bytes": out[0], "capacity_bytes": out[1],
                "num_objects": out[2], "num_puts": out[3],
                "num_gets": out[4], "num_evictions": out[5]}

    def detach(self):
        if self.handle:
            self.lib.shmstore_detach(self.handle)
            self.handle = None
