"""Task-event timeline — Chrome/Perfetto trace export.

Parity: reference ``python/ray/_private/profiling.py``
(``chrome_tracing_dump``) fed by the task-event backbone (GCS task
manager).  Load the output in ``chrome://tracing`` / Perfetto.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional


def chrome_tracing_dump(task_events: List[Dict[str, Any]],
                        filename: Optional[str] = None) -> str:
    """Convert task state transitions into Chrome trace events."""
    # group by task: RUNNING -> FINISHED/FAILED becomes a complete event
    by_task: Dict[str, List[Dict[str, Any]]] = {}
    for ev in task_events:
        by_task.setdefault(ev.get("task_id", "?"), []).append(ev)
    trace = []
    for task_id, events in by_task.items():
        events.sort(key=lambda e: e.get("time", 0))
        name = next((e.get("name") for e in events if e.get("name")),
                    task_id[:8])
        start = None
        worker = None
        for ev in events:
            state = ev.get("state")
            if state == "RUNNING":
                start = ev.get("time")
                worker = ev.get("worker", ev.get("node", "driver"))
            elif state in ("FINISHED", "FAILED") and start is not None:
                trace.append({
                    "cat": "task", "name": name, "ph": "X",
                    "ts": start * 1e6,
                    "dur": (ev["time"] - start) * 1e6,
                    "pid": ev.get("node", "node")[:8],
                    "tid": (worker or "worker")[:8],
                    "args": {"task_id": task_id, "state": state},
                })
                start = None
    out = json.dumps(trace)
    if filename:
        with open(filename, "w") as f:
            f.write(out)
    return out


def timeline(filename: Optional[str] = None,
             limit: int = 100_000) -> str:
    """Cluster task timeline + the unified host/train telemetry events
    (``ray_tpu.telemetry.chrome_trace``) as one Chrome-trace array, so
    the dashboard ``/api/timeline`` shows train steps beside tasks."""
    from ray_tpu._private.worker import global_worker
    events = global_worker().cp.list_task_events(limit)
    trace = json.loads(chrome_tracing_dump(events))
    try:
        from ray_tpu.telemetry import chrome_trace
        trace.extend(chrome_trace.trace_events())
    except Exception:  # noqa: BLE001 — telemetry is optional here
        pass
    out = json.dumps(trace)
    if filename:
        with open(filename, "w") as f:
            f.write(out)
    return out
