"""Task / actor specifications — the wire contract of the scheduler.

TPU-native analogue of the reference's ``src/ray/protobuf/common.proto``
``TaskSpec`` + ``src/ray/common/task/task_spec.cc``.  Specs are plain
dataclasses pickled over the control sockets; argument values are either
inline serialized bytes or ObjectID references (the reference inlines
"small" args the same way — ``transport/dependency_resolver.cc``).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple


@dataclass
class Arg:
    """One task argument: exactly one of ``inline`` / ``object_id`` set."""
    inline: Optional[bytes] = None
    object_id: Optional[bytes] = None


@dataclass
class SchedulingStrategy:
    """Normalized scheduling strategy.

    kinds: "default" (hybrid policy), "spread",
    "node_affinity" (node_id, soft), "placement_group" (pg_id, bundle_index,
    capture_child_tasks).
    """
    kind: str = "default"
    node_id: Optional[bytes] = None
    soft: bool = False
    pg_id: Optional[bytes] = None
    bundle_index: int = -1
    capture_child_tasks: bool = False


@dataclass
class TaskSpec:
    task_id: bytes
    job_id: bytes
    name: str
    # Function: key into the control-plane function table (cloudpickled).
    function_key: bytes
    args: List[Arg] = field(default_factory=list)
    kwargs: Dict[str, Arg] = field(default_factory=dict)
    num_returns: int = 1
    resources: Dict[str, float] = field(default_factory=dict)
    max_retries: int = 0
    retry_exceptions: bool = False
    scheduling_strategy: SchedulingStrategy = field(
        default_factory=SchedulingStrategy)
    # Actor fields
    actor_id: Optional[bytes] = None          # set for actor tasks
    actor_creation: bool = False              # this task constructs the actor
    actor_method: Optional[str] = None
    seq_no: int = 0                           # per-caller ordering
    max_restarts: int = 0
    max_task_retries: int = 0
    max_concurrency: int = 1
    # Generator tasks
    is_generator: bool = False
    # Owner (submitting worker) for lineage/debugging
    owner_id: bytes = b""
    # RPC address of the submitting worker's node manager: return
    # objects are refcounted there (node-granularity ownership;
    # reference: caller-owned returns in reference_count.cc)
    owner_addr: str = ""
    # Owner address per ObjectRef argument, so dependency pins route to
    # each dep's owner instead of the control plane
    ref_owners: Dict[bytes, str] = field(default_factory=dict)
    # Runtime env / accelerator visibility
    runtime_env: Dict[str, Any] = field(default_factory=dict)
    # Depth for hybrid-policy tie-breaking; parent task id for lineage
    parent_task_id: Optional[bytes] = None

    def return_object_ids(self) -> List[bytes]:
        from ray_tpu._private.ids import ObjectID, TaskID
        tid = TaskID(self.task_id)
        return [ObjectID.for_task_return(tid, i).binary()
                for i in range(self.num_returns)]

    def dependencies(self) -> List[bytes]:
        deps = [a.object_id for a in self.args if a.object_id is not None]
        deps += [a.object_id for a in self.kwargs.values()
                 if a.object_id is not None]
        return deps


@dataclass
class Bundle:
    """One placement-group bundle: a resource set reserved atomically."""
    resources: Dict[str, float]
    node_id: Optional[bytes] = None  # filled when committed


def normalize_resources(num_cpus: Optional[float], num_gpus: Optional[float],
                        num_tpus: Optional[float],
                        resources: Optional[Dict[str, float]],
                        memory: Optional[float] = None,
                        default_cpus: float = 1.0) -> Dict[str, float]:
    out: Dict[str, float] = {}
    out["CPU"] = float(num_cpus) if num_cpus is not None else default_cpus
    if num_gpus:
        out["GPU"] = float(num_gpus)
    if num_tpus:
        out["TPU"] = float(num_tpus)
    if memory:
        out["memory"] = float(memory)
    for k, v in (resources or {}).items():
        if k in ("CPU", "GPU", "TPU", "memory"):
            raise ValueError(
                f"Use the dedicated argument for resource {k!r}")
        out[k] = float(v)
    return {k: v for k, v in out.items() if v != 0 or k == "CPU"}


def fits(avail: Dict[str, float], need: Dict[str, float]) -> bool:
    for k, v in need.items():
        if v > 0 and avail.get(k, 0.0) + 1e-9 < v:
            return False
    return True


def acquire(avail: Dict[str, float], need: Dict[str, float]) -> None:
    for k, v in need.items():
        avail[k] = avail.get(k, 0.0) - v


def release(avail: Dict[str, float], need: Dict[str, float]) -> None:
    for k, v in need.items():
        avail[k] = avail.get(k, 0.0) + v
