"""Binary identifiers for the runtime.

TPU-native re-design of the reference's ID scheme (reference:
``src/ray/common/id.h`` / ``id.cc``).  We keep the same structural idea —
IDs are fixed-width random byte strings, ObjectIDs embed the TaskID that
produced them plus a return-index so lineage can be recovered from the ID
alone — but the widths are chosen fresh and there is no CRC suffix.

Layout
------
JobID      4  bytes   random per driver
ActorID   12  bytes   = job_id(4) + random(8)
TaskID    24  bytes   = actor_id(12) + random(12)  for actor tasks,
                        job_id(4) + random(20)      for normal tasks
ObjectID  28  bytes   = task_id(24) + big-endian return index(4)
NodeID    16  bytes   random
WorkerID  16  bytes   random
PlacementGroupID 12 bytes = job_id(4) + random(8)

The 12-byte random portion of actor TaskIDs keeps collision probability
negligible over an actor's lifetime (the reference uses comparably wide
random task components; 4 bytes would collide at ~1% per 10k calls).
The native arena store (src/shmstore) must agree on ObjectID width —
``kIdSize`` there equals ``_OBJECT_ID_SIZE``.
"""

from __future__ import annotations

import os
import threading

_JOB_ID_SIZE = 4
_ACTOR_ID_SIZE = 12
_TASK_ID_SIZE = 24
_OBJECT_ID_SIZE = 28
_UNIQUE_ID_SIZE = 16
_PG_ID_SIZE = 12


class BaseID:
    SIZE = _UNIQUE_ID_SIZE
    __slots__ = ("_binary", "_hash")

    def __init__(self, binary: bytes):
        if not isinstance(binary, bytes) or len(binary) != self.SIZE:
            raise ValueError(
                f"{type(self).__name__} requires {self.SIZE} bytes, "
                f"got {binary!r}"
            )
        self._binary = binary
        self._hash = hash((type(self).__name__, binary))

    @classmethod
    def from_random(cls) -> "BaseID":
        return cls(os.urandom(cls.SIZE))

    @classmethod
    def from_hex(cls, hex_str: str) -> "BaseID":
        return cls(bytes.fromhex(hex_str))

    @classmethod
    def nil(cls) -> "BaseID":
        return cls(b"\x00" * cls.SIZE)

    def is_nil(self) -> bool:
        return self._binary == b"\x00" * self.SIZE

    def binary(self) -> bytes:
        return self._binary

    def hex(self) -> str:
        return self._binary.hex()

    def __hash__(self):
        return self._hash

    def __eq__(self, other):
        return type(other) is type(self) and other._binary == self._binary

    def __lt__(self, other):
        return self._binary < other._binary

    def __repr__(self):
        return f"{type(self).__name__}({self.hex()})"

    def __reduce__(self):
        return (type(self), (self._binary,))


class JobID(BaseID):
    SIZE = _JOB_ID_SIZE


class NodeID(BaseID):
    SIZE = _UNIQUE_ID_SIZE


class WorkerID(BaseID):
    SIZE = _UNIQUE_ID_SIZE


class ActorID(BaseID):
    SIZE = _ACTOR_ID_SIZE

    @classmethod
    def of(cls, job_id: JobID) -> "ActorID":
        return cls(job_id.binary() + os.urandom(cls.SIZE - JobID.SIZE))

    def job_id(self) -> JobID:
        return JobID(self._binary[: JobID.SIZE])


class PlacementGroupID(BaseID):
    SIZE = _PG_ID_SIZE

    @classmethod
    def of(cls, job_id: JobID) -> "PlacementGroupID":
        return cls(job_id.binary() + os.urandom(cls.SIZE - JobID.SIZE))


class TaskID(BaseID):
    SIZE = _TASK_ID_SIZE

    @classmethod
    def for_normal_task(cls, job_id: JobID) -> "TaskID":
        return cls(job_id.binary() + os.urandom(cls.SIZE - JobID.SIZE))

    @classmethod
    def for_actor_task(cls, actor_id: ActorID) -> "TaskID":
        return cls(actor_id.binary() + os.urandom(cls.SIZE - ActorID.SIZE))

    @classmethod
    def for_actor_creation(cls, actor_id: ActorID) -> "TaskID":
        # Deterministic: the creation task of an actor is identified by the
        # actor id padded with 0xff, so restarts resubmit the same task id.
        return cls(actor_id.binary() + b"\xff" * (cls.SIZE - ActorID.SIZE))

    def job_id(self) -> JobID:
        return JobID(self._binary[: JobID.SIZE])


class ObjectID(BaseID):
    SIZE = _OBJECT_ID_SIZE

    @classmethod
    def for_task_return(cls, task_id: TaskID, index: int) -> "ObjectID":
        if not 0 <= index < 2**32:
            raise ValueError(f"return index out of range: {index}")
        return cls(task_id.binary() + index.to_bytes(4, "big"))

    @classmethod
    def from_random(cls) -> "ObjectID":
        # ``ray.put`` objects: owned by a synthetic task id.
        return cls(os.urandom(_TASK_ID_SIZE) + (2**32 - 1).to_bytes(4, "big"))

    def task_id(self) -> TaskID:
        return TaskID(self._binary[:_TASK_ID_SIZE])

    def return_index(self) -> int:
        return int.from_bytes(self._binary[_TASK_ID_SIZE:], "big")

    def is_put_object(self) -> bool:
        return self.return_index() == 2**32 - 1


class _Counter:
    """Thread-safe monotonically increasing counter."""

    def __init__(self):
        self._value = 0
        self._lock = threading.Lock()

    def next(self) -> int:
        with self._lock:
            self._value += 1
            return self._value
