"""Worker process entrypoint (``python -m ray_tpu._private.worker_proc``).

TPU-native analogue of the reference's ``python/ray/_private/workers/
default_worker.py`` + the execution half of the core worker: connects to
the node manager's task channel, executes pushed tasks/actor methods, and
commits results to the object store.
"""

from __future__ import annotations

import asyncio
import inspect
import socket
import os
import sys
import threading
import traceback
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, List, Optional

from ray_tpu._private import protocol, serialization
from ray_tpu._private.ids import JobID, WorkerID
from ray_tpu._private.object_store import ShmStore
from ray_tpu._private.task_spec import Arg, TaskSpec
from ray_tpu._private.worker import CoreWorker, set_global_worker
from ray_tpu.exceptions import TaskError, format_remote_traceback
from ray_tpu.object_ref import ObjectRef


class WorkerProcess:
    def __init__(self):
        self.session_dir = os.environ["RAY_TPU_SESSION_DIR"]
        self.cp_sock = os.environ["RAY_TPU_CP_SOCK"]
        self.nm_sock = os.environ["RAY_TPU_NM_SOCK"]
        self.worker_id = WorkerID.from_hex(os.environ["RAY_TPU_WORKER_ID"])
        self.node_id = bytes.fromhex(os.environ["RAY_TPU_NODE_ID"])
        self.cp = protocol.RpcClient(self.cp_sock)
        self.nm_client = protocol.RpcClient(self.nm_sock)
        self.nm_client.sock_path = self.nm_sock
        self.store = ShmStore(
            os.environ["RAY_TPU_SHM_ROOT"],
            spill_dir=os.environ.get("RAY_TPU_SPILL_DIR") or None)
        self.stream = self.nm_client.hijack(
            "stream_worker", self.worker_id.binary())
        self._send_lock = threading.Lock()
        # direct-channel result push-back: caller worker_id -> stream
        self._direct_res_lock = threading.Lock()
        self._direct_result_conns: Dict[bytes, socket.socket] = {}
        self._direct_res_send_locks: Dict[bytes, threading.Lock] = {}
        from ray_tpu.util.tracing import maybe_enable_from_cluster
        maybe_enable_from_cluster(self.cp)
        self.core = CoreWorker(
            mode="worker", job_id=JobID.nil(), worker_id=self.worker_id,
            node_id=self.node_id, control_plane=self.cp,
            node_manager=self.nm_client, shm_store=self.store,
            session_dir=self.session_dir, nm_notify=self._send,
            nm_addr=self.nm_sock)
        set_global_worker(self.core)
        from ray_tpu._private.ref_tracker import install_tracker
        install_tracker(self.worker_id.binary(), self.cp,
                        node_id=self.node_id)
        self._log_drain = None
        if os.environ.get("RAY_TPU_LOG_TO_DRIVER", "1") == "1":
            from ray_tpu._private.log_streaming import install_worker_tee
            self._log_drain = install_worker_tee(
                self.cp, self.worker_id.binary())
        # actor execution machinery (populated on creation)
        self.actor_pool: Optional[ThreadPoolExecutor] = None
        self.actor_loop: Optional[asyncio.AbstractEventLoop] = None
        self.is_async_actor = False
        # direct caller->callee channel (populated on actor creation)
        self._direct_server = None
        # task_id -> "running" | ("done", error): duplicate deliveries
        # across the direct and relay channels are suppressed, but the
        # NM-notification obligation of a relayed dup is preserved
        import collections
        self._seen_tasks: "dict[bytes, object]" = {}
        self._seen_order: "collections.deque[bytes]" = \
            collections.deque()
        self._late_notify: "set[bytes]" = set()
        self._seen_lock = threading.Lock()

    def _send(self, msg: Dict[str, Any]):
        with self._send_lock:
            protocol.send_msg(self.stream, msg)

    # ------------------------------------------------------------------
    def run(self):
        while True:
            try:
                msg = protocol.recv_msg(self.stream)
            except (protocol.ConnectionClosed, ConnectionResetError,
                    OSError, EOFError):
                # NM channel dropped without an "exit" handshake: the
                # node manager died.  Exit NOW — a lingering actor
                # worker keeps answering cached direct-channel calls,
                # split-braining with the incarnation the health loop
                # restarts elsewhere.
                sys.stdout.flush()
                sys.stderr.flush()
                os._exit(1)
            kind = msg.get("type")
            if kind == "exit":
                self._send({"type": "exit"})
                # fast exit: flush the log tee, then skip interpreter
                # finalization (XLA backend teardown + atexit walks
                # cost ~1.5 s per worker — every session shutdown on
                # the tier-1 box paid it x workers).  The NM-died
                # path above already exits this way.
                if self._log_drain is not None:
                    self._log_drain()
                sys.stdout.flush()
                sys.stderr.flush()
                os._exit(0)
            if kind != "task":
                continue
            spec: TaskSpec = msg["spec"]
            chips = msg.get("chips")
            if spec.actor_creation:
                self._execute_creation(spec, chips)
            elif spec.actor_id is not None:
                self._dispatch_actor_task(spec)
            else:
                self._execute_task(spec, chips)

    # ------------------------------------------------------------------
    def _resolve_args(self, spec: TaskSpec):
        def one(arg: Arg):
            if arg.inline is not None:
                return serialization.loads(arg.inline)
            return self.core.get(
                ObjectRef(arg.object_id,
                          spec.ref_owners.get(arg.object_id)))
        args = [one(a) for a in spec.args]
        kwargs = {k: one(v) for k, v in spec.kwargs.items()}
        return args, kwargs

    def _set_visible_chips(self, chips: Optional[List[int]]):
        # Parity with the reference's per-task accelerator isolation
        # (python/ray/_private/accelerators/tpu.py TPU_VISIBLE_CHIPS).
        if chips is not None:
            os.environ["TPU_VISIBLE_CHIPS"] = ",".join(map(str, chips))
            os.environ.setdefault("TPU_CHIPS_PER_HOST_BOUNDS",
                                  f"1,{len(chips)},1")

    def _commit_results(self, spec: TaskSpec, result: Any):
        if spec.is_generator:
            count = 0
            try:
                if inspect.isgenerator(result) or hasattr(
                        result, "__iter__") and not isinstance(
                            result, (list, tuple, dict, str, bytes)):
                    for item in result:
                        self.core.commit_generator_item(
                            spec.task_id, count, item)
                        count += 1
                else:
                    for item in list(result):
                        self.core.commit_generator_item(
                            spec.task_id, count, item)
                        count += 1
            except BaseException as e:  # noqa: BLE001
                err = TaskError(e, format_remote_traceback(e),
                                spec.task_id.hex())
                self.core.commit_generator_item(spec.task_id, count, err,
                                                is_error=True)
                count += 1
                self.core.commit_generator_done(spec.task_id, count)
                raise
            self.core.commit_generator_done(spec.task_id, count)
            # also commit the nominal return so plain get() works
            self.core.put_object(spec.return_object_ids()[0], count)
            return
        oids = spec.return_object_ids()
        if spec.num_returns == 1:
            return self.core.put_object(oids[0], result,
                                        owner_addr=spec.owner_addr)
        values = list(result)
        if len(values) != spec.num_returns:
            raise ValueError(
                f"task {spec.name} declared num_returns="
                f"{spec.num_returns} but returned {len(values)} values")
        for oid, v in zip(oids, values):
            self.core.put_object(oid, v, owner_addr=spec.owner_addr)
        return None

    def _commit_error(self, spec: TaskSpec, exc: BaseException):
        err = TaskError(exc, format_remote_traceback(exc),
                        spec.task_id.hex())
        inline = None
        try:
            for oid in spec.return_object_ids():
                inline = self.core.put_object(oid, err, is_error=True,
                                              owner_addr=spec.owner_addr)
            if spec.is_generator:
                self.core.commit_generator_item(spec.task_id, 0, err,
                                                is_error=True)
                self.core.commit_generator_done(spec.task_id, 1)
        except Exception:  # noqa: BLE001
            traceback.print_exc()
        return inline

    # ------------------------------------------------------------------
    def _execute_task(self, spec: TaskSpec, chips):
        from ray_tpu.util.tracing import task_span
        self.core.current_task_id = spec.task_id
        error = False
        error_payload = None
        try:
            from ray_tpu._private import runtime_env as _renv
            self._set_visible_chips(chips)
            fn = self.core.load_function(spec.function_key)
            args, kwargs = self._resolve_args(spec)
            with _renv.applied(spec.runtime_env), task_span(spec):
                if inspect.iscoroutinefunction(fn):
                    result = asyncio.run(fn(*args, **kwargs))
                else:
                    result = fn(*args, **kwargs)
            self._commit_results(spec, result)
        except BaseException as e:  # noqa: BLE001
            error = True
            if spec.retry_exceptions:
                # Defer the error commit: the node manager decides whether
                # to resubmit (reference: task retries on app exceptions).
                err = TaskError(e, format_remote_traceback(e),
                                spec.task_id.hex())
                error_payload = serialization.dumps(err)
            else:
                self._commit_error(spec, e)
        finally:
            self.core.current_task_id = None
        self._send({"type": "done", "task_id": spec.task_id, "error": error,
                    "error_payload": error_payload})

    def _execute_creation(self, spec: TaskSpec, chips):
        try:
            from ray_tpu._private import runtime_env as _renv
            self._set_visible_chips(chips)
            if spec.runtime_env:
                # actors own their process: applied for life
                _renv.apply(spec.runtime_env)
            cls = self.core.load_function(spec.function_key)
            args, kwargs = self._resolve_args(spec)
            instance = cls(*args, **kwargs)
            self.core.current_actor = instance
            self.core.current_actor_id = spec.actor_id
            self.is_async_actor = any(
                inspect.iscoroutinefunction(getattr(cls, n, None))
                for n in dir(cls) if not n.startswith("__"))
            if self.is_async_actor:
                self.actor_loop = asyncio.new_event_loop()
                t = threading.Thread(target=self.actor_loop.run_forever,
                                     daemon=True, name="actor-loop")
                t.start()
                asyncio.run_coroutine_threadsafe(
                    self._event_loop_lag_monitor(spec.actor_id),
                    self.actor_loop)
            else:
                # always a pool (size 1 = strict serialization): direct
                # caller connections submit from their own threads, so
                # execution must funnel through one ordered executor
                self.actor_pool = ThreadPoolExecutor(
                    max_workers=max(1, spec.max_concurrency),
                    thread_name_prefix="actor")
            self.core.put_object(spec.return_object_ids()[0], None)
            # publish the direct-call address BEFORE flipping ALIVE:
            # every caller that observes the actor as ALIVE then uses
            # ONE channel from its first call — no relay/direct
            # interleaving window to break per-caller ordering
            self._start_direct_server(spec.actor_id)
            self._send({"type": "actor_ready", "actor_id": spec.actor_id,
                        "pid": os.getpid()})
        except BaseException as e:  # noqa: BLE001
            self._commit_error(spec, e)
            self._send({"type": "actor_init_failed",
                        "actor_id": spec.actor_id})
            self._send({"type": "done", "task_id": spec.task_id,
                        "error": True})

    async def _event_loop_lag_monitor(self, actor_id: bytes,
                                      period: float = 0.5,
                                      warn_ms: float = 200.0):
        """Async-actor responsiveness watchdog (SURVEY §5.2 — the
        asyncio analogue of a blocked-event-loop sanitizer: the
        reference leans on py-spy; here the loop measures its own
        scheduling lag).  A coroutine that blocks the loop shows up as
        lag: exported as the ``async_actor_event_loop_lag_ms`` gauge
        and warned to the worker log (streamed to the driver) when it
        exceeds ``warn_ms``."""
        import time as _time

        from ray_tpu.util.metrics import Gauge
        gauge = None
        warned_at = 0.0
        last_published = -1.0
        ticks = 0
        while True:
            t0 = _time.monotonic()
            await asyncio.sleep(period)
            lag_ms = max(0.0, (_time.monotonic() - t0 - period) * 1e3)
            ticks += 1
            # gauge.set is a synchronous CP RPC: keep it OFF the loop
            # (the watchdog must never become the blocker it detects)
            # and publish only on material change or every ~30 ticks
            if (last_published < 0 or abs(lag_ms - last_published) > 10.0
                    or ticks % 30 == 0):
                last_published = lag_ms
                try:
                    if gauge is None:
                        gauge = Gauge(
                            "async_actor_event_loop_lag_ms",
                            "Scheduling delay of the async actor "
                            "event loop",
                            tag_keys=("actor_id",))
                    g, tag = gauge, {"actor_id": actor_id.hex()[:12]}
                    await asyncio.get_running_loop().run_in_executor(
                        None, lambda: g.set(lag_ms, tags=tag))
                except Exception:  # noqa: BLE001 - best-effort metric
                    pass
            if lag_ms > warn_ms and _time.monotonic() - warned_at > 10.0:
                warned_at = _time.monotonic()
                print(f"WARNING: async actor {actor_id.hex()[:12]} "
                      f"event loop lagged {lag_ms:.0f} ms — a handler "
                      "is blocking the loop (use asyncio.to_thread for "
                      "CPU/blocking work)", flush=True)

    def _dedup(self, spec: TaskSpec, notify_nm: bool = True) -> bool:
        """True if this task was already seen (at-least-once resend
        across the direct and relay channels).

        A relayed duplicate of a task first delivered on the direct
        channel carries an obligation the direct run didn't have: the
        NM that relayed it now tracks the task inflight and holds its
        dependency pins until a 'done' arrives.  Swallowing the dup
        silently would leak both — so a dup with ``notify_nm`` either
        emits 'done' now (run already finished) or flags the running
        task to notify at completion."""
        with self._seen_lock:
            state = self._seen_tasks.get(spec.task_id)
            if state is not None:
                if notify_nm:
                    if state == "running":
                        self._late_notify.add(spec.task_id)
                        return True
                    done, error = state
                else:
                    return True
                # fall through to send outside the lock
            else:
                self._seen_tasks[spec.task_id] = "running"
                self._seen_order.append(spec.task_id)
                if len(self._seen_order) > 4096:
                    # evict the oldest COMPLETED entry — a still-running
                    # task must keep its dedup record or a cross-channel
                    # duplicate would re-execute it.  Bounded rotation:
                    # if everything is running (pathological), grow.
                    for _ in range(len(self._seen_order)):
                        old = self._seen_order.popleft()
                        if self._seen_tasks.get(old) == "running":
                            self._seen_order.append(old)
                            continue
                        self._seen_tasks.pop(old, None)
                        self._late_notify.discard(old)
                        break
                return False
        self._send({"type": "done", "task_id": spec.task_id,
                    "error": error})
        return True

    def _finish_actor_task(self, spec: TaskSpec, notify_nm: bool,
                           error: bool,
                           inline: "Optional[bytes]" = None) -> None:
        """Completion bookkeeping shared by the sync and async runners:
        record the outcome for duplicate deliveries, notify the NM when
        either the original delivery or a relayed duplicate needs it,
        and push inline results back to direct-channel callers."""
        with self._seen_lock:
            if spec.task_id in self._seen_tasks:
                self._seen_tasks[spec.task_id] = ("done", error)
            late = spec.task_id in self._late_notify
            self._late_notify.discard(spec.task_id)
        if notify_nm or late:
            self._send({"type": "done", "task_id": spec.task_id,
                        "error": error})
        if not notify_nm:
            self._push_direct_result(spec, error, inline)
            self._purge_direct_pins(spec)

    def _push_direct_result(self, spec: TaskSpec, error: bool,
                            inline: "Optional[bytes]") -> None:
        """Send the result straight back over the caller's result
        stream (reference: the direct transport replies in-band).  The
        result is ALSO committed to the CP as usual — this push is a
        latency cache, dropping 3 control-plane round trips from the
        sync call+get hot path; a lost push just means the caller falls
        back to the normal location/wait/fetch flow."""
        caller = spec.owner_id
        with self._direct_res_lock:
            conn = self._direct_result_conns.get(caller)
            lock = self._direct_res_send_locks.get(caller)
        if conn is None or lock is None:
            return
        oids = spec.return_object_ids()
        msg = {"oid": oids[0] if oids else b"",
               "payload": inline, "error": error}
        try:
            with lock:
                protocol.send_msg(conn, msg)
        except (OSError, BrokenPipeError):  # caller gone: CP path holds
            pass

    def _dispatch_actor_task(self, spec: TaskSpec,
                             notify_nm: bool = True):
        if self._dedup(spec, notify_nm):
            return
        if self.is_async_actor and self.actor_loop is not None:
            asyncio.run_coroutine_threadsafe(
                self._run_actor_task_async(spec, notify_nm),
                self.actor_loop)
        elif self.actor_pool is not None:
            self.actor_pool.submit(self._run_actor_task, spec, notify_nm)
        else:
            self._run_actor_task(spec, notify_nm)

    # ------------------------------------------------------------------
    # Direct caller->callee channel.  Reference:
    # core_worker/transport/direct_actor_task_submitter.cc — callers
    # dial the actor process's own socket; the hosting node manager
    # stays out of the per-call hot path (placement/restart only).
    # ------------------------------------------------------------------
    class _DirectHandler:
        def __init__(self, proc: "WorkerProcess"):
            self._proc = proc

        def call_actor(self, spec: TaskSpec) -> bool:
            """Enqueue one actor call; returns once queued (results
            travel through the object store as usual).  Per-caller
            ordering: RpcClient conns are FIFO and the actor executor
            drains submissions in order."""
            self._proc._dispatch_actor_task(spec, notify_nm=False)
            return True

        def stream_results(self, conn: socket.socket,
                           caller_id: bytes) -> None:
            """Hijacked per-caller channel for inline result push-back.

            The caller never sends after the handshake; this thread
            parks on recv to notice the peer closing, then drops the
            registration so pushes stop."""
            proc = self._proc
            with proc._direct_res_lock:
                proc._direct_result_conns[caller_id] = conn
                proc._direct_res_send_locks[caller_id] = threading.Lock()
            try:
                while True:
                    if not conn.recv(4096):
                        break
            except OSError:
                pass
            finally:
                with proc._direct_res_lock:
                    if proc._direct_result_conns.get(caller_id) is conn:
                        proc._direct_result_conns.pop(caller_id, None)
                        proc._direct_res_send_locks.pop(caller_id, None)

    def _start_direct_server(self, actor_id: bytes) -> None:
        from ray_tpu._private.protocol import is_tcp_address, \
            parse_tcp_address
        if is_tcp_address(self.nm_sock):
            # TCP session: a UDS path would be unreachable from other
            # hosts — bind an ephemeral TCP port on the NM's interface
            host, _ = parse_tcp_address(self.nm_sock)
            path = f"tcp://{host}:0"
        else:
            path = os.path.join(self.session_dir, "sockets",
                                f"actor_{actor_id.hex()[:12]}_"
                                f"{os.getpid()}.sock")
        try:
            self._direct_server = protocol.RpcServer(
                path, self._DirectHandler(self),
                name=f"actor-{actor_id.hex()[:6]}")
            self.cp.call("update_actor", actor_id,
                         direct_addr=self._direct_server.address)
        except Exception:  # noqa: BLE001 — relay path still works
            traceback.print_exc()
            self._direct_server = None

    def _run_actor_task(self, spec: TaskSpec, notify_nm: bool = True):
        from ray_tpu.util.tracing import task_span
        self.core.current_task_id = spec.task_id
        inline = None
        try:
            method = self._lookup_method(spec)
            args, kwargs = self._resolve_args(spec)
            with task_span(spec):
                result = method(*args, **kwargs)
            if inspect.iscoroutine(result):
                result = asyncio.run(result)
            inline = self._commit_results(spec, result)
            error = False
        except BaseException as e:  # noqa: BLE001
            inline = self._commit_error(spec, e)
            error = True
        finally:
            self.core.current_task_id = None
        self._finish_actor_task(spec, notify_nm, error, inline)
        if spec.actor_method == "__ray_terminate__":
            os._exit(0)

    def _purge_direct_pins(self, spec: TaskSpec) -> None:
        """Direct calls bypass the hosting NM, so the callee releases
        the caller's dependency pre-pins at completion (the relay path
        does this in the NM's _unpin_dependencies)."""
        deps = spec.dependencies()
        if not deps:
            return
        from ray_tpu._private import owner_routing
        owner_routing.route_purge(
            self.cp, self.core._nm_peer, b"task:" + spec.task_id,
            {spec.ref_owners.get(d) for d in deps})

    async def _run_actor_task_async(self, spec: TaskSpec,
                                    notify_nm: bool = True):
        self.core.current_task_id = spec.task_id
        inline = None
        try:
            method = self._lookup_method(spec)
            args, kwargs = self._resolve_args(spec)
            result = method(*args, **kwargs)
            if inspect.iscoroutine(result):
                result = await result
            if spec.is_generator and inspect.isasyncgen(result):
                await self._commit_async_generator(spec, result)
            else:
                inline = self._commit_results(spec, result)
            error = False
        except BaseException as e:  # noqa: BLE001
            inline = self._commit_error(spec, e)
            error = True
        self._finish_actor_task(spec, notify_nm, error, inline)
        if spec.actor_method == "__ray_terminate__":
            os._exit(0)

    async def _commit_async_generator(self, spec: TaskSpec, result):
        """Streaming commit of an async generator (async-actor methods
        yielding items, e.g. Serve streaming responses): each yielded
        item becomes a generator slot as it is produced."""
        count = 0
        try:
            async for item in result:
                self.core.commit_generator_item(spec.task_id, count, item)
                count += 1
        except BaseException as e:  # noqa: BLE001
            err = TaskError(e, format_remote_traceback(e),
                            spec.task_id.hex())
            self.core.commit_generator_item(spec.task_id, count, err,
                                            is_error=True)
            count += 1
            self.core.commit_generator_done(spec.task_id, count)
            raise
        self.core.commit_generator_done(spec.task_id, count)
        self.core.put_object(spec.return_object_ids()[0], count)

    def _lookup_method(self, spec: TaskSpec):
        instance = self.core.current_actor
        if spec.actor_method == "__ray_terminate__":
            return lambda: None
        if spec.actor_method == "__ray_call__":
            # run an arbitrary function against the actor instance
            def _call(fn, *a, **kw):
                return fn(instance, *a, **kw)
            return _call
        method = getattr(instance, spec.actor_method, None)
        if method is None:
            raise AttributeError(
                f"actor {type(instance).__name__} has no method "
                f"{spec.actor_method!r}")
        return method


def main():
    import faulthandler
    import signal
    faulthandler.register(signal.SIGUSR1)
    proc = WorkerProcess()
    try:
        proc.run()
    finally:
        sys.stdout.flush()
        sys.stderr.flush()


if __name__ == "__main__":
    main()
