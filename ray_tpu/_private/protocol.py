"""Framed RPC over unix-domain or TCP sockets.

TPU-native counterpart of the reference's gRPC layer (``src/ray/rpc/``).
The control plane and node managers are in-cluster trusted peers on the
same host or VPC, so the wire format is length-prefixed pickle frames —
simple, fast, and sufficient for the control plane.  The *tensor* plane
never touches this layer: device arrays move over ICI/DCN inside XLA
programs, host objects through the shm object store.

Addresses are strings of two forms (reference: ``src/ray/rpc/grpc_server.cc``
binds TCP; plasma's UDS stays for the local fast path):

- a filesystem path → AF_UNIX (same-host fast path)
- ``tcp://host:port`` → AF_INET (cross-host; port 0 = ephemeral, the
  canonical bound address is ``RpcServer.address``)

Frame: [u64 little-endian length][pickle payload]

Server: thread per connection; handlers may block (long-poll waits).
Client: one persistent connection per thread (so a blocking call only
blocks its own thread), with automatic reconnect.
"""

from __future__ import annotations

import os
import pickle
import socket
import socketserver
import struct
import threading
import traceback
from typing import Any, Callable, Optional, Tuple

_LEN = struct.Struct("<Q")


def is_tcp_address(addr: str) -> bool:
    return addr.startswith("tcp://")


def parse_tcp_address(addr: str) -> Tuple[str, int]:
    hostport = addr[len("tcp://"):]
    host, _, port = hostport.rpartition(":")
    return host or "127.0.0.1", int(port)


def _client_socket(addr: str, timeout: Optional[float]) -> socket.socket:
    if is_tcp_address(addr):
        host, port = parse_tcp_address(addr)
        sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        sock.settimeout(timeout)
        sock.connect((host, port))
    else:
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        sock.settimeout(timeout)
        sock.connect(addr)
    sock.settimeout(None)
    return sock


class ConnectionClosed(ConnectionError):
    pass


def send_frame(sock: socket.socket, payload: bytes) -> None:
    sock.sendall(_LEN.pack(len(payload)) + payload)


def recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    while n > 0:
        chunk = sock.recv(min(n, 4 * 1024 * 1024))
        if not chunk:
            raise ConnectionClosed("peer closed connection")
        chunks.append(chunk)
        n -= len(chunk)
    return b"".join(chunks)


def recv_frame(sock: socket.socket) -> bytes:
    (length,) = _LEN.unpack(recv_exact(sock, _LEN.size))
    return recv_exact(sock, length)


def send_msg(sock: socket.socket, msg: Any) -> None:
    send_frame(sock, pickle.dumps(msg, protocol=5))


def recv_msg(sock: socket.socket) -> Any:
    return pickle.loads(recv_frame(sock))


class RpcServer:
    """Threaded unix-socket server dispatching to a handler object.

    Any public method of ``handler`` is callable remotely.  A request is
    ``("call", method, args, kwargs)``; the reply ``("ok", result)`` or
    ``("err", exc)``.  Connections may also be *hijacked*: if the handler
    method name starts with ``stream_`` it receives the raw socket and owns
    the connection from then on (used for worker task channels).
    """

    def __init__(self, sock_path: str, handler: Any, name: str = "rpc"):
        self.sock_path = sock_path
        self.handler = handler
        self.name = name
        if is_tcp_address(sock_path):
            host, port = parse_tcp_address(sock_path)
            self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            self._sock.bind((host, port))
            # canonical address after an ephemeral (port 0) bind
            self.address = f"tcp://{host}:{self._sock.getsockname()[1]}"
        else:
            os.makedirs(os.path.dirname(sock_path), exist_ok=True)
            if os.path.exists(sock_path):
                os.unlink(sock_path)
            self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            self._sock.bind(sock_path)
            self.address = sock_path
        self._sock.listen(512)
        self._stopped = threading.Event()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name=f"{name}-accept", daemon=True)
        self._accept_thread.start()

    def _accept_loop(self):
        while not self._stopped.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            t = threading.Thread(target=self._serve_conn, args=(conn,),
                                 name=f"{self.name}-conn", daemon=True)
            t.start()

    def _serve_conn(self, conn: socket.socket):
        try:
            while True:
                try:
                    req = recv_msg(conn)
                except (ConnectionClosed, ConnectionResetError, OSError,
                        EOFError):
                    return
                kind = req[0]
                if kind != "call":
                    send_msg(conn, ("err", ValueError(f"bad frame {kind}")))
                    continue
                _, method, args, kwargs = req
                if method.startswith("stream_"):
                    # Connection handoff: handler owns the socket now.
                    fn = getattr(self.handler, method)
                    fn(conn, *args, **kwargs)
                    return
                try:
                    fn = getattr(self.handler, method)
                    if method.startswith("_"):
                        raise AttributeError(method)
                    result = fn(*args, **kwargs)
                    reply = ("ok", result)
                except BaseException as e:  # noqa: BLE001 - ship to caller
                    e._remote_tb = traceback.format_exc()  # type: ignore
                    reply = ("err", e)
                try:
                    send_msg(conn, reply)
                except (BrokenPipeError, OSError):
                    return
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def shutdown(self):
        self._stopped.set()
        try:
            self._sock.close()
        except OSError:
            pass
        if not is_tcp_address(self.sock_path) \
                and os.path.exists(self.sock_path):
            try:
                os.unlink(self.sock_path)
            except OSError:
                pass


class RpcClient:
    """Thread-local persistent connections to an RpcServer."""

    def __init__(self, sock_path: str, connect_timeout: float = 10.0):
        self.sock_path = sock_path
        self.connect_timeout = connect_timeout
        self._local = threading.local()

    def _connect(self) -> socket.socket:
        return _client_socket(self.sock_path, self.connect_timeout)

    def _conn(self) -> socket.socket:
        sock = getattr(self._local, "sock", None)
        if sock is None:
            sock = self._connect()
            self._local.sock = sock
        return sock

    def call(self, method: str, *args, **kwargs) -> Any:
        for attempt in (0, 1):
            sock = self._conn()
            try:
                send_msg(sock, ("call", method, args, kwargs))
                status, payload = recv_msg(sock)
                break
            except (ConnectionClosed, ConnectionResetError, BrokenPipeError,
                    OSError):
                self._local.sock = None
                if attempt == 1:
                    raise
        if status == "ok":
            return payload
        raise payload

    def __getattr__(self, name: str) -> Callable:
        if name.startswith("_"):
            raise AttributeError(name)
        def _proxy(*args, **kwargs):
            return self.call(name, *args, **kwargs)
        _proxy.__name__ = name
        return _proxy

    def hijack(self, method: str, *args, **kwargs) -> socket.socket:
        """Open a fresh connection and hand it to a ``stream_`` handler."""
        sock = self._connect()
        send_msg(sock, ("call", method, args, kwargs))
        return sock

    def close(self):
        sock = getattr(self._local, "sock", None)
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass
            self._local.sock = None
