"""Worker log streaming to the driver.

Parity: the reference's log monitor → driver flow
(``python/ray/_private/log_monitor.py``): worker stdout/stderr still
land in per-worker files (subprocess redirection), but a tee inside the
worker also publishes complete lines to the control-plane pubsub; the
driver runs a background poller printing them with a
``(worker_id, pid)`` prefix.  Disable with
``init(_system_config={"log_to_driver": False})``.
"""

from __future__ import annotations

import io
import os
import sys
import threading
from typing import Optional

CHANNEL = "worker_logs"


_MAX_BUFFERED = 8192


class _TeeStream(io.TextIOBase):
    """Write-through to the original stream + line-buffered publish.

    Thread-safe ('\\n' and '\\r' both delimit lines; the buffer is
    force-flushed at ``_MAX_BUFFERED`` so progress bars that never emit
    a newline can't grow it without bound)."""

    def __init__(self, base, publish, stream_name: str):
        self._base = base
        self._publish = publish
        self._name = stream_name
        self._buf = ""
        self._lock = threading.Lock()

    def write(self, s: str) -> int:
        n = self._base.write(s)
        lines = []
        with self._lock:
            self._buf += s
            normalized = self._buf.replace("\r", "\n")
            while "\n" in normalized:
                line, normalized = normalized.split("\n", 1)
                if line:
                    lines.append(line)
            if len(normalized) > _MAX_BUFFERED:
                lines.append(normalized)
                normalized = ""
            self._buf = normalized
        for line in lines:
            self._publish(self._name, line)
        return n

    def flush(self) -> None:
        self._base.flush()
        with self._lock:
            rest, self._buf = self._buf, ""
        if rest:
            self._publish(self._name, rest)

    @property
    def encoding(self):
        return getattr(self._base, "encoding", "utf-8")

    def fileno(self):
        return self._base.fileno()

    def isatty(self):
        return False


def install_worker_tee(cp, worker_id: bytes):
    """Route this worker's stdout/stderr lines to the CP pubsub.

    Lines go through a bounded queue drained by one background thread —
    a print must never block on a control-plane round trip, and a
    storm of output drops lines (counted) rather than stalling work.

    Returns the drain function (also registered with ``atexit``, and
    idempotent): the worker's fast-exit path calls it explicitly
    before ``os._exit``, which skips atexit handlers.
    """
    import atexit
    import queue

    pid = os.getpid()
    wid = worker_id.hex()[:12]
    q: "queue.Queue" = queue.Queue(maxsize=1000)
    dropped = [0]

    def pump():
        while True:
            item = q.get()
            if item is None:
                return
            try:
                cp.publish(CHANNEL, item)
            except Exception:  # noqa: BLE001 — never kill work for logs
                pass

    t = threading.Thread(target=pump, daemon=True, name="log-tee-pump")
    t.start()

    def publish(stream_name: str, line: str) -> None:
        msg = {"worker": wid, "pid": pid, "stream": stream_name,
               "line": line}
        try:
            q.put_nowait(msg)
        except queue.Full:
            dropped[0] += 1

    drained = [False]

    def drain():
        if drained[0]:
            return
        drained[0] = True
        try:
            sys.stdout.flush()
            sys.stderr.flush()
        except Exception:  # noqa: BLE001
            pass
        if dropped[0]:
            try:
                cp.publish(CHANNEL, {
                    "worker": wid, "pid": pid, "stream": "err",
                    "line": f"[log tee dropped {dropped[0]} lines]"})
            except Exception:  # noqa: BLE001
                pass
        q.put(None)
        t.join(timeout=2)

    atexit.register(drain)
    sys.stdout = _TeeStream(sys.stdout, publish, "out")
    sys.stderr = _TeeStream(sys.stderr, publish, "err")
    return drain


class DriverLogMonitor:
    """Background poller printing streamed worker lines on the driver."""

    def __init__(self, cp, out=None):
        self._cp = cp
        self._out = out
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="driver-log-monitor")
        self._thread.start()

    def _loop(self) -> None:
        cursor = 0
        while not self._stop.is_set():
            try:
                cursor, msgs = self._cp.poll(CHANNEL, cursor, 2.0)
            except Exception:  # noqa: BLE001 — head restarting
                if self._stop.wait(1.0):
                    return
                continue
            out = self._out or sys.stdout
            for m in msgs:
                tag = "" if m.get("stream") == "out" else " [err]"
                try:
                    print(f"({m['worker']} pid={m['pid']}){tag} "
                          f"{m['line']}", file=out, flush=True)
                except Exception:  # noqa: BLE001
                    pass

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            # the loop may be parked in a 2 s control-plane long-poll;
            # it is a daemon thread and every print is exception-
            # guarded, so abandon it rather than paying the remainder
            # of the poll on every session shutdown
            self._thread.join(timeout=0.2)
