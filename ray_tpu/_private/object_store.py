"""Node shared-memory object store.

TPU-native equivalent of the reference's Plasma store
(``src/ray/object_manager/plasma/store.cc``): immutable, sealed objects in
shared memory, read zero-copy by every process on the node.

Design: instead of a store *daemon* owning one big dlmalloc'd mmap and a
socket protocol (the reference's design, built for a world without
``memfd``/tmpfs maturity), each object is a file in a per-session tmpfs
directory (``/dev/shm``).  Creation is atomic (write to ``*.tmp``, then
``rename``), reads are ``mmap(MAP_SHARED, PROT_READ)`` so numpy buffers
deserialize as zero-copy views.  Capacity accounting + LRU eviction +
spill-to-disk are handled by :class:`ShmStore`; a C++ fastpath
(``src/shmstore``) accelerates bulk copies when built, with this module as
the always-available fallback.

The *tensor plane does not live here*: jax device arrays stay in HBM and
move over ICI/DCN via XLA collectives.  This store carries host-side task
args/returns, dataset blocks, and checkpoints.
"""

from __future__ import annotations

import mmap
import os
import shutil
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu._private import serialization
from ray_tpu._private.config import GLOBAL_CONFIG
from ray_tpu.exceptions import ObjectStoreFullError


def _default_capacity() -> int:
    cap = GLOBAL_CONFIG.shm_store_capacity_bytes
    if cap:
        return cap
    try:
        total = os.sysconf("SC_PAGE_SIZE") * os.sysconf("SC_PHYS_PAGES")
    except (ValueError, OSError):
        total = 8 << 30
    return int(total * 0.3)


class _MappedObject:
    """Keeps the mmap alive as long as any deserialized view references it."""

    __slots__ = ("mm", "path")

    def __init__(self, path: str):
        self.path = path
        fd = os.open(path, os.O_RDONLY)
        try:
            size = os.fstat(fd).st_size
            self.mm = mmap.mmap(fd, size, prot=mmap.PROT_READ)
        finally:
            os.close(fd)

    def view(self) -> memoryview:
        return memoryview(self.mm)


class ShmStore:
    """Per-node object store rooted at a tmpfs directory."""

    # objects at or below this size go to the native arena when available
    ARENA_MAX_OBJECT = 4 * 1024 * 1024
    # in-flight pushed objects idle this long are assumed abandoned
    PUSH_STALE_S = 300.0

    def __init__(self, root: str, capacity: Optional[int] = None,
                 spill_dir: Optional[str] = None,
                 on_evict=None):
        self.root = root
        os.makedirs(root, exist_ok=True)
        self.capacity = capacity or _default_capacity()
        self.spill_dir = spill_dir
        # best-effort notification that a *dropped* (not spilled) copy
        # left this node — broadcast-chain bookkeeping hangs off it
        self.on_evict = on_evict
        self._lock = threading.Lock()
        # id -> (size, last_access); rebuilt lazily from disk on miss
        self._index: Dict[bytes, Tuple[int, float]] = {}
        self._used = 0
        # Sealed mmaps cached per process so repeated gets share one mapping.
        self._mapped: Dict[bytes, _MappedObject] = {}
        # In-flight pushed objects: id -> {offsets, total, ts}
        # (offset-keyed so an RPC-level chunk retry can't double-count).
        # In-flight bytes are reserved against capacity so two concurrent
        # big pushes can't jointly overfill the tmpfs, and pushes whose
        # client died mid-stream are purged after PUSH_STALE_S.
        self._push_progress: Dict[bytes, Dict[str, Any]] = {}
        self._push_reserved = 0
        # Native C++ arena fastpath (src/shmstore): one mmap shared by all
        # node processes; first process creates, the rest attach.
        self._arena = None
        if os.environ.get("RAY_TPU_DISABLE_NATIVE_STORE") != "1":
            try:
                from ray_tpu._private.shmstore_native import NativeArena
                arena_cap = min(self.capacity // 4, 2 << 30)
                self._arena = NativeArena(
                    os.path.join(root, "arena"), capacity=arena_cap,
                    create=True)
            except Exception:  # noqa: BLE001 - python file path still works
                self._arena = None

    # -------------------------------------------------------- paths -----
    def _path(self, object_id: bytes) -> str:
        return os.path.join(self.root, object_id.hex())

    def _spill_path(self, object_id: bytes) -> str:
        assert self.spill_dir
        return os.path.join(self.spill_dir, object_id.hex())

    # -------------------------------------------------------- write -----
    def put_serialized(self, object_id: bytes,
                       obj: "serialization.SerializedObject") -> int:
        """Create + seal an object; returns its sealed size."""
        size = obj.total_bytes
        if self._arena is not None and size <= self.ARENA_MAX_OBJECT:
            if self._arena.put(object_id, obj.write_into, size):
                return size
        self._ensure_capacity(size)
        path = self._path(object_id)
        tmp = path + f".tmp.{os.getpid()}"
        with open(tmp, "w+b") as f:
            f.truncate(size)
            with mmap.mmap(f.fileno(), size) as mm:
                obj.write_into(memoryview(mm))
        os.rename(tmp, path)  # seal: atomic visibility
        with self._lock:
            self._index[object_id] = (size, time.monotonic())
            self._used += size
        return size

    def put_bytes(self, object_id: bytes, data: bytes) -> int:
        self._ensure_capacity(len(data))
        path = self._path(object_id)
        tmp = path + f".tmp.{os.getpid()}.{threading.get_ident()}"
        with open(tmp, "wb") as f:
            f.write(data)
        os.rename(tmp, path)
        with self._lock:
            self._index[object_id] = (len(data), time.monotonic())
            self._used += len(data)
        return len(data)

    def write_push_chunk(self, object_id: bytes, total: int,
                         offset: int, data: bytes) -> bool:
        """Assemble an object PUSHED by a remote client, chunk by chunk
        (the write side of the pull protocol — reference:
        ``object_manager/push_manager.cc``).  Returns True once every
        byte arrived and the object sealed."""
        path = self._path(object_id)
        tmp = path + ".push"
        now = time.monotonic()
        with self._lock:
            # reap pushes abandoned by a crashed client
            for oid, st in list(self._push_progress.items()):
                if now - st["ts"] > self.PUSH_STALE_S:
                    self._push_progress.pop(oid, None)
                    self._push_reserved -= st["total"]
                    try:
                        os.unlink(self._path(oid) + ".push")
                    except OSError:
                        pass
            if object_id in self._index:        # already sealed: re-push no-op
                return True
            st = self._push_progress.get(object_id)
            fresh = st is None
            if fresh:
                st = {"offsets": set(), "total": total, "ts": now}
                self._push_progress[object_id] = st
            else:
                st["ts"] = now
        if fresh:
            try:
                self._ensure_capacity(total)
                with self._lock:
                    self._push_reserved += total
            except Exception:
                with self._lock:
                    self._push_progress.pop(object_id, None)
                raise
        mode = "w+b" if fresh else "r+b"
        with open(tmp, mode) as f:
            if fresh:
                f.truncate(total)
            f.seek(offset)
            f.write(data)
        with self._lock:
            st = self._push_progress.get(object_id)
            if st is None:                       # concurrent sealer won
                return object_id in self._index
            st["offsets"].add((offset, len(data)))
            done = sum(n for _, n in st["offsets"]) >= total
            if done:
                self._push_progress.pop(object_id, None)
                self._push_reserved -= total
        if done:
            os.rename(tmp, path)  # seal
            with self._lock:
                if object_id not in self._index:
                    self._index[object_id] = (total, time.monotonic())
                    self._used += total
        return done

    def put_stream(self, object_id: bytes, size: int, chunks) -> int:
        """Create + seal an object from an iterator of byte chunks.

        Write path of the node-to-node pull protocol: chunks arrive over
        RPC and stream straight into the tmpfs file, sealed by rename.
        """
        self._ensure_capacity(size)
        path = self._path(object_id)
        # Per-writer tmp name: two threads pulling the same object
        # concurrently must not interleave into one file.
        tmp = path + f".tmp.{os.getpid()}.{threading.get_ident()}"
        written = 0
        try:
            with open(tmp, "wb") as f:
                for chunk in chunks:
                    f.write(chunk)
                    # visible watermark: the broadcast chain re-serves
                    # this partial file to downstream pullers as chunks
                    # land
                    f.flush()
                    written += len(chunk)
        except BaseException:
            # a failed source mid-stream must not orphan the tmp file:
            # downstream chain pullers read any .tmp.* as "pull in
            # progress here" and would poll this node pointlessly
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        if written != size:
            os.unlink(tmp)
            raise IOError(f"object {object_id.hex()}: streamed {written} "
                          f"bytes, expected {size}")
        with self._lock:
            if object_id in self._index:
                # a concurrent pull of this (immutable) object won the race
                os.unlink(tmp)
                return size
            os.rename(tmp, path)
            self._index[object_id] = (size, time.monotonic())
            self._used += size
        return size

    def read_chunk(self, object_id: bytes, offset: int,
                   length: int) -> Optional[bytes]:
        """Serve one chunk of a sealed object (pull-protocol read side)."""
        view = self.get_view(object_id)
        if view is None:
            return None
        return bytes(view[offset:offset + length])

    def sealed_path(self, object_id: bytes) -> Optional[str]:
        """Filesystem path of a sealed object (same-host fastpath: a
        co-hosted node copies the file kernel-side instead of pulling
        RPC chunks)."""
        path = self._path(object_id)
        if os.path.exists(path):
            return path
        if self.spill_dir is not None:
            sp = self._spill_path(object_id)
            if os.path.exists(sp):
                return sp
        return None

    def read_partial_chunk(self, object_id: bytes, offset: int,
                           length: int) -> Optional[bytes]:
        """Serve a chunk from an IN-PROGRESS pull of this object.

        Broadcast-chain read side (reference: push_manager.cc re-serves
        chunks as they arrive): a downstream puller reads the prefix a
        concurrent upstream pull has already written.  Returns None if
        no writer has reached offset+length yet (caller polls)."""
        import glob as _glob
        sealed = self.read_chunk(object_id, offset, length)
        if sealed is not None:
            return sealed
        best: Optional[str] = None
        best_size = -1
        for cand in _glob.glob(self._path(object_id) + ".tmp.*"):
            try:
                size = os.path.getsize(cand)
            except OSError:
                continue
            if size > best_size:
                best, best_size = cand, size
        if best is None or best_size < offset + length:
            return None
        try:
            with open(best, "rb") as f:
                f.seek(offset)
                data = f.read(length)
            return data if len(data) == length else None
        except OSError:
            return None

    def has_any_copy(self, object_id: bytes) -> bool:
        """Sealed, spilled, or in-progress-pull presence of the object
        on this node (broadcast-chain "is the parent worth polling")."""
        import glob as _glob
        if os.path.exists(self._path(object_id)):
            return True
        if self.spill_dir and os.path.exists(self._spill_path(object_id)):
            return True
        # an active pull flushes every chunk, so its tmp mtime stays
        # fresh; a tmp orphaned by a SIGKILLed writer goes stale and
        # must not read as "in progress" forever
        now = time.time()
        for cand in _glob.glob(self._path(object_id) + ".tmp.*"):
            try:
                if now - os.path.getmtime(cand) < 60.0:
                    return True
            except OSError:
                continue
        return False

    def put_file_copy(self, object_id: bytes, src_path: str,
                      size: int) -> bool:
        """Seal a local secondary copy from another store's sealed file
        (same-host transfer: one kernel-side copy, no RPC)."""
        import shutil
        self._ensure_capacity(size)
        path = self._path(object_id)
        tmp = path + f".tmp.{os.getpid()}.{threading.get_ident()}"
        try:
            shutil.copyfile(src_path, tmp)
            if os.path.getsize(tmp) != size:
                os.unlink(tmp)
                return False
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            return False
        with self._lock:
            if object_id in self._index:
                os.unlink(tmp)
                return True
            os.rename(tmp, path)
            self._index[object_id] = (size, time.monotonic())
            self._used += size
        return True

    # --------------------------------------------------------- read -----
    def contains(self, object_id: bytes) -> bool:
        if self._arena is not None and self._arena.contains(object_id):
            return True
        return os.path.exists(self._path(object_id)) or (
            self.spill_dir is not None
            and os.path.exists(self._spill_path(object_id)))

    def get_view(self, object_id: bytes) -> Optional[memoryview]:
        """Zero-copy view of a sealed object; None if absent."""
        if self._arena is not None:
            view = self._arena.get(object_id)
            if view is not None:
                return view
        with self._lock:
            mapped = self._mapped.get(object_id)
            if mapped is not None:
                self._touch(object_id)
                return mapped.view()
        path = self._path(object_id)
        if not os.path.exists(path):
            if not self._restore_from_spill(object_id):
                return None
        try:
            mapped = _MappedObject(path)
        except (FileNotFoundError, ValueError):
            return None
        with self._lock:
            self._mapped[object_id] = mapped
            self._touch(object_id)
        return mapped.view()

    def get_object(self, object_id: bytes) -> Optional[Any]:
        view = self.get_view(object_id)
        if view is None:
            return None
        return serialization.deserialize_frame(view)

    def size_of(self, object_id: bytes) -> Optional[int]:
        try:
            return os.stat(self._path(object_id)).st_size
        except FileNotFoundError:
            return None

    # ------------------------------------------------------- delete -----
    def delete(self, object_id: bytes) -> bool:
        with self._lock:
            # the native call must not race destroy()'s detach — the
            # NM heartbeat's owner sweep can be mid-delete when the
            # session tears the store down
            arena_removed = (self._arena is not None
                             and self._arena.delete(object_id))
            self._mapped.pop(object_id, None)
            entry = self._index.pop(object_id, None)
            if entry:
                self._used -= entry[0]
        removed = arena_removed
        for path in ([self._path(object_id)]
                     + ([self._spill_path(object_id)] if self.spill_dir
                        else [])):
            try:
                os.unlink(path)
                removed = True
            except FileNotFoundError:
                pass
        return removed

    # ----------------------------------------------- eviction / spill ----
    def _touch(self, object_id: bytes) -> None:
        entry = self._index.get(object_id)
        if entry:
            self._index[object_id] = (entry[0], time.monotonic())

    def _ensure_capacity(self, need: int) -> None:
        if need > self.capacity:
            raise ObjectStoreFullError(
                f"object of {need} bytes exceeds store capacity "
                f"{self.capacity}")
        with self._lock:
            committed = self._used + self._push_reserved
            if committed + need <= self.capacity:
                return
            headroom = int(self.capacity * GLOBAL_CONFIG.shm_eviction_headroom)
            target = committed + need - self.capacity + headroom
            victims = sorted(self._index.items(), key=lambda kv: kv[1][1])
        freed = 0
        for oid, (size, _) in victims:
            if freed >= target:
                break
            if self._evict_one(oid):
                freed += size
        with self._lock:
            if self._used + self._push_reserved + need > self.capacity:
                raise ObjectStoreFullError(
                    f"cannot free {need} bytes (used={self._used}, "
                    f"in-flight pushes={self._push_reserved}, "
                    f"capacity={self.capacity})")

    def _evict_one(self, object_id: bytes) -> bool:
        """Spill to disk if configured, else drop (directory will recommit)."""
        path = self._path(object_id)
        with self._lock:
            if object_id in self._mapped:
                return False  # actively mapped in this process; skip
            entry = self._index.pop(object_id, None)
            if entry:
                self._used -= entry[0]
        if self.spill_dir:
            os.makedirs(self.spill_dir, exist_ok=True)
            try:
                shutil.move(path, self._spill_path(object_id))
                return True
            except FileNotFoundError:
                return False
        try:
            os.unlink(path)
        except FileNotFoundError:
            return False
        if self.on_evict is not None:
            try:
                self.on_evict(object_id)
            except Exception:  # noqa: BLE001 — notification best-effort
                pass
        return True

    def _restore_from_spill(self, object_id: bytes) -> bool:
        if not self.spill_dir:
            return False
        spath = self._spill_path(object_id)
        if not os.path.exists(spath):
            return False
        size = os.stat(spath).st_size
        self._ensure_capacity(size)
        shutil.move(spath, self._path(object_id))
        with self._lock:
            self._index[object_id] = (size, time.monotonic())
            self._used += size
        return True

    # -------------------------------------------------------- stats -----
    def stats(self) -> Dict[str, int]:
        with self._lock:
            out = {"used_bytes": self._used,
                   "capacity_bytes": self.capacity,
                   "num_objects": len(self._index),
                   "num_mapped": len(self._mapped)}
        if self._arena is not None:
            out["arena"] = self._arena.stats()
        return out

    def release_mappings(self) -> None:
        with self._lock:
            self._mapped.clear()

    def release_mapping(self, object_id: bytes) -> None:
        """Drop one cached mmap (existing views keep the map alive)."""
        with self._lock:
            self._mapped.pop(object_id, None)

    def destroy(self) -> None:
        self.release_mappings()
        with self._lock:
            arena, self._arena = self._arena, None
        if arena is not None:
            arena.detach()
        shutil.rmtree(self.root, ignore_errors=True)
