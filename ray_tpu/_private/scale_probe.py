"""Scalability-envelope probes.

Parity target: the reference's published envelope
(``release/benchmarks/README.md``: 1M+ queued tasks on one node, 10k+
concurrent tasks, 40k actors across 2k nodes, 1 GiB broadcast, 10k-ref
``wait``) scaled to one host.  Each probe prints one line and the driver
records the dict; run via ``python -m ray_tpu._private.scale_probe``
(writes ``SCALE_r*.json`` at the repo root when invoked by the round
driver or by hand).

These are *probes*, not unit tests: they exist to find the knee of the
curve.  Budget guards keep a regression from hanging the round.
"""

from __future__ import annotations

import json
import os
import sys
import time
from typing import Any, Dict

import numpy as np


def probe_queue_tasks(n: int = 100_000) -> Dict[str, Any]:
    """Queue ``n`` no-op tasks on one node, then drain them all."""
    import ray_tpu

    @ray_tpu.remote(num_cpus=1)
    def noop():
        return None

    t0 = time.perf_counter()
    refs = [noop.remote() for _ in range(n)]
    submit_s = time.perf_counter() - t0
    t1 = time.perf_counter()
    # drain in windows so the driver-side wait set stays bounded
    done = 0
    while refs:
        chunk, refs = refs[:10_000], refs[10_000:]
        ray_tpu.get(chunk)
        done += len(chunk)
    drain_s = time.perf_counter() - t1
    return {
        "n": n,
        "submit_per_s": round(n / submit_s, 1),
        "drain_per_s": round(n / drain_s, 1),
        "submit_s": round(submit_s, 2),
        "drain_s": round(drain_s, 2),
    }


def probe_wait_many_refs(n: int = 10_000) -> Dict[str, Any]:
    """10k-object ``put`` burst + one ``wait`` over all of them."""
    import ray_tpu

    t0 = time.perf_counter()
    refs = [ray_tpu.put(i) for i in range(n)]
    put_s = time.perf_counter() - t0
    t1 = time.perf_counter()
    ready, not_ready = ray_tpu.wait(refs, num_returns=n, timeout=60)
    wait_s = time.perf_counter() - t1
    assert len(ready) == n, (len(ready), len(not_ready))
    return {
        "n": n,
        "puts_per_s": round(n / put_s, 1),
        "wait_all_s": round(wait_s, 3),
    }


def probe_actors(n: int = 256, calls_per_actor: int = 4) -> Dict[str, Any]:
    """Create ``n`` actors across simulated nodes, call each, kill all."""
    import ray_tpu
    from ray_tpu._private.worker import global_node

    # Spread actors over a few extra in-process nodes so one worker
    # pool's cap isn't the artificial limit.  Density note: the probe
    # host has ONE core, so every extra node-manager process is pure
    # scheduling thrash against the workers themselves — 16 sim nodes
    # measured 2.3/s where 3 nodes measure ~45/s for the same 1,024
    # actors.  Real deployments run one raylet per host; 2-3 sim nodes
    # at ~340 actors/node already exceeds the reference envelope's
    # per-node density (40k actors / 2k nodes = 20/node,
    # release/benchmarks/README.md).
    extra_nodes = max(1, n // 512)
    for _ in range(extra_nodes):
        global_node().add_node(num_cpus=512)

    @ray_tpu.remote(num_cpus=0.01)
    class A:
        def ping(self, x):
            return x + 1

    t0 = time.perf_counter()
    actors = [A.options(scheduling_strategy="SPREAD").remote()
              for _ in range(n)]
    # first call forces creation to complete
    ray_tpu.get([a.ping.remote(0) for a in actors])
    create_s = time.perf_counter() - t0
    t1 = time.perf_counter()
    refs = [a.ping.remote(i) for _ in range(calls_per_actor)
            for i, a in enumerate(actors)]
    ray_tpu.get(refs)
    call_s = time.perf_counter() - t1
    for a in actors:
        ray_tpu.kill(a)
    return {
        "n_actors": n,
        "create_total_s": round(create_s, 2),
        "create_per_s": round(n / create_s, 1),
        "calls_per_s": round(n * calls_per_actor / call_s, 1),
    }


def probe_broadcast(size_mb: int = 1024, n_nodes: int = 8) -> Dict[str, Any]:
    """1 GiB object fetched by a task on each of ``n_nodes`` sim nodes."""
    import ray_tpu
    from ray_tpu._private.worker import global_node

    node_ids = [global_node().add_node(num_cpus=1)
                for _ in range(n_nodes)]
    # add_node returns as soon as the process is spawned; wait for the
    # node managers to register before hard-affinity dispatch
    from ray_tpu._private.worker import global_worker
    cp = global_worker().cp
    deadline = time.perf_counter() + 120
    for nid in node_ids:
        while time.perf_counter() < deadline:
            info = cp.get_node(nid)
            if info is not None and info.get("state") == "ALIVE":
                break
            time.sleep(0.2)
        else:
            raise TimeoutError("sim node failed to register")
    big = np.random.default_rng(0).integers(
        0, 255, size_mb * 1024 * 1024, dtype=np.uint8)
    ref = ray_tpu.put(big)

    @ray_tpu.remote(num_cpus=1)
    def touch(arr):
        if isinstance(arr, int):
            return arr
        return int(arr[0]) + int(arr[-1])

    from ray_tpu.util.scheduling_strategies import (
        NodeAffinitySchedulingStrategy)

    # warm a worker on every node so spawn time stays out of the
    # transfer measurement
    ray_tpu.get([touch.options(
        scheduling_strategy=NodeAffinitySchedulingStrategy(
            nid.hex())).remote(0) for nid in node_ids], timeout=300)

    t0 = time.perf_counter()
    outs = []
    for nid in node_ids:
        outs.append(touch.options(
            scheduling_strategy=NodeAffinitySchedulingStrategy(
                nid.hex())).remote(ref))
    ray_tpu.get(outs, timeout=600)
    dt = time.perf_counter() - t0
    return {
        "size_mb": size_mb,
        "n_nodes": n_nodes,
        "total_s": round(dt, 2),
        "aggregate_mb_per_s": round(size_mb * n_nodes / dt, 1),
    }


def main() -> Dict[str, Any]:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import ray_tpu
    from ray_tpu._private import ray_perf

    results: Dict[str, Any] = {"host_cpus": os.cpu_count()}
    t_all = time.perf_counter()
    # Each probe gets a FRESH cluster (the reference's release
    # benchmarks are separate jobs too): on a 1-core host the residue
    # of one probe — 500k task events, the worker storm's process
    # churn — otherwise degrades the next by up to 8x, measuring
    # contamination instead of the subsystem.
    def perf_all():
        return {r["name"]: round(r["rate"], 2)
                for r in ray_perf.main(duration=1.0)}

    for name, fn in (
        ("wait_10k_refs", probe_wait_many_refs),
        ("broadcast_1gib_8_nodes", probe_broadcast),
        ("queue_500k_noop_tasks", lambda: probe_queue_tasks(500_000)),
        ("actors_1024", lambda: probe_actors(1024)),
        ("ray_perf", perf_all),
    ):
        t0 = time.perf_counter()
        try:
            ray_tpu.init(num_cpus=16)
            results[name] = fn()
            if isinstance(results[name], dict) and name != "ray_perf":
                results[name]["probe_s"] = round(
                    time.perf_counter() - t0, 2)
        except Exception as e:  # noqa: BLE001
            import traceback
            traceback.print_exc()
            results[name] = {"error": f"{type(e).__name__}: {e}"}
        finally:
            try:
                ray_tpu.shutdown()
            except Exception:  # noqa: BLE001
                pass
        print(f"[scale_probe] {name}: {json.dumps(results[name])}",
              flush=True)
    results["total_s"] = round(time.perf_counter() - t_all, 1)
    print(json.dumps(results))
    return results


if __name__ == "__main__":
    out = main()
    path = sys.argv[1] if len(sys.argv) > 1 else "SCALE_r05.json"
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
