"""Runtime environment application inside workers.

Parity: the reference's runtime-env agent
(``python/ray/_private/runtime_env/agent/runtime_env_agent.py:161``),
compressed to what a single-image TPU cluster needs:

- ``env_vars``: set for the duration of the task/actor (restored after
  tasks; actors keep them for life — the process is theirs).
- ``working_dir``: a local directory to chdir into (local paths only —
  remote URIs need an artifact store; raise rather than half-apply).
- ``pip`` / ``conda``: rejected loudly — the cluster image is immutable
  by design (no network egress on TPU pods at runtime).

``applied(spec)`` is a context manager used around non-actor tasks;
``apply(env)`` applies permanently (actor creation).
"""

from __future__ import annotations

import contextlib
import os
from typing import Any, Dict, Optional

_SUPPORTED = {"env_vars", "working_dir"}
_REJECTED = {"pip", "conda", "py_modules", "container"}


def validate(env: Optional[Dict[str, Any]]) -> Dict[str, Any]:
    env = env or {}
    bad = _REJECTED & set(env)
    if bad:
        raise ValueError(
            f"runtime_env keys {sorted(bad)} are not supported: the "
            "cluster image is immutable (install dependencies in the "
            "image; reference parity: runtime_env_agent)")
    unknown = set(env) - _SUPPORTED - _REJECTED
    # unknown keys are ignored (forward compatibility), not fatal
    return {k: env[k] for k in _SUPPORTED if k in env}


def apply(env: Optional[Dict[str, Any]]) -> Dict[str, Any]:
    """Apply permanently (actor creation); returns the undo state."""
    env = validate(env)
    # validate everything BEFORE mutating process state: a failure
    # halfway must not leak env vars into a pooled worker (the undo
    # state would never reach applied()'s finally)
    wd = env.get("working_dir")
    if wd and not os.path.isdir(wd):
        raise ValueError(f"runtime_env working_dir {wd!r} does not "
                         "exist on this node")
    undo: Dict[str, Any] = {"env_vars": {}, "cwd": None}
    for key, value in (env.get("env_vars") or {}).items():
        undo["env_vars"][key] = os.environ.get(key)
        os.environ[key] = str(value)
    if wd:
        undo["cwd"] = os.getcwd()
        os.chdir(wd)
    return undo


def undo(state: Dict[str, Any]) -> None:
    for key, old in state.get("env_vars", {}).items():
        if old is None:
            os.environ.pop(key, None)
        else:
            os.environ[key] = old
    if state.get("cwd"):
        try:
            os.chdir(state["cwd"])
        except OSError:
            pass


@contextlib.contextmanager
def applied(env: Optional[Dict[str, Any]]):
    """Scoped application around one task on a pooled worker."""
    if not env:
        yield
        return
    state = apply(env)
    try:
        yield
    finally:
        undo(state)
