"""Pre-warmed worker forkserver.

Cold worker startup is dominated by the child's imports (~0.7 s of CPU
for python + numpy + the runtime).  On a busy or small host, an actor
burst that needs N fresh workers pays N of those serially — the round-4
scale probe measured 2 actor creations/s for exactly this reason.

The forkserver is the reference's prestarted-worker idea taken one step
further (reference: ``raylet/worker_pool.cc`` prestarts idle workers,
and CPython's ``multiprocessing.forkserver`` is the same shape): the
node manager starts ONE template process per node which imports the
whole worker runtime once, then forks on request.  A fork costs
milliseconds and the child shares the template's pages copy-on-write,
so a 128-actor burst starts 128 workers in roughly the time one cold
spawn took.

Protocol (single persistent connection from the NM, strictly serial):
    request  = pickled {"env": {...}, "log_path": str}
    response = pickled {"pid": int}
The template stays single-threaded, so forking is safe; children are
auto-reaped via SIG_IGN on SIGCHLD.  TPU workers keep the cold-spawn
path (the TPU runtime plugin is not fork-safe once initialized).
"""

from __future__ import annotations

import os
import pickle
import signal
import socket
import struct
import sys

_LEN = struct.Struct("<I")


def _recv_exact(conn: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = conn.recv(n - len(buf))
        if not chunk:
            raise EOFError
        buf += chunk
    return buf


def _recv_obj(conn: socket.socket):
    (n,) = _LEN.unpack(_recv_exact(conn, _LEN.size))
    return pickle.loads(_recv_exact(conn, n))


def _send_obj(conn: socket.socket, obj) -> None:
    payload = pickle.dumps(obj)
    conn.sendall(_LEN.pack(len(payload)) + payload)


def proc_start_time(pid: int) -> Optional[int]:
    """Kernel start-time ticks of ``pid`` (field 22 of /proc/pid/stat) —
    (pid, starttime) uniquely identifies a process across pid reuse."""
    try:
        with open(f"/proc/{pid}/stat", "rb") as f:
            stat = f.read()
        # comm can contain spaces/parens: split after the last ')'
        fields = stat[stat.rindex(b")") + 2:].split()
        return int(fields[19])  # starttime is field 22 overall
    except (OSError, ValueError, IndexError):
        return None


def _child_exec(req: dict) -> None:
    """In the forked child: become the worker process."""
    signal.signal(signal.SIGCHLD, signal.SIG_DFL)
    os.setsid()
    log_path = req.get("log_path")
    if log_path:
        fd = os.open(log_path, os.O_WRONLY | os.O_CREAT | os.O_APPEND,
                     0o644)
        os.dup2(fd, 1)
        os.dup2(fd, 2)
        if fd > 2:
            os.close(fd)
    os.environ.update(req["env"])
    from ray_tpu._private import worker_proc
    try:
        worker_proc.main()
    finally:
        sys.stdout.flush()
        sys.stderr.flush()
        os._exit(0)


def _die_with_parent() -> None:
    """SIGTERM this template when the owning node manager process dies
    (a SIGKILLed NM can't run its stop() path; without this the
    template would orphan and sit in accept() forever)."""
    try:
        import ctypes
        PR_SET_PDEATHSIG = 1
        libc = ctypes.CDLL(None, use_errno=True)
        libc.prctl(PR_SET_PDEATHSIG, signal.SIGTERM, 0, 0, 0)
        if os.getppid() == 1:  # parent already gone before prctl
            sys.exit(0)
    except Exception:  # noqa: BLE001 — non-Linux: best effort
        pass


def main() -> None:
    sock_path = os.environ["RAY_TPU_FORKSRV_SOCK"]
    _die_with_parent()
    # pre-warm: everything a worker needs at startup, imported once
    from ray_tpu._private import worker_proc  # noqa: F401
    signal.signal(signal.SIGCHLD, signal.SIG_IGN)  # auto-reap children
    if os.path.exists(sock_path):
        os.unlink(sock_path)
    srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    srv.bind(sock_path)
    srv.listen(8)
    while True:
        try:
            conn, _ = srv.accept()
        except OSError:
            return
        try:
            while True:
                try:
                    req = _recv_obj(conn)
                except (EOFError, ConnectionResetError, OSError):
                    break
                if req.get("op") == "exit":
                    return
                pid = os.fork()
                if pid == 0:
                    srv.close()
                    conn.close()
                    try:
                        _child_exec(req)
                    finally:
                        os._exit(1)
                _send_obj(conn, {"pid": pid,
                                 "start_time": proc_start_time(pid)})
        finally:
            try:
                conn.close()
            except OSError:
                pass


if __name__ == "__main__":
    main()
