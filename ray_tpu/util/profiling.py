"""TPU/device profiling helpers.

Parity: the reference's profiling story (``ray timeline`` +
``torch.profiler`` integration in train); TPU-native: wraps
``jax.profiler`` so a train loop (or a Serve replica) captures an
xplane trace viewable in TensorBoard/XProf or Perfetto alongside the
cluster-level chrome trace (``ray-tpu timeline``).
"""

from __future__ import annotations

import contextlib
import os
import time
from typing import Iterator, Optional


@contextlib.contextmanager
def profile_device(logdir: Optional[str] = None,
                   host_tracer_level: int = 2) -> Iterator[str]:
    """Capture a jax device profile around a code block.

    Yields the log directory; afterwards it holds
    ``plugins/profile/<ts>/*.xplane.pb`` (TensorBoard "Profile" tab or
    ``xprof``) and a ``*.trace.json.gz`` for Perfetto.
    """
    import jax
    logdir = logdir or os.path.join(
        "/tmp", f"ray_tpu_profile_{int(time.time())}")
    try:
        opts = jax.profiler.ProfileOptions()
        opts.host_tracer_level = host_tracer_level
        ctx = jax.profiler.trace(logdir, profiler_options=opts)
    except (AttributeError, TypeError):  # older jax: no options
        ctx = jax.profiler.trace(logdir)
    with ctx:
        yield logdir


@contextlib.contextmanager
def annotate(name: str) -> Iterator[None]:
    """Named region inside a device profile (TraceAnnotation)."""
    import jax
    with jax.profiler.TraceAnnotation(name):
        yield


def device_memory_stats() -> dict:
    """Per-device live-memory stats (HBM pressure at a glance)."""
    import jax
    out = {}
    for d in jax.local_devices():
        try:
            stats = d.memory_stats() or {}
        except Exception:  # noqa: BLE001 — backend may not support it
            stats = {}
        out[str(d)] = {
            "bytes_in_use": stats.get("bytes_in_use"),
            "peak_bytes_in_use": stats.get("peak_bytes_in_use"),
            "bytes_limit": stats.get("bytes_limit"),
        }
    return out
