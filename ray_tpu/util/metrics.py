"""User-defined metrics (parity: ``python/ray/util/metrics.py``).

Counter / Gauge / Histogram recorded through the control plane;
exported in Prometheus text format by the dashboard's ``/metrics``.
"""

from __future__ import annotations

import bisect
import json
from typing import Dict, List, Optional, Tuple


def _cp():
    from ray_tpu._private.worker import global_worker
    return global_worker().cp


def _tag_key(tags: Optional[Dict[str, str]]) -> str:
    return json.dumps(sorted((tags or {}).items()))


def _sanitize(name: str) -> str:
    return "".join(c if c.isalnum() or c == "_" else "_" for c in name)


def _escape_label(value: str) -> str:
    """Prometheus label-value escaping: backslash, quote, newline."""
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _tag_labels(tag_key: str) -> str:
    """``_tag_key`` JSON -> Prometheus label body (no braces)."""
    try:
        items = json.loads(tag_key)
    except (ValueError, TypeError):
        return ""
    return ",".join(f'{_sanitize(k)}="{_escape_label(v)}"'
                    for k, v in items)


class Metric:
    def __init__(self, name: str, description: str = "",
                 tag_keys: Tuple[str, ...] = ()):
        self._name = name
        self._description = description
        self._tag_keys = tag_keys
        self._default_tags: Dict[str, str] = {}

    def set_default_tags(self, tags: Dict[str, str]):
        self._default_tags = dict(tags)
        return self

    def _merged(self, tags: Optional[Dict[str, str]]):
        merged = dict(self._default_tags)
        merged.update(tags or {})
        return merged


class Counter(Metric):
    def inc(self, value: float = 1.0,
            tags: Optional[Dict[str, str]] = None):
        # true float accumulation through the control plane — the old
        # path collapsed any non-integer increment to +1
        _cp().incr(f"user_counter:{self._name}"
                   f":{_tag_key(self._merged(tags))}", float(value))


class Gauge(Metric):
    def set(self, value: float, tags: Optional[Dict[str, str]] = None):
        _cp().kv_put(
            f"gauge:{self._name}:{_tag_key(self._merged(tags))}".encode(),
            repr(float(value)).encode(), namespace="_metrics")


_DEFAULT_BOUNDARIES = [0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0,
                       2.5, 5.0, 10.0]


class Histogram(Metric):
    def __init__(self, name: str, description: str = "",
                 boundaries: Optional[List[float]] = None,
                 tag_keys: Tuple[str, ...] = ()):
        super().__init__(name, description, tag_keys)
        self.boundaries = boundaries or _DEFAULT_BOUNDARIES
        self._spec_published = False

    def observe(self, value: float,
                tags: Optional[Dict[str, str]] = None,
                exemplar: Optional[Dict[str, str]] = None):
        cp = _cp()
        if not self._spec_published:
            # boundaries live beside the samples so the exposition can
            # rebuild cumulative le-buckets without the Histogram object
            cp.kv_put(f"histspec:{self._name}".encode(),
                      json.dumps(self.boundaries).encode(),
                      namespace="_metrics")
            self._spec_published = True
        tk = _tag_key(self._merged(tags))
        idx = bisect.bisect_left(self.boundaries, value)
        cp.incr(f"user_histogram:{self._name}:{tk}:bucket:{idx}")
        cp.incr(f"user_histogram:{self._name}:{tk}:sum", float(value))
        cp.incr(f"user_histogram:{self._name}:{tk}:count")
        if exemplar:
            # latest-wins exemplar per series (OpenMetrics style: a
            # trace id that explains one recent observation) — rendered
            # after the +Inf bucket by ``prometheus_text``
            cp.kv_put(f"histexemplar:{self._name}:{tk}".encode(),
                      json.dumps({"labels": exemplar,
                                  "value": float(value)}).encode(),
                      namespace="_metrics")


def _render_value(value) -> str:
    """Integers render bare (3, not 3.0); floats keep full precision."""
    f = float(value)
    return repr(int(f)) if f.is_integer() else repr(f)


def _histograms(counters: Dict[str, float]) -> Dict[str, Dict[str, dict]]:
    """``user_histogram:*`` counters -> {name: {tag_key: {buckets, sum,
    count}}}."""
    out: Dict[str, Dict[str, dict]] = {}
    for key, value in counters.items():
        if not key.startswith("user_histogram:"):
            continue
        # user_histogram:<name>:<tag json>:(bucket:<idx>|sum|count)
        rest = key[len("user_histogram:"):]
        name, _, rest = rest.partition(":")
        tk, _, kind = rest.rpartition(":")
        if kind.isdigit() and tk.endswith(":bucket"):
            tk, idx = tk[:-len(":bucket")], int(kind)
            kind = "bucket"
        elif kind not in ("sum", "count"):
            continue
        series = out.setdefault(name, {}).setdefault(
            tk, {"buckets": {}, "sum": 0.0, "count": 0.0})
        if kind == "bucket":
            series["buckets"][idx] = series["buckets"].get(idx, 0) + value
        else:
            series[kind] += value
    return out


def prometheus_text() -> str:
    """Render counters, gauges + histograms in Prometheus exposition
    format (histograms as proper cumulative ``_bucket{le=...}`` /
    ``_sum`` / ``_count`` lines)."""
    cp = _cp()
    counters = cp.counters()
    lines = []
    for name, value in sorted(counters.items()):
        if name.startswith("user_histogram:"):
            continue                   # rendered as histograms below
        safe = _sanitize(name.replace(":", "_")
                         .replace("{", "").replace("}", ""))
        lines.append(f"# TYPE {safe} counter")
        lines.append(f"{safe} {_render_value(value)}")
    for key in cp.kv_keys(b"gauge:", namespace="_metrics"):
        raw = cp.kv_get(key, namespace="_metrics")
        parts = key.decode().split(":")
        safe = _sanitize(parts[1])
        labels = _tag_labels(":".join(parts[2:]))
        lines.append(f"# TYPE {safe} gauge")
        lines.append(f"{safe}{{{labels}}} {float(raw)}"
                     if labels else f"{safe} {float(raw)}")
    for name, by_tags in sorted(_histograms(counters).items()):
        raw_spec = cp.kv_get(f"histspec:{name}".encode(),
                             namespace="_metrics")
        boundaries = json.loads(raw_spec) if raw_spec else []
        safe = f"user_histogram_{_sanitize(name)}"
        lines.append(f"# TYPE {safe} histogram")
        for tk, series in sorted(by_tags.items()):
            base = _tag_labels(tk)
            sep = "," if base else ""
            cum = 0.0
            for idx, bound in enumerate(boundaries):
                cum += series["buckets"].get(idx, 0)
                lines.append(
                    f'{safe}_bucket{{{base}{sep}le="{bound}"}} '
                    f'{_render_value(cum)}')
            inf_line = (f'{safe}_bucket{{{base}{sep}le="+Inf"}} '
                        f'{_render_value(series["count"])}')
            raw_ex = cp.kv_get(f"histexemplar:{name}:{tk}".encode(),
                               namespace="_metrics")
            if raw_ex:
                ex = json.loads(raw_ex)
                ex_labels = ",".join(
                    f'{_sanitize(k)}="{_escape_label(v)}"'
                    for k, v in sorted(ex["labels"].items()))
                inf_line += (f' # {{{ex_labels}}} '
                             f'{_render_value(ex["value"])}')
            lines.append(inf_line)
            suffix = f"{{{base}}}" if base else ""
            lines.append(
                f'{safe}_sum{suffix} {_render_value(series["sum"])}')
            lines.append(
                f'{safe}_count{suffix} '
                f'{_render_value(series["count"])}')
    return "\n".join(lines) + "\n"
