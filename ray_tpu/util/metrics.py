"""User-defined metrics (parity: ``python/ray/util/metrics.py``).

Counter / Gauge / Histogram recorded through the control plane;
exported in Prometheus text format by the dashboard's ``/metrics``.
"""

from __future__ import annotations

import bisect
import json
import threading
from typing import Dict, List, Optional, Tuple


def _cp():
    from ray_tpu._private.worker import global_worker
    return global_worker().cp


def _tag_key(tags: Optional[Dict[str, str]]) -> str:
    return json.dumps(sorted((tags or {}).items()))


class Metric:
    def __init__(self, name: str, description: str = "",
                 tag_keys: Tuple[str, ...] = ()):
        self._name = name
        self._description = description
        self._tag_keys = tag_keys
        self._default_tags: Dict[str, str] = {}

    def set_default_tags(self, tags: Dict[str, str]):
        self._default_tags = dict(tags)
        return self

    def _merged(self, tags: Optional[Dict[str, str]]):
        merged = dict(self._default_tags)
        merged.update(tags or {})
        return merged


class Counter(Metric):
    def inc(self, value: float = 1.0,
            tags: Optional[Dict[str, str]] = None):
        _cp().kv_put(
            f"metric:counter:{self._name}:{_tag_key(self._merged(tags))}"
            .encode(),
            repr(value).encode(), namespace="_metrics_inc")
        _cp().incr(f"user_counter:{self._name}"
                   f":{_tag_key(self._merged(tags))}",
                   int(value) if float(value).is_integer() else 1)


class Gauge(Metric):
    def set(self, value: float, tags: Optional[Dict[str, str]] = None):
        _cp().kv_put(
            f"gauge:{self._name}:{_tag_key(self._merged(tags))}".encode(),
            repr(float(value)).encode(), namespace="_metrics")


_DEFAULT_BOUNDARIES = [0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0,
                       2.5, 5.0, 10.0]


class Histogram(Metric):
    def __init__(self, name: str, description: str = "",
                 boundaries: Optional[List[float]] = None,
                 tag_keys: Tuple[str, ...] = ()):
        super().__init__(name, description, tag_keys)
        self.boundaries = boundaries or _DEFAULT_BOUNDARIES

    def observe(self, value: float,
                tags: Optional[Dict[str, str]] = None):
        idx = bisect.bisect_left(self.boundaries, value)
        label = (f"le_{self.boundaries[idx]}"
                 if idx < len(self.boundaries) else "le_inf")
        _cp().incr(f"user_histogram:{self._name}:{label}"
                   f":{_tag_key(self._merged(tags))}")
        _cp().incr(f"user_histogram:{self._name}:count")


def prometheus_text() -> str:
    """Render counters + gauges in Prometheus exposition format."""
    cp = _cp()
    lines = []
    for name, value in sorted(cp.counters().items()):
        safe = name.replace(":", "_").replace("{", "").replace("}", "")
        safe = "".join(c if c.isalnum() or c == "_" else "_"
                       for c in safe)
        lines.append(f"# TYPE {safe} counter")
        lines.append(f"{safe} {value}")
    for key in cp.kv_keys(b"gauge:", namespace="_metrics"):
        raw = cp.kv_get(key, namespace="_metrics")
        parts = key.decode().split(":")
        safe = "".join(c if c.isalnum() or c == "_" else "_"
                       for c in parts[1])
        lines.append(f"# TYPE {safe} gauge")
        lines.append(f"{safe} {float(raw)}")
    return "\n".join(lines) + "\n"
