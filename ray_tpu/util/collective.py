"""``ray_tpu.util.collective`` — process-group collectives between actors.

API parity: reference ``python/ray/util/collective/collective.py``
(init_collective_group, allreduce, allgather, reducescatter, broadcast,
barrier, send, recv).  Backends:

- ``"host"`` (gloo-equivalent): ring collectives over p2p sends, like the
  reference's ring NCCL (``collective_group/nccl_collective_group.py:402``).
  Payloads ride the shm object store worker-to-worker; the rendezvous
  actor only shuttles ObjectRefs (control plane), so per-rank traffic is
  O(2·N·(W-1)/W) and no single process sees more than its ring share.
- ``"xla"``: arrays are sharded over this process's device mesh and
  reduced by XLA collectives over ICI — used inside SPMD worker groups
  where each actor owns a slice of chips.
- ``"ici"``: multi-process device world — rank 0 publishes a coordinator
  address in the control-plane KV, every rank calls
  ``jax.distributed.initialize``, and verbs execute as XLA collectives
  over ICI/DCN on the *global* device set.  ``global_mesh()`` exposes the
  multi-process mesh for pjit programs (gradients should move inside
  pjit, not through verbs).

Group state is per-process, keyed by group name (reference
``GroupManager``).
"""

from __future__ import annotations

import asyncio
import os
import threading
from typing import Any, Dict, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.actor import get_actor

_groups: Dict[str, "BaseGroup"] = {}
_lock = threading.Lock()

_BINOPS = {"sum": np.add, "product": np.multiply, "min": np.minimum,
           "max": np.maximum}

REDUCE_OPS = {name: (lambda arrs, f=f: _tree_reduce(arrs, f))
              for name, f in _BINOPS.items()}


def _tree_reduce(arrs, op):
    out = arrs[0]
    for a in arrs[1:]:
        out = op(out, a)
    return out


@ray_tpu.remote
class CollectiveStore:
    """Async rendezvous + reduction actor (one per group)."""

    def __init__(self, world_size: int):
        self.world_size = world_size
        self._bufs: Dict[str, Dict[int, Any]] = {}
        self._events: Dict[str, asyncio.Event] = {}
        self._results: Dict[str, Any] = {}
        self._consumed: Dict[str, int] = {}
        self._p2p: Dict[str, Any] = {}
        self._p2p_events: Dict[str, asyncio.Event] = {}

    def _event(self, key: str) -> asyncio.Event:
        if key not in self._events:
            self._events[key] = asyncio.Event()
        return self._events[key]

    async def gather(self, op_id: str, rank: int, value: Any):
        """Collect one contribution; resolves once all ranks arrived."""
        bufs = self._bufs.setdefault(op_id, {})
        bufs[rank] = value
        ev = self._event(op_id)
        if len(bufs) == self.world_size:
            self._results[op_id] = [bufs[r]
                                    for r in range(self.world_size)]
            ev.set()
        else:
            await ev.wait()
        result = self._results[op_id]
        # garbage-collect once every rank has read
        self._consumed[op_id] = self._consumed.get(op_id, 0) + 1
        if self._consumed[op_id] == self.world_size:
            self._bufs.pop(op_id, None)
            self._events.pop(op_id, None)
            self._results.pop(op_id, None)
            self._consumed.pop(op_id, None)
        return result

    async def set_config(self, key: str, value: Any):
        """Group-wide config agreed at init (e.g. the ring threshold):
        rank 0 sets, everyone else waits — per-rank env divergence
        would silently deadlock mixed algorithm choices."""
        self._p2p[("cfg", key)] = value
        ev = self._event(f"cfg:{key}")
        ev.set()

    async def get_config(self, key: str):
        await self._event(f"cfg:{key}").wait()
        return self._p2p[("cfg", key)]

    async def put_p2p(self, key: str, value: Any):
        self._p2p[key] = value
        if key not in self._p2p_events:
            self._p2p_events[key] = asyncio.Event()
        self._p2p_events[key].set()

    async def get_p2p(self, key: str):
        """Return the mailbox entry WITHOUT popping: the mailbox must keep
        the contained ObjectRef alive until the receiver has fetched the
        payload (``ack_p2p``), else GC can free the object in flight."""
        if key not in self._p2p_events:
            self._p2p_events[key] = asyncio.Event()
        await self._p2p_events[key].wait()
        return self._p2p[key]

    async def ack_p2p(self, key: str):
        self._p2p.pop(key, None)
        self._p2p_events.pop(key, None)


class BaseGroup:
    def __init__(self, world_size: int, rank: int, group_name: str):
        self.world_size = world_size
        self.rank = rank
        self.group_name = group_name
        self._seq = 0

    def _next_op(self, verb: str) -> str:
        self._seq += 1
        return f"{self.group_name}:{verb}:{self._seq}"


class HostGroup(BaseGroup):
    """Ring collectives; payloads via the object store, refs via mailbox.

    Every rank calls each verb in the same order (standard collective
    contract), so the per-group op sequence numbers agree across ranks
    and key the per-step mailboxes.
    """

    def __init__(self, world_size: int, rank: int, group_name: str):
        super().__init__(world_size, rank, group_name)
        self._p2p_seq: Dict[Any, int] = {}
        self._ring_min: Optional[int] = None
        store_name = f"__collective_{group_name}"
        if rank == 0:
            try:
                self.store = CollectiveStore.options(
                    name=store_name, lifetime="detached").remote(world_size)
            except ValueError:
                self.store = get_actor(store_name)
            try:
                ring_min = int(os.environ.get(
                    "RAY_TPU_COLLECTIVE_RING_MIN", self.RING_MIN_BYTES))
            except ValueError:
                ring_min = self.RING_MIN_BYTES
            ray_tpu.get(self.store.set_config.remote("ring_min",
                                                     ring_min))
        else:
            deadline = 30.0
            import time
            t0 = time.time()
            while True:
                try:
                    self.store = get_actor(store_name)
                    break
                except ValueError:
                    if time.time() - t0 > deadline:
                        raise
                    time.sleep(0.05)

    def _exchange(self, verb: str, value: Any) -> List[Any]:
        """Full gather through the actor — only for tiny payloads
        (barrier tokens, refs)."""
        op = self._next_op(verb)
        return ray_tpu.get(self.store.gather.remote(op, self.rank, value))

    def _exchange_arrays(self, verb: str, arr) -> List[np.ndarray]:
        """All ranks see all arrays; payloads ride the object store,
        the actor shuttles only refs.  The trailing exchange is the ack
        barrier keeping every rank's ref (the GC pin) alive until all
        have fetched."""
        ref = ray_tpu.put(np.ascontiguousarray(arr))
        refs = self._exchange(verb, [ref])
        values = ray_tpu.get([r[0] for r in refs])
        self._exchange(verb + "_ack", None)
        return [np.asarray(v) for v in values]

    def _ring_threshold(self) -> int:
        t = self._ring_min
        if t is None:
            t = ray_tpu.get(self.store.get_config.remote("ring_min"))
            self._ring_min = t
        return t

    # -- ring plumbing ------------------------------------------------
    def _ring_send(self, op: str, step: int, dst: int, arr) -> None:
        key = f"{op}:s{step}:{self.rank}->{dst}"
        ref = ray_tpu.put(np.ascontiguousarray(arr))
        # wait for the ack so the mailbox holds (and refcounts) the ref
        # before our local handle can be dropped
        ray_tpu.get(self.store.put_p2p.remote(key, [ref]))

    def _ring_recv(self, op: str, step: int, src: int):
        key = f"{op}:s{step}:{src}->{self.rank}"
        (ref,) = ray_tpu.get(self.store.get_p2p.remote(key))
        value = ray_tpu.get(ref)
        self.store.ack_p2p.remote(key)  # safe to drop now that we hold it
        return value

    def _ring_reduce_scatter(self, op_id: str, chunks, binop):
        """In-place ring reduce-scatter; afterwards chunk[(rank+1) % W]
        holds the full reduction on this rank."""
        W, r = self.world_size, self.rank
        nxt, prv = (r + 1) % W, (r - 1) % W
        for step in range(W - 1):
            send_idx = (r - step) % W
            recv_idx = (r - step - 1) % W
            self._ring_send(op_id, step, nxt, chunks[send_idx])
            chunks[recv_idx] = binop(chunks[recv_idx],
                                     self._ring_recv(op_id, step, prv))
        return chunks

    def _ring_allgather(self, op_id: str, chunks, owned_idx: int):
        """Circulate chunks so every rank ends with all of them;
        ``owned_idx`` is the chunk this rank holds authoritative data
        for at the start."""
        W, r = self.world_size, self.rank
        nxt, prv = (r + 1) % W, (r - 1) % W
        for step in range(W - 1):
            send_idx = (owned_idx - step) % W
            recv_idx = (owned_idx - step - 1) % W
            self._ring_send(op_id, step, nxt, chunks[send_idx])
            chunks[recv_idx] = self._ring_recv(op_id, step, prv)
        return chunks

    # Below this payload size the ring's 2(W-1) sequential hops cost
    # more than one rendezvous round trip — the latency-vs-bandwidth
    # algorithm switch NCCL makes between tree/direct and ring.
    # Measured crossover on a 1-core host is ~2-4 MiB (32KiB: 18ms
    # direct vs 547ms ring; 8MiB: 1.7s direct vs 0.76s ring); tune per
    # deployment via RAY_TPU_COLLECTIVE_RING_MIN (rank 0's value is
    # published to the group so every rank picks the same algorithm).
    RING_MIN_BYTES = 4 * 1024 * 1024

    # -- verbs --------------------------------------------------------
    def allreduce(self, tensor, op: str = "sum"):
        arr = np.asarray(tensor)
        W = self.world_size
        if W == 1:
            return arr
        binop = _BINOPS[op]
        if arr.nbytes < self._ring_threshold():
            # latency path: one rendezvous round trip
            return REDUCE_OPS[op](self._exchange_arrays("allreduce",
                                                        arr))
        flat = arr.reshape(-1)
        chunks = [c.copy() for c in np.array_split(flat, W)]
        op_rs = self._next_op("ar_rs")
        op_ag = self._next_op("ar_ag")
        chunks = self._ring_reduce_scatter(op_rs, chunks, binop)
        chunks = self._ring_allgather(op_ag, chunks,
                                      (self.rank + 1) % W)
        return np.concatenate(chunks).reshape(arr.shape)

    def allgather(self, tensor) -> List[np.ndarray]:
        arr = np.asarray(tensor)
        W = self.world_size
        if W == 1:
            return [arr]
        if arr.nbytes < self._ring_threshold():
            return self._exchange_arrays("allgather", arr)
        chunks: List[Any] = [None] * W
        chunks[self.rank] = arr
        op_ag = self._next_op("ag")
        chunks = self._ring_allgather(op_ag, chunks, self.rank)
        return [np.asarray(c) for c in chunks]

    def reducescatter(self, tensor, op: str = "sum"):
        arr = np.asarray(tensor)
        W = self.world_size
        if W == 1:
            return arr
        binop = _BINOPS[op]
        if arr.nbytes < self._ring_threshold():
            red = REDUCE_OPS[op](self._exchange_arrays("rs_direct",
                                                       arr))
            return np.array_split(red, W)[self.rank]
        chunks = [c.copy() for c in np.array_split(arr, W)]
        op_rs = self._next_op("rs")
        chunks = self._ring_reduce_scatter(op_rs, chunks, binop)
        # rank holds chunk (rank+1)%W reduced; route it to its owner
        op_mv = self._next_op("rs_mv")
        owner = (self.rank + 1) % W
        if owner != self.rank:
            self._ring_send(op_mv, 0, owner, chunks[owner])
            mine = self._ring_recv(op_mv, 0, (self.rank - 1) % W)
        else:
            mine = chunks[owner]
        return np.asarray(mine)

    def broadcast(self, tensor, src_rank: int = 0):
        # one put by src; everyone else pulls the ref from the store.
        # The trailing exchange is an ack barrier: src's local ref (the
        # object's GC pin) stays alive until every rank has fetched.
        if self.rank == src_rank:
            ref = ray_tpu.put(np.asarray(tensor))
            arrs = self._exchange("broadcast", [ref])
        else:
            arrs = self._exchange("broadcast", None)
        (ref,) = arrs[src_rank]
        value = np.asarray(ray_tpu.get(ref))
        self._exchange("broadcast_ack", None)
        return value

    def reduce(self, tensor, dst_rank: int = 0, op: str = "sum"):
        arr = np.asarray(tensor)
        W = self.world_size
        if W == 1:
            return arr
        binop = _BINOPS[op]
        if arr.nbytes < self._ring_threshold():
            arrs = self._exchange_arrays("red_direct", arr)
            return (REDUCE_OPS[op](arrs) if self.rank == dst_rank
                    else arr)
        chunks = [c.copy() for c in np.array_split(arr.reshape(-1), W)]
        op_rs = self._next_op("red_rs")
        chunks = self._ring_reduce_scatter(op_rs, chunks, binop)
        # every rank sends its reduced chunk to dst
        op_gv = self._next_op("red_gather")
        mine_idx = (self.rank + 1) % W
        if self.rank != dst_rank:
            self._ring_send(op_gv, mine_idx, dst_rank, chunks[mine_idx])
            return arr
        for i in range(W):
            src = (i - 1) % W
            if src == dst_rank:
                continue
            chunks[i] = self._ring_recv(op_gv, i, src)
        return np.concatenate(chunks).reshape(arr.shape)

    def barrier(self):
        self._exchange("barrier", None)

    def send(self, tensor, dst_rank: int, tag: int = 0):
        # per-(peer, tag) sequence keeps every key unique, so a delayed
        # fire-and-forget ack can never delete a later message
        n = self._p2p_seq.setdefault(("s", dst_rank, tag), 0)
        self._p2p_seq[("s", dst_rank, tag)] = n + 1
        key = f"{self.group_name}:p2p:{self.rank}->{dst_rank}:{tag}:{n}"
        ref = ray_tpu.put(np.asarray(tensor))
        ray_tpu.get(self.store.put_p2p.remote(key, [ref]))

    def recv(self, src_rank: int, tag: int = 0):
        n = self._p2p_seq.setdefault(("r", src_rank, tag), 0)
        self._p2p_seq[("r", src_rank, tag)] = n + 1
        key = f"{self.group_name}:p2p:{src_rank}->{self.rank}:{tag}:{n}"
        (ref,) = ray_tpu.get(self.store.get_p2p.remote(key))
        value = np.asarray(ray_tpu.get(ref))
        self.store.ack_p2p.remote(key)
        return value

    def destroy(self):
        pass


class XlaGroup(BaseGroup):
    """Single-process multi-device collectives over ICI via XLA.

    ``world_size`` here is the number of local devices; verbs shard the
    array over them and let XLA emit the ICI collective.  This is the
    building block SPMD worker groups use intra-host; cross-host tensor
    collectives happen inside pjit'd programs instead (see
    ``ray_tpu.parallel``).
    """

    def __init__(self, world_size: int, rank: int, group_name: str,
                 devices=None):
        super().__init__(world_size, rank, group_name)
        import jax
        self.devices = devices or jax.devices()[:world_size]
        from ray_tpu.parallel.mesh import make_mesh
        self.mesh = make_mesh(dp=len(self.devices), devices=self.devices)

    def _run_manual(self, x, body, out_spec=None):
        """device_put x split on dim 0, run ``body(shard)`` under
        shard_map over dp, return the result."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ray_tpu.parallel.compat import shard_map
        sharded = jax.device_put(x, NamedSharding(self.mesh, P("dp")))
        fn = shard_map(body, mesh=self.mesh, in_specs=P("dp"),
                       out_specs=P() if out_spec is None else out_spec)
        return jax.jit(fn)(sharded)

    def allreduce(self, tensor, op: str = "sum"):
        """Leading axis of ``tensor`` = per-device contributions."""
        import jax
        import jax.numpy as jnp
        x = np.asarray(tensor)
        if op == "sum":
            body = lambda s: jax.lax.psum(s, "dp")           # noqa: E731
        elif op == "max":
            body = lambda s: jax.lax.pmax(s, "dp")           # noqa: E731
        elif op == "min":
            body = lambda s: jax.lax.pmin(s, "dp")           # noqa: E731
        elif op == "product":
            body = lambda s: jnp.prod(                        # noqa: E731
                jax.lax.all_gather(s, "dp"), axis=0)
        else:
            raise ValueError(f"unknown reduce op {op!r}")
        return np.asarray(self._run_manual(x, body))

    def allgather(self, tensor) -> List[np.ndarray]:
        import jax
        x = np.asarray(tensor)
        out = self._run_manual(
            x, lambda s: jax.lax.all_gather(s, "dp"))
        return [np.asarray(o) for o in out]

    def reducescatter(self, tensor, op: str = "sum"):
        """Per-device rows reduced then scattered; returns the host copy
        of every device's shard stacked on dim 0 (single process owns
        all shards)."""
        import jax
        from jax.sharding import PartitionSpec as P
        assert op == "sum", "xla reducescatter supports sum"
        x = np.asarray(tensor)
        if x.shape[0] != len(self.devices):
            raise ValueError(
                f"xla reducescatter needs one leading row per device "
                f"({len(self.devices)}), got shape {x.shape}")
        out = self._run_manual(
            x, lambda s: jax.lax.psum_scatter(
                s[0], "dp", scatter_dimension=0, tiled=True)[None],
            out_spec=P("dp"))
        return np.asarray(out)

    def broadcast(self, tensor, src_rank: int = 0):
        return np.asarray(tensor)  # single process: already everywhere

    def barrier(self):
        import jax
        self._run_manual(np.zeros((len(self.devices),), np.float32),
                         lambda s: jax.lax.psum(s, "dp"))


class IciGroup(BaseGroup):
    """Multi-process device world over ``jax.distributed``.

    The TPU-native replacement for NCCL process groups (SURVEY §2.3):
    rank 0 publishes ``ip:port`` under a control-plane KV key; every rank
    calls ``jax.distributed.initialize(coordinator, world, rank)``; after
    that ``jax.devices()`` is the global device set and verbs execute as
    XLA collectives over ICI/DCN.  Big tensors should be moved inside
    pjit programs over ``global_mesh()`` — the verbs here are for
    control-plane reductions (metrics, losses, small grads).
    """

    def __init__(self, world_size: int, rank: int, group_name: str,
                 coordinator: Optional[str] = None, timeout: float = 60.0):
        super().__init__(world_size, rank, group_name)
        import jax

        # NB: probe distributed state without jax.process_count() — that
        # would initialize the XLA backend and forbid initialize().
        from jax._src import distributed as _jd
        already = getattr(_jd.global_state, "client", None) is not None
        if already:
            # reuse the live world; rank 0 republishes its coordinator so
            # fresh ranks don't rendezvous on an address nobody serves
            coordinator = coordinator or getattr(
                _jd.global_state, "coordinator_address", None)
            if rank == 0 and coordinator:
                self._publish(coordinator)
        else:
            if coordinator is None:
                coordinator = self._rendezvous(timeout)
            # The CPU backend ships its cross-process collectives behind
            # a config (default "none" → "Multiprocess computations
            # aren't implemented on the CPU backend" at the first verb).
            # Enable gloo before the backend initializes; builds without
            # it (or jax versions that dropped the knob) just proceed —
            # tests/test_collective_pg.py detects that and skips.
            try:
                jax.config.update("jax_cpu_collectives_implementation",
                                  "gloo")
            except Exception:  # noqa: BLE001
                pass
            jax.distributed.initialize(coordinator_address=coordinator,
                                       num_processes=world_size,
                                       process_id=rank)
        self.coordinator = coordinator

    @property
    def _kv_key(self) -> bytes:
        return f"__ici_coordinator_{self.group_name}".encode()

    # joiners ignore coordinator records older than this — a crashed
    # run's stale key (which cp_persistence may even have journaled)
    # must not capture a fresh group's rendezvous
    _COORD_FRESH_S = 120.0

    def _publish(self, coordinator: str) -> None:
        import json
        import time

        from ray_tpu._private.worker import global_worker
        payload = json.dumps({"addr": coordinator, "ts": time.time()})
        global_worker().cp.kv_put(self._kv_key, payload.encode(),
                                  namespace="_collective")

    def _rendezvous(self, timeout: float) -> str:
        import time

        from ray_tpu._private.worker import global_worker
        worker = global_worker()
        if self.rank == 0:
            import socket
            s = socket.socket()
            s.bind(("0.0.0.0", 0))
            port = s.getsockname()[1]
            s.close()
            node = worker.cp.get_node(worker.node_id) or {}
            ip = node.get("ip") or "127.0.0.1"
            coordinator = f"{ip}:{port}"
            self._publish(coordinator)
            return coordinator
        import json
        t0 = time.time()
        while True:
            raw = worker.cp.kv_get(self._kv_key, namespace="_collective")
            if raw:
                try:
                    rec = json.loads(raw.decode())
                    if rec["ts"] >= t0 - self._COORD_FRESH_S:
                        return rec["addr"]
                except (ValueError, KeyError, TypeError):
                    pass  # stale/legacy record — keep polling
            if time.time() - t0 > timeout:
                raise TimeoutError(
                    f"no ici coordinator published for group "
                    f"{self.group_name!r} within {timeout}s")
            time.sleep(0.05)

    def global_mesh(self, **axes):
        """A mesh over the global (all-process) device set."""
        import jax

        from ray_tpu.parallel.mesh import make_mesh
        if not axes:
            axes = {"dp": -1}
        return make_mesh(devices=jax.devices(), **axes)

    def allreduce(self, tensor, op: str = "sum"):
        """XLA-collective allreduce over the device world.

        Each process contributes its local tensor as one shard of a
        [world, ...] global array; a jitted reduction with replicated
        output makes XLA insert the cross-process collective (ICI/DCN
        on TPU pods) — O(N) traffic per link, not the O(W*N) of the old
        allgather-then-local-reduce.  Falls back to the host-gather path
        if the device construction fails (every rank falls back together
        since the failure is deterministic in shapes/topology).
        """
        try:
            return self._allreduce_device(tensor, op)
        except Exception:  # noqa: BLE001
            return self._allreduce_host(tensor, op)

    def _allreduce_device(self, tensor, op: str):
        import jax
        import jax.numpy as jnp
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        # mesh + jitted reducer cached per (op): a fresh jit(lambda)
        # per call would retrace/recompile every gradient step
        if not hasattr(self, "_ar_mesh"):
            per_proc = {}
            for d in jax.devices():
                per_proc.setdefault(d.process_index, d)
            devs = [per_proc[i] for i in range(jax.process_count())]
            self._ar_mesh = Mesh(np.asarray(devs), ("p",))
            self._ar_local_dev = per_proc[jax.process_index()]
            self._ar_fns = {}
        if op not in self._ar_fns:
            red = {"sum": jnp.sum, "max": jnp.max, "min": jnp.min,
                   "product": jnp.prod}[op]
            self._ar_fns[op] = jax.jit(
                lambda a: red(a, axis=0),
                out_shardings=NamedSharding(self._ar_mesh, P()))
        mesh = self._ar_mesh
        x = jnp.asarray(np.asarray(tensor))
        local = jax.device_put(x[None], self._ar_local_dev)
        arr = jax.make_array_from_single_device_arrays(
            (mesh.size,) + x.shape, NamedSharding(mesh, P("p")), [local])
        return np.asarray(self._ar_fns[op](arr))

    def _allreduce_host(self, tensor, op: str):
        import jax.numpy as jnp

        from jax.experimental import multihost_utils
        gathered = multihost_utils.process_allgather(
            jnp.asarray(np.asarray(tensor)))
        return np.asarray(REDUCE_OPS[op](list(np.asarray(gathered))))

    def allgather(self, tensor) -> List[np.ndarray]:
        import jax.numpy as jnp

        from jax.experimental import multihost_utils
        gathered = multihost_utils.process_allgather(
            jnp.asarray(np.asarray(tensor)))
        return [np.asarray(g) for g in np.asarray(gathered)]

    def reducescatter(self, tensor, op: str = "sum"):
        red = self.allreduce(tensor, op=op)
        return np.array_split(red, self.world_size)[self.rank]

    def broadcast(self, tensor, src_rank: int = 0):
        arrs = self.allgather(np.asarray(tensor))
        return arrs[src_rank]

    def reduce(self, tensor, dst_rank: int = 0, op: str = "sum"):
        red = self.allreduce(tensor, op=op)
        return red if self.rank == dst_rank else np.asarray(tensor)

    def barrier(self):
        from jax.experimental import multihost_utils
        self._seq += 1
        multihost_utils.sync_global_devices(
            f"{self.group_name}:barrier:{self._seq}")

    def destroy(self):
        if self.rank == 0:
            try:
                from ray_tpu._private.worker import global_worker
                global_worker().cp.kv_del(self._kv_key,
                                          namespace="_collective")
            except Exception:  # noqa: BLE001 — best-effort cleanup
                pass


def init_collective_group(world_size: int, rank: int,
                          backend: str = "host",
                          group_name: str = "default", **kwargs) -> None:
    """Register this process/actor as ``rank`` of a collective group."""
    with _lock:
        if group_name in _groups:
            raise ValueError(f"group {group_name!r} already initialized")
        if backend in ("host", "cpu", "gloo"):
            group = HostGroup(world_size, rank, group_name)
        elif backend in ("xla", "tpu", "nccl"):
            group = XlaGroup(world_size, rank, group_name, **kwargs)
        elif backend == "ici":
            group = IciGroup(world_size, rank, group_name, **kwargs)
        else:
            raise ValueError(f"unknown backend {backend!r}")
        _groups[group_name] = group


def create_collective_group(actors, world_size: int, ranks: List[int],
                            backend: str = "host",
                            group_name: str = "default"):
    """Driver-side declarative setup (reference ``create_collective_group``):
    calls ``init_collective_group`` on each actor."""
    refs = []
    for actor, rank in zip(actors, ranks):
        refs.append(actor.__ray_call__.remote(
            _remote_init, world_size, rank, backend, group_name))
    ray_tpu.get(refs)


def _remote_init(self_instance, world_size, rank, backend, group_name):
    init_collective_group(world_size, rank, backend, group_name)
    return rank


def _group(group_name: str) -> BaseGroup:
    group = _groups.get(group_name)
    if group is None:
        raise ValueError(
            f"collective group {group_name!r} is not initialized in this "
            "process; call init_collective_group() first")
    return group


def is_group_initialized(group_name: str = "default") -> bool:
    return group_name in _groups


def get_rank(group_name: str = "default") -> int:
    return _group(group_name).rank


def get_collective_group_size(group_name: str = "default") -> int:
    return _group(group_name).world_size


def destroy_collective_group(group_name: str = "default") -> None:
    with _lock:
        group = _groups.pop(group_name, None)
    if group is not None:
        group.destroy()


def allreduce(tensor, group_name: str = "default", op: str = "sum"):
    return _group(group_name).allreduce(tensor, op=op)


def allgather(tensor, group_name: str = "default"):
    return _group(group_name).allgather(tensor)


def reducescatter(tensor, group_name: str = "default", op: str = "sum"):
    return _group(group_name).reducescatter(tensor, op=op)


def broadcast(tensor, src_rank: int = 0, group_name: str = "default"):
    return _group(group_name).broadcast(tensor, src_rank=src_rank)


def reduce(tensor, dst_rank: int = 0, group_name: str = "default",
           op: str = "sum"):
    return _group(group_name).reduce(tensor, dst_rank=dst_rank, op=op)


def barrier(group_name: str = "default"):
    _group(group_name).barrier()


def send(tensor, dst_rank: int, group_name: str = "default", tag: int = 0):
    _group(group_name).send(tensor, dst_rank, tag)


def recv(src_rank: int, group_name: str = "default", tag: int = 0):
    return _group(group_name).recv(src_rank, tag)
