"""``ray_tpu.util.collective`` — process-group collectives between actors.

API parity: reference ``python/ray/util/collective/collective.py``
(init_collective_group, allreduce, allgather, reducescatter, broadcast,
barrier, send, recv).  Backends:

- ``"host"`` (gloo-equivalent): host-memory arrays, rendezvous through a
  named async actor (the reference's ``NCCLUniqueIDStore`` pattern —
  ``collective_group/nccl_collective_group.py`` Rendezvous) which also
  performs the reduction.  Correctness-first; data rides the object store.
- ``"xla"`` (NCCL-replacement): arrays are sharded over this process's
  device mesh and reduced by XLA collectives over ICI — used inside SPMD
  worker groups where each actor owns a slice of chips.

Group state is per-process, keyed by group name (reference
``GroupManager``).
"""

from __future__ import annotations

import asyncio
import threading
from typing import Any, Dict, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.actor import get_actor

_groups: Dict[str, "BaseGroup"] = {}
_lock = threading.Lock()

REDUCE_OPS = {
    "sum": lambda arrs: _tree_reduce(arrs, np.add),
    "product": lambda arrs: _tree_reduce(arrs, np.multiply),
    "min": lambda arrs: _tree_reduce(arrs, np.minimum),
    "max": lambda arrs: _tree_reduce(arrs, np.maximum),
}


def _tree_reduce(arrs, op):
    out = arrs[0]
    for a in arrs[1:]:
        out = op(out, a)
    return out


@ray_tpu.remote
class CollectiveStore:
    """Async rendezvous + reduction actor (one per group)."""

    def __init__(self, world_size: int):
        self.world_size = world_size
        self._bufs: Dict[str, Dict[int, Any]] = {}
        self._events: Dict[str, asyncio.Event] = {}
        self._results: Dict[str, Any] = {}
        self._consumed: Dict[str, int] = {}
        self._p2p: Dict[str, Any] = {}
        self._p2p_events: Dict[str, asyncio.Event] = {}

    def _event(self, key: str) -> asyncio.Event:
        if key not in self._events:
            self._events[key] = asyncio.Event()
        return self._events[key]

    async def gather(self, op_id: str, rank: int, value: Any):
        """Collect one contribution; resolves once all ranks arrived."""
        bufs = self._bufs.setdefault(op_id, {})
        bufs[rank] = value
        ev = self._event(op_id)
        if len(bufs) == self.world_size:
            self._results[op_id] = [bufs[r]
                                    for r in range(self.world_size)]
            ev.set()
        else:
            await ev.wait()
        result = self._results[op_id]
        # garbage-collect once every rank has read
        self._consumed[op_id] = self._consumed.get(op_id, 0) + 1
        if self._consumed[op_id] == self.world_size:
            self._bufs.pop(op_id, None)
            self._events.pop(op_id, None)
            self._results.pop(op_id, None)
            self._consumed.pop(op_id, None)
        return result

    async def put_p2p(self, key: str, value: Any):
        self._p2p[key] = value
        if key not in self._p2p_events:
            self._p2p_events[key] = asyncio.Event()
        self._p2p_events[key].set()

    async def get_p2p(self, key: str):
        if key not in self._p2p_events:
            self._p2p_events[key] = asyncio.Event()
        await self._p2p_events[key].wait()
        value = self._p2p.pop(key)
        self._p2p_events.pop(key, None)
        return value


class BaseGroup:
    def __init__(self, world_size: int, rank: int, group_name: str):
        self.world_size = world_size
        self.rank = rank
        self.group_name = group_name
        self._seq = 0

    def _next_op(self, verb: str) -> str:
        self._seq += 1
        return f"{self.group_name}:{verb}:{self._seq}"


class HostGroup(BaseGroup):
    """Host-memory collectives through the rendezvous actor."""

    def __init__(self, world_size: int, rank: int, group_name: str):
        super().__init__(world_size, rank, group_name)
        store_name = f"__collective_{group_name}"
        if rank == 0:
            try:
                self.store = CollectiveStore.options(
                    name=store_name, lifetime="detached").remote(world_size)
            except ValueError:
                self.store = get_actor(store_name)
        else:
            deadline = 30.0
            import time
            t0 = time.time()
            while True:
                try:
                    self.store = get_actor(store_name)
                    break
                except ValueError:
                    if time.time() - t0 > deadline:
                        raise
                    time.sleep(0.05)

    def _exchange(self, verb: str, value: Any) -> List[Any]:
        op = self._next_op(verb)
        return ray_tpu.get(self.store.gather.remote(op, self.rank, value))

    def allreduce(self, tensor, op: str = "sum"):
        arrs = self._exchange("allreduce", np.asarray(tensor))
        return REDUCE_OPS[op](arrs)

    def allgather(self, tensor) -> List[np.ndarray]:
        return [np.asarray(a) for a in
                self._exchange("allgather", np.asarray(tensor))]

    def reducescatter(self, tensor, op: str = "sum"):
        arrs = self._exchange("reducescatter", np.asarray(tensor))
        red = REDUCE_OPS[op](arrs)
        return np.array_split(red, self.world_size)[self.rank]

    def broadcast(self, tensor, src_rank: int = 0):
        arrs = self._exchange("broadcast",
                              np.asarray(tensor) if self.rank == src_rank
                              else None)
        return np.asarray(arrs[src_rank])

    def reduce(self, tensor, dst_rank: int = 0, op: str = "sum"):
        arrs = self._exchange("reduce", np.asarray(tensor))
        if self.rank == dst_rank:
            return REDUCE_OPS[op](arrs)
        return np.asarray(tensor)

    def barrier(self):
        self._exchange("barrier", None)

    def send(self, tensor, dst_rank: int, tag: int = 0):
        key = f"{self.group_name}:p2p:{self.rank}->{dst_rank}:{tag}"
        ray_tpu.get(self.store.put_p2p.remote(key, np.asarray(tensor)))

    def recv(self, src_rank: int, tag: int = 0):
        key = f"{self.group_name}:p2p:{src_rank}->{self.rank}:{tag}"
        return np.asarray(ray_tpu.get(self.store.get_p2p.remote(key)))

    def destroy(self):
        pass


class XlaGroup(BaseGroup):
    """Single-process multi-device collectives over ICI via XLA.

    ``world_size`` here is the number of local devices; verbs shard the
    array over them and let XLA emit the ICI collective.  This is the
    building block SPMD worker groups use intra-host; cross-host tensor
    collectives happen inside pjit'd programs instead (see
    ``ray_tpu.parallel``).
    """

    def __init__(self, world_size: int, rank: int, group_name: str,
                 devices=None):
        super().__init__(world_size, rank, group_name)
        import jax
        self.devices = devices or jax.devices()[:world_size]
        from ray_tpu.parallel.mesh import make_mesh
        self.mesh = make_mesh(dp=len(self.devices), devices=self.devices)

    def _psum(self, x):
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P
        sharded = jax.device_put(
            x, NamedSharding(self.mesh, P("dp")))

        @jax.jit
        def reduce_fn(a):
            from ray_tpu.parallel.compat import shard_map
            import functools
            return shard_map(
                lambda s: jax.lax.psum(s, "dp"), mesh=self.mesh,
                in_specs=P("dp"), out_specs=P())(a)
        return reduce_fn(sharded)

    def allreduce(self, tensor, op: str = "sum"):
        """Leading axis of ``tensor`` = per-device contributions."""
        assert op == "sum", "xla backend supports sum"
        x = np.asarray(tensor)
        return np.asarray(self._psum(x))

    def barrier(self):
        import numpy as np
        self._psum(np.zeros((len(self.devices),), np.float32))


def init_collective_group(world_size: int, rank: int,
                          backend: str = "host",
                          group_name: str = "default") -> None:
    """Register this process/actor as ``rank`` of a collective group."""
    with _lock:
        if group_name in _groups:
            raise ValueError(f"group {group_name!r} already initialized")
        if backend in ("host", "cpu", "gloo"):
            group = HostGroup(world_size, rank, group_name)
        elif backend in ("xla", "ici", "tpu", "nccl"):
            group = XlaGroup(world_size, rank, group_name)
        else:
            raise ValueError(f"unknown backend {backend!r}")
        _groups[group_name] = group


def create_collective_group(actors, world_size: int, ranks: List[int],
                            backend: str = "host",
                            group_name: str = "default"):
    """Driver-side declarative setup (reference ``create_collective_group``):
    calls ``init_collective_group`` on each actor."""
    refs = []
    for actor, rank in zip(actors, ranks):
        refs.append(actor.__ray_call__.remote(
            _remote_init, world_size, rank, backend, group_name))
    ray_tpu.get(refs)


def _remote_init(self_instance, world_size, rank, backend, group_name):
    init_collective_group(world_size, rank, backend, group_name)
    return rank


def _group(group_name: str) -> BaseGroup:
    group = _groups.get(group_name)
    if group is None:
        raise ValueError(
            f"collective group {group_name!r} is not initialized in this "
            "process; call init_collective_group() first")
    return group


def is_group_initialized(group_name: str = "default") -> bool:
    return group_name in _groups


def get_rank(group_name: str = "default") -> int:
    return _group(group_name).rank


def get_collective_group_size(group_name: str = "default") -> int:
    return _group(group_name).world_size


def destroy_collective_group(group_name: str = "default") -> None:
    with _lock:
        group = _groups.pop(group_name, None)
    if group is not None:
        group.destroy()


def allreduce(tensor, group_name: str = "default", op: str = "sum"):
    return _group(group_name).allreduce(tensor, op=op)


def allgather(tensor, group_name: str = "default"):
    return _group(group_name).allgather(tensor)


def reducescatter(tensor, group_name: str = "default", op: str = "sum"):
    return _group(group_name).reducescatter(tensor, op=op)


def broadcast(tensor, src_rank: int = 0, group_name: str = "default"):
    return _group(group_name).broadcast(tensor, src_rank=src_rank)


def reduce(tensor, dst_rank: int = 0, group_name: str = "default",
           op: str = "sum"):
    return _group(group_name).reduce(tensor, dst_rank=dst_rank, op=op)


def barrier(group_name: str = "default"):
    _group(group_name).barrier()


def send(tensor, dst_rank: int, group_name: str = "default", tag: int = 0):
    _group(group_name).send(tensor, dst_rank, tag)


def recv(src_rank: int, group_name: str = "default", tag: int = 0):
    return _group(group_name).recv(src_rank, tag)
