"""Scheduling strategy types (parity:
``python/ray/util/scheduling_strategies.py``)."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional


@dataclass
class NodeAffinitySchedulingStrategy:
    node_id: str            # hex node id
    soft: bool = False


@dataclass
class PlacementGroupSchedulingStrategy:
    placement_group: "object"
    placement_group_bundle_index: int = -1
    placement_group_capture_child_tasks: Optional[bool] = None


SPREAD = "SPREAD"
DEFAULT = "DEFAULT"
