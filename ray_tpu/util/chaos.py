"""Chaos testing — kill random workers/actors/nodes under load, and
deterministic fault injection for the ML stack.

Parity: the reference's chaos-testing utilities
(``python/ray/_private/test_utils.py`` get_and_run_resource_killer /
WorkerKillerActor shapes, used by the chaos release tests): a
background thread that periodically kills a random victim so fault-
tolerance paths (task retries, actor restarts, lineage reconstruction,
node-death recovery) are exercised for real, not just unit-tested.

**Deterministic faults (r15).**  :class:`ResourceKiller` is random by
design, which is right for soak tests and wrong for acceptance tests:
a recovery *invariant* ("the RL loop survives an actor death with zero
steady-state recompiles") needs the death to land at an exact,
reproducible point.  :class:`FaultPlan` is that: named injection
**sites** in the ML stack call :func:`maybe_fail`/:func:`should_fire`,
and a spec — ``RAY_TPU_FAULTS`` or :func:`install_faults` — arms the
Nth hit of a site to raise :class:`InjectedFault` (or, for action
sites like checkpoint truncation, to return True so the site corrupts
itself).  Current sites:

- ``rl.rollout`` — a rollout actor dies entering its Nth rollout
  (the supervised loop must restart it from the latest weights);
- ``rl.learner`` — the learner dies entering its Nth update (the
  supervised loop must restore it from its checkpoint);
- ``rl.publish`` — the Nth weight publication fails (the loop keeps
  training; actors stay on the previous version);
- ``infer.decode`` — the Nth engine decode tick raises *before* the
  compiled step dispatches (donated buffers untouched — the engine
  stays drainable);
- ``ckpt.write`` — the Nth background checkpoint write fails;
- ``ckpt.truncate`` — the Nth checkpoint write is truncated on disk
  *after* writing (the resume path must fall back to the previous
  retained snapshot, loudly);
- ``serve.replica`` — the Nth fleet-replica engine tick kills the
  replica mid-traffic (the router must fail its in-flight streams
  over to healthy replicas; the reconciler must restore the target
  count with zero steady-state recompiles);
- ``serve.route`` — the Nth routed submit fails in flight (the
  router must re-route to another replica, counting the retry);
- ``data.read`` — the Nth shard-reader fetch dies (the data plane
  must restart the reader and re-issue the fetch verbatim —
  exactly-once sample accounting, no drop, no dup);
- ``data.pack`` — the Nth batch assembly dies before mutating packer
  state (the plane retries; the replayed batch is bit-identical);
- ``data.stall`` — the Nth shard read sleeps (slow-shard
  backpressure: the bounded prefetch queue drains and the trainer's
  ``data_stall_seconds`` histogram shows the block).  Prefer the
  ``:delay=S`` grammar; a bare ``data.stall@N`` entry is the
  deprecated alias that sleeps ``RAY_TPU_DATA_STALL_S``;
- ``mesh.loss`` — at the Nth elastic-loop step the training mesh
  loses devices (slice preemption): the loop snapshots (graceful) or
  falls back to the latest retained checkpoint, rebuilds at the
  surviving size with the gradient-accumulation factor scaled to keep
  the global batch, and reshards (``resilience/elastic.py``);
- ``mesh.restore`` — at the Nth step the lost capacity returns: the
  loop re-expands to the full mesh the same way;
- ``serve.tick`` — per-replica engine-tick latency (the r19 gray-
  failure site): a ``:delay=`` entry stretches the tick's wall time
  instead of killing anything — the slow-but-alive replica the
  health-scored router must demote and hedge around.  Counted twice:
  once fleet-wide as ``serve.tick`` and once per replica as
  ``serve.tick[<replica_id>]``, so a plan can slow exactly one
  replica for a sustained window deterministically;
- ``mesh.step`` — per-step train-loop latency: a ``:delay=`` window
  stretches step wall time (a straggling host gates the synchronous
  step), which the straggler supervisor must detect and convert into
  a degraded-mesh shrink instead of stalling the run forever;
- ``serve.handoff`` — the r20 disaggregated prefill→decode KV-page
  handoff: fires on BOTH legs of every transfer (once on the export
  leg, before the pages leave the prefill replica's allocator, and
  once on the import leg, before the decode side admits), so hits
  count two per handoff and a plan can fault either side — or
  ``:delay=`` the transfer itself.  Any fault degrades to the
  re-prefill-from-prompt failover with the held pages and the
  in-flight store object released (the disagg leak audit covers
  both);
- ``kv.spill`` — the r23 tiered-cache demote legs: fires once on the
  HBM→host-DRAM spill (before the page's contents leave the device)
  and once per host-pool overflow on the DRAM→store leg.  A faulted
  leg simply *forgets* the page — the pre-r23 eviction semantics — so
  a later request re-prefills it from the prompt; nothing hangs and
  the leak audit's tier partition stays exact;
- ``kv.fetch`` — the promote legs: fires per page as admission
  installs a DRAM/store hit back into HBM, or ``:delay=`` stretches
  the fetch (a slow object-store read).  A fault stops the install
  walk at that page and the suffix prefill covers the rest — greedy
  continuations stay bit-exact vs the unfaulted run;
- ``serve.adapter_load`` — the r25 multi-tenant adapter-cache miss
  leg: fires as a replica resolves a request's ``model_id`` that is
  not yet resident in its LoRA bank (cache hits never pay the site),
  before the store checkout, or ``:delay=`` stretches the load (a
  slow adapter fetch).  A fault surfaces as the typed
  ``AdapterUnavailableError``: submit-time rejections re-route to
  another replica, a resolution-time fault retires the waiting
  request with the error on its stream — either way degraded, never
  a hang — and resident tenants keep decoding untouched.

Spec grammar: comma-separated entries::

    site[@N[..M]][:delay=S]

``N`` is the 1-based hit index (bare ``site`` means ``site@1``).
Without ``:delay=``, the entry is a **fault**: hit ``N`` raises (or,
for action sites, returns True) exactly once; a hit *range* is
meaningless for faults and is rejected.  With ``:delay=S``, the entry
is a **slowdown**: every hit in ``[N, M]`` (``M`` defaults to ``N``)
sleeps ``S`` seconds inside the site before proceeding — gray failure,
replayable because it is driven off the same deterministic hit
counters.  E.g. ``RAY_TPU_FAULTS="rl.rollout@3,serve.tick[r0]@5..40:
delay=0.1,data.read@2:delay=0.5"``.

Hit counters are lock-protected: the ``StreamingLoader`` producer
thread, hedged standby readers and the main thread may count sites
concurrently, and deterministic replay must not race.
"""

from __future__ import annotations

import os
import random
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import ray_tpu


# ---------------------------------------------------------------- faults
class InjectedFault(RuntimeError):
    """Raised by an armed fault-injection site (never by real code
    paths) — supervisors treat it like any other death, tests can
    assert it specifically."""

    def __init__(self, site: str, hit: int):
        super().__init__(f"injected fault at site {site!r} (hit {hit})")
        self.site = site
        self.hit = hit

    def __reduce__(self):
        # rebuild from constructor args, not the message — injected
        # faults cross process boundaries (killed remote actors)
        return (InjectedFault, (self.site, self.hit))


class FaultPlan:
    """Parsed fault spec: deterministic per-site hit counters.

    ``fires(site)`` counts one hit of ``site``, sleeps any armed
    slowdown for this hit, and reports whether an armed fault triggers
    on exactly this hit.  Counters are process-global per plan and
    lock-protected (producer threads and hedged standby readers count
    sites concurrently with the main thread), so a fixed spec +
    deterministic call order reproduces the same failure point every
    run.  ``fired`` logs every triggered ``(site, hit)`` and
    ``slowed`` every injected ``(site, hit, seconds)`` so tests can
    assert the gray failure actually landed.
    """

    def __init__(self, spec: str = ""):
        self._armed: Dict[str, List[int]] = {}
        # site -> [(first_hit, last_hit, delay_s)] slowdown windows
        self._delays: Dict[str, List[Tuple[int, int, float]]] = {}
        self._hits: Dict[str, int] = {}
        self._lock = threading.Lock()
        self.fired: List[Tuple[str, int]] = []
        self.slowed: List[Tuple[str, int, float]] = []
        self.spec = spec.strip()
        for entry in self.spec.split(","):
            entry = entry.strip()
            if not entry:
                continue
            head, _, tail = entry.partition(":")
            delay = None
            if tail:
                key, _, val = tail.partition("=")
                if key.strip() != "delay" or not val:
                    raise ValueError(
                        f"bad RAY_TPU_FAULTS entry {entry!r}: the "
                        "only site modifier is ':delay=S' (seconds)")
                try:
                    delay = float(val)
                except ValueError:
                    raise ValueError(
                        f"bad RAY_TPU_FAULTS entry {entry!r}: "
                        f"delay {val!r} is not a number of seconds")
                if delay < 0:
                    raise ValueError(
                        f"bad RAY_TPU_FAULTS entry {entry!r}: delay "
                        "must be >= 0 seconds")
            site, _, at = head.partition("@")
            site = site.strip()
            lo, _, hi = at.partition("..")
            try:
                first = int(lo) if lo else 1
                last = int(hi) if hi else first
            except ValueError:
                raise ValueError(
                    f"bad RAY_TPU_FAULTS entry {entry!r}: expected "
                    "'site', 'site@N' or 'site@N..M' (1-based hit "
                    "indices)")
            if first < 1 or last < first:
                raise ValueError(
                    f"bad RAY_TPU_FAULTS entry {entry!r}: hit index "
                    "must be >= 1 (and N <= M for a window)")
            if delay is None:
                if hi:
                    raise ValueError(
                        f"bad RAY_TPU_FAULTS entry {entry!r}: a hit "
                        "range only makes sense for a slowdown — add "
                        "':delay=S' (a fault fires once, at one hit)")
                self._armed.setdefault(site, []).append(first)
            else:
                self._delays.setdefault(site, []).append(
                    (first, last, delay))

    def fires(self, site: str) -> bool:
        """Count one hit of ``site``; sleep this hit's armed slowdown
        (if any); True iff an armed fault triggers on exactly this hit
        (each armed entry fires at most once)."""
        with self._lock:
            hit = self._hits.get(site, 0) + 1
            self._hits[site] = hit
            delay = 0.0
            for first, last, d in self._delays.get(site, ()):
                if first <= hit <= last:
                    delay += d
            if delay > 0:
                self.slowed.append((site, hit, delay))
            fired = hit in self._armed.get(site, ())
            if fired:
                self.fired.append((site, hit))
        if delay > 0:           # sleep OUTSIDE the lock: a slowed
            time.sleep(delay)   # site must not block other counters
        return fired

    def hits(self, site: str) -> int:
        with self._lock:
            return self._hits.get(site, 0)

    def slowdown_s(self, site: str) -> float:
        """Total injected delay the plan has charged to ``site`` so
        far (test/telemetry accounting)."""
        with self._lock:
            return sum(d for s, _, d in self.slowed if s == site)


_PLAN: Optional[FaultPlan] = None
_PLAN_FROM_ENV = False


def install_faults(spec: str) -> FaultPlan:
    """Arm a fault plan programmatically (tests / drivers); returns it
    so the caller can assert on ``plan.fired``."""
    global _PLAN, _PLAN_FROM_ENV
    _PLAN = FaultPlan(spec)
    _PLAN_FROM_ENV = True       # explicit install wins over the env
    return _PLAN


def clear_faults() -> None:
    global _PLAN, _PLAN_FROM_ENV
    _PLAN = None
    _PLAN_FROM_ENV = False


def fault_plan() -> Optional[FaultPlan]:
    """The active plan: an installed one, else lazily from the
    ``RAY_TPU_FAULTS`` env spec (read once), else None."""
    global _PLAN, _PLAN_FROM_ENV
    if _PLAN is None and not _PLAN_FROM_ENV:
        spec = os.environ.get("RAY_TPU_FAULTS", "")
        _PLAN_FROM_ENV = True
        if spec.strip():
            _PLAN = FaultPlan(spec)
    return _PLAN


def should_fire(site: str) -> bool:
    """Count a hit of an *action* site (the site corrupts something
    itself when True — e.g. truncating a just-written checkpoint)."""
    plan = fault_plan()
    return plan.fires(site) if plan is not None else False


def maybe_fail(site: str) -> None:
    """Count a hit of a *raise* site; raises :class:`InjectedFault`
    when an armed fault triggers.  Free when no plan is armed."""
    plan = fault_plan()
    if plan is not None and plan.fires(site):
        hit = plan.hits(site)
        # r24: every injected fault is a flight-recorder anomaly —
        # lazy import keeps the un-armed fast path free of telemetry
        from ray_tpu.telemetry import trace as trace_mod
        trace_mod.on_injected_fault(site, hit)
        raise InjectedFault(site, hit)


class ResourceKiller:
    """Kill a random victim every ``interval_s`` while running.

    ``kind``: "worker" (SIGKILL a task worker process), "actor"
    (ray_tpu.kill a random live actor), or "node" (terminate a random
    non-head node process).
    """

    def __init__(self, kind: str = "worker", interval_s: float = 1.0,
                 max_kills: Optional[int] = None,
                 rng_seed: Optional[int] = None):
        assert kind in ("worker", "actor", "node")
        self.kind = kind
        self.interval_s = interval_s
        self.max_kills = max_kills
        self.kills: List[str] = []
        self._rng = random.Random(rng_seed)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- victim selection ---------------------------------------------
    def _pick_worker_pid(self) -> Optional[int]:
        # pids via task events would be racy; read the head node
        # manager's live worker table instead
        from ray_tpu._private.worker import global_node
        nm = global_node().node_manager
        with nm._lock:
            pids = [w.proc.pid for w in nm._workers.values()
                    if w.proc is not None and w.state == "busy"]
        return self._rng.choice(pids) if pids else None

    def _pick_actor(self):
        from ray_tpu.util.state import list_actors
        rows = [r for r in list_actors() if r["state"] == "ALIVE"
                and not (r.get("name") or "").startswith("__")]
        if not rows:
            return None
        return bytes.fromhex(self._rng.choice(rows)["actor_id"])

    def _pick_node(self) -> Optional[bytes]:
        from ray_tpu._private.worker import global_node
        extra = [nid for nid, proc in global_node()._extra_nodes
                 if proc.poll() is None]
        return self._rng.choice(extra) if extra else None

    # -- kill actions --------------------------------------------------
    def _kill_once(self) -> bool:
        import os
        import signal
        if self.kind == "worker":
            pid = self._pick_worker_pid()
            if pid is None:
                return False
            try:
                os.kill(pid, signal.SIGKILL)
            except ProcessLookupError:
                return False
            self.kills.append(f"worker pid={pid}")
        elif self.kind == "actor":
            aid = self._pick_actor()
            if aid is None:
                return False
            from ray_tpu._private.worker import global_worker
            global_worker().kill_actor(aid, no_restart=False)
            self.kills.append(f"actor {aid.hex()[:12]}")
        else:
            nid = self._pick_node()
            if nid is None:
                return False
            from ray_tpu._private.worker import global_node
            global_node().remove_node(nid)
            self.kills.append(f"node {nid.hex()[:12]}")
        return True

    # -- lifecycle -----------------------------------------------------
    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            if self.max_kills is not None and \
                    len(self.kills) >= self.max_kills:
                return
            try:
                self._kill_once()
            except Exception:  # noqa: BLE001 — chaos must not crash
                pass

    def start(self) -> "ResourceKiller":
        if self.kind in ("worker", "node"):
            from ray_tpu._private.worker import global_node
            if getattr(global_node(), "node_manager", None) is None:
                raise ValueError(
                    f"chaos kind={self.kind!r} needs the head driver "
                    "(an attached driver has no local node manager)")
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name=f"chaos-{self.kind}")
        self._thread.start()
        return self

    def stop(self) -> List[str]:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        return list(self.kills)

    def __enter__(self) -> "ResourceKiller":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
