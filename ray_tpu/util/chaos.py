"""Chaos testing — kill random workers/actors/nodes under load.

Parity: the reference's chaos-testing utilities
(``python/ray/_private/test_utils.py`` get_and_run_resource_killer /
WorkerKillerActor shapes, used by the chaos release tests): a
background thread that periodically kills a random victim so fault-
tolerance paths (task retries, actor restarts, lineage reconstruction,
node-death recovery) are exercised for real, not just unit-tested.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Any, Dict, List, Optional

import ray_tpu


class ResourceKiller:
    """Kill a random victim every ``interval_s`` while running.

    ``kind``: "worker" (SIGKILL a task worker process), "actor"
    (ray_tpu.kill a random live actor), or "node" (terminate a random
    non-head node process).
    """

    def __init__(self, kind: str = "worker", interval_s: float = 1.0,
                 max_kills: Optional[int] = None,
                 rng_seed: Optional[int] = None):
        assert kind in ("worker", "actor", "node")
        self.kind = kind
        self.interval_s = interval_s
        self.max_kills = max_kills
        self.kills: List[str] = []
        self._rng = random.Random(rng_seed)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- victim selection ---------------------------------------------
    def _pick_worker_pid(self) -> Optional[int]:
        # pids via task events would be racy; read the head node
        # manager's live worker table instead
        from ray_tpu._private.worker import global_node
        nm = global_node().node_manager
        with nm._lock:
            pids = [w.proc.pid for w in nm._workers.values()
                    if w.proc is not None and w.state == "busy"]
        return self._rng.choice(pids) if pids else None

    def _pick_actor(self):
        from ray_tpu.util.state import list_actors
        rows = [r for r in list_actors() if r["state"] == "ALIVE"
                and not (r.get("name") or "").startswith("__")]
        if not rows:
            return None
        return bytes.fromhex(self._rng.choice(rows)["actor_id"])

    def _pick_node(self) -> Optional[bytes]:
        from ray_tpu._private.worker import global_node
        extra = [nid for nid, proc in global_node()._extra_nodes
                 if proc.poll() is None]
        return self._rng.choice(extra) if extra else None

    # -- kill actions --------------------------------------------------
    def _kill_once(self) -> bool:
        import os
        import signal
        if self.kind == "worker":
            pid = self._pick_worker_pid()
            if pid is None:
                return False
            try:
                os.kill(pid, signal.SIGKILL)
            except ProcessLookupError:
                return False
            self.kills.append(f"worker pid={pid}")
        elif self.kind == "actor":
            aid = self._pick_actor()
            if aid is None:
                return False
            from ray_tpu._private.worker import global_worker
            global_worker().kill_actor(aid, no_restart=False)
            self.kills.append(f"actor {aid.hex()[:12]}")
        else:
            nid = self._pick_node()
            if nid is None:
                return False
            from ray_tpu._private.worker import global_node
            global_node().remove_node(nid)
            self.kills.append(f"node {nid.hex()[:12]}")
        return True

    # -- lifecycle -----------------------------------------------------
    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            if self.max_kills is not None and \
                    len(self.kills) >= self.max_kills:
                return
            try:
                self._kill_once()
            except Exception:  # noqa: BLE001 — chaos must not crash
                pass

    def start(self) -> "ResourceKiller":
        if self.kind in ("worker", "node"):
            from ray_tpu._private.worker import global_node
            if getattr(global_node(), "node_manager", None) is None:
                raise ValueError(
                    f"chaos kind={self.kind!r} needs the head driver "
                    "(an attached driver has no local node manager)")
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name=f"chaos-{self.kind}")
        self._thread.start()
        return self

    def stop(self) -> List[str]:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        return list(self.kills)

    def __enter__(self) -> "ResourceKiller":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
