"""Tracing hooks (parity: ``python/ray/util/tracing/tracing_helper.py``).

The reference patches every remote call with OpenTelemetry spans when
``ray.init(_tracing_startup_hook=...)`` is set.  Here tracing is a
light seam over the same points: if ``opentelemetry`` is importable the
spans are real OTel spans (exported by whatever provider the user
configured); otherwise an in-process recorder keeps (name, start, end,
attributes) tuples so tests and the timeline can still observe the
graph.  Zero overhead when never enabled.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Any, Dict, List, Optional

_lock = threading.Lock()
_enabled = False
_tracer = None          # otel tracer when available
_records: List[Dict[str, Any]] = []   # fallback recorder
_MAX_RECORDS = 10_000


def enable_tracing() -> bool:
    """Turn on span emission; True if real OpenTelemetry is active."""
    global _enabled, _tracer
    with _lock:
        _enabled = True
        if _tracer is None:
            try:
                from opentelemetry import trace as otel_trace
                _tracer = otel_trace.get_tracer("ray_tpu")
            except Exception:  # noqa: BLE001 — recorder fallback
                _tracer = None
        return _tracer is not None


def disable_tracing() -> None:
    global _enabled
    with _lock:
        _enabled = False


def is_enabled() -> bool:
    return _enabled


def recorded_spans() -> List[Dict[str, Any]]:
    """Fallback-recorder contents (OTel-less environments/tests)."""
    with _lock:
        return list(_records)


def clear_recorded() -> None:
    with _lock:
        _records.clear()


@contextlib.contextmanager
def span(name: str, **attributes):
    """Trace one operation.  No-op (two attr reads) when disabled."""
    if not _enabled:
        yield None
        return
    if _tracer is not None:
        with _tracer.start_as_current_span(name) as s:
            for k, v in attributes.items():
                try:
                    s.set_attribute(k, v)
                except Exception:  # noqa: BLE001
                    pass
            yield s
        return
    rec = {"name": name, "start": time.time(), "attributes": attributes}
    try:
        yield rec
    finally:
        rec["end"] = time.time()
        with _lock:
            _records.append(rec)
            if len(_records) > _MAX_RECORDS:
                del _records[:len(_records) - _MAX_RECORDS]


def task_span(spec) -> "contextlib.AbstractContextManager":
    """Span for one task/actor-method execution (worker side)."""
    if not _enabled:
        return contextlib.nullcontext()
    return span(
        f"task::{getattr(spec, 'name', '?')}",
        task_id=getattr(spec, 'task_id', b'').hex()[:16],
        actor_method=getattr(spec, 'actor_method', None) or "",
    )


def submit_span(name: str) -> "contextlib.AbstractContextManager":
    """Span for a submission on the caller side."""
    if not _enabled:
        return contextlib.nullcontext()
    return span(f"submit::{name}")
